"""Minimal TPU sanity-check deployment — the tpu-native analog of the
reference's gpu-test app (ref apps/gpu-test/gpu_test_deployment.py:34-77:
ping + `nvidia-smi -L` + CUDA_VISIBLE_DEVICES). Here the device probe is
`jax.devices()` plus a tiny jitted matmul that proves the XLA backend is
alive, and the env report covers the TPU/JAX variables instead of CUDA.
Stdlib + jax only so the deployment is cheap to schedule.
"""

import os
import time

from bioengine_tpu.rpc import schema_method

_TPU_ENV_KEYS = (
    "JAX_PLATFORMS",
    "TPU_CHIPS_PER_HOST_BOUNDS",
    "TPU_HOST_BOUNDS",
    "TPU_WORKER_ID",
    "TPU_ACCELERATOR_TYPE",
    "XLA_FLAGS",
)


class TpuTest:
    def __init__(self) -> None:
        self.start_time = time.time()

    @schema_method
    async def ping(self, context=None):
        """Cheap liveness probe; does not touch the XLA backend."""
        return {
            "status": "ok",
            "uptime": time.time() - self.start_time,
            "jax_platforms": os.environ.get("JAX_PLATFORMS"),
        }

    @schema_method
    async def tpu_info(self, context=None):
        """Enumerate visible XLA devices and run one jitted matmul.

        Returns platform, device list (kind/id/process), and the result
        norm of a 128x128 bf16 matmul as proof the backend executes.
        """
        try:
            import jax
            import jax.numpy as jnp

            devices = [
                {
                    "id": d.id,
                    "platform": d.platform,
                    "device_kind": d.device_kind,
                    "process_index": d.process_index,
                }
                for d in jax.devices()
            ]
            x = jnp.ones((128, 128), jnp.bfloat16)
            y = jax.jit(lambda a: a @ a)(x)
            norm = float(jnp.linalg.norm(y.astype(jnp.float32)))
            return {
                "backend": jax.default_backend(),
                "device_count": len(devices),
                "devices": devices,
                "matmul_norm": norm,
                "env": {k: os.environ.get(k) for k in _TPU_ENV_KEYS},
                "error": "",
            }
        except Exception as e:  # report instead of failing the health check
            return {
                "backend": None,
                "device_count": 0,
                "devices": [],
                "matmul_norm": None,
                "env": {k: os.environ.get(k) for k in _TPU_ENV_KEYS},
                "error": str(e),
            }

    @schema_method
    async def memory_info(self, context=None):
        """Per-device memory stats where the backend exposes them."""
        import jax

        stats = []
        for d in jax.devices():
            try:
                s = d.memory_stats() or {}
            except Exception:
                s = {}
            stats.append(
                {
                    "id": d.id,
                    "bytes_in_use": s.get("bytes_in_use"),
                    "bytes_limit": s.get("bytes_limit"),
                    "peak_bytes_in_use": s.get("peak_bytes_in_use"),
                }
            )
        return {"devices": stats}
