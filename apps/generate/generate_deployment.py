"""Streaming generation over the decode engine.

The tentpole app for token streaming: ``generate_stream`` is an async
generator the serving plane carries end to end — DecodeLoop (step-level
continuous batching) → Replica.call_stream → host ``replica_stream``
verb → controller stream bridge → DeploymentHandle.call_stream — with
one token per stream1 fast frame on the wire.

Mesh-aware like model-runner's RuntimeDeployment: the deployment reads
its chip lease (``bioengine_device_ids``) and optional mesh shard
(``bioengine_mesh_shard``) injected before ``async_init`` and builds
its :class:`DecodeEngine` over exactly those devices — a decoder that
outgrows one lease is a manifest ``mesh:``/``chips:`` edit, not new
code. Greedy decoding keeps every placement bit-exact, which is what
the 1-chip vs mesh parity test and mid-stream resume both rely on.
"""

import asyncio
import os

from bioengine_tpu.rpc import schema_method
from bioengine_tpu.utils import tracing


def encode(text: str) -> list:
    """Char-level tokenization into the toy decoder's 256-way vocab."""
    return [ord(c) % 256 for c in text]


def decode(tokens) -> str:
    return "".join(chr(int(t) % 256) for t in tokens)


class GenerateDeployment:
    def __init__(self, max_active: int = None, interactive_reserve: int = 1):
        self.max_active = max_active
        self.interactive_reserve = interactive_reserve
        self.engine = None
        self.loop = None
        self.ready = False

    async def async_init(self):
        # heavy imports deferred so manifest validation/builder scans
        # don't pay for jax
        from bioengine_tpu.runtime.decode_engine import DecodeEngine
        from bioengine_tpu.serving.decode import DecodeLoop

        lease = list(getattr(self, "bioengine_device_ids", None) or [])
        shard = getattr(self, "bioengine_mesh_shard", None)
        axes = None
        if shard and shard.get("axes"):
            axes = dict(shard["axes"])
        elif len(lease) > 1:
            # multi-chip lease without an explicit mesh block still
            # shards the step batch — dp is the only decoder axis
            axes = {"dp": -1}

        def build():
            eng = DecodeEngine(
                device_ids=lease or None,
                mesh_axes=axes,
                seed=int(os.environ.get("BIOENGINE_GENERATE_SEED", "0")),
            )
            eng.warmup(prompt_lens=(16,), batches=(1,))
            return eng

        self.engine = await asyncio.to_thread(build)
        self.loop = DecodeLoop(
            self.engine,
            name="generate",
            max_active=self.max_active,
            interactive_reserve=self.interactive_reserve,
        )
        self.ready = True

    async def test_deployment(self):
        out = await self.generate(prompt="hello", max_new_tokens=4)
        assert len(out["tokens"]) == 4, f"expected 4 tokens, got {out}"

    async def check_health(self):
        if not self.ready:
            raise RuntimeError("decode engine not initialized")

    async def close(self):
        if self.loop is not None:
            await self.loop.close()

    # ---- streaming entry ----------------------------------------------------

    async def generate_stream(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        klass: str = "interactive",
        deadline_s=None,
        resume_from: int = 0,
        seq_id=None,
        context=None,
    ):
        """Async generator: one ``{"token", "text", "index"}`` item per
        generated token. ``resume_from`` makes a resumed stream emit
        exactly the missing suffix (greedy decoding regenerates the
        prefix deterministically without re-sending it)."""
        stream = self.loop.submit(
            encode(prompt),
            max_new_tokens,
            klass=klass,
            deadline_s=deadline_s,
            seq_id=seq_id,
            resume_from=int(resume_from or 0),
        )
        booked = 0.0
        index = int(resume_from or 0)
        try:
            async for tok in stream.tokens():
                # book the fair-share device cost incrementally into the
                # caller's request-scoped accounting — the stream can
                # outlive many decode steps, and billing at each token
                # keeps a mid-stream disconnect accounted too
                delta = stream.chip_seconds - booked
                if delta > 0:
                    tracing.add_chip_seconds(delta)
                    booked += delta
                yield {
                    "token": int(tok),
                    "text": chr(int(tok) % 256),
                    "index": index,
                }
                index += 1
        finally:
            delta = stream.chip_seconds - booked
            if delta > 0:
                tracing.add_chip_seconds(delta)

    # ---- unary surface -------------------------------------------------------

    @schema_method
    async def generate(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        klass: str = "interactive",
        context=None,
    ):
        """Drain a full generation and return it in one response."""
        tokens = []
        async for item in self.generate_stream(
            prompt, max_new_tokens=max_new_tokens, klass=klass
        ):
            tokens.append(item["token"])
        return {"prompt": prompt, "tokens": tokens, "text": decode(tokens)}

    @schema_method
    async def describe_engine(self, context=None):
        """Engine placement + KV cache + decode-loop occupancy stats."""
        return {
            "engine": self.engine.describe() if self.engine else None,
            "loop": self.loop.stats if self.loop else None,
        }
