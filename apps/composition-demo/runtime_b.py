class RuntimeB:
    async def transform(self, value):
        return value + 100
