"""Multi-deployment composition: init params named after sibling file
stems receive DeploymentHandles (parity with ref apps/composition-demo/
entry_deployment.py + apps/builder.py:1474-1508 binding)."""

import asyncio

from bioengine_tpu.rpc import schema_method


class EntryDeployment:
    def __init__(self, runtime_a, runtime_b):
        self.runtime_a = runtime_a
        self.runtime_b = runtime_b

    @schema_method
    async def fan_out(self, value: int, context=None):
        """Send the value to both runtimes concurrently; gather results."""
        a, b = await asyncio.gather(
            self.runtime_a.call("transform", value),
            self.runtime_b.call("transform", value),
        )
        return {"a": a, "b": b, "sum": a + b}
