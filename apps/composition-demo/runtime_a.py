class RuntimeA:
    async def transform(self, value):
        return value * 2
