"""Model-runner TPU runtime — executes BioImage Model Zoo packages on XLA.

The reference's runtime (ref apps/model-runner/runtime_deployment.py) is
a 1-GPU Ray Serve replica that builds bioimageio.core torch prediction
pipelines, caches them via ``@serve.multiplexed`` keyed on an md5 of the
call kwargs (:160-232), and normalizes CUDA OOM to RuntimeError
(:234-312). This TPU-native runtime keeps the same responsibilities with
an XLA design:

- A pipeline wraps (RDF axes/processing) around the framework's
  ``InferenceEngine`` — bucketed padding, a compiled-program cache keyed
  on (model, shape, dtype), and overlap-tile stitching for large images.
- Weight paths, in preference order:
  * ``jax_params``  — TPU-native extension: an .npz pytree + a registry
    architecture name; runs jitted on the MXU in bf16/f32.
  * ``pytorch_state_dict`` — the RDF's architecture source is executed
    with torch (CPU/torch-xla) and the state dict loaded into it.
  * ``torchscript`` — host torch fallback behind the same interface.
- Test reports are cached next to the package keyed on weight mtimes
  (ref runtime_deployment.py:345-364 ``.test_cache.json``).
- XLA RESOURCE_EXHAUSTED is normalized to RuntimeError the way the
  reference normalizes CUDA OOM.
"""

import asyncio
import hashlib
import json
import time
from collections import OrderedDict
from pathlib import Path

import numpy as np

from bioengine_tpu.rpc import schema_method
from bioengine_tpu.runtime.engine import EngineConfig, InferenceEngine
from bioengine_tpu.utils import tracing
from bioengine_tpu.runtime.rdf import (
    apply_processing,
    from_nhwc,
    load_model_rdf,
    to_nhwc,
)


def _normalize_oom(e: Exception) -> Exception:
    """XLA OOM surfaces as XlaRuntimeError RESOURCE_EXHAUSTED; report it
    the way the reference reports CUDA OOM (a plain RuntimeError the RPC
    layer can serialize, ref runtime_deployment.py:297-312)."""
    msg = str(e)
    if "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg.lower():
        return RuntimeError(
            f"TPU out of memory while executing the model: {msg[:500]}. "
            f"Try a smaller input or enable tiled prediction "
            f"(default_blocksize_parameter)."
        )
    return e


class Pipeline:
    """One loaded model: RDF bookkeeping + an execution backend."""

    def __init__(
        self,
        package_path: Path,
        weights_format: str | None = None,
        default_blocksize_parameter: int | None = None,
        devices=None,
    ):
        # the replica's leased chip group (list of jax.Device): the XLA
        # engine builds its dp mesh over exactly these chips. None =
        # legacy single-device behavior.
        self.devices = list(devices) if devices else None
        self.package_path = Path(package_path)
        # cold-start accounting: how this pipeline's weights landed
        # (eager vs streamed, seconds, bytes) — Replica.describe reads
        # it through RuntimeDeployment.cold_start_info
        self.load_info: dict = {}
        self._weight_loader = None
        rdf_path = self.package_path / "rdf.yaml"
        self.rdf = load_model_rdf(rdf_path)
        self.weights_format, self.weights_entry = self._select_weights(
            weights_format
        )
        config = EngineConfig()
        if default_blocksize_parameter:
            config.tile = int(default_blocksize_parameter)
            config.max_tile = int(default_blocksize_parameter)
            # when the default overlap (64 px) meets or exceeds a small
            # blocksize, the engine clamps overlap to tile-1: stride-1
            # tiling, every pixel recomputed ~tile^2 times (observed:
            # 6699 tiles for a 150x140 image at blocksize 64). Only the
            # degenerate case is rescaled — larger blocksizes keep the
            # standard 64 px blend ramp unchanged.
            if config.tile_overlap >= config.tile:
                config.tile_overlap = max(config.tile // 8, 1)
        self.backend, self.engine = self._build_backend(config)

    # ---- weights selection --------------------------------------------------

    def _select_weights(self, requested: str | None):
        weights = self.rdf.weights
        if requested:
            if requested not in weights:
                raise ValueError(
                    f"weights format '{requested}' not in model "
                    f"(has: {sorted(weights)})"
                )
            return requested, weights[requested]
        for fmt in ("jax_params", "pytorch_state_dict", "torchscript"):
            if fmt in weights:
                return fmt, weights[fmt]
        return self.rdf.preferred_weights

    def _resolve(self, source: str) -> Path:
        p = self.package_path / source
        if not p.exists():
            raise FileNotFoundError(f"weight source '{source}' not in package")
        return p

    # ---- backend construction ----------------------------------------------

    def _build_backend(self, config: EngineConfig):
        entry = self.weights_entry
        if self.weights_format == "jax_params":
            import os as _os
            import time as _time

            from bioengine_tpu.models.registry import get_model

            from bioengine_tpu.runtime.convert import load_params_npz
            from bioengine_tpu.runtime.weight_stream import (
                StreamedWeightLoader,
                load_manifest,
                skeleton_from_manifest,
            )

            arch = entry.get("architecture") or {}
            model = get_model(arch.get("name", ""), **(arch.get("kwargs") or {}))
            source = self._resolve(entry["source"])
            # streamed path: a key→shape manifest next to the npz lets
            # the engine build (and compile/warm) against a zero-filled
            # skeleton immediately while the real bytes stream in
            # background threads; prediction gates on residency so the
            # output is bit-identical to an eager load. No manifest (or
            # BIOENGINE_WEIGHT_STREAMING=0) → the eager path, unchanged.
            manifest = (
                load_manifest(source)
                if _os.environ.get("BIOENGINE_WEIGHT_STREAMING", "1") != "0"
                else None
            )
            t_load = _time.perf_counter()
            if manifest is not None:
                params = skeleton_from_manifest(manifest)
            else:
                params = load_params_npz(str(source))
            engine = InferenceEngine(
                model_id=self._model_key(),
                apply_fn=lambda prm, x: model.apply({"params": prm}, x),
                params=params,
                divisor=getattr(model, "divisor", 1),
                z_divisor=getattr(model, "z_divisor", 1),
                config=config,
                devices=self.devices,
            )
            if manifest is not None:
                engine.begin_param_streaming()
                self._weight_loader = StreamedWeightLoader(
                    source,
                    manifest,
                    on_complete=engine.complete_param_streaming,
                    on_error=engine.fail_param_streaming,
                    model_id=self._model_key(),
                ).start()
                self.load_info = {
                    "streamed": True,
                    "manifest_keys": len(manifest),
                }
            else:
                self.load_info = {
                    "streamed": False,
                    "weights_seconds": round(
                        _time.perf_counter() - t_load, 4
                    ),
                }
            return "xla", engine

        from bioengine_tpu.runtime.torch_fallback import TorchFallbackRunner

        if self.weights_format == "torchscript":
            runner = TorchFallbackRunner(
                torchscript_path=str(self._resolve(entry["source"]))
            )
        elif self.weights_format == "pytorch_state_dict":
            runner = TorchFallbackRunner(module=self._torch_module_from_rdf())
        else:
            raise NotImplementedError(
                f"weights format '{self.weights_format}' is not supported "
                f"on the TPU runtime (supported: jax_params, "
                f"pytorch_state_dict, torchscript)"
            )
        return "torch", runner

    def _torch_module_from_rdf(self):
        """RDF 0.4/0.5 pytorch architecture: exec the model source file
        shipped in the package and instantiate the named callable."""
        import torch

        entry = self.weights_entry
        arch = entry.get("architecture")
        if isinstance(arch, str):
            # 0.4 style "file.py:Callable"
            src, _, callable_name = arch.partition(":")
            arch_kwargs = entry.get("kwargs", {}) or {}
        elif isinstance(arch, dict):
            callable_name = arch.get("callable", "")
            src = (arch.get("source") or "").partition(":")[0]
            arch_kwargs = arch.get("kwargs", {}) or {}
        else:
            raise ValueError("pytorch_state_dict weights without architecture")
        src_path = self._resolve(src)
        namespace: dict = {"__name__": f"bioengine_model_{src_path.stem}"}
        exec(compile(src_path.read_text(), str(src_path), "exec"), namespace)
        factory = namespace.get(callable_name)
        if factory is None:
            raise ValueError(
                f"architecture callable '{callable_name}' not found in {src}"
            )
        module = factory(**arch_kwargs)
        state = torch.load(
            self._resolve(self.weights_entry["source"]),
            map_location="cpu",
            weights_only=True,
        )
        if isinstance(state, dict) and "state_dict" in state:
            state = state["state_dict"]
        module.load_state_dict(state)
        return module

    def _model_key(self) -> str:
        return f"{self.rdf.rdf_id or self.rdf.name}@{self.package_path.name}"

    # ---- prediction ---------------------------------------------------------

    @property
    def input_spec(self):
        return self.rdf.inputs[0]

    @property
    def output_spec(self):
        return self.rdf.outputs[0]

    @staticmethod
    def extract_array(inputs) -> np.ndarray:
        """array | single-entry {input_name: array} -> f32 array (the
        single source of the single-input contract; shared with the
        deployment's batching path)."""
        if isinstance(inputs, dict):
            if len(inputs) != 1:
                raise ValueError(
                    "the TPU runtime currently executes single-input "
                    f"models; got {sorted(inputs)}"
                )
            inputs = next(iter(inputs.values()))
        return np.asarray(inputs, np.float32)

    def predict(self, inputs) -> dict[str, np.ndarray]:
        """inputs: array | {input_name: array} -> {output_name: array}.

        Arrays arrive in the RDF's declared axes, are canonicalized to
        NHWC for the engine, and returned in the declared output axes.
        """
        spec = self.input_spec
        x = to_nhwc(self.extract_array(inputs), spec.axes)
        x = apply_processing(x, spec.preprocessing)
        y = self.engine.predict(x)  # InferenceEngine and TorchFallbackRunner share .predict
        out_spec = self.output_spec
        y = apply_processing(y, out_spec.postprocessing)
        y = from_nhwc(y, out_spec.axes)
        return {out_spec.name: y}

    async def predict_async(self, inputs) -> dict[str, np.ndarray]:
        """Async front door into the engine's overlapped pipeline: the
        whole prediction (pre/post processing + tiled inference) runs
        on the engine's single dispatch thread, so concurrent callers
        never race for one device and the event loop never blocks —
        without spawning a thread per request via asyncio.to_thread.
        The torch fallback has no dispatch thread; it keeps to_thread."""
        if self.backend == "xla":
            # carry a sampled trace context onto the dispatch thread so
            # engine.predict's stage span lands in the request's tree
            fn = tracing.carry(tracing.current_trace(), self.predict)
            return await asyncio.wrap_future(self.engine.submit(fn, inputs))
        return await asyncio.to_thread(self.predict, inputs)

    def pipeline_stats(self) -> dict:
        """Per-stage pipeline accounting (runtime/pipeline.py
        PipelineStats) — surfaced by Replica.describe and the
        controller's get_app_status."""
        stats = getattr(self.engine, "pipeline_stats", None)
        return stats.as_dict() if stats is not None else {}

    def cold_start_info(self) -> dict:
        """This pipeline's cold-start breakdown: how the weights landed
        (eager vs streamed, seconds, bytes) and what its compiles cost
        (real XLA seconds vs persistent/tier cache hits)."""
        info = dict(self.load_info)
        if self._weight_loader is not None:
            st = self._weight_loader.stats()
            info["weights_seconds"] = st["seconds"]
            info["bytes_loaded"] = st["bytes_loaded"]
            info["stream_done"] = st["done"]
            if st["error"]:
                info["stream_error"] = st["error"]
        describe = getattr(self.engine, "describe", None)
        if callable(describe):
            progs = describe().get("programs", {})
            info["compile_seconds"] = progs.get("real_compile_seconds")
            info["persistent_cache_hits"] = progs.get("persistent_hits")
            info["real_compiles"] = progs.get("real_compiles")
        return info

    def close(self) -> None:
        close = getattr(self.engine, "close", None)
        if callable(close):
            close()

    # ---- self test ----------------------------------------------------------

    def run_test(self) -> dict:
        """Run the packaged test tensors through the pipeline and compare
        against the expected outputs (the reference delegates this to
        bioimageio.core test_model, ref runtime_deployment.py:86-156)."""
        t0 = time.monotonic()
        test_in = self._load_test_arrays("inputs", "test_inputs")
        if test_in is None:
            spec = self.input_spec
            # z kept thin: synthesized 3D self-tests shouldn't pay a
            # 64^3 volume when 16 planes exercise the same code path
            shape = [
                1 if a in "bc" else (16 if a == "z" else 64)
                for a in spec.axes.lower()
            ]
            test_in = np.random.default_rng(0).normal(size=shape).astype(
                np.float32
            )
            synthesized = True
        else:
            synthesized = False
        result = self.predict(test_in)
        output = next(iter(result.values()))
        report = {
            "status": "passed",
            "backend": self.backend,
            "weights_format": self.weights_format,
            "synthesized_input": synthesized,
            "input_shape": list(np.asarray(test_in).shape),
            "output_shape": list(output.shape),
            "duration_seconds": round(time.monotonic() - t0, 3),
        }
        expected = self._load_test_arrays("outputs", "test_outputs")
        if expected is not None and not synthesized:
            # bf16 MXU compute vs the zoo's f32 torch reference outputs:
            # ~3 decimal digits is the honest comparison tolerance
            close = np.allclose(output, expected, rtol=1e-2, atol=1e-2)
            report["output_matches_expected"] = bool(close)
            if not close:
                report["status"] = "failed"
                report["max_abs_error"] = float(
                    np.max(np.abs(output - expected))
                )
        return report

    def _load_test_arrays(self, field_05: str, field_04: str):
        """Test tensors: 0.5 inputs[i].test_tensor.source / 0.4 test_inputs."""
        raw = self.rdf.raw
        entries = raw.get(field_05) or []
        if entries and isinstance(entries[0], dict):
            tt = entries[0].get("test_tensor")
            if isinstance(tt, dict) and tt.get("source"):
                p = self.package_path / tt["source"]
                if p.exists():
                    return np.load(p)
        sources = raw.get(field_04) or []
        if sources:
            p = self.package_path / sources[0]
            if p.exists():
                return np.load(p)
        return None


class RuntimeDeployment:
    """TPU inference replica: pipeline LRU + test-report cache +
    continuous batching (concurrent predicts against the same model and
    shape bucket run as ONE batched forward — serving/batching.py; the
    reference forwards each request individually,
    ref runtime_deployment.py:234-312)."""

    def __init__(
        self,
        max_pipelines: int = 4,
        batch_max: int = 8,
        batch_wait_ms: float = 5.0,
    ):
        self.max_pipelines = max_pipelines
        self.batch_max = batch_max
        self.batch_wait_ms = batch_wait_ms
        self._devices = None  # set from the replica lease in async_init
        self._pipelines: OrderedDict[str, Pipeline] = OrderedDict()
        self._lock = asyncio.Lock()
        self._batcher = None

    async def async_init(self):
        import jax

        self.backend = jax.default_backend()
        self.device_count = jax.local_device_count()
        # the replica lifecycle injects the leased chip group before
        # async_init (serving/replica.py); resolve it onto jax devices
        # once so every pipeline this replica builds shares the mesh
        lease = getattr(self, "bioengine_device_ids", None)
        if lease:
            from bioengine_tpu.runtime.engine import resolve_devices

            self._devices = resolve_devices(list(lease))
        else:
            self._devices = None
        # operator-tuned batching knobs from the deployment spec /
        # manifest (deployment_config.<dep>.batching), injected by the
        # replica lifecycle before async_init — they override the
        # constructor defaults so batching is tunable without code
        # changes
        batch_cfg = getattr(self, "bioengine_batch_config", None) or {}
        if batch_cfg.get("max_batch") is not None:
            self.batch_max = int(batch_cfg["max_batch"])
        if batch_cfg.get("max_wait_ms") is not None:
            self.batch_wait_ms = float(batch_cfg["max_wait_ms"])
        if self.batch_max > 1:
            from bioengine_tpu.serving import ContinuousBatcher

            self._batcher = ContinuousBatcher(
                self._run_batch,
                max_batch=self.batch_max,
                max_wait_ms=self.batch_wait_ms,
            )

    async def _run_batch(self, signature, payloads):
        """One flushed group: same pipeline + same per-item shape, so
        the arrays concatenate along the batch axis into a single
        engine call, then split back per request."""
        pipeline = payloads[0][0]
        arrays = [a for _, a in payloads]
        sizes = [len(a) for a in arrays]
        with tracing.trace_span("batch.assemble", requests=len(arrays)):
            merged = np.concatenate(arrays, axis=0)
        result = await pipeline.predict_async(merged)
        out_name, y = next(iter(result.items()))
        outs = []
        start = 0
        for n in sizes:
            outs.append({out_name: y[start : start + n]})
            start += n
        return outs

    async def check_health(self):
        if not self._pipelines:
            return  # nothing loaded is a healthy state
        # a wedged XLA client would hang here and fail the health check

    @staticmethod
    def _status_key(key: str, p: "Pipeline") -> str:
        """Status-entry key: model key PLUS the cache-key prefix — the
        same model loaded with different weights_format/blocksize is a
        different pipeline and must not collapse into one entry. Shared
        by pipeline_stats and mesh_info so the controller can join the
        two views on the same key."""
        return f"{p._model_key()}#{key[:8]}"

    def pipeline_stats(self) -> dict:
        """Per-pipeline overlapped-pipeline accounting — picked up by
        Replica.describe (and from there the controller's
        get_app_status)."""
        return {
            self._status_key(key, p): p.pipeline_stats()
            for key, p in self._pipelines.items()
            if p.backend == "xla"
        }

    def cold_start_info(self) -> dict:
        """Per-pipeline cold-start breakdown (weights load path +
        compile cost), keyed like pipeline_stats/mesh_info so the
        controller can join all three views — picked up by
        Replica.describe as the ``cold_start.pipelines`` section."""
        return {
            self._status_key(key, p): p.cold_start_info()
            for key, p in self._pipelines.items()
            if p.backend == "xla"
        }

    def mesh_info(self) -> dict:
        """How this replica's leased chip group is used — mesh shape,
        chip ids, and per-chip utilization per loaded engine. Surfaced
        by Replica.describe so the controller can see sharding health
        (a K-chip lease running a 1-chip mesh is a provisioning bug)."""
        info: dict = {
            "lease": list(getattr(self, "bioengine_device_ids", []) or []),
            "engines": {},
        }
        for key, p in self._pipelines.items():
            describe = getattr(p.engine, "describe", None)
            if callable(describe):
                info["engines"][self._status_key(key, p)] = describe()
        # mesh_shape comes from the engines (the one source of mesh
        # truth — a tp axis threaded through later is reported without
        # touching this code); until the first pipeline loads, fall back
        # to the shape the lease implies. None = legacy single-device
        # path, matching engine.describe()["mesh"].
        shapes = [e.get("mesh") for e in info["engines"].values()]
        if shapes:
            info["mesh_shape"] = shapes[0]
        elif self._devices and len(self._devices) > 1:
            info["mesh_shape"] = {"dp": len(self._devices)}
        else:
            info["mesh_shape"] = None
        return info

    async def close(self) -> None:
        """Replica.stop's hook: flush the batcher and release every
        cached pipeline's engine dispatch thread (LRU eviction only
        covers pipelines pushed out while running)."""
        if self._batcher is not None:
            await self._batcher.close()
        async with self._lock:
            pipelines = list(self._pipelines.values())
            self._pipelines.clear()
        for p in pipelines:
            p.close()

    # ---- pipeline cache (the reference's multiplexed cache,
    # ref runtime_deployment.py:160-232) ---------------------------------

    @staticmethod
    def _cache_key(rdf_path: str, **kwargs) -> str:
        blob = json.dumps({"rdf_path": rdf_path, **kwargs}, sort_keys=True)
        return hashlib.md5(blob.encode()).hexdigest()

    def _mesh_tag(self) -> str:
        """Mesh-shape component of the pipeline cache key: the same
        model loaded on a different chip group compiles different
        (sharded) programs and must be a different pipeline entry. A
        1-chip lease IS the legacy single-device path (engine semantics),
        so it shares the '1dev' tag with the no-lease case. One
        definition of mesh identity: engine.mesh_cache_tag, the same
        function the compiled-program cache key uses."""
        from bioengine_tpu.runtime.engine import mesh_cache_tag

        return mesh_cache_tag(len(self._devices) if self._devices else 1)

    async def _get_pipeline(
        self,
        rdf_path: str,
        weights_format: str | None,
        default_blocksize_parameter: int | None,
    ) -> Pipeline:
        key = self._cache_key(
            rdf_path,
            weights_format=weights_format,
            blocksize=default_blocksize_parameter,
            mesh=self._mesh_tag(),
        )
        async with self._lock:
            if key in self._pipelines:
                self._pipelines.move_to_end(key)
                return self._pipelines[key]
        # build outside the lock (compile can take tens of seconds)
        pipeline = await asyncio.to_thread(
            Pipeline,
            Path(rdf_path).parent if rdf_path.endswith(".yaml") else rdf_path,
            weights_format,
            default_blocksize_parameter,
            self._devices,
        )
        async with self._lock:
            existing = self._pipelines.get(key)
            if existing is not None:
                # lost a concurrent-build race: keep the first-stored
                # pipeline (its engine already owns the dispatch thread
                # and warm programs) and drop our duplicate
                self._pipelines.move_to_end(key)
                pipeline.close()
                return existing
            self._pipelines[key] = pipeline
            while len(self._pipelines) > self.max_pipelines:
                _, evicted = self._pipelines.popitem(last=False)
                evicted.close()  # release the engine's dispatch thread
        return pipeline

    # ---- handle API (called by the entry deployment) --------------------

    @schema_method
    async def predict(
        self,
        rdf_path: str,
        inputs,
        weights_format: str | None = None,
        default_blocksize_parameter: int | None = None,
        sample_id: str = "sample",
        context=None,
    ):
        """Run one inference; returns {output_name: np.ndarray}.

        Concurrent calls against the same model whose declared axes are
        batch-first and whose per-item shapes match ride one batched
        engine call (continuous batching); anything else takes the
        direct path unchanged."""
        t0 = time.monotonic()
        try:
            pipeline = await self._get_pipeline(
                rdf_path, weights_format, default_blocksize_parameter
            )
            array = pipeline.extract_array(inputs)
            if self._batchable(pipeline, array):
                # the full pipeline-cache key, NOT just the model key —
                # same model with different weights_format/blocksize is
                # a different pipeline and must never co-batch
                signature = (
                    self._cache_key(
                        rdf_path,
                        weights_format=weights_format,
                        blocksize=default_blocksize_parameter,
                        mesh=self._mesh_tag(),
                    ),
                    tuple(array.shape[1:]),
                )
                result = await self._batcher.submit(
                    signature, (pipeline, array)
                )
            else:
                result = await pipeline.predict_async(array)
        except Exception as e:
            raise _normalize_oom(e) from e
        ms = (time.monotonic() - t0) * 1000
        return {
            **result,
            "_meta": {
                "sample_id": sample_id,
                "backend": pipeline.backend,
                "weights_format": pipeline.weights_format,
                "duration_ms": round(ms, 1),
            },
        }

    # processing ops that treat each sample independently (or use fixed
    # constants), so co-batched requests can't contaminate each other's
    # statistics — batch-global zero_mean/scale_range must NOT co-batch
    # (their mean/percentiles would mix requests)
    _PER_SAMPLE_SAFE_OPS = frozenset(
        {"scale_linear", "sigmoid", "binarize", "clip"}
    )

    @classmethod
    def _processing_per_sample_safe(cls, ops) -> bool:
        for op in ops or []:
            name = op.get("name", op.get("id"))
            kw = op.get("kwargs", {}) or {}
            if name in cls._PER_SAMPLE_SAFE_OPS:
                continue
            if (
                name in ("zero_mean_unit_variance",
                         "fixed_zero_mean_unit_variance")
                and (kw.get("mean") is not None
                     or kw.get("mode") == "per_sample")
            ):
                continue  # fixed constants or per-sample stats
            return False
        return True

    def _batchable(self, pipeline: Pipeline, array: np.ndarray) -> bool:
        return (
            self._batcher is not None
            and pipeline.input_spec.axes.startswith("b")
            and pipeline.output_spec.axes.startswith("b")
            and array.ndim == len(pipeline.input_spec.axes)
            and self._processing_per_sample_safe(
                pipeline.input_spec.preprocessing
            )
            and self._processing_per_sample_safe(
                pipeline.output_spec.postprocessing
            )
        )

    @schema_method
    async def test(
        self,
        rdf_path: str,
        weights_format: str | None = None,
        skip_cache: bool = False,
        context=None,
    ):
        """Test a model package; report cached keyed on weight mtimes
        (ref runtime_deployment.py:345-364)."""
        package = (
            Path(rdf_path).parent
            if rdf_path.endswith(".yaml")
            else Path(rdf_path)
        )
        cache_file = package / ".test_cache.json"
        stamp = self._weights_stamp(package)
        if not skip_cache and cache_file.exists():
            try:
                cached = json.loads(cache_file.read_text())
                if cached.get("stamp") == stamp:
                    return cached["report"]
            except (json.JSONDecodeError, KeyError):
                pass
        try:
            pipeline = await self._get_pipeline(str(package), weights_format, None)
            report = await asyncio.to_thread(pipeline.run_test)
        except Exception as e:
            report = {"status": "failed", "error": str(_normalize_oom(e))}
        try:
            cache_file.write_text(
                json.dumps({"stamp": stamp, "report": report})
            )
        except OSError:
            pass  # read-only package dirs still get a fresh report
        return report

    @staticmethod
    def _weights_stamp(package: Path) -> str:
        parts = []
        for p in sorted(package.glob("*")):
            if p.suffix in (".npz", ".pt", ".pth", ".onnx") or "weight" in p.name:
                parts.append(f"{p.name}:{p.stat().st_mtime_ns}")
        return ";".join(parts)

    @schema_method
    async def get_status(self, context=None):
        """Loaded pipelines + backend info."""
        import jax

        return {
            "backend": jax.default_backend(),
            "device_count": jax.local_device_count(),
            "loaded_pipelines": [
                {
                    "model": p._model_key(),
                    "backend": p.backend,
                    "weights_format": p.weights_format,
                }
                for p in self._pipelines.values()
            ],
        }
