"""Model-runner entry — model discovery, caching, and inference dispatch.

Parity with the reference entry deployment (ref apps/model-runner/
entry_deployment.py): ``search_models`` filtered by the collection's
"passed inference check" results (:1306-1366), RDF/documentation fetch
(:1369-1466), format validation (:1469-1507), ``test`` delegation with
report caching, upload/download of image arrays (:1822-1867), and
``infer`` resolving string inputs before delegating to the runtime
replica (:1869-1990).

ModelCache reproduces the reference's cross-replica atomic download
protocol (:73-1009): an exclusive-create ``.downloading`` marker with a
stale-age threshold, download into a temp dir + atomic rename,
``.last_access`` touch files driving LRU eviction under a byte budget,
and in-use refcounts that block eviction during inference.

Model sources: a local collection directory (``BIOENGINE_LOCAL_MODEL_PATH``
— the hermetic analog of the reference's local artifact override) or the
bioimage.io artifact HTTP endpoints.
"""

import asyncio
import io
import json
import os
import shutil
import time
import uuid
from pathlib import Path

import numpy as np
import yaml

from bioengine_tpu.rpc import schema_method

STALE_DOWNLOAD_SECONDS = 600
SUPPORTED_FILE_TYPES = (".npy", ".png", ".tiff", ".tif", ".jpeg", ".jpg")


# ---- model sources ----------------------------------------------------------


class LocalCollectionSource:
    """Models laid out as ``root/<model_id>/rdf.yaml`` + files; an
    optional ``root/collection.yaml`` carries ``bioengine_inference``
    check results (the reference reads these from the collection
    manifest, ref entry_deployment.py:1337-1346)."""

    def __init__(self, root: str | Path):
        self.root = Path(root)

    async def list_models(self) -> list[dict]:
        models = []
        for d in sorted(self.root.iterdir()):
            if (d / "rdf.yaml").exists():
                rdf = yaml.safe_load((d / "rdf.yaml").read_text()) or {}
                models.append(
                    {
                        "model_id": d.name,
                        "description": rdf.get("description", ""),
                        "tags": rdf.get("tags", []),
                        "name": rdf.get("name", d.name),
                    }
                )
        return models

    async def inference_checks(self) -> dict:
        cpath = self.root / "collection.yaml"
        if cpath.exists():
            data = yaml.safe_load(cpath.read_text()) or {}
            return data.get("bioengine_inference", {})
        return {}

    async def fetch_file_list(self, model_id: str, stage: bool) -> list[dict]:
        d = self.root / model_id
        if not (d / "rdf.yaml").exists():
            raise FileNotFoundError(f"model '{model_id}' not in collection")
        return [
            {"name": str(p.relative_to(d)), "size": p.stat().st_size}
            for p in sorted(d.rglob("*"))
            if p.is_file() and not p.name.startswith(".")
        ]

    async def fetch_file(self, model_id: str, name: str, stage: bool) -> bytes:
        return await asyncio.to_thread(
            (self.root / model_id / name).read_bytes
        )

    async def is_published(self, model_id: str) -> bool:
        checks = await self.inference_checks()
        if model_id in checks:
            return checks[model_id].get("status") == "passed"
        return (self.root / model_id / "rdf.yaml").exists()


class HttpCollectionSource:
    """bioimage.io artifact endpoints (ref entry_deployment.py:163-214,
    564-595): list via the collection children API, files via
    ``{server}/bioimage-io/artifacts/{id}/files/{path}``."""

    CHECKS_TTL_SECONDS = 60

    def __init__(self, server_url: str = "https://hypha.aicell.io"):
        self.server_url = server_url.rstrip("/")
        import httpx

        self._client = httpx.AsyncClient(timeout=60, follow_redirects=True)
        self._checks_cache: tuple[float, dict] | None = None

    async def _get(self, url: str, **kw):
        last = None
        for attempt in range(4):
            try:
                r = await self._client.get(url, **kw)
                if r.status_code < 400 or (
                    400 <= r.status_code < 500 and r.status_code != 429
                ):
                    return r
                last = RuntimeError(f"HTTP {r.status_code} for {url}")
            except Exception as e:
                last = e
            await asyncio.sleep(0.2 * 2**attempt)
        raise last

    async def list_models(self) -> list[dict]:
        url = f"{self.server_url}/public/services/artifact-manager/list"
        r = await self._get(
            url,
            params={
                "parent_id": "bioimage-io/bioimage.io",
                "filters": json.dumps({"type": "model"}),
                "limit": 1000,
            },
        )
        r.raise_for_status()
        return [
            {
                "model_id": a["alias"],
                "description": a.get("manifest", {}).get("description", ""),
                "tags": a.get("manifest", {}).get("tags", []),
                "name": a.get("manifest", {}).get("name", a["alias"]),
            }
            for a in r.json()
        ]

    async def inference_checks(self) -> dict:
        # TTL-cached: is_published runs on every infer() and must not
        # add a collection round-trip to the inference hot path
        if (
            self._checks_cache
            and time.time() - self._checks_cache[0] < self.CHECKS_TTL_SECONDS
        ):
            return self._checks_cache[1]
        url = f"{self.server_url}/public/services/artifact-manager/read"
        r = await self._get(url, params={"artifact_id": "bioimage-io/bioimage.io"})
        r.raise_for_status()
        checks = r.json().get("manifest", {}).get("bioengine_inference", {})
        self._checks_cache = (time.time(), checks)
        return checks

    async def fetch_file_list(self, model_id: str, stage: bool) -> list[dict]:
        url = (
            f"{self.server_url}/bioimage-io/artifacts/{model_id}/files/"
        )
        r = await self._get(url, params={"stage": str(stage).lower()})
        r.raise_for_status()
        return [
            {"name": f["name"], "size": f.get("size", 0)}
            for f in r.json()
            if f.get("type") != "directory"
        ]

    async def fetch_file(self, model_id: str, name: str, stage: bool) -> bytes:
        url = f"{self.server_url}/bioimage-io/artifacts/{model_id}/files/{name}"
        r = await self._get(url, params={"stage": str(stage).lower()})
        r.raise_for_status()
        return r.content

    async def is_published(self, model_id: str) -> bool:
        checks = await self.inference_checks()
        return checks.get(model_id, {}).get("status") == "passed"


# ---- model cache ------------------------------------------------------------


class ModelPackage:
    """In-use guard: holding it blocks LRU eviction during inference
    (ref entry_deployment.py:32-69 ``BioimageioPackage``). The refcount
    is mirrored to an on-disk ``.inuse-*`` marker so eviction is safe
    across replicas sharing one cache dir, not just in-process."""

    def __init__(self, cache: "ModelCache", model_id: str, path: Path):
        self.cache = cache
        self.model_id = model_id
        self.path = path
        self._marker = (
            cache.cache_dir / f".inuse-{model_id}-{os.getpid()}-{id(self):x}"
        )

    async def __aenter__(self):
        self.cache._in_use[self.model_id] = (
            self.cache._in_use.get(self.model_id, 0) + 1
        )
        self._marker.write_text(self.model_id)
        return self

    async def __aexit__(self, *exc):
        self.cache._in_use[self.model_id] -= 1
        if self.cache._in_use[self.model_id] <= 0:
            del self.cache._in_use[self.model_id]
        self._marker.unlink(missing_ok=True)


class ModelCache:
    def __init__(
        self,
        cache_dir: str | Path,
        source,
        max_size_bytes: int = 20 * 1024**3,
    ):
        self.cache_dir = Path(cache_dir).expanduser()
        self.cache_dir.mkdir(parents=True, exist_ok=True)
        self.source = source
        self.max_size_bytes = max_size_bytes
        self._in_use: dict[str, int] = {}

    def _package_dir(self, model_id: str, stage: bool) -> Path:
        return self.cache_dir / (f"{model_id}-staged" if stage else model_id)

    def _marker(self, model_id: str, stage: bool) -> Path:
        return self.cache_dir / f".downloading-{model_id}{'-staged' if stage else ''}"

    @staticmethod
    def _touch_access(package: Path) -> None:
        (package / ".last_access").write_text(str(time.time()))

    async def get_model_package(
        self,
        model_id: str,
        stage: bool = False,
        allow_unpublished: bool = False,
        skip_cache: bool = False,
    ) -> ModelPackage:
        if "/" in model_id or model_id.startswith("http"):
            raise ValueError(
                f"'{model_id}' is not a model id (URLs are not accepted)"
            )
        if not allow_unpublished and not await self.source.is_published(
            model_id
        ):
            raise ValueError(
                f"model '{model_id}' has not passed the bioengine inference "
                f"check; pass allow_unpublished=True to force"
            )
        package = self._package_dir(model_id, stage)
        if skip_cache and package.exists():
            if self._in_use.get(model_id):
                raise RuntimeError(
                    f"cannot re-download '{model_id}' while it is in use"
                )
            # rename first (sync, atomic) so no coroutine interleaving
            # with the threaded delete can see a half-deleted package
            # and adopt it; dot-prefix keeps it out of package listings
            doomed = package.with_name(f".purge-{package.name}-{os.getpid()}")
            package.rename(doomed)
            await asyncio.to_thread(shutil.rmtree, doomed)
        if not package.exists():
            await self._download(model_id, stage, package)
        self._touch_access(package)
        return ModelPackage(self, model_id, package)

    async def _download(self, model_id: str, stage: bool, package: Path):
        """Cross-replica safe: first claimant creates the marker with
        O_EXCL and downloads into a temp dir renamed atomically into
        place; others poll for completion (ref :259-347, 597-705)."""
        marker = self._marker(model_id, stage)
        while True:
            try:
                fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                break  # we own the download
            except FileExistsError:
                try:
                    age = time.time() - marker.stat().st_mtime
                except FileNotFoundError:
                    continue  # owner just finished; re-contend
                if age > STALE_DOWNLOAD_SECONDS:
                    marker.unlink(missing_ok=True)
                    continue
                await asyncio.sleep(0.25)
                if package.exists():
                    return  # a sibling finished it
        if package.exists():
            # a sibling completed between our exists() check and the
            # marker claim — nothing to do
            marker.unlink(missing_ok=True)
            return
        try:
            files = await self.source.fetch_file_list(model_id, stage)
            total = sum(f.get("size", 0) for f in files)
            await self._ensure_space(total)
            tmp = self.cache_dir / f".tmp-{model_id}-{os.getpid()}"
            if tmp.exists():
                await asyncio.to_thread(shutil.rmtree, tmp)
            tmp.mkdir(parents=True)
            for f in files:
                data = await self.source.fetch_file(model_id, f["name"], stage)
                dest = tmp / f["name"]
                dest.parent.mkdir(parents=True, exist_ok=True)
                await asyncio.to_thread(dest.write_bytes, data)
            tmp.rename(package)
        except BaseException:
            # cleanup must stay synchronous: awaiting inside a handler
            # that may hold a CancelledError would get re-cancelled and
            # leak the temp dir
            # bioengine: ignore[BE-ASYNC-001]
            shutil.rmtree(
                self.cache_dir / f".tmp-{model_id}-{os.getpid()}",
                ignore_errors=True,
            )
            raise
        finally:
            marker.unlink(missing_ok=True)

    async def _ensure_space(self, incoming_bytes: int):
        """Evict least-recently-accessed packages not in use until the
        incoming model fits the budget (ref :475-562)."""
        packages = [
            p
            for p in self.cache_dir.iterdir()
            if p.is_dir() and not p.name.startswith(".")
        ]

        def size(p: Path) -> int:
            return sum(f.stat().st_size for f in p.rglob("*") if f.is_file())

        def last_access(p: Path) -> float:
            f = p / ".last_access"
            try:
                return float(f.read_text())
            except (OSError, ValueError):
                return 0.0

        used = {p: size(p) for p in packages}
        budget = self.max_size_bytes - incoming_bytes
        current = sum(used.values())
        # cross-replica in-use markers (fresh ones only — a crashed
        # replica's markers go stale and stop blocking eviction)
        disk_in_use = set()
        for m in self.cache_dir.glob(".inuse-*"):
            try:
                if time.time() - m.stat().st_mtime < STALE_DOWNLOAD_SECONDS:
                    disk_in_use.add(m.read_text().strip())
            except OSError:
                continue

        for p in sorted(packages, key=last_access):
            if current <= budget:
                break
            model_id = p.name.removesuffix("-staged")
            if self._in_use.get(model_id) or model_id in disk_in_use:
                continue
            # sync rename, threaded delete: the in-use / exists checks
            # above stay atomic w.r.t. the event loop (no adoption of a
            # half-deleted package during the await)
            doomed = p.with_name(f".evict-{p.name}-{os.getpid()}")
            p.rename(doomed)
            await asyncio.to_thread(shutil.rmtree, doomed)
            current -= used[p]
        # best-effort budget: if every remaining package is in use the
        # cache overflows temporarily rather than failing the download
        # (the next _ensure_space pass reclaims once refcounts drop)

    async def cached_models(self) -> list[dict]:
        out = []
        for p in sorted(self.cache_dir.iterdir()):
            if p.is_dir() and not p.name.startswith("."):
                la = p / ".last_access"
                out.append(
                    {
                        "model_id": p.name,
                        "size_bytes": sum(
                            f.stat().st_size for f in p.rglob("*") if f.is_file()
                        ),
                        "last_access": float(la.read_text()) if la.exists() else 0.0,
                        "in_use": bool(
                            self._in_use.get(p.name.removesuffix("-staged"))
                        ),
                    }
                )
        return out


# ---- entry deployment -------------------------------------------------------


class EntryDeployment:
    def __init__(
        self,
        runtime_deployment,
        collection_url: str = "https://hypha.aicell.io",
        cache_dir: str = "~/.bioengine/model-cache",
        max_cache_size_gb: float = 20.0,
    ):
        self.runtime_deployment = runtime_deployment
        local_root = os.environ.get("BIOENGINE_LOCAL_MODEL_PATH")
        if local_root:
            source = LocalCollectionSource(local_root)
        else:
            source = HttpCollectionSource(collection_url)
        self.model_cache = ModelCache(
            cache_dir=cache_dir,
            source=source,
            max_size_bytes=int(max_cache_size_gb * 1024**3),
        )
        # dot-prefixed so the cache's LRU eviction never touches uploads
        self._uploads_dir = Path(cache_dir).expanduser() / ".uploads"
        self._uploads_dir.mkdir(parents=True, exist_ok=True)

    async def async_init(self):
        await self._check_runtime_available()

    async def test_deployment(self):
        models = await self.model_cache.source.list_models()
        assert isinstance(models, list)

    async def check_health(self):
        await self._check_runtime_available()

    async def _check_runtime_available(self):
        status = await asyncio.wait_for(
            self.runtime_deployment.call("get_status"), timeout=10
        )
        if not status.get("device_count"):
            raise RuntimeError("runtime replica reports no XLA devices")

    # ---- discovery ----------------------------------------------------------

    @schema_method
    async def search_models(
        self,
        keywords: list | None = None,
        limit: int = 10,
        ignore_checks: bool = False,
        context=None,
    ):
        """Search the model collection; by default only models that
        passed the bioengine inference check are returned."""
        models = await self.model_cache.source.list_models()
        if not ignore_checks:
            checks = await self.model_cache.source.inference_checks()
            if checks:
                passed = {
                    mid for mid, r in checks.items() if r.get("status") == "passed"
                }
                models = [m for m in models if m["model_id"] in passed]
        if keywords:
            kws = [k.lower() for k in keywords]
            models = [
                m
                for m in models
                if any(
                    k in m["model_id"].lower()
                    or k in m["description"].lower()
                    or k in m["name"].lower()
                    or any(k in str(t).lower() for t in m.get("tags", []))
                    for k in kws
                )
            ]
        return [
            {"model_id": m["model_id"], "description": m["description"]}
            for m in models[: limit or 10]
        ]

    @schema_method
    async def get_model_rdf(
        self, model_id: str, stage: bool = False, context=None
    ):
        """Fetch and parse a model's rdf.yaml."""
        data = await self.model_cache.source.fetch_file(
            model_id, "rdf.yaml", stage
        )
        return yaml.safe_load(data)

    @schema_method
    async def get_model_documentation(
        self, model_id: str, stage: bool = False, context=None
    ):
        """Fetch the file referenced by the RDF's 'documentation' field,
        or None when absent."""
        rdf = await self.get_model_rdf(model_id=model_id, stage=stage)
        doc_path = rdf.get("documentation")
        if not doc_path:
            return None
        try:
            data = await self.model_cache.source.fetch_file(
                model_id, doc_path, stage
            )
        except Exception:
            # missing doc file (404 / FileNotFoundError / transport
            # error) -> None per contract, never a failed RPC
            return None
        return data.decode(errors="replace")

    @schema_method
    async def validate(self, rdf_dict: dict, context=None):
        """Format-validate a model RDF (no IO checks) — the subset of
        bioimageio.spec validate_format the TPU runtime relies on."""
        problems = []
        for field in ("name", "inputs", "outputs", "weights"):
            if not rdf_dict.get(field):
                problems.append(f"missing required field '{field}'")
        if rdf_dict.get("type") not in (None, "model"):
            problems.append(f"type must be 'model', got '{rdf_dict.get('type')}'")
        for section in ("inputs", "outputs"):
            for i, entry in enumerate(rdf_dict.get(section) or []):
                if not isinstance(entry, dict) or "axes" not in entry:
                    problems.append(f"{section}[{i}] missing 'axes'")
        weights = rdf_dict.get("weights") or {}
        if isinstance(weights, dict):
            for fmt, entry in weights.items():
                if not isinstance(entry, dict) or not entry.get("source"):
                    problems.append(f"weights['{fmt}'] missing 'source'")
        else:
            problems.append("'weights' must be a mapping")
        return {
            "success": not problems,
            "details": "; ".join(problems) if problems else "valid-format",
        }

    # ---- test + infer -------------------------------------------------------

    @schema_method
    async def test(
        self,
        model_id: str,
        stage: bool = False,
        skip_cache: bool = False,
        context=None,
    ):
        """Download (or reuse) the model package and run the runtime's
        self-test on it; reports are cached keyed on weight mtimes."""
        package = await self.model_cache.get_model_package(
            model_id, stage=stage, allow_unpublished=True, skip_cache=skip_cache
        )
        async with package:
            return await self.runtime_deployment.call(
                "test", rdf_path=str(package.path), skip_cache=skip_cache
            )

    @schema_method
    async def infer(
        self,
        model_id: str,
        inputs,
        weights_format: str | None = None,
        default_blocksize_parameter: int | None = None,
        sample_id: str = "sample",
        skip_cache: bool = False,
        return_download_url: bool = False,
        context=None,
    ):
        """Run inference on a published model. ``inputs``: array, dict of
        arrays, an http(s) URL, or a file path from ``get_upload_url``."""
        if isinstance(inputs, str):
            inputs = await self._load_image_from_source(inputs)
        elif isinstance(inputs, dict):
            inputs = {
                k: (
                    await self._load_image_from_source(v)
                    if isinstance(v, str)
                    else v
                )
                for k, v in inputs.items()
            }
        package = await self.model_cache.get_model_package(
            model_id, allow_unpublished=False, skip_cache=skip_cache
        )
        async with package:
            result = await self.runtime_deployment.call(
                "predict",
                rdf_path=str(package.path),
                inputs=inputs,
                weights_format=weights_format,
                default_blocksize_parameter=default_blocksize_parameter,
                sample_id=sample_id,
            )
        if return_download_url:
            # np.save of full-size masks/flows is bulk disk I/O —
            # serialize each array off the event loop
            result = {
                k: (
                    await asyncio.to_thread(self._save_temp_array, v)
                    if isinstance(v, np.ndarray)
                    else v
                )
                for k, v in result.items()
            }
        return result

    # ---- image upload/download ----------------------------------------------

    @schema_method
    async def get_upload_url(self, file_type: str, context=None):
        """Reserve a temporary upload slot; returns an upload path usable
        with the datasets save API and a ``file_path`` to pass to
        ``infer`` (the reference returns S3 presigned URLs,
        ref entry_deployment.py:1822-1867; here uploads go through the
        worker's datasets plane or direct RPC bytes)."""
        if file_type not in SUPPORTED_FILE_TYPES:
            raise ValueError(
                f"file_type must be one of {SUPPORTED_FILE_TYPES}"
            )
        file_path = f"temp/{uuid.uuid4()}{file_type}"
        dest = self._uploads_dir / file_path
        dest.parent.mkdir(parents=True, exist_ok=True)
        return {"upload_path": str(dest), "file_path": file_path}

    @schema_method
    async def upload_image(self, file_path: str, data: bytes, context=None):
        """Direct-RPC companion to get_upload_url: store the encoded
        image bytes under the reserved file_path."""
        dest = (self._uploads_dir / file_path).resolve()
        if not dest.is_relative_to(self._uploads_dir.resolve()):
            raise ValueError("file_path escapes the upload area")
        dest.parent.mkdir(parents=True, exist_ok=True)
        await asyncio.to_thread(dest.write_bytes, bytes(data))
        return {"file_path": file_path, "size": len(data)}

    async def _load_image_from_source(self, source: str) -> np.ndarray:
        """URL / uploaded-file-path -> numpy array
        (ref entry_deployment.py:1196-1263)."""
        if source.startswith(("http://", "https://")):
            import httpx

            async with httpx.AsyncClient(
                timeout=60, follow_redirects=True
            ) as client:
                r = await client.get(source)
                r.raise_for_status()
                raw, name = r.content, source
        else:
            path = (self._uploads_dir / source).resolve()
            if not path.is_relative_to(self._uploads_dir.resolve()):
                raise ValueError("file path escapes the upload area")
            if not path.exists():
                raise FileNotFoundError(
                    f"uploaded file '{source}' not found or expired"
                )
            raw, name = await asyncio.to_thread(path.read_bytes), str(path)
        # decode (np.load / PNG decompress) is CPU+alloc heavy — off-loop
        return await asyncio.to_thread(self._decode_array, raw, name)

    @staticmethod
    def _decode_array(raw: bytes, name: str) -> np.ndarray:
        lower = name.lower()
        if lower.endswith(".npy"):
            return np.load(io.BytesIO(raw), allow_pickle=False)
        if lower.endswith((".tif", ".tiff")):
            try:
                import tifffile

                return tifffile.imread(io.BytesIO(raw))
            except ImportError as e:
                raise RuntimeError("tifffile not available") from e
        try:
            from PIL import Image

            return np.asarray(Image.open(io.BytesIO(raw)))
        except ImportError as e:
            raise RuntimeError(
                f"no decoder available for '{name}'"
            ) from e

    def _save_temp_array(self, array: np.ndarray) -> str:
        file_path = f"temp/{uuid.uuid4()}.npy"
        dest = self._uploads_dir / file_path
        dest.parent.mkdir(parents=True, exist_ok=True)
        np.save(dest, array)
        return file_path

    # ---- cache inspection ---------------------------------------------------

    @schema_method
    async def list_cached_models(self, context=None):
        """Cached packages with size, last access, and in-use flags."""
        return await self.model_cache.cached_models()
