"""Cell Morphology Search Engine — TPU-native.

API parity with the reference's CellImageSearch deployment
(ref apps/cell-image-search/main.py:1051-1522): ping, get_index_stats,
list_datasets / add_dataset / remove_dataset, start_ingestion /
get_ingestion_status / stop_ingestion / get_active_sessions, search,
get_umap_preview (projection), project_query_onto_umap.

TPU redesign (SURVEY.md §2.2): the embedder is the framework's
dp-sharded jitted Flax ViT (embedder.py), similarity search runs on
the MXU for flat indexes and over IVF/PQ lists otherwise (index.py),
ingestion streams from the egress-free datasets plane instead of S3
(ingestion.py).
"""

from __future__ import annotations

import asyncio
import time
from pathlib import Path
from typing import Any, Optional

import numpy as np

from bioengine_tpu.rpc import schema_method


class CellImageSearch:
    def __init__(
        self,
        workspace_dir: str = "~/.bioengine/cell-image-search",
        weights_path: Optional[str] = None,
        batch_bucket: int = 64,
        crop_size: int = 224,
        n_crops_per_image: int = 50,
    ):
        from embedder import ViTEmbedder

        self.workspace_dir = Path(workspace_dir).expanduser()
        self.workspace_dir.mkdir(parents=True, exist_ok=True)
        self.embedder = ViTEmbedder(
            weights_path=weights_path, batch_bucket=batch_bucket
        )
        self.crop_size = crop_size
        self.n_crops_per_image = n_crops_per_image
        self.started_at = time.time()
        self._index = None
        self._metadata = None
        self._index_info: dict = {}
        self._sessions: dict[str, asyncio.Task] = {}
        self._index_lock = asyncio.Lock()

    # ---- lifecycle hooks --------------------------------------------------

    async def async_init(self):
        await self._try_load_index()

    async def test_deployment(self):
        """Embed one synthetic image and round-trip the pipeline."""
        from ingestion import make_synthetic_images

        _, img = next(iter(make_synthetic_images(n_images=1, size=256)))
        emb = await asyncio.to_thread(self.embedder.embed_single, img)
        assert emb.shape == (self.embedder.EMBED_DIM,), emb.shape
        norm = float(np.linalg.norm(emb))
        assert abs(norm - 1.0) < 1e-3, f"embedding not unit-norm: {norm}"

    async def check_health(self):
        if not self.embedder.loaded:
            raise RuntimeError("embedder not loaded")

    async def _try_load_index(self) -> bool:
        from index import load_index

        try:
            index, df, info = await asyncio.to_thread(
                load_index, self.workspace_dir
            )
        except FileNotFoundError:
            return False
        self._index, self._metadata, self._index_info = index, df, info
        return True

    # ---- status -----------------------------------------------------------

    @schema_method
    async def ping(self, context=None):
        """Liveness + device/backend summary."""
        import jax

        return {
            "status": "ok",
            "uptime_seconds": time.time() - self.started_at,
            "backend": jax.default_backend(),
            "n_devices": jax.local_device_count(),
            "embedder_loaded": self.embedder.loaded,
            "pretrained": self.embedder.pretrained,
            "index_loaded": self._index is not None,
        }

    @schema_method
    async def get_index_stats(self, context=None):
        """Index size/type/build stats, or {loaded: False}."""
        if self._index is None and not await self._try_load_index():
            return {"loaded": False, "n_cells": 0}
        return {
            "loaded": True,
            "n_cells": self._index.ntotal,
            "index_type": self._index.kind,
            **self._index_info,
        }

    # ---- dataset registry --------------------------------------------------

    @schema_method
    async def list_datasets(self, context=None):
        """Registered ingestion sources + datasets-plane datasets."""
        from ingestion import load_registry

        registered = load_registry(self.workspace_dir)
        remote = []
        client = getattr(self, "bioengine_datasets", None)
        if client is not None and client.available:
            try:
                remote = await client.list_datasets()
            except Exception:
                remote = []
        return {"registered": registered, "data_server": remote}

    @schema_method
    async def add_dataset(
        self,
        name: str,
        source: str = "synthetic",
        path: Optional[str] = None,
        n_images: int = 8,
        image_size: int = 896,
        context=None,
    ):
        """Register an ingestion source. source: 'synthetic' (demo
        generator), 'local' (directory on the worker), or 'datasets'
        (a dataset served by the framework's data server)."""
        from ingestion import upsert_registry

        if source not in ("synthetic", "local", "datasets"):
            raise ValueError(f"unknown source '{source}'")
        if source == "local" and not path:
            raise ValueError("source 'local' requires path")
        entry = {
            "name": name,
            "source": source,
            "path": path,
            "n_images": n_images,
            "image_size": image_size,
            "added_at": time.time(),
        }
        upsert_registry(self.workspace_dir, entry)
        return {"added": True, "dataset": entry}

    @schema_method
    async def remove_dataset(self, name: str, context=None):
        """Drop a dataset from the registry."""
        from ingestion import load_registry, save_registry

        registry = load_registry(self.workspace_dir)
        kept = [r for r in registry if r.get("name") != name]
        save_registry(self.workspace_dir, kept)
        return {"removed": len(kept) < len(registry)}

    # ---- ingestion ---------------------------------------------------------

    @schema_method
    async def start_ingestion(
        self,
        dataset_name: str,
        session_id: Optional[str] = None,
        n_crops_per_image: Optional[int] = None,
        context=None,
    ):
        """Launch background ingestion of a registered dataset; returns
        the session id to poll with get_ingestion_status."""
        from ingestion import (
            load_registry,
            run_ingestion,
            session_dir,
            write_status,
            IngestionStatus,
        )

        entry = next(
            (
                r
                for r in load_registry(self.workspace_dir)
                if r.get("name") == dataset_name
            ),
            None,
        )
        if entry is None:
            raise ValueError(
                f"dataset '{dataset_name}' not registered — add_dataset first"
            )
        session_id = session_id or f"ingest-{int(time.time())}"
        live = self._sessions.get(session_id)
        if live is not None and not live.done():
            raise RuntimeError(f"session '{session_id}' already running")
        # prune finished task handles so the registry tracks only live
        # runs — session history lives on disk (status.json), not here
        for sid in [s for s, t in self._sessions.items() if t.done()]:
            self._sessions.pop(sid, None)
        # fresh session dir per run
        sdir = session_dir(self.workspace_dir, session_id)
        if sdir.exists():
            import os
            import shutil

            # rename synchronously so a concurrent start for the same
            # session_id can't pass the liveness guard mid-delete and
            # race on the session dir; delete the renamed tree off-loop
            doomed = sdir.with_name(f".{sdir.name}.deleting-{os.getpid()}")
            sdir.rename(doomed)
            await asyncio.to_thread(shutil.rmtree, doomed)
        write_status(
            self.workspace_dir, session_id,
            IngestionStatus.WAITING, "Queued",
            dataset_name=dataset_name,
        )
        dataset = dict(entry)
        if dataset["source"] == "datasets":
            dataset["client"] = getattr(self, "bioengine_datasets", None)

        async def _run():
            from ingestion import IngestionStatus, write_status

            try:
                async with self._index_lock:
                    await run_ingestion(
                        workspace_dir=self.workspace_dir,
                        session_id=session_id,
                        dataset=dataset,
                        embedder=self.embedder,
                        crop_size=self.crop_size,
                        n_crops_per_image=(
                            n_crops_per_image or self.n_crops_per_image
                        ),
                        batch_bucket=self.embedder.batch_bucket,
                    )
                    await self._try_load_index()
            except Exception as e:
                write_status(
                    self.workspace_dir, session_id,
                    IngestionStatus.FAILED, f"Error: {e}",
                )

        self._sessions[session_id] = asyncio.create_task(_run())
        return {"session_id": session_id, "status": "started"}

    @schema_method
    async def get_ingestion_status(self, session_id: str, context=None):
        """Poll a session's status.json."""
        from ingestion import read_status

        return read_status(self.workspace_dir, session_id)

    @schema_method
    async def stop_ingestion(self, session_id: str, context=None):
        """Request a running session to stop (between batches)."""
        from ingestion import request_stop

        request_stop(self.workspace_dir, session_id)
        return {"session_id": session_id, "stop_requested": True}

    @schema_method
    async def get_active_sessions(self, context=None):
        """All known sessions with their latest status."""
        from ingestion import read_status, session_dir

        root = session_dir(self.workspace_dir, "x").parent
        sessions = {}
        if root.exists():
            for d in sorted(root.iterdir()):
                # skip '.{name}.deleting-*' rename-away trees (crashed
                # mid-delete) and other hidden dirs — not sessions
                if d.is_dir() and not d.name.startswith("."):
                    sessions[d.name] = read_status(
                        self.workspace_dir, d.name
                    )
        return sessions

    # ---- search ------------------------------------------------------------

    @schema_method
    async def search(
        self,
        image: Any = None,
        image_bytes: Optional[bytes] = None,
        top_k: int = 20,
        context=None,
    ):
        """Find morphologically similar cells. ``image`` is any
        microscopy array (1-5 channels); ``image_bytes`` a PNG/JPEG/
        TIFF. Returns ranked matches with metadata + the query's 2-D
        map position."""
        from index import project_query, search_index
        from normalizer import decode_image_bytes

        if self._index is None and not await self._try_load_index():
            raise RuntimeError("no index built yet — run ingestion first")
        if image is None and image_bytes is None:
            raise ValueError("provide image or image_bytes")
        if image is None:
            image = decode_image_bytes(image_bytes)
        t0 = time.time()
        query = await asyncio.to_thread(
            self.embedder.embed_single, np.asarray(image)
        )
        t_embed = time.time() - t0
        t0 = time.time()
        results = await asyncio.to_thread(
            search_index, self._index, self._metadata, query, top_k
        )
        t_search = time.time() - t0
        return {
            "results": results,
            "n_results": len(results),
            "embed_ms": round(t_embed * 1000, 2),
            "search_ms": round(t_search * 1000, 2),
            "query_projection": project_query(self.workspace_dir, query),
        }

    # ---- projection (UMAP-analog) -----------------------------------------

    @schema_method
    async def get_umap_preview(
        self,
        n_samples: int = 10_000,
        force_recompute: bool = False,
        context=None,
    ):
        """2-D projection of an index sample for the dashboard scatter
        (PCA projector, cached with components so queries map into the
        same space)."""
        from index import compute_projection

        return await asyncio.to_thread(
            compute_projection,
            self.workspace_dir,
            n_samples,
            42,
            force_recompute,
        )

    @schema_method
    async def project_query_onto_umap(
        self, image: Any, context=None
    ):
        """Embed an image and return its position on the cached 2-D map."""
        from index import project_query

        query = await asyncio.to_thread(
            self.embedder.embed_single, np.asarray(image)
        )
        pos = project_query(self.workspace_dir, query)
        if pos is None:
            raise RuntimeError(
                "no projection cache — call get_umap_preview first"
            )
        return pos
