"""Microscopy image normalization for ViT embedding.

Capability parity with the reference's normalizer
(ref apps/cell-image-search/normalizer.py:34-170): uint8/uint16/float
inputs, 1-5 channel fluorescence, percentile stretch, 5-channel Cell
Painting → RGB composite, ImageNet scaling. Pure numpy — this runs on
the host; the device-side model consumes the (B, 224, 224, 3) float32
output directly (NHWC, the TPU conv layout).
"""

from __future__ import annotations

import numpy as np

# JUMP Cell Painting channel order (0-based):
# 0=DNA(DAPI), 1=ER, 2=RNA(SYTO), 3=AGP, 4=Mito
JUMP_CH_DNA = 0
JUMP_CH_ER = 1
JUMP_CH_RNA = 2
JUMP_CH_AGP = 3
JUMP_CH_MITO = 4

# Standard Cell Painting RGB composite: R=AGP, G=ER, B=DNA
JUMP_RGB_CHANNELS = [JUMP_CH_AGP, JUMP_CH_ER, JUMP_CH_DNA]

# ImageNet statistics (DINOv2 input convention), applied after [0, 1]
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], dtype=np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], dtype=np.float32)


def percentile_stretch(
    img: np.ndarray, plow: float = 1.0, phigh: float = 99.0
) -> np.ndarray:
    """Stretch one channel to [0, 255] uint8 with percentile clipping —
    robust to shot noise and hot pixels."""
    lo = np.percentile(img, plow)
    hi = np.percentile(img, phigh)
    if hi <= lo:
        hi = lo + 1.0
    stretched = (img.astype(np.float32) - lo) / (hi - lo)
    return (np.clip(stretched, 0.0, 1.0) * 255.0).astype(np.uint8)


def to_rgb_uint8(img: np.ndarray) -> np.ndarray:
    """Any (H, W), (H, W, C<=5) or (C<=5, H, W) image → (H, W, 3) uint8.

    1 channel → grayscale replicated; 2 → [ch0, ch1, ch0]; 3 → as-is;
    4/5 → Cell Painting composite (AGP, ER, DNA), falling back to the
    first three channels when fewer exist.
    """
    a = np.asarray(img)
    if a.ndim == 2:
        g = percentile_stretch(a)
        return np.stack([g, g, g], axis=-1)
    if a.ndim != 3:
        raise ValueError(f"expected 2D or 3D image, got shape {a.shape}")
    # channels-first heuristic: small leading axis
    if a.shape[0] <= 5 and a.shape[0] < min(a.shape[1:]):
        a = np.moveaxis(a, 0, -1)
    c = a.shape[-1]
    if c == 1:
        return to_rgb_uint8(a[..., 0])
    if c == 2:
        ch0 = percentile_stretch(a[..., 0])
        ch1 = percentile_stretch(a[..., 1])
        return np.stack([ch0, ch1, ch0], axis=-1)
    if c == 3:
        return np.stack([percentile_stretch(a[..., i]) for i in range(3)], -1)
    if c in (4, 5):
        picks = [ch for ch in JUMP_RGB_CHANNELS if ch < c]
        while len(picks) < 3:
            picks.append(picks[-1])
        return np.stack(
            [percentile_stretch(a[..., ch]) for ch in picks], axis=-1
        )
    raise ValueError(f"unsupported channel count {c}")


def resize_rgb(img_rgb: np.ndarray, size: int = 224) -> np.ndarray:
    """(H, W, 3) uint8 → (size, size, 3) uint8 (bilinear)."""
    if img_rgb.shape[:2] == (size, size):
        return img_rgb
    from PIL import Image

    return np.asarray(
        Image.fromarray(img_rgb).resize((size, size), Image.BILINEAR)
    )


def to_model_input(img: np.ndarray, size: int = 224) -> np.ndarray:
    """Any microscopy image → (size, size, 3) float32, ImageNet-scaled —
    one row of the embedder's NHWC batch."""
    rgb = resize_rgb(to_rgb_uint8(img), size)
    x = rgb.astype(np.float32) / 255.0
    return (x - IMAGENET_MEAN) / IMAGENET_STD


def decode_image_bytes(data: bytes) -> np.ndarray:
    """PNG/JPEG/TIFF bytes → numpy array (any dtype/channels)."""
    import io

    from PIL import Image

    return np.asarray(Image.open(io.BytesIO(data)))
