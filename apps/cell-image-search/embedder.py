"""TPU-native ViT embedder for cell crops.

Replaces the reference's torch-hub DINOv2 wrapper
(ref apps/cell-image-search/embedder.py:23-101: lazy CUDA load, fp16,
batch 64, ~500 img/s on one A100) with the framework's Flax ViT:

- bf16 matmuls on the MXU, flash-attention Pallas kernel on TPU;
- one jitted program per batch *bucket* (batches pad up to the bucket
  so arbitrary request sizes never trigger recompiles);
- data-parallel sharding over every local chip via the dp mesh — corpus
  embedding scales across a slice with zero code change (the reference's
  multi-GPU path was aspirational, SURVEY.md §6).

Pretrained DINOv2 weights convert from the torch checkpoint via
``bioengine_tpu.runtime.convert`` — one-time:
``bioengine models convert dinov2_vitb14.pth weights.npz --arch dinov2``
— then pass the npz as ``weights_path``. Without one the model runs
randomly initialized (deterministic seed), which preserves the full
pipeline shape for tests and benchmarks.
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)


class ViTEmbedder:
    MODEL_NAME = "dinov2_vitb14"
    EMBED_DIM = 768
    INPUT_SIZE = 224

    def __init__(
        self,
        weights_path: Optional[str] = None,
        # 128 measured fastest on v5e (1912 -> 2062 img/s vs bucket 64
        # with bf16 softmax); larger buckets regress (bench.py sweep)
        batch_bucket: int = 128,
        use_flash_attention: Optional[bool] = None,
    ) -> None:
        self.weights_path = weights_path
        self.batch_bucket = batch_bucket
        self.use_flash_attention = use_flash_attention
        self.pretrained = weights_path is not None
        self._model = None
        self._params = None
        self._embed_fn = None
        self._mesh = None
        import threading

        self._load_lock = threading.Lock()

    @property
    def loaded(self) -> bool:
        return self._model is not None

    def load(self) -> None:
        with self._load_lock:
            if self._embed_fn is None:
                self._load()

    def _load(self) -> None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bioengine_tpu.models.vit import ViT
        from bioengine_tpu.parallel.mesh import make_mesh

        # Flash attention only pays off on LONG token sequences: at this
        # model's N=257 (224/14 patches + cls) the blocked Pallas kernel
        # measured ~3x SLOWER than XLA's fused attention on v5e (block
        # padding + f32 accumulation dominate short rows), so auto mode
        # keeps XLA attention below 1024 tokens.
        n_tokens = (self.INPUT_SIZE // 14) ** 2 + 1
        use_flash = self.use_flash_attention
        if use_flash is None:
            use_flash = jax.default_backend() == "tpu" and n_tokens >= 1024
        attn_fn = None
        if use_flash:
            from bioengine_tpu.ops.pallas import make_attn_fn

            attn_fn = make_attn_fn()

        model = ViT(
            patch_size=14, dim=768, depth=12, num_heads=12, attn_fn=attn_fn
        )
        if self.weights_path:
            from bioengine_tpu.runtime.convert import load_params_npz

            params = load_params_npz(self.weights_path)
            logger.info("loaded ViT weights from %s", self.weights_path)
        else:
            params = model.init(
                jax.random.key(0),
                jnp.zeros((1, self.INPUT_SIZE, self.INPUT_SIZE, 3)),
            )["params"]
            logger.warning(
                "no weights_path — running randomly-initialized ViT "
                "(pipeline-shape mode, embeddings are not DINOv2)"
            )

        n_dev = jax.local_device_count()
        # dp over the largest power of two that divides the bucket
        dp = 1
        while dp * 2 <= n_dev and self.batch_bucket % (dp * 2) == 0:
            dp *= 2
        mesh = make_mesh({"dp": dp}, jax.devices()[:dp])
        repl = NamedSharding(mesh, P())
        data_sh = NamedSharding(mesh, P("dp"))
        params = jax.device_put(params, repl)

        def fwd(params, images):
            emb = model.apply({"params": params}, images)  # (B, 768) f32
            norms = jnp.linalg.norm(emb, axis=-1, keepdims=True)
            return emb / jnp.maximum(norms, 1e-9)

        embed = jax.jit(fwd, in_shardings=(repl, data_sh), out_shardings=repl)

        self._model, self._params = model, params
        self._embed_fn, self._mesh = embed, mesh
        logger.info(
            "ViT embedder ready: backend=%s dp=%d flash_attention=%s "
            "pretrained=%s",
            jax.default_backend(), dp, use_flash, self.pretrained,
        )

    def embed_batch(
        self, images_rgb: list[np.ndarray], batch_size: Optional[int] = None
    ) -> np.ndarray:
        """List of (H, W, 3)-ish microscopy arrays → (N, 768) float32
        L2-normalised. Batches pad to ``batch_bucket`` so every call
        reuses one compiled program."""
        from normalizer import to_model_input

        if self._embed_fn is None:
            self.load()
        import jax.numpy as jnp

        bucket = batch_size or self.batch_bucket
        prepped = np.stack(
            [to_model_input(img, self.INPUT_SIZE) for img in images_rgb]
        )
        out = []
        for i in range(0, len(prepped), bucket):
            chunk = prepped[i : i + bucket]
            n = len(chunk)
            if n < bucket:
                chunk = np.pad(chunk, ((0, bucket - n), (0, 0), (0, 0), (0, 0)))
            emb = self._embed_fn(self._params, jnp.asarray(chunk))
            out.append(np.asarray(emb, np.float32)[:n])
        return np.vstack(out)

    def embed_single(self, image_rgb: np.ndarray) -> np.ndarray:
        return self.embed_batch([image_rgb])[0]
