"""TPU-native vector index for cell-embedding similarity search.

Replaces the reference's FAISS dependency
(ref apps/cell-image-search/index_manager.py:36-183) with the same
auto-selection policy but TPU-first execution:

- **FlatIP** (< 100K vectors): exact search as one MXU matmul +
  ``lax.top_k`` on device (``bioengine_tpu.ops.knn``). The published
  FAISS CPU number is <5 ms at 100K; a 100K x 768 matvec is ~0.15
  GFLOP — microseconds of MXU time.
- **IVFFlat** (< 5M): coarse k-means quantizer (MiniBatchKMeans) +
  exact inner product over the probed lists, scored on device in one
  gathered matmul.
- **IVFPQ** (>= 5M): 96 sub-quantizers x 8 bits (96 bytes/vector, the
  reference's layout), ADC lookup-table search; encode runs on device
  (per-subspace distance matmuls), query scan is numpy over the probed
  lists' codes.
- **PQFlatTPU** (>= 5M when a TPU is present): the same PQ codes held
  RESIDENT in HBM and exact-scanned per query by a jitted gather
  scan + on-device top-k — no probe selection, no recall loss; 58M
  codes are ~5.5 GB and fit one v5e chip.

Persistence: ``cell_search_index.npz`` + ``metadata.parquet`` +
``index_info.json`` under ``<workspace>/index`` — same file roles as
the reference (index/metadata/info, ref index_manager.py:93-111).
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Any, Optional

import numpy as np

logger = logging.getLogger(__name__)

EMBED_DIM = 768


def index_dir(workspace_dir: str | Path) -> Path:
    return Path(workspace_dir).expanduser() / "index"


def _topk_pad(
    parts_s: list[np.ndarray], parts_i: list[np.ndarray], top_k: int
) -> tuple[np.ndarray, np.ndarray]:
    """Top-k over concatenated candidate (scores, ids), padded to
    ``top_k`` with (-inf, -1) — shared by the probed-list index kinds."""
    if not parts_s:
        return (
            np.full(top_k, -np.inf, np.float32),
            np.full(top_k, -1, np.int64),
        )
    scores = np.concatenate(parts_s)
    ids = np.concatenate(parts_i)
    k = min(top_k, scores.size)
    sel = np.argpartition(-scores, k - 1)[:k]
    sel = sel[np.argsort(-scores[sel])]
    s = np.full(top_k, -np.inf, np.float32)
    i = np.full(top_k, -1, np.int64)
    s[:k], i[:k] = scores[sel], ids[sel]
    return s, i


# ---------------------------------------------------------------------------
# index variants
# ---------------------------------------------------------------------------


class FlatIPIndex:
    """Exact inner-product search; corpus lives on device in bf16."""

    kind = "FlatIP"

    def __init__(self, embeddings: np.ndarray):
        self.embeddings = np.ascontiguousarray(embeddings, np.float32)
        self._device_corpus = None

    @property
    def ntotal(self) -> int:
        return len(self.embeddings)

    def search(self, query: np.ndarray, top_k: int):
        import jax.numpy as jnp

        from bioengine_tpu.ops.knn import topk_inner_product

        if self._device_corpus is None:
            self._device_corpus = jnp.asarray(self.embeddings, jnp.bfloat16)
        q = np.atleast_2d(query).astype(np.float32)
        k = min(top_k, self.ntotal)
        s, i = topk_inner_product(self._device_corpus, jnp.asarray(q), k)
        return np.asarray(s), np.asarray(i)

    def reconstruct(self, ids: np.ndarray) -> np.ndarray:
        return self.embeddings[ids]

    def save(self, path: Path):
        np.savez_compressed(path, kind=self.kind, embeddings=self.embeddings)

    @classmethod
    def load(cls, data) -> "FlatIPIndex":
        return cls(data["embeddings"])


class IVFFlatIndex:
    """Coarse-quantized exact search: k-means lists, probe the nearest
    ``nprobe`` lists, exact IP over their members.

    Embeddings are stored list-sorted so each probed list is a
    CONTIGUOUS slice — scoring is ``nprobe`` dense matvecs instead of a
    corpus-sized fancy-index gather per query (the gather dominated
    latency ~10x at 200K vectors)."""

    kind = "IVFFlat"

    def __init__(
        self,
        embeddings: np.ndarray,
        centroids: np.ndarray,
        assignments: np.ndarray,
        nprobe: int = 16,
    ):
        embeddings = np.ascontiguousarray(embeddings, np.float32)
        self.centroids = centroids.astype(np.float32)
        self.assignments = assignments.astype(np.int32)
        self.nprobe = nprobe
        order = np.argsort(assignments, kind="stable")
        self._order = order.astype(np.int64)       # sorted pos -> orig id
        self._sorted_emb = np.ascontiguousarray(embeddings[order])
        self._pos = np.empty(len(order), np.int64)  # orig id -> sorted pos
        self._pos[order] = np.arange(len(order))
        sorted_assign = assignments[order]
        nlist = len(centroids)
        starts = np.searchsorted(sorted_assign, np.arange(nlist))
        ends = np.searchsorted(sorted_assign, np.arange(nlist), side="right")
        self._list_bounds = np.stack([starts, ends], axis=1)

    @classmethod
    def build(
        cls,
        embeddings: np.ndarray,
        nlist: int,
        nprobe: int = 16,
        n_init: int = 3,
    ) -> "IVFFlatIndex":
        from sklearn.cluster import MiniBatchKMeans

        km = MiniBatchKMeans(
            n_clusters=nlist, batch_size=4096, n_init=n_init, random_state=0
        )
        assignments = km.fit_predict(embeddings)
        return cls(embeddings, km.cluster_centers_, assignments, nprobe)

    @property
    def ntotal(self) -> int:
        return len(self._sorted_emb)

    def search(self, query: np.ndarray, top_k: int):
        q = np.atleast_2d(query).astype(np.float32)
        nprobe = min(self.nprobe, len(self.centroids))
        # probe selection: q @ centroids^T (tiny — numpy)
        cscores = q @ self.centroids.T
        probes = np.argpartition(-cscores, nprobe - 1, axis=1)[:, :nprobe]
        all_s, all_i = [], []
        for row, plist in enumerate(probes):
            parts_s, parts_i = [], []
            for p in plist:
                s0, s1 = self._list_bounds[p]
                if s1 <= s0:
                    continue
                # contiguous slice: a dense matvec, no gather
                parts_s.append(self._sorted_emb[s0:s1] @ q[row])
                parts_i.append(self._order[s0:s1])
            s, i = _topk_pad(parts_s, parts_i, top_k)
            all_s.append(s)
            all_i.append(i)
        return np.stack(all_s), np.stack(all_i)

    def reconstruct(self, ids: np.ndarray) -> np.ndarray:
        return self._sorted_emb[self._pos[np.asarray(ids)]]

    def save(self, path: Path):
        np.savez_compressed(
            path,
            kind=self.kind,
            # original-row order keeps the on-disk format stable
            embeddings=self._sorted_emb[self._pos],
            centroids=self.centroids,
            assignments=self.assignments,
            nprobe=self.nprobe,
        )

    @classmethod
    def load(cls, data) -> "IVFFlatIndex":
        return cls(
            data["embeddings"],
            data["centroids"],
            data["assignments"],
            int(data["nprobe"]),
        )


def _train_pq(
    vectors: np.ndarray,
    M: int,
    ksub_max: int,
    train_n: Optional[int] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Per-subspace PQ training + full encode, shared by IVFPQIndex
    (on residuals) and PQFlatIndex (on raw vectors). Returns
    (codebooks (M, ksub, dsub), codes (N, M) uint8)."""
    from sklearn.cluster import MiniBatchKMeans

    n, d = vectors.shape
    assert d % M == 0, f"dim {d} not divisible by m={M}"
    dsub = d // M
    train_len = min(train_n or min(n, 1_000_000), n)
    ksub = min(ksub_max, train_len)
    codebooks = np.empty((M, ksub, dsub), np.float32)
    codes = np.empty((n, M), np.uint8)
    for m in range(M):
        sub = np.ascontiguousarray(vectors[:, m * dsub : (m + 1) * dsub])
        km = MiniBatchKMeans(
            n_clusters=ksub, batch_size=8192, n_init=1, random_state=m
        )
        km.fit(sub[:train_len])
        codebooks[m] = km.cluster_centers_
        codes[:, m] = km.predict(sub).astype(np.uint8)
    return codebooks, codes


class IVFPQIndex:
    """IVF + product quantization: 96 bytes/vector (m=96 subspaces x
    8 bits), asymmetric-distance search over probed lists."""

    kind = "IVFPQ"
    M = 96          # sub-quantizers; 768 / 96 = 8 dims each
    KSUB = 256      # 8-bit codebooks

    def __init__(
        self,
        centroids: np.ndarray,
        codebooks: np.ndarray,      # (M, KSUB, dsub)
        codes: np.ndarray,          # (N, M) uint8, list-sorted order
        ids: np.ndarray,            # (N,) original ids, list-sorted
        list_bounds: np.ndarray,    # (nlist, 2)
        nprobe: int = 32,
    ):
        self.centroids = centroids.astype(np.float32)
        self.codebooks = codebooks.astype(np.float32)
        self.codes = codes
        self.ids = ids
        self.list_bounds = list_bounds
        self.nprobe = nprobe
        self.dsub = codebooks.shape[-1]

    @classmethod
    def build(
        cls,
        embeddings: np.ndarray,
        nlist: int,
        nprobe: int = 32,
        train_n: Optional[int] = None,
    ) -> "IVFPQIndex":
        from sklearn.cluster import MiniBatchKMeans

        n, d = embeddings.shape
        train_len = train_n or min(n, 1_000_000)
        train = embeddings[:train_len]

        coarse = MiniBatchKMeans(
            n_clusters=nlist, batch_size=8192, n_init=3, random_state=0
        )
        coarse.fit(train)
        assignments = coarse.predict(embeddings)
        residuals = embeddings - coarse.cluster_centers_[assignments]
        codebooks, codes = _train_pq(residuals, cls.M, cls.KSUB, train_len)

        order = np.argsort(assignments, kind="stable")
        sorted_assign = assignments[order]
        starts = np.searchsorted(sorted_assign, np.arange(nlist))
        ends = np.searchsorted(sorted_assign, np.arange(nlist), side="right")
        bounds = np.stack([starts, ends], axis=1)
        return cls(
            coarse.cluster_centers_,
            codebooks,
            codes[order],
            order.astype(np.int64),
            bounds,
            nprobe,
        )

    @property
    def ntotal(self) -> int:
        return len(self.codes)

    def search(self, query: np.ndarray, top_k: int):
        q = np.atleast_2d(query).astype(np.float32)
        nprobe = min(self.nprobe, len(self.centroids))
        cscores = q @ self.centroids.T
        probes = np.argpartition(-cscores, nprobe - 1, axis=1)[:, :nprobe]
        # flat-LUT layout: one 1-D gather of (codes + per-subspace
        # offset) replaces a 2-array fancy index — and concatenating
        # every probed list's (contiguous, list-sorted) code block
        # first turns 32 small per-list gathers into ONE big one
        offs = (np.arange(self.M, dtype=np.int32) * self.codebooks.shape[1])
        all_s, all_i = [], []
        for row, plist in enumerate(probes):
            qr = q[row]
            # ADC table from q itself: x_hat = c + r_hat, so
            # q·x_hat = q·c + q·r_hat — the table scores q against
            # the residual codebooks (FAISS IP-by-residual does the
            # same; building it from q - c would add a spurious
            # -c·r_hat ranking term). Probe-independent: built once
            # per query, not per probed list.
            lut = np.einsum(
                "mkd,md->mk",
                self.codebooks,
                qr.reshape(self.M, self.dsub),
            ).ravel()  # (M * KSUB,)
            bounds = self.list_bounds[plist]
            live = bounds[:, 1] > bounds[:, 0]
            if not live.any():
                s, i = _topk_pad([], [], top_k)
                all_s.append(s)
                all_i.append(i)
                continue
            bounds = bounds[live]
            lens = bounds[:, 1] - bounds[:, 0]
            codes = np.concatenate(
                [self.codes[s0:s1] for s0, s1 in bounds]
            )  # (Ltot, M)
            ids = np.concatenate([self.ids[s0:s1] for s0, s1 in bounds])
            scores = lut[codes.astype(np.int32) + offs].sum(axis=1)
            # q·c base term: reuse the coarse scores already computed
            scores += np.repeat(cscores[row, plist[live]], lens)
            s, i = _topk_pad([scores], [ids], top_k)
            all_s.append(s)
            all_i.append(i)
        return np.stack(all_s), np.stack(all_i)

    def reconstruct(self, ids: np.ndarray) -> np.ndarray:
        """Approximate reconstruction from codes (for projections)."""
        pos = np.empty_like(self.ids)
        pos[self.ids] = np.arange(len(self.ids))
        out = np.empty((len(ids), self.M * self.dsub), np.float32)
        # list centroid of each id
        list_of_pos = np.zeros(len(self.ids), np.int32)
        for li, (s0, s1) in enumerate(self.list_bounds):
            list_of_pos[s0:s1] = li
        for j, ident in enumerate(np.asarray(ids)):
            p = pos[ident]
            code = self.codes[p]
            resid = self.codebooks[np.arange(self.M), code]  # (M, dsub)
            out[j] = self.centroids[list_of_pos[p]] + resid.reshape(-1)
        return out

    def save(self, path: Path):
        np.savez_compressed(
            path,
            kind=self.kind,
            centroids=self.centroids,
            codebooks=self.codebooks,
            codes=self.codes,
            ids=self.ids,
            list_bounds=self.list_bounds,
            nprobe=self.nprobe,
        )

    @classmethod
    def load(cls, data) -> "IVFPQIndex":
        return cls(
            data["centroids"],
            data["codebooks"],
            data["codes"],
            data["ids"],
            data["list_bounds"],
            int(data["nprobe"]),
        )


class PQFlatIndex:
    """Device-resident PQ flat scan — the TPU-native answer to FAISS's
    CPU IVFPQ at full-corpus scale.

    Codes live in TPU HBM as an (M, N) uint8 plane: at 96 bytes/vector
    the reference's ENTIRE 58M-cell JUMP corpus is ~5.5 GB — it fits a
    single v5e chip's HBM, so search needs no coarse quantizer, no
    probe selection, and no recall loss from unprobed lists: every
    query exactly-scans all N codes. Per query the ADC table (M x 256
    inner products) uploads ~100 KB; the scan is a jitted
    ``lax.scan`` over subspaces accumulating ``take`` gathers — pure
    HBM-bandwidth work the VPU streams — followed by an on-device
    ``top_k`` so only (Q, k) scores/ids ever cross the wire. The
    reference's CPU path scans <0.2% of the corpus (nprobe/nlist) to
    hit <80 ms at 58M; this scans 100% of it from HBM instead of RAM.
    """

    kind = "PQFlatTPU"
    M = 96
    KSUB = 256

    def __init__(
        self,
        codebooks: np.ndarray,     # (M, KSUB, dsub)
        codes: np.ndarray,         # (N, M) uint8
        ids: Optional[np.ndarray] = None,
    ):
        self.codebooks = codebooks.astype(np.float32)
        self.codes = codes
        self.ids = (
            ids.astype(np.int64)
            if ids is not None
            else np.arange(len(codes), dtype=np.int64)
        )
        self.dsub = codebooks.shape[-1]
        self._codes_dev = None
        self._topk_fns: dict[int, Any] = {}

    @classmethod
    def build(
        cls,
        embeddings: np.ndarray,
        train_n: Optional[int] = None,
    ) -> "PQFlatIndex":
        codebooks, codes = _train_pq(
            embeddings, cls.M, cls.KSUB, train_n
        )
        if codebooks.shape[1] < cls.KSUB:  # tiny corpora: pad to 8-bit
            codebooks = np.pad(
                codebooks,
                ((0, 0), (0, cls.KSUB - codebooks.shape[1]), (0, 0)),
            )
        return cls(codebooks, codes)

    @property
    def ntotal(self) -> int:
        return len(self.codes)

    def _scan_fn(self, k: int):
        """Jitted full-corpus ADC scan + top-k, cached per k (top_k is
        a compile-time constant for lax.top_k)."""
        if k in self._topk_fns:
            return self._topk_fns[k]
        import jax
        import jax.numpy as jnp

        @jax.jit
        def run(luts, codes_t):
            # luts: (Q, M, KSUB); codes_t: (M, N) uint8 — RESIDENT at
            # 1 byte/code (the whole point: 58M x 96 = ~5.5 GB fits one
            # chip); each scan step widens ONE (N,) row to int32 for
            # the gather, a transient XLA handles, never 4x residency
            def body(acc, mk):
                lut_m, codes_m = mk        # (Q, KSUB), (N,) uint8
                idx = codes_m.astype(jnp.int32)
                return acc + jnp.take(lut_m, idx, axis=1), None

            acc0 = jnp.zeros(
                (luts.shape[0], codes_t.shape[1]), jnp.float32
            )
            scores, _ = jax.lax.scan(
                body, acc0, (jnp.moveaxis(luts, 1, 0), codes_t)
            )
            return jax.lax.top_k(scores, k)

        self._topk_fns[k] = run
        return run

    # cap on the transient (Q_chunk, N) f32 score plane the scan holds
    # in HBM: at 58M codes a 64-query batch would be ~15 GB and OOM the
    # chip whose 5.5 GB code residency is the whole selling point, so
    # batches chunk to keep scores under this budget (58M -> 8/chunk;
    # 1M -> the full batch)
    SCORE_BUDGET_BYTES = 2 << 30

    def search(self, query: np.ndarray, top_k: int):
        import jax.numpy as jnp

        if self._codes_dev is None:
            self._codes_dev = jnp.asarray(
                np.ascontiguousarray(self.codes.T)  # stays uint8 in HBM
            )
        q = np.atleast_2d(query).astype(np.float32)
        k = min(top_k, self.ntotal)
        q_chunk = max(1, int(self.SCORE_BUDGET_BYTES // (self.ntotal * 4)))
        out_s = np.full((len(q), top_k), -np.inf, np.float32)
        out_i = np.full((len(q), top_k), -1, np.int64)
        for c0 in range(0, len(q), q_chunk):
            qc = q[c0 : c0 + q_chunk]
            luts = np.einsum(
                "mkd,qmd->qmk",
                self.codebooks,
                qc.reshape(len(qc), self.M, self.dsub),
            )
            s, i = self._scan_fn(k)(jnp.asarray(luts), self._codes_dev)
            out_s[c0 : c0 + len(qc), :k] = np.asarray(s)
            out_i[c0 : c0 + len(qc), :k] = self.ids[np.asarray(i)]
        return out_s, out_i

    def reconstruct(self, ids: np.ndarray) -> np.ndarray:
        pos = np.empty(int(self.ids.max()) + 1, np.int64)
        pos[self.ids] = np.arange(len(self.ids))
        code = self.codes[pos[np.asarray(ids)]]          # (B, M)
        resid = self.codebooks[
            np.arange(self.M)[None, :], code
        ]                                                 # (B, M, dsub)
        return resid.reshape(len(code), -1).astype(np.float32)

    def save(self, path: Path):
        np.savez_compressed(
            path,
            kind=self.kind,
            codebooks=self.codebooks,
            codes=self.codes,
            ids=self.ids,
        )

    @classmethod
    def load(cls, data) -> "PQFlatIndex":
        return cls(data["codebooks"], data["codes"], data["ids"])


_KINDS = {
    c.kind: c
    for c in (FlatIPIndex, IVFFlatIndex, IVFPQIndex, PQFlatIndex)
}


# ---------------------------------------------------------------------------
# build / load / search / project — the reference's module API
# ---------------------------------------------------------------------------


def build_index(
    embeddings: np.ndarray,
    metadata_df,
    workspace_dir: str | Path,
    n_cells_total: Optional[int] = None,
) -> dict[str, Any]:
    """Auto-select Flat/IVFFlat/IVFPQ by target size — same thresholds
    as the reference (ref index_manager.py:67-88) — and persist."""
    t0 = time.time()
    n, d = embeddings.shape
    n_target = n_cells_total or n
    out = index_dir(workspace_dir)
    out.mkdir(parents=True, exist_ok=True)

    if n_target < 100_000:
        index = FlatIPIndex(embeddings)
    elif n_target < 5_000_000:
        nlist = min(4096, max(64, int(np.sqrt(n_target))), n)
        index = IVFFlatIndex.build(embeddings, nlist)
    else:
        import jax

        if jax.default_backend() == "tpu":
            # HBM-resident exact PQ scan: zero probe-miss recall loss,
            # and the whole 58M-scale corpus fits one chip
            index = PQFlatIndex.build(embeddings)
        else:
            nlist = min(65536, max(4096, int(np.sqrt(n_target))), n)
            index = IVFPQIndex.build(embeddings, nlist)

    index_path = out / "cell_search_index.npz"
    index.save(index_path)
    metadata_df.to_parquet(out / "metadata.parquet", index=False)
    elapsed = time.time() - t0
    stats = {
        "n_cells": n,
        "embed_dim": d,
        "index_type": index.kind,
        "index_size_mb": index_path.stat().st_size / 1024**2,
        "build_seconds": elapsed,
        "build_time_iso": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
    }
    (out / "index_info.json").write_text(json.dumps(stats, indent=2))
    logger.info("built %s index: n=%d in %.1fs", index.kind, n, elapsed)
    return stats


def load_index(workspace_dir: str | Path):
    """→ (index, metadata_df, info) or raises FileNotFoundError."""
    import pandas as pd

    out = index_dir(workspace_dir)
    path = out / "cell_search_index.npz"
    if not path.exists():
        raise FileNotFoundError(f"no index at {path}")
    with np.load(path, allow_pickle=False) as data:
        kind = str(data["kind"])
        index = _KINDS[kind].load(data)
    df = pd.read_parquet(out / "metadata.parquet")
    info = json.loads((out / "index_info.json").read_text())
    return index, df, info


def search_index(index, metadata_df, query_embedding, top_k=20):
    """→ list of result dicts with rank/score/metadata
    (ref index_manager.py:147-183)."""
    scores, ids = index.search(query_embedding, top_k)
    scores, ids = scores[0], ids[0]
    results = []
    for rank, (score, idx) in enumerate(zip(scores, ids)):
        if idx < 0 or not np.isfinite(score):
            continue
        meta = {}
        if metadata_df is not None and idx < len(metadata_df):
            meta = {
                k: (v.item() if hasattr(v, "item") else v)
                for k, v in metadata_df.iloc[int(idx)].to_dict().items()
            }
        results.append(
            {"rank": rank + 1, "score": float(score), "index_id": int(idx),
             **meta}
        )
    return results


def compute_projection(
    workspace_dir: str | Path,
    n_samples: int = 10_000,
    random_state: int = 42,
    force_recompute: bool = False,
) -> dict[str, Any]:
    """2-D map of a random sample for the dashboard scatter plot.

    The reference uses UMAP with a PCA fallback (ref
    index_manager.py:237-247); here the projector is PCA (fit once,
    cached with its components so queries project into the same space
    in O(d) — the reference re-embeds queries through UMAP transform).
    """
    out = index_dir(workspace_dir)
    cache = out / "projection_cache.npz"
    if cache.exists() and not force_recompute:
        data = np.load(cache, allow_pickle=True)
        return {
            "x": data["x"].tolist(),
            "y": data["y"].tolist(),
            "labels": data["labels"].tolist(),
            "colors": data["colors"].tolist(),
            "n_total": int(data["n_total"]),
        }
    try:
        index, df, _ = load_index(workspace_dir)
    except FileNotFoundError:
        return {"x": [], "y": [], "labels": [], "colors": [], "n_total": 0}

    n_total = index.ntotal
    n_samples = min(n_samples, n_total)
    rng = np.random.default_rng(random_state)
    sample = np.sort(rng.choice(n_total, size=n_samples, replace=False))
    vecs = index.reconstruct(sample)

    from sklearn.decomposition import PCA

    pca = PCA(n_components=2, random_state=random_state)
    coords = pca.fit_transform(vecs)

    labels = ["unknown"] * n_samples
    colors = ["#888888"] * n_samples
    label_col = next(
        (c for c in ("moa_class", "compound", "label") if c in df.columns),
        None,
    )
    if label_col is not None:
        uniques = df[label_col].astype(str).unique().tolist()
        palette = _generate_palette(len(uniques))
        cmap = {u: palette[i % len(palette)] for i, u in enumerate(uniques)}
        for i, idx in enumerate(sample):
            if idx < len(df):
                lbl = str(df.iloc[int(idx)][label_col])
                labels[i] = lbl
                colors[i] = cmap.get(lbl, "#888888")

    np.savez(
        cache,
        x=coords[:, 0], y=coords[:, 1],
        labels=np.array(labels), colors=np.array(colors),
        n_total=np.array(n_total),
        mean=pca.mean_, components=pca.components_,
    )
    return {
        "x": coords[:, 0].tolist(),
        "y": coords[:, 1].tolist(),
        "labels": labels,
        "colors": colors,
        "n_total": n_total,
    }


def project_query(
    workspace_dir: str | Path, query_embedding: np.ndarray
) -> Optional[dict[str, float]]:
    """Project a query embedding onto the cached 2-D map."""
    cache = index_dir(workspace_dir) / "projection_cache.npz"
    if not cache.exists():
        return None
    data = np.load(cache, allow_pickle=True)
    if "components" not in data:
        return None
    xy = (query_embedding - data["mean"]) @ data["components"].T
    return {"x": float(xy[0]), "y": float(xy[1])}


def _generate_palette(n: int) -> list[str]:
    """n visually-spread hex colors (golden-angle hue walk)."""
    colors = []
    for i in range(max(n, 1)):
        h = (i * 0.61803398875) % 1.0
        r, g, b = _hsv_to_rgb(h, 0.65, 0.95)
        colors.append(f"#{int(r*255):02x}{int(g*255):02x}{int(b*255):02x}")
    return colors


def _hsv_to_rgb(h, s, v):
    import colorsys

    return colorsys.hsv_to_rgb(h, s, v)
