"""Ingestion pipeline: dataset → cell crops → TPU embeddings → index.

Capability parity with the reference's ingestion
(ref apps/cell-image-search/ingestion.py:40-591 — session dirs with
status.json / stop_requested files, crop extraction around nuclei,
batched embedding, registry of ingested datasets), redesigned for the
TPU worker:

- Sources are egress-free: the framework's **datasets plane** (zarr
  over HTTP, ``bioengine_datasets``), **local directories** of
  npy/npz/png/tif images, and a **synthetic** generator for demos and
  tests. The reference's JUMP-S3 streaming maps onto the datasets
  plane (the data server fronts the plates).
- Embedding batches pipeline through the dp-sharded jitted ViT — crops
  accumulate into full buckets so every device step is a full matmul.
- Crop extraction is scipy.ndimage (Otsu threshold + labeled blobs),
  with the reference's grid fallback when too few nuclei are found
  (ref main.py:668-703).
"""

from __future__ import annotations

import asyncio
import json
import time
from enum import Enum
from pathlib import Path
from typing import Any, Optional

import numpy as np


class IngestionStatus(str, Enum):
    WAITING = "waiting"
    PREPARING = "preparing"
    RUNNING = "running"
    BUILDING_INDEX = "building_index"
    COMPLETED = "completed"
    STOPPED = "stopped"
    FAILED = "failed"


def session_dir(workspace_dir: str | Path, session_id: str) -> Path:
    return Path(workspace_dir).expanduser() / "sessions" / session_id


def write_status(
    workspace_dir: str | Path,
    session_id: str,
    status: IngestionStatus,
    message: str,
    n_embedded: Optional[int] = None,
    n_total: Optional[int] = None,
    throughput_per_sec: Optional[float] = None,
    elapsed_seconds: Optional[float] = None,
    dataset_name: str = "",
    log_lines: Optional[list[str]] = None,
    **extra: Any,
) -> None:
    """Counters default to None = keep the previous values, so a
    terminal FAILED/STOPPED write never wipes accumulated progress."""
    path = session_dir(workspace_dir, session_id) / "status.json"
    path.parent.mkdir(parents=True, exist_ok=True)
    existing: dict = {}
    if path.exists():
        try:
            existing = json.loads(path.read_text())
        except (OSError, ValueError):
            existing = {}  # unreadable/corrupt status: start fresh
    prev_log = existing.get("log_tail", [])
    if log_lines:
        prev_log = (prev_log + list(log_lines))[-20:]
    if n_embedded is None:
        n_embedded = existing.get("n_embedded", 0)
    if n_total is None:
        n_total = existing.get("n_total", 0)
    if throughput_per_sec is None:
        throughput_per_sec = existing.get("throughput_per_sec", 0.0)
    if elapsed_seconds is None:
        elapsed_seconds = existing.get("elapsed_seconds", 0.0)
    data = {
        **existing,
        "status": status.value,
        "message": message,
        "dataset_name": dataset_name or existing.get("dataset_name", ""),
        "n_embedded": n_embedded,
        "n_total": n_total,
        "progress_pct": round(100.0 * n_embedded / max(n_total, 1), 1),
        "throughput_per_sec": round(throughput_per_sec, 1),
        "elapsed_seconds": round(elapsed_seconds, 1),
        "eta_seconds": round(
            max(n_total - n_embedded, 0) / max(throughput_per_sec, 0.1)
        ),
        "log_tail": prev_log,
        "updated_at": time.time(),
        **extra,
    }
    tmp = path.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(data, indent=2))
    tmp.replace(path)  # atomic — readers never see a partial file


def read_status(workspace_dir: str | Path, session_id: str) -> dict:
    path = session_dir(workspace_dir, session_id) / "status.json"
    if not path.exists():
        return {
            "status": IngestionStatus.WAITING.value,
            "message": "Not started",
        }
    try:
        return json.loads(path.read_text())
    except Exception:
        return {"status": "unknown", "message": "Error reading status"}


def is_stop_requested(workspace_dir: str | Path, session_id: str) -> bool:
    return (session_dir(workspace_dir, session_id) / "stop_requested").exists()


def request_stop(workspace_dir: str | Path, session_id: str) -> None:
    p = session_dir(workspace_dir, session_id) / "stop_requested"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text("1")


# ---------------------------------------------------------------------------
# crop extraction
# ---------------------------------------------------------------------------


def _otsu_threshold(img_u8: np.ndarray) -> float:
    """Otsu's method on a uint8 image (scipy/numpy — skimage-free)."""
    hist = np.bincount(img_u8.ravel(), minlength=256).astype(np.float64)
    total = hist.sum()
    w0 = np.cumsum(hist)
    w1 = total - w0
    mu = np.cumsum(hist * np.arange(256))
    mu_t = mu[-1]
    with np.errstate(divide="ignore", invalid="ignore"):
        between = (mu_t * w0 - mu) ** 2 / (w0 * w1)
    between[~np.isfinite(between)] = -1
    return float(np.argmax(between))


def extract_cell_crops(
    image: np.ndarray,
    crop_size: int = 224,
    n_crops: int = 100,
    min_area: int = 200,
    dna_channel: int = 0,
) -> list[np.ndarray]:
    """Find nuclei (threshold + connected components on the DNA
    channel) and crop ``crop_size`` windows around their centroids;
    grid fallback when segmentation finds <10 blobs
    (ref apps/cell-image-search/main.py:668-703)."""
    from scipy import ndimage

    from normalizer import percentile_stretch

    H, W = image.shape[:2]
    half = crop_size // 2
    centroids: list[tuple[int, int]] = []
    try:
        dna = (
            image[..., dna_channel] if image.ndim == 3 else image
        ).astype(np.float32)
        dna_u8 = percentile_stretch(dna)
        mask = dna_u8 > _otsu_threshold(dna_u8)
        labels, n_labels = ndimage.label(mask)
        if n_labels:
            areas = ndimage.sum_labels(
                np.ones_like(labels), labels, index=np.arange(1, n_labels + 1)
            )
            keep = np.where(areas > min_area)[0] + 1
            if keep.size:
                coms = ndimage.center_of_mass(mask, labels, keep.tolist())
                order = np.argsort(-areas[keep - 1])
                centroids = [
                    (int(coms[j][0]), int(coms[j][1])) for j in order
                ][:n_crops]
    except Exception:
        centroids = []
    if len(centroids) < 10:
        stride = max(
            crop_size, min(H, W) // max(1, int(np.sqrt(n_crops)))
        )
        # range() already starts at the first valid CENTER (half) —
        # adding half again offset the whole grid by a half-window,
        # pushing every crop past the image edge whenever crop_size
        # was close to the image size (0 crops out of a valid image)
        centroids = [
            (y, x)
            for y in range(half, H - half + 1, stride)
            for x in range(half, W - half + 1, stride)
        ][:n_crops]
    crops = []
    for cy, cx in centroids[:n_crops]:
        y0, y1 = cy - half, cy + half
        x0, x1 = cx - half, cx + half
        if y0 < 0 or y1 > H or x0 < 0 or x1 > W:
            continue
        crops.append(image[y0:y1, x0:x1])
    return crops


# ---------------------------------------------------------------------------
# image sources
# ---------------------------------------------------------------------------


def make_synthetic_images(
    n_images: int = 8, size: int = 896, n_cells: int = 30, seed: int = 0
):
    """Generator of (name, (H, W) float32) synthetic fluorescence fields
    with gaussian-blob nuclei — the egress-free demo/test source."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[: size, : size]
    for i in range(n_images):
        img = rng.normal(40, 5, (size, size)).astype(np.float32)
        for _ in range(n_cells):
            cy, cx = rng.integers(60, size - 60, 2)
            r = rng.integers(12, 25)
            blob = np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * r**2)))
            img += 400.0 * blob.astype(np.float32)
        yield f"synthetic_{i:04d}", img


def iter_local_images(path: str | Path):
    """Yield (name, array) from a directory of npy/npz/png/tif files."""
    from normalizer import decode_image_bytes

    base = Path(path).expanduser()
    exts = {".npy", ".npz", ".png", ".jpg", ".jpeg", ".tif", ".tiff"}
    for f in sorted(base.rglob("*")):
        if not f.is_file() or f.suffix.lower() not in exts:
            continue
        if f.suffix.lower() == ".npy":
            yield f.name, np.load(f)
        elif f.suffix.lower() == ".npz":
            with np.load(f) as data:
                for key in data.files:
                    yield f"{f.name}:{key}", data[key]
        else:
            yield f.name, decode_image_bytes(f.read_bytes())


async def iter_dataset_images(datasets_client, dataset_name: str):
    """Async generator of (name, array) from the framework datasets
    plane. ``.zarr`` arrays stream chunk-by-chunk over HTTP and yield
    2-D planes (or (C, H, W) channel stacks when the leading axis is
    small); other image files decode from bytes."""
    from normalizer import decode_image_bytes

    files = await datasets_client.list_files(dataset_name)
    img_exts = (".png", ".jpg", ".jpeg", ".tif", ".tiff")
    for f in files:
        fname = f["name"] if isinstance(f, dict) else f
        if fname.endswith(".zarr"):
            handle = await datasets_client.get_file(dataset_name, fname)
            if hasattr(handle, "read"):
                arrays = [handle]
            else:
                arrays = [
                    await handle.array(m) for m in await handle.members()
                ]
            for arr in arrays:
                if arr.ndim == 2:
                    yield fname, await arr.read()
                elif arr.ndim == 3 and arr.shape[0] <= 5:
                    # (C, H, W) multichannel plane
                    yield fname, await arr.read()
                else:
                    # iterate the leading axis as separate planes
                    for z in range(arr.shape[0]):
                        plane = await arr.read(
                            (slice(z, z + 1),)
                        )
                        yield f"{fname}[{z}]", np.squeeze(plane, axis=0)
        elif fname.lower().endswith(img_exts):
            data = await datasets_client.get_file(dataset_name, fname)
            yield fname, decode_image_bytes(data)


# ---------------------------------------------------------------------------
# ingestion runner
# ---------------------------------------------------------------------------


async def run_ingestion(
    *,
    workspace_dir: str | Path,
    session_id: str,
    dataset: dict,
    embedder,
    crop_size: int = 224,
    n_crops_per_image: int = 50,
    batch_bucket: int = 64,
    status_every: float = 2.0,
) -> dict:
    """Stream images → crops → embeddings, then build the index.

    ``dataset``: {"name", "source": "synthetic"|"local"|"datasets",
    "path"/"n_images"...}. Embedding runs in a thread (jax releases the
    GIL during device execution); status.json updates atomically for
    pollers; the stop file aborts between batches.
    """
    t0 = time.time()
    ws = Path(workspace_dir).expanduser()
    name = dataset.get("name", "dataset")
    write_status(
        ws, session_id, IngestionStatus.PREPARING,
        f"Preparing ingestion of '{name}'", dataset_name=name,
    )

    async def _as_async(sync_iter):
        # pull each item off-loop: local-source iteration np.loads /
        # PNG-decodes full images, which would stall query traffic
        # sharing this event loop
        it = iter(sync_iter)
        sentinel = object()
        while True:
            item = await asyncio.to_thread(next, it, sentinel)
            if item is sentinel:
                return
            yield item

    source = dataset.get("source", "synthetic")
    est_total = 0
    if source == "synthetic":
        images = _as_async(
            make_synthetic_images(
                n_images=int(dataset.get("n_images", 8)),
                size=int(dataset.get("image_size", 896)),
                seed=int(dataset.get("seed", 0)),
            )
        )
        est_total = int(dataset.get("n_images", 8)) * n_crops_per_image
    elif source == "local":
        images = _as_async(iter_local_images(dataset["path"]))
    elif source == "datasets":
        client = dataset.get("client")
        if client is None:
            raise ValueError(
                "source 'datasets' needs the deployment's datasets client"
            )
        images = iter_dataset_images(client, dataset["name"])
    else:
        raise ValueError(f"unknown ingestion source '{source}'")

    embeddings: list[np.ndarray] = []
    metadata: list[dict] = []
    pending: list[np.ndarray] = []
    pending_meta: list[dict] = []
    n_embedded = 0
    last_status = 0.0

    def flush():
        nonlocal n_embedded
        if not pending:
            return
        embs = embedder.embed_batch(pending, batch_size=batch_bucket)
        embeddings.append(embs)
        metadata.extend(pending_meta)
        n_embedded += len(pending)
        pending.clear()
        pending_meta.clear()

    async for img_name, img in images:
        if is_stop_requested(ws, session_id):
            write_status(
                ws, session_id, IngestionStatus.STOPPED,
                "Stopped by user", n_embedded=n_embedded,
                n_total=max(est_total, n_embedded),
                elapsed_seconds=time.time() - t0,
            )
            return {"status": "stopped", "n_embedded": n_embedded}
        crops = extract_cell_crops(
            img, crop_size=crop_size, n_crops=n_crops_per_image
        )
        for j, crop in enumerate(crops):
            pending.append(crop)
            pending_meta.append(
                {"dataset": name, "image": img_name, "crop": j}
            )
            if len(pending) >= batch_bucket:
                await asyncio.to_thread(flush)
        now = time.time()
        if now - last_status > status_every:
            last_status = now
            write_status(
                ws, session_id, IngestionStatus.RUNNING,
                f"Embedding '{img_name}'",
                n_embedded=n_embedded,
                n_total=max(est_total, n_embedded + len(pending)),
                throughput_per_sec=n_embedded / max(now - t0, 1e-6),
                elapsed_seconds=now - t0,
                dataset_name=name,
            )
    await asyncio.to_thread(flush)

    if n_embedded == 0:
        write_status(
            ws, session_id, IngestionStatus.FAILED,
            "No cells found in dataset",
            elapsed_seconds=time.time() - t0,
        )
        return {"status": "failed", "n_embedded": 0}

    write_status(
        ws, session_id, IngestionStatus.BUILDING_INDEX,
        f"Building index over {n_embedded} cells",
        n_embedded=n_embedded, n_total=n_embedded,
        elapsed_seconds=time.time() - t0,
    )

    import pandas as pd

    from index import build_index

    all_embeddings = np.vstack(embeddings)
    stats = await asyncio.to_thread(
        build_index, all_embeddings, pd.DataFrame(metadata), ws
    )
    elapsed = time.time() - t0
    write_status(
        ws, session_id, IngestionStatus.COMPLETED,
        f"Ingested {n_embedded} cells in {elapsed:.1f}s",
        n_embedded=n_embedded, n_total=n_embedded,
        throughput_per_sec=n_embedded / max(elapsed, 1e-6),
        elapsed_seconds=elapsed,
        index=stats,
    )
    return {"status": "completed", "n_embedded": n_embedded, **stats}


# ---------------------------------------------------------------------------
# dataset registry (ref main.py:975-1026)
# ---------------------------------------------------------------------------


def registry_path(workspace_dir: str | Path) -> Path:
    return Path(workspace_dir).expanduser() / "dataset_registry.json"


def load_registry(workspace_dir: str | Path) -> list[dict]:
    p = registry_path(workspace_dir)
    if not p.exists():
        return []
    try:
        return json.loads(p.read_text())
    except Exception:
        return []


def save_registry(workspace_dir: str | Path, registry: list[dict]) -> None:
    p = registry_path(workspace_dir)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(registry, indent=2))
    tmp.replace(p)


def upsert_registry(workspace_dir: str | Path, entry: dict) -> None:
    registry = load_registry(workspace_dir)
    registry = [r for r in registry if r.get("name") != entry.get("name")]
    registry.append(entry)
    save_registry(workspace_dir, registry)
