"""Mitochondria Analysis — tiled 2D EM segmentation, TPU edition.

Capability parity with the reference
(ref apps/fibsem-mito-analysis/analysis_deployment.py:1-286): tile a
large EM image, delegate probability-map inference to the deployed
model-runner service, stitch with Gaussian-blended accumulation,
threshold → close → split → per-instance morphology.

TPU redesign:
- **Batched tile inference**: the reference round-trips one tile per
  request through S3 (ref :88-108). Here tiles are stacked into one
  (N, 1, t, t) array and sent in a single RPC — the model-runner's
  runtime executes the whole batch as one jitted XLA call, keeping the
  MXU fed instead of paying per-tile dispatch + network latency.
- **App→app composition over the framework RPC** (the reference's
  Hypha get_service pattern): arrays travel in-band, no S3 presign hop.
- Post-processing is scipy/numpy only (no skimage in the image):
  Otsu-free fixed threshold as in the reference, small-object removal
  via labeled areas, binary closing, instance splitting by
  distance-transform peaks + nearest-peak assignment, and
  moments-based regionprops (area / centroid / axis lengths /
  eccentricity — same fields as skimage's).
"""

from __future__ import annotations

import asyncio
import os
import time
from datetime import datetime
from typing import Optional

import numpy as np

from bioengine_tpu.rpc import schema_method


class MitoAnalysis:
    def __init__(
        self,
        model_runner_service: str = "bioengine/model-runner",
        model_id: str = "tiny-unet",
        server_url: Optional[str] = None,
        batch_size: int = 8,
        input_layout: str = "NCHW",
    ) -> None:
        self.start_time = time.time()
        self.model_runner_service = model_runner_service
        self.model_id = model_id
        if input_layout not in ("NCHW", "NHWC"):
            raise ValueError(f"input_layout must be NCHW or NHWC")
        self.input_layout = input_layout
        self.server_url = server_url or os.environ.get(
            "BIOENGINE_SERVER_URL"
        )
        self.batch_size = batch_size
        self._model_runner = None
        self._connection = None

    # ---- lifecycle ---------------------------------------------------------

    async def async_init(self) -> None:
        from bioengine_tpu.rpc.client import connect_to_server

        if self.server_url is None:
            raise RuntimeError(
                "no server_url configured (param or BIOENGINE_SERVER_URL)"
            )
        token = os.environ.get("BIOENGINE_TOKEN") or os.environ.get(
            "HYPHA_TOKEN"
        )
        self._connection = await connect_to_server(
            {"server_url": self.server_url, "token": token}
        )
        self._model_runner = await self._connection.get_service(
            self.model_runner_service
        )

    async def test_deployment(self) -> None:
        test_img = np.random.rand(64, 64).astype(np.float32)
        prob = await self._infer_batch(test_img[None])
        assert prob.shape == (1, 64, 64), f"unexpected shape {prob.shape}"

    async def check_health(self) -> None:
        if self._model_runner is None:
            raise RuntimeError("model-runner not connected")

    async def close(self) -> None:
        if self._connection is not None:
            await self._connection.disconnect()
            self._connection = None

    # ---- inference ---------------------------------------------------------

    async def _infer_batch(self, tiles: np.ndarray) -> np.ndarray:
        """(N, h, w) float32 → (N, h, w) probability maps, one RPC."""
        if self.input_layout == "NCHW":
            inp = tiles[:, None].astype(np.float32)  # (N, 1, h, w)
        else:
            inp = tiles[..., None].astype(np.float32)  # (N, h, w, 1)
        result = await self._model_runner.infer(
            model_id=self.model_id, inputs=inp
        )
        out = result[next(iter(result))] if isinstance(result, dict) else result
        out = np.asarray(out, np.float32)
        # normalize layouts: (N,1,h,w) / (N,h,w,1) / (N,h,w)
        if out.ndim == 4 and out.shape[1] == 1:
            out = out[:, 0]
        elif out.ndim == 4 and out.shape[-1] == 1:
            out = out[..., 0]
        return out

    async def _infer_tiled(
        self,
        image_norm: np.ndarray,
        tile_size: int = 512,
        overlap: int = 64,
    ) -> np.ndarray:
        """Tile → batched inference → Gaussian-blended stitch
        (ref analysis_deployment.py:110-157, batched here)."""
        H, W = image_norm.shape
        if not 0 <= overlap < tile_size:
            raise ValueError(
                f"overlap ({overlap}) must be in [0, tile_size={tile_size})"
            )
        stride = tile_size - overlap

        yy = np.linspace(-1, 1, tile_size)
        xx = np.linspace(-1, 1, tile_size)
        weight_win = np.outer(
            np.exp(-2 * yy**2), np.exp(-2 * xx**2)
        ).astype(np.float32)

        coords = [
            (y0, x0)
            for y0 in range(0, H, stride)
            for x0 in range(0, W, stride)
        ]
        tiles = np.empty((len(coords), tile_size, tile_size), np.float32)
        spans = []
        for n, (y0, x0) in enumerate(coords):
            y1, x1 = min(y0 + tile_size, H), min(x0 + tile_size, W)
            tile = image_norm[y0:y1, x0:x1]
            th, tw = tile.shape
            if th < tile_size or tw < tile_size:
                tile = np.pad(
                    tile,
                    ((0, tile_size - th), (0, tile_size - tw)),
                    mode="reflect",
                )
            tiles[n] = tile
            spans.append((y0, x0, th, tw))

        prob_acc = np.zeros((H, W), np.float64)
        weight_acc = np.zeros((H, W), np.float64)
        for i in range(0, len(tiles), self.batch_size):
            probs = await self._infer_batch(tiles[i : i + self.batch_size])
            for j, prob in enumerate(probs):
                y0, x0, th, tw = spans[i + j]
                w = weight_win[:th, :tw]
                prob_acc[y0 : y0 + th, x0 : x0 + tw] += prob[:th, :tw] * w
                weight_acc[y0 : y0 + th, x0 : x0 + tw] += w
        return np.divide(
            prob_acc,
            weight_acc,
            out=np.zeros_like(prob_acc),
            where=weight_acc > 0,
        ).astype(np.float32)

    # ---- post-processing (scipy/numpy only) --------------------------------

    @staticmethod
    def _remove_small(binary: np.ndarray, min_size: int) -> np.ndarray:
        from scipy import ndimage as ndi

        labels, n = ndi.label(binary)
        if not n:
            return binary
        areas = ndi.sum_labels(
            np.ones_like(labels), labels, index=np.arange(1, n + 1)
        )
        keep = np.zeros(n + 1, bool)
        keep[1:] = areas >= min_size
        return keep[labels]

    @staticmethod
    def _peak_markers(
        dist: np.ndarray, mask: np.ndarray, min_distance: int = 8
    ) -> np.ndarray:
        """Local maxima of the distance transform → labeled markers."""
        from scipy import ndimage as ndi

        size = 2 * min_distance + 1
        maxf = ndi.maximum_filter(dist, size=size)
        peaks = (dist == maxf) & mask & (dist > 1.0)
        markers, _ = ndi.label(peaks)
        return markers

    @classmethod
    def _prob_to_instances(cls, prob: np.ndarray) -> np.ndarray:
        """Threshold → close → remove small → distance peaks →
        nearest-peak instance assignment (watershed analog,
        ref analysis_deployment.py:161-177)."""
        from scipy import ndimage as ndi

        binary = cls._remove_small(prob > 0.5, min_size=300)
        if not binary.any():
            return np.zeros(prob.shape, np.int32)
        closed = ndi.binary_closing(
            binary, structure=ndi.generate_binary_structure(2, 2),
            iterations=2,
        )
        dist = ndi.distance_transform_edt(closed)
        markers = cls._peak_markers(dist, closed)
        if markers.max() == 0:
            labels, _ = ndi.label(closed)
            return labels.astype(np.int32)
        # assign every mask pixel to its nearest marker (voronoi split
        # by euclidean distance — the watershed approximation)
        _, (iy, ix) = ndi.distance_transform_edt(
            markers == 0, return_indices=True
        )
        labels = np.where(closed, markers[iy, ix], 0)
        return labels.astype(np.int32)

    @staticmethod
    def _region_properties(labels: np.ndarray, pixel_um: float) -> dict:
        """Moments-based per-instance morphology — area, centroid,
        major/minor axis lengths, eccentricity, aspect ratio (the
        skimage.regionprops fields the reference reports,
        ref analysis_deployment.py:259-276)."""
        from scipy import ndimage as ndi

        n = int(labels.max())
        out = {
            "label": [], "area_um2": [], "aspect_ratio": [],
            "eccentricity": [], "centroid_y": [], "centroid_x": [],
        }
        # per-label bounding boxes: each instance is measured on its own
        # window instead of rescanning the full image per label
        slices = ndi.find_objects(labels) if n else []
        for lbl, sl in enumerate(slices, start=1):
            if sl is None:
                continue
            ys, xs = np.nonzero(labels[sl] == lbl)
            area = len(ys)
            if area == 0:
                continue
            ys = ys + sl[0].start
            xs = xs + sl[1].start
            cy, cx = ys.mean(), xs.mean()
            dy, dx = ys - cy, xs - cx
            # central second moments (+1/12 pixel-integration term,
            # matching skimage's definition)
            myy = dy @ dy / area + 1 / 12
            mxx = dx @ dx / area + 1 / 12
            mxy = dy @ dx / area
            common = np.sqrt(((mxx - myy) / 2) ** 2 + mxy**2)
            l1 = (mxx + myy) / 2 + common
            l2 = (mxx + myy) / 2 - common
            major = 4 * np.sqrt(max(l1, 0))
            minor = 4 * np.sqrt(max(l2, 0))
            ecc = np.sqrt(1 - l2 / l1) if l1 > 0 else 0.0
            out["label"].append(lbl)
            out["area_um2"].append(float(area) * pixel_um**2)
            out["aspect_ratio"].append(float(major / (minor + 1e-6)))
            out["eccentricity"].append(float(ecc))
            out["centroid_y"].append(float(cy))
            out["centroid_x"].append(float(cx))
        return out

    # ---- public API --------------------------------------------------------

    @schema_method
    async def ping(self, context=None) -> dict:
        """Service status + the delegated model."""
        return {
            "status": "ok",
            "model": self.model_id,
            "model_runner": self.model_runner_service,
            "uptime_s": round(time.time() - self.start_time, 1),
            "timestamp": datetime.now().isoformat(),
        }

    @schema_method
    async def analyze(
        self,
        image,
        pixel_size_nm: float = 5.0,
        tile_size: int = 512,
        overlap: int = 64,
        context=None,
    ) -> dict:
        """Segment mitochondria in a 2D grayscale EM image.

        ``image``: (H, W) array (uint8 or float). Returns instance
        ``labels`` (H x W int32 array), per-instance ``properties`` (area_um2,
        aspect_ratio, eccentricity, centroids), ``n_mitochondria``,
        ``image_shape``, ``pixel_size_nm``, ``model``, and
        ``processing_time_s``.
        """
        t0 = time.time()
        image_np = np.asarray(image, np.float32)
        if image_np.ndim != 2:
            raise ValueError(
                f"expected 2-D image, got shape {image_np.shape}"
            )
        H, W = image_np.shape
        p1, p99 = np.percentile(image_np, [1, 99])
        image_norm = np.clip(
            (image_np - p1) / (p99 - p1 + 1e-6), 0, 1
        ).astype(np.float32)

        if H <= tile_size and W <= tile_size:
            prob = (await self._infer_batch(image_norm[None]))[0]
        else:
            prob = await self._infer_tiled(
                image_norm, tile_size=tile_size, overlap=overlap
            )

        labels = self._prob_to_instances(prob)
        n_mito = int(labels.max())
        pixel_um = pixel_size_nm / 1000.0
        properties = self._region_properties(labels, pixel_um)

        return {
            # int32 ndarray — the RPC codec carries arrays natively; a
            # nested-list blowup of a 4k x 4k label image would be
            # hundreds of MB of Python objects
            "labels": labels,
            "properties": properties,
            "n_mitochondria": n_mito,
            "image_shape": [H, W],
            "pixel_size_nm": pixel_size_nm,
            "model": self.model_id,
            "processing_time_s": round(time.time() - t0, 2),
        }
