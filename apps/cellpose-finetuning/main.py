"""Cellpose fine-tuning on TPU — training sessions, live inference, export.

The reference (ref apps/cellpose-finetuning/main.py, 5211 LoC) fine-tunes
Cellpose-SAM on exactly one GPU through a re-implemented torch train loop
with callbacks, a stop-file check, per-epoch snapshots feeding live
inference, and a ``status.json`` session protocol polled by the browser
frontend (:1740-1900, :1278-1360, :3682-4966). This TPU rebuild keeps the
session protocol — session dirs, ``status.json``, STOP file, per-epoch
snapshots, restart-from-snapshot — and replaces the compute:

- ``CellposeNet`` (bioengine_tpu/models/cellpose.py), a JAX/optax train
  step jitted **data-parallel over every local chip** via
  ``jit_data_parallel_step`` — gradients all-reduce over ICI, a
  capability the reference does not have (SURVEY.md §2.3).
- Training targets (flow fields) from instance masks via
  ``ops.flows.masks_to_flows`` on host, once per session.
- Snapshots are flat-npz ``jax_params`` — the exact weight format the
  model-runner app serves, so ``export_model`` emits a ready-to-serve
  BioImage-Model-Zoo-style package.
"""

import asyncio
import contextlib
import json
import shutil
import time
import uuid
from pathlib import Path
from typing import Optional

import numpy as np
import yaml

from bioengine_tpu.rpc import schema_method

# session states with no train thread behind them anymore
_TERMINAL_STATES = ("completed", "failed", "stopped", "interrupted")

DEFAULT_CONFIG = {
    # "unet" = CellposeNet (residual U-Net); "sam" = CellposeSAM, the
    # transformer-backbone family member (models/cellpose_sam.py);
    # "cpsam" = models/sam.CpSAM, the faithful pretrained Cellpose-SAM
    # architecture (SAM ViT encoder + readout) — set "pretrained_path"
    # to a converted checkpoint (runtime.convert.convert_checkpoint /
    # `bioengine models convert --arch cpsam`) to fine-tune from the
    # foundation weights like the reference does
    # (ref apps/cellpose-finetuning/main.py:2248, model_type="cpsam");
    # "stardist" = models/stardist.StarDist2D, star-convex polygons
    # (prob + ray-distance heads) instead of flow fields — a capability
    # the reference app does not have (it is cellpose-only)
    "backbone": "unet",
    "features": [32, 64, 128, 256],      # unet/stardist backbones
    "patch_size": 8,                      # sam/cpsam backbones
    "dim": 256,
    "depth": 8,
    "num_heads": 8,
    "n_rays": 32,                         # stardist backbone (even)
    "max_dist": 64,                       # stardist ray-length cap (px):
    #   raise it when instances exceed ~64 px radius or ray targets (and
    #   therefore predicted polygons) truncate at the cap
    "pretrained_path": None,              # flat-npz jax_params to start from
    "learning_rate": 1e-4,
    "weight_decay": 1e-5,
    "epochs": 10,
    "batch_size": 8,
    "tile": 128,
    "seed": 0,
}

# cpsam-only architecture knobs, overridable in config; the defaults in
# models/sam.py are the ViT-L checkpoint shape
_CPSAM_KEYS = (
    "window_size", "global_attn_indexes", "neck_dim", "pretrain_grid",
    "mlp_ratio",
)


# the pretrained cpsam checkpoint shape (ViT-L @ patch 8). When the
# user selects backbone "cpsam" these beat DEFAULT_CONFIG's small
# unet/sam sizes — otherwise the documented minimal config
# {"backbone": "cpsam", "pretrained_path": ...} would silently build a
# dim-256/depth-8 model and reject every real checkpoint.
_CPSAM_ARCH_DEFAULTS = {
    "patch_size": 8, "dim": 1024, "depth": 24, "num_heads": 16,
    "tile": 256,
}


def _merge_config(config: Optional[dict]) -> dict:
    config = dict(config or {})
    cfg = {**DEFAULT_CONFIG, **config}
    if cfg.get("backbone") == "cpsam":
        for k, v in _CPSAM_ARCH_DEFAULTS.items():
            if k not in config:
                cfg[k] = v
    if cfg.get("backbone") == "stardist":
        n_rays = float(cfg["n_rays"])
        if not n_rays.is_integer() or n_rays < 2 or int(n_rays) % 2:
            # reject HERE, synchronously in start_training — target
            # derivation is the expensive step and must not run for a
            # config the train loop would refuse anyway (and int()
            # truncation must not silently accept 8.9 as 8)
            raise ValueError(
                f"n_rays must be an even integer >= 2, got {cfg['n_rays']}"
            )
        cfg["n_rays"] = int(n_rays)
    return cfg


def _model_channels(cfg: dict) -> int:
    """cpsam is a 3-channel model (its pretrained patch embedding is
    3-channel); the app's prepared batches are [cyto, nucleus] and get
    a zero third channel at the model boundary."""
    return 3 if cfg.get("backbone") == "cpsam" else 2


def _to_model_channels(x: np.ndarray, cfg: dict) -> np.ndarray:
    c = _model_channels(cfg)
    if x.shape[-1] == c:
        return x
    pad = np.zeros((*x.shape[:-1], c - x.shape[-1]), x.dtype)
    return np.concatenate([x, pad], axis=-1)


def _flat_shapes(tree: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            out.update(_flat_shapes(v, f"{prefix}{k}/"))
        else:
            out[f"{prefix}{k}"] = tuple(v.shape)
    return out


def _check_pretrained_tree(params: dict, expect: dict) -> None:
    """Loud structural validation of a pretrained checkpoint against the
    configured architecture: missing/unexpected leaves and shape
    mismatches name themselves instead of failing deep inside jit.
    Position/rel-pos tables are declared at their checkpoint extent
    (``pretrain_grid``/``window_size`` config) and resized at apply, so
    exact shape equality is the correct check for every leaf."""
    got, want = _flat_shapes(params), _flat_shapes(expect)
    missing = sorted(set(want) - set(got))
    extra = sorted(set(got) - set(want))
    bad = [
        f"{k}: checkpoint {got[k]} vs model {want[k]}"
        for k in sorted(set(got) & set(want))
        if got[k] != want[k]
    ]
    if missing or extra or bad:
        raise ValueError(
            "pretrained_path does not match the configured architecture: "
            f"missing={missing[:5]} unexpected={extra[:5]} "
            f"shape_mismatch={bad[:5]}"
        )


def build_model(cfg: dict):
    """(model, divisor) for the configured backbone.

    The cellpose family (unet/sam/cpsam) shares one output contract —
    (B, H, W, 3) flow/cellprob logits — so its train step, loss, and
    flow postprocessing are backbone-agnostic. The stardist backbone
    emits (B, H, W, 1 + n_rays) prob/ray logits instead: adding a
    backbone with its own output contract means wiring ALL of
    _prepare_training_data (targets), _train_loop (step + aug),
    _infer (postprocessing), and infer_3d (support or reject), the way
    the stardist branches in each of those do."""
    backbone = cfg.get("backbone", "unet")
    if backbone == "cpsam":
        from bioengine_tpu.models.sam import CpSAM

        kw = {k: cfg[k] for k in _CPSAM_KEYS if k in cfg}
        if "global_attn_indexes" in kw:
            kw["global_attn_indexes"] = tuple(kw["global_attn_indexes"])
        model = CpSAM(
            patch_size=int(cfg.get("patch_size", 8)),
            dim=int(cfg.get("dim", 1024)),
            depth=int(cfg.get("depth", 24)),
            num_heads=int(cfg.get("num_heads", 16)),
            **kw,
        )
        return model, model.divisor
    if backbone == "sam":
        from bioengine_tpu.models.cellpose_sam import CellposeSAM

        model = CellposeSAM(
            patch_size=int(cfg.get("patch_size", 8)),
            dim=int(cfg.get("dim", 256)),
            depth=int(cfg.get("depth", 8)),
            num_heads=int(cfg.get("num_heads", 8)),
            in_channels=2,
        )
        return model, model.divisor
    if backbone == "stardist":
        from bioengine_tpu.models.stardist import StarDist2D

        # always merged by _merge_config (which also rejects odd counts
        # — the horizontal-flip augmentation permutes ray indices by
        # (n_rays/2 - r) mod n_rays, only a bijection for even counts)
        model = StarDist2D(
            n_rays=int(cfg["n_rays"]), features=tuple(cfg["features"]),
            in_channels=2,
        )
        return model, model.divisor
    from bioengine_tpu.models.cellpose import CellposeNet

    model = CellposeNet(features=tuple(cfg["features"]), in_channels=2)
    return model, 2 ** (len(cfg["features"]) - 1)


def _arch_entry(cfg: dict) -> dict:
    """rdf.yaml architecture stanza for the configured backbone — the
    registry name + kwargs the model-runner uses to rebuild it."""
    backbone = cfg.get("backbone", "unet")
    if backbone == "cpsam":
        kw = {
            "patch_size": int(cfg.get("patch_size", 8)),
            "dim": int(cfg.get("dim", 1024)),
            "depth": int(cfg.get("depth", 24)),
            "num_heads": int(cfg.get("num_heads", 16)),
        }
        for k in _CPSAM_KEYS:
            if k in cfg:
                kw[k] = (
                    list(cfg[k]) if k == "global_attn_indexes" else cfg[k]
                )
        return {"name": "cpsam", "kwargs": kw}
    if backbone == "sam":
        return {
            "name": "cellpose-sam",
            "kwargs": {
                "patch_size": int(cfg.get("patch_size", 8)),
                "dim": int(cfg.get("dim", 256)),
                "depth": int(cfg.get("depth", 8)),
                "num_heads": int(cfg.get("num_heads", 8)),
                "in_channels": 2,
            },
        }
    if backbone == "stardist":
        return {
            "name": "stardist2d",
            "kwargs": {
                "n_rays": int(cfg["n_rays"]),
                "features": list(cfg["features"]),
                "in_channels": 2,
            },
        }
    return {
        "name": "cellpose",
        "kwargs": {"features": list(cfg["features"]), "in_channels": 2},
    }


def _now() -> float:
    return time.time()


class TrainingSession:
    """One fine-tune run: a directory with status.json, snapshots, STOP."""

    def __init__(self, root: Path, session_id: str, config: dict):
        self.session_id = session_id
        self.dir = root / session_id
        self.models_dir = self.dir / "models"
        self.data_dir = self.dir / "data"
        self.models_dir.mkdir(parents=True, exist_ok=True)
        self.data_dir.mkdir(parents=True, exist_ok=True)
        self.config = config
        self.task: asyncio.Task | None = None
        # True while start_training is still writing this session's data
        self.preparing = False

    # ---- status.json protocol (ref main.py:1740-1900) --------------------

    @property
    def status_path(self) -> Path:
        return self.dir / "status.json"

    @property
    def stop_path(self) -> Path:
        return self.dir / "STOP"

    def read_status(self) -> dict:
        try:
            return json.loads(self.status_path.read_text())
        except (OSError, json.JSONDecodeError):
            return {"session_id": self.session_id, "status": "unknown"}

    def write_status(self, **updates) -> dict:
        status = self.read_status()
        status.update(updates, session_id=self.session_id, updated_at=_now())
        tmp = self.status_path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(status))
        tmp.rename(self.status_path)
        return status

    def stop_requested(self) -> bool:
        return self.stop_path.exists()

    # ---- snapshots -------------------------------------------------------

    def snapshot_path(self, epoch: int) -> Path:
        return self.models_dir / f"epoch_{epoch:04d}.npz"

    @property
    def latest_path(self) -> Path:
        return self.models_dir / "latest.npz"

    def save_snapshot(self, epoch: int, params) -> None:
        from bioengine_tpu.runtime.convert import save_params_npz

        path = self.snapshot_path(epoch)
        save_params_npz(str(path), params)
        tmp = self.latest_path.with_suffix(".npz.tmp")
        shutil.copyfile(path, tmp)
        tmp.rename(self.latest_path)  # atomic: live inference never sees a partial file

    def snapshots(self) -> list[str]:
        return sorted(p.name for p in self.models_dir.glob("epoch_*.npz"))

    @property
    def train_state_path(self) -> Path:
        """Full TrainState (params + optimizer moments + step) so resume
        continues adamw where it left off instead of re-warming."""
        return self.models_dir / "train_state.msgpack"

    def save_train_state(self, state_bytes: bytes) -> None:
        tmp = self.train_state_path.with_suffix(".msgpack.tmp")
        tmp.write_bytes(state_bytes)
        tmp.rename(self.train_state_path)


class CellposeFinetune:
    def __init__(self, sessions_root: str = "~/.bioengine/cellpose-sessions"):
        self.sessions_root = Path(sessions_root).expanduser()
        self.sessions_root.mkdir(parents=True, exist_ok=True)
        self.sessions: dict[str, TrainingSession] = {}
        # serializes start/stop/restart/delete per session id — the busy
        # check can suspend (waiting out a task wind-down), so without
        # a lock two callers could both pass it and then both mutate.
        # value = [lock, refcount]; the entry is reclaimed when the last
        # holder/waiter leaves, so ids probed once don't accumulate
        self._locks: dict[str, list] = {}
        self._fwd_cache: dict[tuple, object] = {}  # features -> jitted forward
        self._recover_sessions()

    @contextlib.asynccontextmanager
    async def _lifecycle_lock(self, session_id: str):
        entry = self._locks.setdefault(session_id, [asyncio.Lock(), 0])
        entry[1] += 1
        try:
            async with entry[0]:
                yield
        finally:
            entry[1] -= 1
            if entry[1] == 0 and self._locks.get(session_id) is entry:
                del self._locks[session_id]

    def _recover_sessions(self) -> None:
        """Re-adopt session dirs from a previous replica life (the
        reference recovers sessions from disk the same way; training
        tasks do not survive, so running ones become 'interrupted')."""
        for d in self.sessions_root.iterdir():
            if d.name.startswith("."):
                # a '.{name}.deleting-*' dir is a failed start_training's
                # renamed-away tree whose threaded rmtree didn't finish
                # (crash/restart mid-delete) — sweep it, never adopt it.
                # Only OUR rename pattern: any other hidden directory
                # (.cache, .snapshots, ...) is not ours to delete.
                if ".deleting-" in d.name and d.is_dir():
                    shutil.rmtree(d, ignore_errors=True)
                continue
            if (d / "status.json").exists():
                try:
                    cfg = json.loads((d / "config.json").read_text())
                except (OSError, json.JSONDecodeError):
                    cfg = dict(DEFAULT_CONFIG)
                s = TrainingSession(self.sessions_root, d.name, cfg)
                if s.read_status().get("status") == "training":
                    s.write_status(
                        status="interrupted",
                        error="worker restarted during training",
                    )
                self.sessions[d.name] = s

    async def check_health(self):
        if not self.sessions_root.exists():
            raise RuntimeError("sessions root vanished")

    # ---- data handling ---------------------------------------------------

    @staticmethod
    def _prepare_images(images: list) -> np.ndarray:
        """-> (N, H, W, 2) float32, per-image 1-99 percentile normalized.
        Grayscale gets a zero second channel (cellpose channel
        convention: [cyto, nucleus])."""
        out = []
        for img in images:
            # always copy: normalization below is in-place and must not
            # write through to the caller's array
            a = np.array(img, np.float32, copy=True)
            if a.ndim == 2:
                a = np.stack([a, np.zeros_like(a)], axis=-1)
            elif a.ndim == 3 and a.shape[-1] == 1:
                a = np.concatenate([a, np.zeros_like(a)], axis=-1)
            elif a.ndim == 3 and a.shape[-1] > 2:
                a = a[..., :2]
            # per-channel percentiles — mixed-bit-depth channels (8-bit
            # cyto + 16-bit nucleus) must each land in [0, 1]
            for c in range(a.shape[-1]):
                lo, hi = np.percentile(a[..., c], [1, 99])
                a[..., c] = (a[..., c] - lo) / max(hi - lo, 1e-6)
            out.append(a)
        return np.stack(out)

    def _prepare_training_data(
        self, session: TrainingSession, images: list, labels: list
    ) -> None:
        """Normalize images, derive the backbone's targets from masks
        (flow fields for cellpose-family backbones, edt-prob +
        ray-distances for stardist), persist to the session's data dir
        (restart_training reuses them)."""
        x = self._prepare_images(images)
        masks = np.stack([np.asarray(m) for m in labels]).astype(np.int32)
        if masks.shape[:3] != x.shape[:3]:
            raise ValueError(
                f"images {x.shape[:3]} and labels {masks.shape[:3]} disagree"
            )
        if session.config.get("backbone") == "stardist":
            from bioengine_tpu.ops.stardist import masks_to_stardist

            cfg = session.config
            pairs = [
                masks_to_stardist(
                    m,
                    n_rays=int(cfg["n_rays"]),
                    max_dist=int(cfg["max_dist"]),
                )
                for m in masks
            ]
            targets = {
                "prob": np.stack([p for p, _ in pairs]),       # (N, H, W)
                "dist": np.stack([d for _, d in pairs]),       # (N, H, W, R)
            }
        else:
            from bioengine_tpu.ops.flows import masks_to_flows

            flows = np.stack([masks_to_flows(m) for m in masks])
            targets = {
                "flows": np.moveaxis(flows, 1, -1),            # (N, H, W, 2)
                "cellprob": (masks > 0).astype(np.float32),    # (N, H, W)
            }
        np.savez(session.data_dir / "train.npz", images=x, **targets)

    # ---- the train loop (runs in a thread) -------------------------------

    def _train_loop(self, session: TrainingSession, resume: bool) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from bioengine_tpu.models.cellpose import TrainState, make_train_step
        from bioengine_tpu.parallel.data_parallel import (
            jit_data_parallel_step, replicate, shard_batch,
        )
        from bioengine_tpu.parallel.mesh import make_mesh
        from bioengine_tpu.runtime.convert import load_params_npz

        cfg = session.config
        stardist = cfg.get("backbone") == "stardist"
        data = np.load(session.data_dir / "train.npz")
        images = data["images"]
        if stardist:
            t_a, t_b = data["prob"], data["dist"]          # (N,H,W), (N,H,W,R)
        else:
            t_a, t_b = data["flows"], data["cellprob"]     # (N,H,W,2), (N,H,W)
        n, H, W = images.shape[:3]
        model, divisor = build_model(cfg)
        # tile must divide through the encoder (pool stages / patch
        # grid) or the decoder output misaligns
        tile = min(cfg["tile"], H, W)
        if tile < divisor:
            raise ValueError(
                f"images ({H}x{W}) smaller than the model's minimum tile "
                f"{divisor} for this backbone config"
            )
        tile = (tile // divisor) * divisor

        # dp over every local chip that divides the batch
        n_dev = jax.local_device_count()
        batch = cfg["batch_size"]
        dp = 1
        while dp * 2 <= n_dev and batch % (dp * 2) == 0:
            dp *= 2
        mesh = make_mesh({"dp": dp}, jax.devices()[:dp])

        rng = np.random.default_rng(cfg["seed"])
        start_epoch = 0
        restored_state = None
        tx = optax.adamw(cfg["learning_rate"], weight_decay=cfg["weight_decay"])
        if resume and session.latest_path.exists():
            from flax import serialization

            params = load_params_npz(str(session.latest_path))
            start_epoch = len(session.snapshots())
            if session.train_state_path.exists():
                template = TrainState.create(model.apply, params, tx)
                restored_state = serialization.from_bytes(
                    template, session.train_state_path.read_bytes()
                )
        elif cfg.get("pretrained_path"):
            # fine-tune from converted foundation weights (the
            # reference's whole value proposition: start from cpsam,
            # ref main.py:2248) — validate the tree against the
            # architecture cheaply via eval_shape so a wrong checkpoint
            # fails loudly naming the mismatched leaves, not deep in jit
            params = load_params_npz(cfg["pretrained_path"])
            expect = jax.eval_shape(
                lambda: model.init(
                    jax.random.key(0),
                    jnp.zeros(
                        (1, tile, tile, _model_channels(cfg)), jnp.float32
                    ),
                )
            )["params"]
            _check_pretrained_tree(params, expect)
        else:
            params = model.init(
                jax.random.key(cfg["seed"]),
                jnp.zeros((1, tile, tile, _model_channels(cfg)), jnp.float32),
            )["params"]
        state = replicate(
            mesh,
            restored_state
            if restored_state is not None
            else TrainState.create(model.apply, params, tx),
        )
        if stardist:
            from bioengine_tpu.models.stardist import make_stardist_train_step

            step = jit_data_parallel_step(make_stardist_train_step(), mesh)
            R = t_b.shape[-1]
            # flips permute ray indices: rays live at angles 2*pi*r/R
            # with direction (sin, cos); x -> -x maps theta to pi-theta
            # (index R/2 - r), y -> -y maps theta to -theta (index -r)
            h_perm = (R // 2 - np.arange(R)) % R
            v_perm = (-np.arange(R)) % R
        else:
            step = jit_data_parallel_step(make_train_step(), mesh)

        def sample_batch():
            idx = rng.integers(0, n, size=batch)
            ys = rng.integers(0, H - tile + 1, size=batch)
            xs = rng.integers(0, W - tile + 1, size=batch)
            bi = np.empty((batch, tile, tile, 2), np.float32)
            ba = np.empty((batch, tile, tile, *t_a.shape[3:]), np.float32)
            bb = np.empty((batch, tile, tile, *t_b.shape[3:]), np.float32)
            for j, (i, y0, x0) in enumerate(zip(idx, ys, xs)):
                sl = np.s_[y0 : y0 + tile, x0 : x0 + tile]
                im, ta, tb = images[i][sl], t_a[i][sl], t_b[i][sl]
                if rng.random() < 0.5:  # horizontal flip
                    im, ta, tb = im[:, ::-1], ta[:, ::-1], tb[:, ::-1]
                    if stardist:
                        tb = tb[..., h_perm]       # dist rays remap
                    else:
                        ta = ta * np.array([1.0, -1.0], np.float32)  # x-flow
                if rng.random() < 0.5:  # vertical flip
                    im, ta, tb = im[::-1], ta[::-1], tb[::-1]
                    if stardist:
                        tb = tb[..., v_perm]
                    else:
                        ta = ta * np.array([-1.0, 1.0], np.float32)  # y-flow
                bi[j], ba[j], bb[j] = im, ta, tb
            return _to_model_channels(bi, cfg), ba, bb

        steps_per_epoch = max(1, n * max(H // tile, 1) * max(W // tile, 1) // batch)
        session.write_status(
            status="training",
            total_epochs=cfg["epochs"],
            current_epoch=start_epoch,
            steps_per_epoch=steps_per_epoch,
            mesh={"dp": dp},
        )
        losses = session.read_status().get("losses", [])
        for epoch in range(start_epoch, cfg["epochs"]):
            epoch_losses = []
            for _ in range(steps_per_epoch):
                if session.stop_requested():
                    session.write_status(status="stopped", current_epoch=epoch)
                    return
                bi, ba, bb = sample_batch()
                sharded = shard_batch(
                    mesh, (jnp.asarray(bi), jnp.asarray(ba), jnp.asarray(bb))
                )
                state, metrics = step(state, *sharded)
                epoch_losses.append(float(metrics["loss"]))
            mean_loss = float(np.mean(epoch_losses))
            losses.append(mean_loss)
            # per-epoch snapshot feeds live inference (ref main.py:1825-1835)
            session.save_snapshot(epoch, jax.device_get(state.params))
            from flax import serialization

            session.save_train_state(
                serialization.to_bytes(jax.device_get(state))
            )
            session.write_status(
                status="training",
                current_epoch=epoch + 1,
                losses=losses,
                last_loss=mean_loss,
            )
        session.write_status(status="completed", current_epoch=cfg["epochs"])

    async def _run_training(self, session: TrainingSession, resume: bool):
        try:
            await asyncio.to_thread(self._train_loop, session, resume)
        except Exception as e:
            session.write_status(status="failed", error=str(e))

    # ---- service API ------------------------------------------------------

    @schema_method
    async def get_default_config(self, context=None):
        """Training hyperparameters and their defaults."""
        return dict(DEFAULT_CONFIG)

    @schema_method
    async def start_training(
        self,
        train_images: list,
        train_labels: list,
        config: dict | None = None,
        session_id: str | None = None,
        context=None,
    ):
        """Start a fine-tuning session. ``train_images``: list of (H, W)
        or (H, W, C) arrays; ``train_labels``: instance-label masks of
        the same spatial shape. Returns the session id to poll with
        ``get_training_status``."""
        cfg = _merge_config(config)
        session_id = session_id or f"session-{uuid.uuid4().hex[:8]}"
        async with self._lifecycle_lock(session_id):
            existing = self.sessions.get(session_id)
            if existing is not None and await self._busy(existing):
                raise RuntimeError(f"session '{session_id}' already training")
            # a reused id is a fresh run: stale snapshots/data would poison
            # restart_training's epoch counting and live inference
            old_dir = self.sessions_root / session_id
            if old_dir.exists():
                await asyncio.to_thread(shutil.rmtree, old_dir)
            session = TrainingSession(self.sessions_root, session_id, cfg)
            # claim the id with ``preparing`` set before releasing the
            # lock — other mutators fail fast instead of queueing for
            # the whole (potentially long) data-prep below
            session.preparing = True
            self.sessions[session_id] = session
        try:
            (session.dir / "config.json").write_text(json.dumps(cfg))
            session.write_status(
                status="initializing", started_at=_now(), losses=[],
                n_images=len(train_images),
            )
            await asyncio.to_thread(
                self._prepare_training_data,
                session, train_images, train_labels,
            )
            # spawn before clearing ``preparing`` so there is no instant
            # where the session is neither preparing nor tracked by a task
            session.task = asyncio.create_task(
                self._run_training(session, False)
            )
        except BaseException:
            self.sessions.pop(session_id, None)
            # don't leave a half-initialized dir for _recover_sessions
            # to re-adopt as a ghost session after a restart. Rename
            # synchronously (atomic, cheap) so a concurrent retry of the
            # same id never races the delete of a live path, then delete
            # the renamed tree in a thread so a large half-written data
            # dir can't stall the event loop
            doomed = session.dir.with_name(
                f".{session.dir.name}.deleting-{uuid.uuid4().hex[:8]}"
            )
            try:
                session.dir.rename(doomed)
            except OSError:
                doomed = None
            if doomed is not None:
                await asyncio.to_thread(
                    shutil.rmtree, doomed, ignore_errors=True
                )
            raise
        finally:
            session.preparing = False
        return {"session_id": session_id, "status": "started"}

    @schema_method
    async def stop_training(self, session_id: str, context=None):
        """Request a graceful stop (checked per batch, like the
        reference's stop-file, ref main.py:1278-1360)."""
        async with self._lifecycle_lock(session_id):
            session = self._get_session(session_id)
            session.stop_path.touch()
            if session.task:
                await asyncio.wait([session.task], timeout=30)
            return session.read_status()

    @schema_method
    async def restart_training(self, session_id: str, context=None):
        """Resume a stopped/interrupted/failed session from its latest
        snapshot (ref main.py:4117)."""
        async with self._lifecycle_lock(session_id):
            session = self._get_session(session_id)
            if await self._busy(session):
                raise RuntimeError(f"session '{session_id}' is still running")
            if not (session.data_dir / "train.npz").exists():
                raise RuntimeError(
                    f"session '{session_id}' has no persisted training data"
                )
            session.stop_path.unlink(missing_ok=True)
            session.write_status(status="initializing", error=None)
            session.task = asyncio.create_task(
                self._run_training(session, True)
            )
        return {"session_id": session_id, "status": "restarted"}

    @schema_method
    async def get_training_status(self, session_id: str, context=None):
        """The session's status.json: state, epoch progress, losses."""
        return self._get_session(session_id).read_status()

    @schema_method
    async def list_sessions(self, context=None):
        """All sessions with their current status and snapshot count."""
        return [
            {
                **s.read_status(),
                "snapshots": len(s.snapshots()),
            }
            for s in self.sessions.values()
        ]

    async def _busy(self, session) -> bool:
        """True if the session must not be mutated right now.

        status.json is written from inside the train thread, so a
        terminal status can land a beat before the asyncio task itself
        completes — callers that gate on "not training" wait out that
        wind-down here instead of rejecting a session the status file
        already reports finished. Callers must hold the session's
        lifecycle lock: this method can suspend, and the lock is what
        keeps a concurrent mutator from acting in that window.

        A task-less, non-preparing session (re-adopted after an app
        restart, including one that crashed mid-initialization) has
        nothing running in this process and is never busy."""
        if session.preparing:
            return True
        if session.task is None or session.task.done():
            return False
        if session.read_status().get("status") not in _TERMINAL_STATES:
            return True
        try:
            await asyncio.wait_for(asyncio.shield(session.task), timeout=30)
        except asyncio.TimeoutError:
            return True
        return False

    @schema_method
    async def delete_session(self, session_id: str, context=None):
        """Remove a session directory (must not be training)."""
        async with self._lifecycle_lock(session_id):
            session = self._get_session(session_id)
            if await self._busy(session):
                raise RuntimeError(f"stop session '{session_id}' first")
            # deregister first so infer/export on this id fail fast
            # instead of racing the threaded rmtree below
            self.sessions.pop(session_id, None)
            await asyncio.to_thread(
                shutil.rmtree, session.dir, ignore_errors=True
            )
        return {"deleted": session_id}

    @schema_method
    async def infer(
        self,
        session_id: str,
        images: list,
        cellprob_threshold: float = 0.0,
        min_size: int = 15,
        context=None,
    ):
        """Segment images with the session's latest snapshot — live
        inference against a training run works because snapshots are
        written atomically per epoch."""
        session = self._get_session(session_id)
        if not session.latest_path.exists():
            raise RuntimeError(
                f"session '{session_id}' has no snapshot yet"
            )
        try:
            masks = await asyncio.to_thread(
                self._infer, session, images, cellprob_threshold, min_size
            )
        except FileNotFoundError as exc:
            # an in-flight call can race delete_session's threaded rmtree
            # after the id is deregistered — surface a clean error
            raise RuntimeError(f"session '{session_id}' was deleted") from exc
        return {
            "masks": masks,
            "n_cells": [int(m.max()) for m in masks],
            "snapshot": session.snapshots()[-1] if session.snapshots() else None,
        }

    def _load_snapshot(self, session):
        from bioengine_tpu.runtime.convert import load_params_npz

        return load_params_npz(str(session.latest_path))

    def _predict_raw(self, session, x: np.ndarray, params=None) -> np.ndarray:
        """(N, H, W, 2) prepared batch -> raw network output:
        (N, H, W, 3) (dy, dx, cellprob logits) for cellpose-family
        backbones, (N, H, W, 1 + n_rays) (prob logit, ray distances)
        for stardist. ``params`` preloaded via ``_load_snapshot`` keeps
        multi-pass callers (infer_3d's three orientations) on ONE
        snapshot even while training is writing new ones; None loads
        the latest."""
        import jax

        from bioengine_tpu.runtime.buckets import bucket_shape, crop_to, pad_to

        cfg = session.config
        model, divisor = build_model(cfg)
        # one jitted forward per architecture: params are an argument, so
        # per-epoch snapshots and repeated infer calls reuse the compiled
        # program instead of retracing a fresh lambda every request
        arch_key = (
            cfg.get("backbone", "unet"),
            tuple(cfg["features"]),
            cfg.get("patch_size"), cfg.get("dim"),
            cfg.get("depth"), cfg.get("num_heads"),
            cfg.get("n_rays"),
            # cpsam-only knobs change the architecture too — without
            # them two cpsam sessions differing only in e.g.
            # window_size would share one compiled model
            *(
                tuple(cfg[k]) if isinstance(cfg.get(k), (list, tuple))
                else cfg.get(k)
                for k in _CPSAM_KEYS
            ),
        )
        if arch_key not in self._fwd_cache:
            # compiled-forward memo: bounded by distinct architecture
            # tuples, and evicting on session delete would retrigger an
            # XLA compile for siblings sharing the arch
            # bioengine: ignore[BE-LIFE-401]
            self._fwd_cache[arch_key] = jax.jit(
                lambda p, a, m=model: m.apply({"params": p}, a)
            )
        fwd = self._fwd_cache[arch_key]
        if params is None:
            params = self._load_snapshot(session)
        x = _to_model_channels(x, cfg)
        H, W = x.shape[1:3]
        bh, bw = bucket_shape((H, W), divisor=divisor)
        pred = np.asarray(fwd(params, pad_to(x, (bh, bw))))
        return crop_to(pred, (H, W))

    def _infer(self, session, images, cellprob_threshold, min_size):
        pred = self._predict_raw(session, self._prepare_images(images))
        if session.config.get("backbone") == "stardist":
            from bioengine_tpu.ops.stardist import (
                predictions_to_masks_stardist,
            )

            # the caller-facing threshold is a LOGIT for both families
            # (0.0 = probability 0.5); stardist's NMS takes probability
            prob_threshold = float(1.0 / (1.0 + np.exp(-cellprob_threshold)))
            return [
                predictions_to_masks_stardist(
                    p, prob_threshold=prob_threshold, min_size=min_size
                )
                for p in pred
            ]
        from bioengine_tpu.ops.flows import predictions_to_masks

        return [
            predictions_to_masks(
                p, cellprob_threshold=cellprob_threshold, min_size=min_size
            )
            for p in pred
        ]

    @schema_method
    async def infer_3d(
        self,
        session_id: str,
        volumes: list,
        cellprob_threshold: float = 0.0,
        min_size: int = 15,
        anisotropy: float = 1.0,
        context=None,
    ):
        """Segment (D, H, W) grayscale volumes with the session's 2D
        model via the cellpose ``do_3D`` recipe: the network runs over
        yx, zx, and zy slice orientations, shared flow components are
        averaged into one (dz, dy, dx) field, and voxels are followed
        to 3D sinks (ops/flows.py). ``anisotropy`` = z-spacing /
        xy-spacing: the stack is resampled along z by this factor first
        so cells appear isotropic to the 2D network, and the masks are
        resampled back. The reference delegates all of this to the
        upstream cellpose library; here it is first-class and the flow
        following runs jitted on TPU."""
        session = self._get_session(session_id)
        if session.config.get("backbone") == "stardist":
            raise RuntimeError(
                "infer_3d needs flow-field outputs (the cellpose do_3D "
                "recipe); the stardist backbone predicts 2D polygons — "
                "use infer per z-slice instead"
            )
        if not session.latest_path.exists():
            raise RuntimeError(f"session '{session_id}' has no snapshot yet")
        if anisotropy <= 0:
            raise ValueError(f"anisotropy must be positive, got {anisotropy}")
        try:
            masks = await asyncio.to_thread(
                self._infer_3d, session, volumes, cellprob_threshold,
                min_size, anisotropy,
            )
        except FileNotFoundError as exc:
            # same delete_session race as ``infer``
            raise RuntimeError(f"session '{session_id}' was deleted") from exc
        return {
            "masks": masks,
            "n_cells": [int(m.max()) for m in masks],
            "snapshot": session.snapshots()[-1] if session.snapshots() else None,
        }

    def _infer_3d(
        self, session, volumes, cellprob_threshold, min_size, anisotropy=1.0
    ):
        from scipy import ndimage as ndi

        from bioengine_tpu.ops.flows import (
            FLOW_SCALE,
            aggregate_orthogonal_flows,
            filter_and_relabel,
            masks_from_flows,
        )

        # one snapshot for the whole request: the three orientation
        # passes must not mix weights when training is concurrently
        # writing new epochs
        params = self._load_snapshot(session)
        out = []
        for vol in volumes:
            v = np.array(vol, np.float32, copy=True)
            if v.ndim != 3:
                raise ValueError(
                    f"infer_3d expects (D, H, W) grayscale volumes, "
                    f"got shape {v.shape}"
                )
            orig_depth = v.shape[0]
            if anisotropy != 1.0:
                # make voxels isotropic for the 2D net's zx/zy passes;
                # the explicit factor guarantees >= 1 output plane for
                # tiny anisotropy values
                new_depth = max(1, int(round(orig_depth * anisotropy)))
                v = ndi.zoom(v, (new_depth / orig_depth, 1.0, 1.0), order=1)
            # actual resampling ratio (rounding can make it differ from
            # the requested anisotropy, including a no-op) — min_size
            # scales by this, not by the raw parameter
            depth_ratio = v.shape[0] / orig_depth
            # normalize the whole volume once — per-slice percentile
            # normalization would flicker along the slicing axis
            lo, hi = np.percentile(v, [1, 99])
            v = (v - lo) / max(hi - lo, 1e-6)
            preds = []
            for axes in ((0, 1, 2), (1, 0, 2), (2, 0, 1)):  # yx, zx, zy
                slices = np.ascontiguousarray(np.transpose(v, axes))
                x = np.stack([slices, np.zeros_like(slices)], axis=-1)
                preds.append(self._predict_raw(session, x, params=params))
            flow, cellprob = aggregate_orthogonal_flows(*preds)
            # min_size is a caller-resolution voxel count: at the
            # z-resampled resolution it scales by the actual depth
            # ratio, and the authoritative filter runs after resampling
            # back
            masks = masks_from_flows(
                flow / FLOW_SCALE,
                cellprob,
                cellprob_threshold=cellprob_threshold,
                min_size=max(1, int(round(min_size * depth_ratio))),
            )
            if masks.shape[0] != orig_depth:
                # nearest-neighbour back to the caller's z sampling —
                # labels must not be interpolated
                masks = ndi.zoom(
                    masks, (orig_depth / masks.shape[0], 1.0, 1.0), order=0
                )
                masks = masks[:orig_depth]
                if masks.shape[0] < orig_depth:
                    masks = np.pad(
                        masks,
                        ((0, orig_depth - masks.shape[0]), (0, 0), (0, 0)),
                        mode="edge",
                    )
                # resampling can erase whole instances: re-filter and
                # re-label at the caller's resolution so n_cells ==
                # masks.max() stays truthful
                masks = filter_and_relabel(masks, min_size)
            out.append(masks)
        return out

    @schema_method
    async def export_model(
        self,
        session_id: str,
        model_name: str | None = None,
        context=None,
    ):
        """Package the session's latest snapshot as a model-runner-ready
        ``jax_params`` model directory (rdf.yaml + weights.npz + test
        tensors) — the TPU analog of the reference's BioImage Model Zoo
        export (ref main.py:4413+, model_template.py:18)."""
        session = self._get_session(session_id)
        if not session.latest_path.exists():
            raise RuntimeError(f"session '{session_id}' has no snapshot")
        cfg = session.config
        stardist = cfg.get("backbone") == "stardist"
        family = "stardist" if stardist else "cellpose"
        name = model_name or f"{family}-{session_id}"
        export_dir = self.sessions_root / "exports" / name
        export_dir.mkdir(parents=True, exist_ok=True)
        await asyncio.to_thread(
            shutil.copyfile, session.latest_path, export_dir / "weights.npz"
        )
        rdf = {
            "type": "model",
            "name": name,
            "description": (
                f"StarDist star-convex polygon model (prob + "
                f"{cfg.get('n_rays')} ray distances) fine-tuned in "
                f"BioEngine-TPU session {session_id}"
                if stardist
                else f"Cellpose flow-field model fine-tuned in "
                f"BioEngine-TPU session {session_id}"
            ),
            "tags": [family, "segmentation", "fine-tuned"],
            "inputs": [{"name": "input0", "axes": "byxc"}],
            "outputs": [{"name": "output0", "axes": "byxc"}],
            "weights": {
                "jax_params": {
                    "source": "weights.npz",
                    "architecture": _arch_entry(cfg),
                }
            },
            "training": {
                "session_id": session_id,
                "config": cfg,
                "final_loss": session.read_status().get("last_loss"),
            },
        }
        (export_dir / "rdf.yaml").write_text(yaml.safe_dump(rdf))
        return {
            "model_path": str(export_dir),
            "name": name,
            "weights_format": "jax_params",
        }

    def _get_session(self, session_id: str) -> TrainingSession:
        if session_id not in self.sessions:
            raise KeyError(
                f"unknown session '{session_id}' "
                f"(have: {sorted(self.sessions)})"
            )
        return self.sessions[session_id]
