"""Canonical lifecycle example app (parity with the reference's
demo-app, ref apps/demo-app/demo_deployment.py: async_init /
test_deployment / check_health hooks plus simple schema methods)."""

import asyncio
import os
import time

from bioengine_tpu.rpc import schema_method


class DemoDeployment:
    def __init__(self, greeting: str = "Hello"):
        self.greeting = greeting
        self.started_at = time.time()
        self.ready = False
        self.ping_count = 0

    async def async_init(self):
        await asyncio.sleep(0)
        self.ready = True

    async def test_deployment(self):
        result = await self.echo(message="self-test")
        assert result["echo"] == "self-test", "echo self-test failed"

    async def check_health(self):
        if not self.ready:
            raise RuntimeError("not initialized")

    @schema_method
    async def ping(self, context=None):
        """Liveness check; returns 'pong' and a counter."""
        self.ping_count += 1
        return {"pong": True, "count": self.ping_count}

    @schema_method
    async def echo(self, message: str, context=None):
        """Echo a message back with uptime metadata."""
        return {
            "echo": message,
            "uptime_seconds": time.time() - self.started_at,
            "greeting": self.greeting,
        }

    @schema_method
    async def get_env(self, key: str, context=None):
        """Read an environment variable visible to the deployment."""
        return {"key": key, "value": os.environ.get(key)}
