// bioengine-tpu shared-memory object store.
//
// The reference runs on Ray, whose C++ core provides plasma — a
// shared-memory object store for zero-copy object passing between the
// worker processes on one node (SURVEY.md §2 "Native deps to replace",
// §5.8). This is the TPU framework's equivalent: a POSIX-shm arena
// with a process-shared robust mutex, an open-addressing key index, a
// first-fit block allocator with coalescing, LRU eviction, and pin
// counts so readers holding a zero-copy view block eviction of their
// object. Python maps the same segment and serves memoryviews over it
// (bioengine_tpu/native/store.py); replicas and data loaders on one
// host share decoded zarr chunks and model weights without pickling.
//
// Layout invariants (keep the walk arithmetic exact):
//   - Block headers are exactly ALIGN (64) bytes.
//   - Block::size (the payload capacity) is always a multiple of ALIGN.
//   - A block's footprint is size + ALIGN; blocks tile the data region
//     with no gaps, so `off + b->size + ALIGN` is always the next
//     block's payload offset.
//
// Build: `make` in this directory → libbioengine_store.so (ctypes ABI,
// plain C symbols — no pybind11).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t MAGIC = 0x42494F454E47544CULL;  // "BIOENGTL"
constexpr uint32_t VERSION = 1;
constexpr uint32_t KEY_MAX = 112;  // bytes incl. NUL
constexpr uint64_t ALIGN = 64;

struct Header {
  uint64_t magic;
  uint32_t version;
  uint32_t n_slots;
  uint64_t capacity;      // bytes in the data region (multiple of ALIGN)
  uint64_t data_offset;   // from segment start (multiple of ALIGN)
  uint64_t used_bytes;    // payload capacity currently allocated
  uint64_t clock;         // LRU tick
  uint64_t hits, misses, evictions, put_count;
  pthread_mutex_t mutex;  // process-shared, robust
};

struct Slot {
  char key[KEY_MAX];
  uint32_t state;      // 0 empty, 1 used, 2 tombstone
  uint32_t pins;
  uint64_t offset;     // payload offset from segment start
  uint64_t size;       // exact user payload size (<= block capacity)
  uint64_t last_access;
};

struct Block {
  uint64_t size;       // payload capacity, multiple of ALIGN
  uint64_t prev_size;  // previous block's capacity (0 = first block)
  uint32_t used;
  uint32_t slot;       // owning slot index when used
  uint8_t _pad[ALIGN - 24];
};
static_assert(sizeof(Block) == ALIGN, "block header must be ALIGN bytes");

struct Store {
  int fd;
  uint64_t map_size;
  uint8_t* base;
  Header* hdr;
  Slot* slots;
};

inline uint64_t align_up(uint64_t v) { return (v + ALIGN - 1) & ~(ALIGN - 1); }

inline Block* block_at(Store* s, uint64_t payload_off) {
  return reinterpret_cast<Block*>(s->base + payload_off - sizeof(Block));
}

inline uint64_t first_payload_off(Store* s) {
  return s->hdr->data_offset + sizeof(Block);
}

inline uint64_t region_end(Store* s) {
  return s->hdr->data_offset + s->hdr->capacity;
}

uint64_t fnv1a(const char* key) {
  uint64_t h = 1469598103934665603ULL;
  for (const char* p = key; *p; ++p) {
    h ^= static_cast<uint8_t>(*p);
    h *= 1099511628211ULL;
  }
  return h;
}

int lock(Store* s) {
  int rc = pthread_mutex_lock(&s->hdr->mutex);
  if (rc == EOWNERDEAD) {
    // previous holder died mid-section; flags are flipped only after
    // list surgery so the structure is still consistent
    pthread_mutex_consistent(&s->hdr->mutex);
    return 0;
  }
  return rc;
}

void unlock(Store* s) { pthread_mutex_unlock(&s->hdr->mutex); }

Slot* find_slot(Store* s, const char* key, bool for_insert) {
  uint32_t n = s->hdr->n_slots;
  uint64_t idx = fnv1a(key) % n;
  Slot* tombstone = nullptr;
  for (uint32_t probe = 0; probe < n; ++probe) {
    Slot* sl = &s->slots[(idx + probe) % n];
    if (sl->state == 0)
      return for_insert ? (tombstone ? tombstone : sl) : nullptr;
    if (sl->state == 2) {
      if (!tombstone) tombstone = sl;
      continue;
    }
    if (std::strncmp(sl->key, key, KEY_MAX) == 0) return sl;
  }
  return for_insert ? tombstone : nullptr;
}

void fix_next_prev(Store* s, uint64_t payload_off) {
  Block* b = block_at(s, payload_off);
  uint64_t nxt = payload_off + b->size + sizeof(Block);
  if (nxt < region_end(s)) block_at(s, nxt)->prev_size = b->size;
}

void free_block(Store* s, uint64_t payload_off) {
  Block* b = block_at(s, payload_off);
  b->used = 0;
  s->hdr->used_bytes -= b->size;
  // coalesce with next
  uint64_t nxt = payload_off + b->size + sizeof(Block);
  if (nxt < region_end(s)) {
    Block* nb = block_at(s, nxt);
    if (!nb->used) {
      b->size += sizeof(Block) + nb->size;
      fix_next_prev(s, payload_off);
    }
  }
  // coalesce with prev
  if (b->prev_size != 0) {
    uint64_t prev = payload_off - sizeof(Block) - b->prev_size;
    Block* pb = block_at(s, prev);
    if (!pb->used) {
      pb->size += sizeof(Block) + b->size;
      fix_next_prev(s, prev);
    }
  }
}

// first-fit; returns payload offset or 0. `size` is the exact user
// size; capacity consumed is align_up(size).
uint64_t alloc_block(Store* s, uint64_t size, uint32_t slot_idx) {
  uint64_t need = align_up(size ? size : 1);
  uint64_t off = first_payload_off(s);
  while (off < region_end(s)) {
    Block* b = block_at(s, off);
    if (!b->used && b->size >= need) {
      uint64_t spare = b->size - need;
      if (spare >= sizeof(Block) + ALIGN) {
        b->size = need;
        uint64_t new_off = off + need + sizeof(Block);
        Block* nb = block_at(s, new_off);
        nb->size = spare - sizeof(Block);
        nb->prev_size = need;
        nb->used = 0;
        fix_next_prev(s, new_off);
      }
      b->used = 1;
      b->slot = slot_idx;
      s->hdr->used_bytes += b->size;
      return off;
    }
    off += b->size + sizeof(Block);
  }
  return 0;
}

// true once a free block can hold `size`
bool fits(Store* s, uint64_t size) {
  uint64_t need = align_up(size ? size : 1);
  uint64_t off = first_payload_off(s);
  while (off < region_end(s)) {
    Block* b = block_at(s, off);
    if (!b->used && b->size >= need) return true;
    off += b->size + sizeof(Block);
  }
  return false;
}

// evict least-recently-used unpinned entries until `size` fits
bool evict_until_fits(Store* s, uint64_t size) {
  while (!fits(s, size)) {
    Slot* victim = nullptr;
    for (uint32_t i = 0; i < s->hdr->n_slots; ++i) {
      Slot* sl = &s->slots[i];
      if (sl->state == 1 && sl->pins == 0 &&
          (!victim || sl->last_access < victim->last_access))
        victim = sl;
    }
    if (!victim) return false;
    free_block(s, victim->offset);
    victim->state = 2;
    s->hdr->evictions++;
  }
  return true;
}

}  // namespace

extern "C" {

struct BesStats {
  uint64_t capacity;
  uint64_t used_bytes;
  uint64_t n_objects;
  uint64_t hits, misses, evictions, put_count;
};

static int bes_create_impl(const char* name, uint64_t capacity,
                           uint32_t n_slots, bool overwrite) {
  capacity = align_up(capacity);
  if (overwrite) shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return -errno;
  uint64_t slots_bytes = sizeof(Slot) * static_cast<uint64_t>(n_slots);
  uint64_t data_offset = align_up(sizeof(Header) + slots_bytes);
  uint64_t total = data_offset + capacity;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    int e = errno;
    close(fd);
    shm_unlink(name);
    return -e;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    int e = errno;
    close(fd);
    shm_unlink(name);
    return -e;
  }
  auto* hdr = static_cast<Header*>(mem);
  std::memset(mem, 0, data_offset);
  hdr->magic = MAGIC;
  hdr->version = VERSION;
  hdr->n_slots = n_slots;
  hdr->capacity = capacity;
  hdr->data_offset = data_offset;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  auto* first =
      reinterpret_cast<Block*>(static_cast<uint8_t*>(mem) + data_offset);
  first->size = capacity - sizeof(Block);
  first->prev_size = 0;
  first->used = 0;

  munmap(mem, total);
  close(fd);
  return 0;
}

// Create (or overwrite) a store segment. Returns 0 or -errno.
int bes_create(const char* name, uint64_t capacity, uint32_t n_slots) {
  return bes_create_impl(name, capacity, n_slots, true);
}

// Create only if absent — never unlinks an existing segment, so
// concurrent attach-or-create races resolve to one winner.
// Returns 0, -EEXIST, or another -errno.
int bes_create_excl(const char* name, uint64_t capacity, uint32_t n_slots) {
  return bes_create_impl(name, capacity, n_slots, false);
}

int bes_destroy(const char* name) {
  return shm_unlink(name) == 0 ? 0 : -errno;
}

Store* bes_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<uint64_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  auto* hdr = static_cast<Header*>(mem);
  if (hdr->magic != MAGIC || hdr->version != VERSION) {
    munmap(mem, static_cast<uint64_t>(st.st_size));
    close(fd);
    return nullptr;
  }
  auto* s = new Store;
  s->fd = fd;
  s->map_size = static_cast<uint64_t>(st.st_size);
  s->base = static_cast<uint8_t*>(mem);
  s->hdr = hdr;
  s->slots = reinterpret_cast<Slot*>(s->base + sizeof(Header));
  return s;
}

void bes_close(Store* s) {
  if (!s) return;
  munmap(s->base, s->map_size);
  close(s->fd);
  delete s;
}

// Put: copies data into the arena, evicting LRU entries as needed.
// 0 | -EEXIST | -ENOSPC (can never fit / all pinned) | -ENAMETOOLONG |
// -ENOMEM (slot table full).
int bes_put(Store* s, const char* key, const void* data, uint64_t size) {
  if (std::strlen(key) >= KEY_MAX) return -ENAMETOOLONG;
  if (align_up(size) + sizeof(Block) > s->hdr->capacity) return -ENOSPC;
  if (lock(s) != 0) return -EDEADLK;
  if (find_slot(s, key, false)) {
    unlock(s);
    return -EEXIST;
  }
  Slot* sl = find_slot(s, key, true);
  if (!sl) {
    unlock(s);
    return -ENOMEM;
  }
  uint32_t slot_idx = static_cast<uint32_t>(sl - s->slots);
  uint64_t off = alloc_block(s, size, slot_idx);
  if (off == 0) {
    if (!evict_until_fits(s, size)) {
      unlock(s);
      return -ENOSPC;
    }
    off = alloc_block(s, size, slot_idx);
    if (off == 0) {
      unlock(s);
      return -ENOSPC;
    }
  }
  if (size) std::memcpy(s->base + off, data, size);
  std::strncpy(sl->key, key, KEY_MAX);
  sl->key[KEY_MAX - 1] = '\0';
  sl->state = 1;
  sl->pins = 0;
  sl->offset = off;
  sl->size = size;
  sl->last_access = ++s->hdr->clock;
  s->hdr->put_count++;
  unlock(s);
  return 0;
}

// Get + pin: bumps LRU + pin count, returns payload offset/size. The
// caller reads bytes from its own mapping and MUST bes_release(key).
int bes_get_pin(Store* s, const char* key, uint64_t* offset_out,
                uint64_t* size_out) {
  if (lock(s) != 0) return -EDEADLK;
  Slot* sl = find_slot(s, key, false);
  if (!sl) {
    s->hdr->misses++;
    unlock(s);
    return -ENOENT;
  }
  sl->last_access = ++s->hdr->clock;
  sl->pins++;
  s->hdr->hits++;
  *offset_out = sl->offset;
  *size_out = sl->size;
  unlock(s);
  return 0;
}

int bes_release(Store* s, const char* key) {
  if (lock(s) != 0) return -EDEADLK;
  Slot* sl = find_slot(s, key, false);
  if (!sl || sl->pins == 0) {
    unlock(s);
    return -ENOENT;
  }
  sl->pins--;
  unlock(s);
  return 0;
}

int bes_contains(Store* s, const char* key) {
  if (lock(s) != 0) return -EDEADLK;
  Slot* sl = find_slot(s, key, false);
  unlock(s);
  return sl ? 1 : 0;
}

int bes_delete(Store* s, const char* key) {
  if (lock(s) != 0) return -EDEADLK;
  Slot* sl = find_slot(s, key, false);
  if (!sl) {
    unlock(s);
    return -ENOENT;
  }
  if (sl->pins > 0) {
    unlock(s);
    return -EBUSY;
  }
  free_block(s, sl->offset);
  sl->state = 2;
  unlock(s);
  return 0;
}

// Clear every unpinned entry in place (the segment stays mapped by
// all attached processes). Returns the number of entries removed.
int bes_clear(Store* s) {
  if (lock(s) != 0) return -EDEADLK;
  int removed = 0;
  for (uint32_t i = 0; i < s->hdr->n_slots; ++i) {
    Slot* sl = &s->slots[i];
    if (sl->state == 1 && sl->pins == 0) {
      free_block(s, sl->offset);
      sl->state = 2;
      removed++;
    }
  }
  unlock(s);
  return removed;
}

// CRC32-C (Castagnoli, poly 0x82F63B78), slice-by-8. Used by the zarr
// codec layer to verify v3 crc32c-suffixed chunks at full speed (the
// pure-python fallback is fine for shard indexes but not multi-MB
// chunk payloads).
static uint32_t g_crc32c_tab[8][256];
static bool g_crc32c_init = false;

static void crc32c_init_tables() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int k = 0; k < 8; ++k)
      crc = (crc & 1) ? (crc >> 1) ^ 0x82F63B78u : crc >> 1;
    g_crc32c_tab[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = g_crc32c_tab[0][i];
    for (int t = 1; t < 8; ++t) {
      crc = g_crc32c_tab[0][crc & 0xFF] ^ (crc >> 8);
      g_crc32c_tab[t][i] = crc;
    }
  }
  g_crc32c_init = true;
}

uint32_t bes_crc32c(const uint8_t* data, uint64_t len, uint32_t seed) {
  if (!g_crc32c_init) crc32c_init_tables();
  uint32_t crc = seed ^ 0xFFFFFFFFu;
  while (len >= 8) {
    uint32_t lo = crc ^ (uint32_t(data[0]) | uint32_t(data[1]) << 8 |
                         uint32_t(data[2]) << 16 | uint32_t(data[3]) << 24);
    uint32_t hi = uint32_t(data[4]) | uint32_t(data[5]) << 8 |
                  uint32_t(data[6]) << 16 | uint32_t(data[7]) << 24;
    crc = g_crc32c_tab[7][lo & 0xFF] ^ g_crc32c_tab[6][(lo >> 8) & 0xFF] ^
          g_crc32c_tab[5][(lo >> 16) & 0xFF] ^ g_crc32c_tab[4][lo >> 24] ^
          g_crc32c_tab[3][hi & 0xFF] ^ g_crc32c_tab[2][(hi >> 8) & 0xFF] ^
          g_crc32c_tab[1][(hi >> 16) & 0xFF] ^ g_crc32c_tab[0][hi >> 24];
    data += 8;
    len -= 8;
  }
  while (len--) {
    crc = g_crc32c_tab[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  }
  return crc ^ 0xFFFFFFFFu;
}

int bes_stats(Store* s, BesStats* out) {
  if (lock(s) != 0) return -EDEADLK;
  out->capacity = s->hdr->capacity;
  out->used_bytes = s->hdr->used_bytes;
  uint64_t n = 0;
  for (uint32_t i = 0; i < s->hdr->n_slots; ++i)
    if (s->slots[i].state == 1) n++;
  out->n_objects = n;
  out->hits = s->hdr->hits;
  out->misses = s->hdr->misses;
  out->evictions = s->hdr->evictions;
  out->put_count = s->hdr->put_count;
  unlock(s);
  return 0;
}

}  // extern "C"
