#!/usr/bin/env python
"""Standalone app uploader — the analog of the reference's
``scripts/upload_app.py`` (which pushes an app dir to the Hypha
artifact manager). Two transports, auto-selected from the URL:

- ``ws://host:port/ws``  — the worker's RPC plane (``upload_app`` with
  in-memory file contents, same path as ``bioengine apps upload``)
- ``http://host:port``   — the artifact manager's presigned-PUT flow
  (bioengine_tpu/apps/artifact_http.py), usable without a websocket
  client, e.g. from CI

Usage:
    python scripts/upload_app.py apps/demo-app \\
        --server-url http://127.0.0.1:9527 --token $(cat ~/.bioengine/admin_token)
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))


from bioengine_tpu.cli.utils import read_dir_files  # noqa: E402 — path set above


async def upload_ws(args) -> dict:
    from bioengine_tpu.rpc.client import connect_to_server

    conn = await connect_to_server(
        {"server_url": args.server_url, "token": args.token}
    )
    try:
        worker = await conn.get_service("bioengine-worker")
        return await worker.upload_app(
            files=read_dir_files(args.src_dir),
            artifact_id=args.artifact_id,
            version=args.version,
        )
    finally:
        await conn.disconnect()


def upload_http(args) -> dict:
    from bioengine_tpu.apps.artifact_http import RemoteArtifactStore

    store = RemoteArtifactStore(args.server_url, token=args.token)
    try:
        artifact_id, version = store.put_files(
            read_dir_files(args.src_dir),
            artifact_id=args.artifact_id,
            version=args.version,
        )
        return {"artifact_id": artifact_id, "version": version}
    finally:
        store.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Upload a BioEngine app directory to a worker"
    )
    parser.add_argument("src_dir", help="app directory (with manifest.yaml)")
    parser.add_argument(
        "--server-url",
        default=os.environ.get("BIOENGINE_SERVER_URL"),
        help="ws://host:port/ws (RPC) or http://host:port (artifact "
        "manager); env BIOENGINE_SERVER_URL",
    )
    parser.add_argument(
        "--token",
        default=os.environ.get("BIOENGINE_ADMIN_TOKEN"),
        help="admin token; env BIOENGINE_ADMIN_TOKEN",
    )
    parser.add_argument("--artifact-id", default=None)
    parser.add_argument("--version", default=None)
    args = parser.parse_args(argv)
    if not args.server_url:
        parser.error("--server-url (or BIOENGINE_SERVER_URL) is required")
    if not (Path(args.src_dir) / "manifest.yaml").is_file():
        parser.error(f"{args.src_dir} has no manifest.yaml")

    if args.server_url.startswith(("ws://", "wss://")):
        result = asyncio.run(upload_ws(args))
    else:
        result = upload_http(args)
    print(
        f"uploaded {result['artifact_id']}@{result['version']} "
        f"to {args.server_url}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
