#!/usr/bin/env bash
# Chaos-fuzz gate (the coverage-guided fault-schedule fuzzer,
# bioengine_tpu/testing/fuzz.py) — three time-boxed legs:
#
#   1. corpus replay   every checked-in repro in tests/fuzz_corpus
#                      must reproduce its recorded red set and replay
#                      bit-deterministically (two runs, identical
#                      outcome signatures)
#   2. the drill       BIOENGINE_FUZZ_DRILL=1 arms a deliberate
#                      lease-accounting defect (cluster/state.py); the
#                      search must FIND it via the lease_conservation
#                      universal invariant and shrink it to <= 3
#                      events inside the budget — the end-to-end proof
#                      on a KNOWN bug
#   3. clean search    a short budget against the honest engine must
#                      find NOTHING (every universal invariant holds
#                      across generated schedules — the zero-false-
#                      positive bar)
#
# Knobs:
#   BIOENGINE_FUZZ_BUDGET_S  wall-clock budget per search leg (default 120)
#   BIOENGINE_FUZZ_SEED      search seed (default 1)
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
BUDGET="${BIOENGINE_FUZZ_BUDGET_S:-120}"
SEED="${BIOENGINE_FUZZ_SEED:-1}"
# hard wall per CLI invocation: the budget plus room for the baseline
# run, shrinking, and artifact replay
BOX=$((BUDGET + 120))

echo "== fuzz gate (budget ${BUDGET}s/leg, seed ${SEED}) =="

echo "-- corpus replay (deterministic regression repros)"
timeout -k 10 "$BOX" python -m bioengine_tpu.cli fuzz \
    --corpus tests/fuzz_corpus

echo "-- drill: search must find + shrink the armed lease leak"
out="$(mktemp -d)"
timeout -k 10 "$BOX" python -m bioengine_tpu.cli fuzz \
    --drill --seed "$SEED" --budget-s "$BUDGET" --out "$out" > "$out/report.json"
python - "$out/report.json" <<'EOF'
import json
import sys

with open(sys.argv[1]) as f:
    d = json.load(f)
arts = d["artifacts"]
assert arts, f"drill found nothing: {d['stats']}"
a = arts[0]
assert a["expect"]["red"] == ["lease_conservation"], a["expect"]
assert len(a["events"]) <= 3, (
    f"shrinker left {len(a['events'])} events (want <= 3)"
)
print(
    f"drill OK: found + shrunk to {len(a['events'])} event(s) in "
    f"{d['stats']['runs']} runs / {d['stats']['elapsed_s']}s"
)
EOF

echo "-- clean search: the honest engine must survive the same budget"
timeout -k 10 "$BOX" python -m bioengine_tpu.cli fuzz \
    --seed "$SEED" --budget-s "$BUDGET" --keep-going

echo "fuzz gate OK"
