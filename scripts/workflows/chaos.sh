#!/usr/bin/env bash
# Chaos soak gate: repeated kill/reconnect cycles under traffic against
# the in-process multi-host harness (tests/test_chaos.py). The fast
# deterministic chaos tests run in tier-1; this job runs the slow soak
# with a higher cycle count and fails on any dropped request, leaked
# pin/task, or chip-accounting drift.
#
# Knobs:
#   BIOENGINE_CHAOS_CYCLES   kill/rejoin cycles per soak run (default 20 here)
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu
export BIOENGINE_CHAOS_CYCLES="${BIOENGINE_CHAOS_CYCLES:-20}"

echo "== chaos soak (${BIOENGINE_CHAOS_CYCLES} cycles) =="
timeout -k 10 600 python -m pytest tests/test_chaos.py -m slow -q -rA \
    -p no:cacheprovider

echo "== fast deterministic chaos tests (tier-1 members, rerun for locality) =="
timeout -k 10 600 python -m pytest tests/test_chaos.py -m "not slow" -q \
    -p no:cacheprovider

echo "chaos gate OK"
