#!/usr/bin/env bash
# CI job: topology-portable multi-host meshes — fails fast on cross-host
# placement/execution regressions without waiting for the slow suite.
#
# Three checks on a forced 4-virtual-device CPU layout (the same trick
# as tests/conftest.py and the MULTICHIP dryruns):
#   1. the full multichip dryrun (__graft_entry__.dryrun_multichip),
#      which now ends with a cross-host mesh phase: the CrossHostEngine
#      pipeline composition over two per-device-group engine shards,
#      parity-pinned against the composed reference;
#   2. the mesh suite (tests/test_mesh.py): planner + config units,
#      CrossHostEngine composition, 2-in-process-host serving with
#      parity + the RpcStats OOB pin, mesh1 capability gating, and the
#      kill-a-shard-host chaos leg with exact chip accounting;
#   3. a bench smoke of the multihost_mesh stage (schema + parity +
#      OOB pin; CPU throughput is informational).
#
# Run locally from the repo root:  scripts/workflows/multihost.sh
set -euo pipefail
cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=4"

echo "multihost: multichip dryrun with cross-host mesh phase (4-device CPU)"
python __graft_entry__.py 4

echo "multihost: mesh planner/engine/serving/chaos suite"
python -m pytest tests/test_mesh.py -q -p no:cacheprovider

echo "multihost: multihost_mesh bench smoke (schema + parity + OOB pin)"
BENCH_PLATFORM=cpu BENCH_CONFIGS=multihost_mesh BENCH_DEADLINE=170 \
python - <<'EOF'
import json
import os
import subprocess
import sys

proc = subprocess.run(
    [sys.executable, "bench.py"], capture_output=True, text=True,
    timeout=200, env=dict(os.environ),
)
assert proc.returncode == 0, proc.stderr[-2000:]
lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
st = json.loads(lines[-1])["extra"]["multihost_mesh"]
assert st["ok"], st
assert st["parity_ok"], st
assert st["cross_host_2host"] and not st["cross_host_1host"], st
assert st["oob_payloads_out"] > 0 and st["legacy_msgs_out"] == 0, st
print(
    "multihost_mesh OK: "
    f"1host={st['images_per_sec_1host']} img/s "
    f"2host={st['images_per_sec_2host']} img/s "
    f"efficiency={st['scaling_efficiency']} "
    f"transfer={st['transfer_bytes_per_request']}B/req"
)
EOF
