#!/usr/bin/env bash
# Global-scheduler gate: admission/fairness/coalescing/predictive-
# autoscale unit layers, the cross-host __batch__ round-trip tests, and
# the mixed-priority soak (2 scheduled apps x 2 replicas over real
# websockets, one host killed mid-soak) at a higher request count than
# tier-1 runs — then a scheduler_goodput bench smoke asserting the
# stage emits its schema with zero failed requests and a non-degraded
# batch occupancy.
#
# Knobs:
#   BIOENGINE_SCHED_SOAK_N   requests per soak worker stream (default 25 here)
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu
export BIOENGINE_SCHED_SOAK_N="${BIOENGINE_SCHED_SOAK_N:-25}"

echo "== scheduler test suite (soak streams: ${BIOENGINE_SCHED_SOAK_N} req/worker) =="
timeout -k 10 600 python -m pytest tests/test_scheduler.py -q -rA \
    -p no:cacheprovider

echo "== scheduler_goodput bench smoke =="
out="$(mktemp)"
timeout -k 10 300 env BENCH_PLATFORM=cpu BENCH_DEADLINE=240 \
    BENCH_CONFIGS=scheduler_goodput python bench.py | tail -n1 > "$out"
python - "$out" <<'EOF'
import json
import sys

with open(sys.argv[1]) as f:
    d = json.loads(f.read())
st = d["extra"]["scheduler_goodput"]
assert st and st.get("ok"), st
for leg in ("router", "scheduler"):
    assert st["legs"][leg]["failed"] == 0, (leg, st["legs"][leg])
    assert st["legs"][leg]["goodput_rps"] > 0, (leg, st["legs"][leg])
# the mechanism gate: coalescing must not LOWER occupancy vs the
# per-request router on the same workload (the goodput headline is a
# hardware number; CI cores are too noisy to gate on it)
assert (
    st["legs"]["scheduler"]["batch_occupancy"]
    >= st["legs"]["router"]["batch_occupancy"]
), st["legs"]
print(
    f"scheduler_goodput OK: speedup={st['goodput_speedup']} "
    f"occupancy_gain={st['occupancy_gain']} "
    f"uncontended_overhead={st['uncontended']['overhead_scheduler_pct']}%"
)
EOF

echo "scheduler gate OK"
