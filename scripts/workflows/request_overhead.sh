#!/usr/bin/env bash
# Small-request hot-path gate: the fast1/BEFS codec suites, a
# request_overhead bench run with its throughput-regression check
# against the committed decomposition artifact, and the analyzer's
# hot-path diff check so a PR that adds a new per-request env read (or
# any BE-PERF-3xx cost) to the request path fails before it ships.
#
# Regression gate: absolute req/s across heterogeneous CI hosts is
# weather, so the gate reads the DIMENSIONLESS paired speedup the
# stage computes (fast leg vs same-interpreter pre-fast1 baseline,
# median of per-round paired ratios). A hot-path regression makes the
# fast leg slower relative to its own baseline on ANY machine; the
# gate fails when that normalized throughput drops >10% below the
# committed request-overhead.json.
#
# Knobs:
#   REQ_GATE_MIN_SPEEDUP  override the computed floor (escape hatch
#                         for a known-noisy runner)
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu

echo "== fast-frame codec + rpc test suites =="
timeout -k 10 600 python -m pytest \
    tests/test_rpc_fast_frames.py tests/test_rpc.py -q -rA \
    -p no:cacheprovider

echo "== request_overhead bench =="
out="$(mktemp)"
timeout -k 10 300 env BENCH_PLATFORM=cpu BENCH_DEADLINE=240 \
    BENCH_CONFIGS=request_overhead python bench.py | tail -n1 > "$out"
REQ_GATE_MIN_SPEEDUP="${REQ_GATE_MIN_SPEEDUP:-}" python - "$out" <<'EOF'
import json
import os
import sys

with open(sys.argv[1]) as f:
    d = json.loads(f.read())
st = d["extra"]["request_overhead"]
assert st and st.get("ok"), st

# wiring, not weather: the fast legs must actually have run on BEFS
assert st["legs"]["baseline"]["fast_frames"] is False
assert st["legs"]["baseline"]["small_frames_out"] == 0
for leg in ("fast_tcp", "fast"):
    assert st["legs"][leg]["fast_frames"] is True, leg
    assert st["legs"][leg]["fast_frame_hit_rate"] == 1.0, leg

committed = json.load(open("request-overhead.json"))
floor = os.environ.get("REQ_GATE_MIN_SPEEDUP")
floor = (
    float(floor) if floor else 0.9 * committed["uncontended_speedup"]
)
live = st["uncontended_speedup"]
assert live >= floor, (
    f"uncontended small-request speedup regressed: live {live}x < "
    f"floor {floor:.2f}x (committed {committed['uncontended_speedup']}x "
    "- 10%); the fast path got slower relative to its own baseline"
)
print(
    f"request_overhead OK: uncontended {live}x (floor {floor:.2f}x), "
    f"concurrent {st['concurrent_speedup']}x, "
    f"fast p50 {st['legs']['fast']['uncontended']['p50_us']}us vs "
    f"baseline {st['legs']['baseline']['uncontended']['p50_us']}us"
)
EOF

echo "== hot-path report diff check =="
fresh="$(mktemp)"
hp_rc=0
python -m bioengine_tpu.analysis bioengine_tpu/ apps/ \
    --hot-path-report "$fresh" >/dev/null || hp_rc=$?
if [[ "$hp_rc" -ge 2 ]]; then
    echo "request_overhead: analyzer error (rc=$hp_rc)" >&2
    exit "$hp_rc"
fi
python - "$fresh" <<'EOF'
import json
import sys

fresh = json.load(open(sys.argv[1]))
committed = json.load(open("hot-path-report.json"))
assert fresh["schema"] == committed["schema"], fresh.get("schema")
new = fresh["totals"]["findings"]
old = committed["totals"]["findings"]
assert new <= old, (
    f"hot-path findings grew {old} -> {new}: this change adds "
    "per-request overhead (new BE-PERF-3xx finding on a request-path "
    "root). Fix it or regenerate hot-path-report.json with an inline "
    "justification."
)
print(f"hot-path diff OK: {new} finding(s) (committed {old})")
EOF

echo "request_overhead gate OK"
