#!/usr/bin/env bash
# Token-streaming gate (the generative-serving job): the decode unit
# suite (paged KV cache, step-level continuous batching, golden-pinned
# toy decoder incl. dp-mesh parity), the streaming integration suite
# (RPC stream plane, idempotent mid-stream resume, the generate app
# end-to-end), the token_streaming scenario (a host SIGKILL'd
# mid-generation: exact token sequences survive resume, co-batching
# observed, chip accounting exact), and a token_streaming bench smoke
# (co-batching must beat sequential decode and a short request must
# join a running batch instead of queueing behind a long one).
#
# Knobs:
#   BIOENGINE_SCENARIO_SEED   workload seed (default 7)
#   BIOENGINE_SCENARIO_SCALE  time-compression stretch for slow CI boxes
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu
SEED="${BIOENGINE_SCENARIO_SEED:-7}"

echo "== decode + streaming suites =="
timeout -k 10 600 python -m pytest tests/test_decode.py tests/test_streaming.py -q \
    -p no:cacheprovider

echo "== token_streaming scenario, determinism double-run (seed ${SEED}) =="
out="$(mktemp)"
timeout -k 10 300 python -m bioengine_tpu.cli scenarios run token_streaming \
    --seed "$SEED" --check-determinism --out "$out" > /dev/null
python - "$out" <<'EOF'
import json
import sys

with open(sys.argv[1]) as f:
    d = json.load(f)
res = d["result"]
assert d["deterministic"] is True, (
    "token_streaming is not replay-deterministic for one seed"
)
inv = res["invariants"]
for name in (
    "zero_failed_idempotent",
    "chip_accounting_exact",
    "decode_cobatch_observed",
    "stream_resume_observed",
    "slo_attainment",
):
    assert inv[name]["ok"], (name, inv[name])
assert res["passed"], inv
assert res["counts"] == {"ok": res["requests"]}, res["counts"]
print(
    f"token_streaming OK: {res['requests']} stream(s), "
    f"{inv['decode_cobatch_observed']['detail']}, "
    f"{inv['stream_resume_observed']['detail']}"
)
EOF

echo "== token_streaming bench smoke =="
BENCH_PLATFORM=cpu BENCH_DEADLINE=240 \
    BENCH_CONFIGS=token_streaming python bench.py \
    | grep '^{' | tail -n 1 > /tmp/_ts_bench.json
python - /tmp/_ts_bench.json <<'EOF'
import json
import sys

with open(sys.argv[1]) as f:
    st = json.load(f)["extra"]["token_streaming"]
assert st["ok"], st
thr = st["throughput"]
assert thr["tokens_per_sec"] > 0, thr
# co-batching really engaged: steps << streams x tokens, occupancy > 1
assert thr["batch_occupancy"] > 1.0, thr
join = st["join_mid_batch"]
assert join["joined_mid_batch"] == 1, join
assert join["long_still_running"] == 1, join
print(
    f"token_streaming bench OK: {thr['tokens_per_sec']:.0f} tok/s, "
    f"occupancy {thr['batch_occupancy']:.2f}, "
    f"mid-batch ttft {join['mid_batch_ttft_ms']:.1f}ms"
)
EOF

echo "token streaming gate OK"
