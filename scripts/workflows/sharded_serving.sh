#!/usr/bin/env bash
# CI job: sharded-serving leg of the multichip dryrun — fails fast on
# sharding regressions without waiting for the slow suite or a TPU.
#
# Two checks on a forced 4-virtual-device CPU mesh (the same trick as
# tests/conftest.py and the MULTICHIP dryruns):
#   1. the full multichip dryrun (__graft_entry__.dryrun_multichip),
#      which now ends with a sharded-serving engine phase: a dp-mesh
#      InferenceEngine forward checked for parity against the 1-chip
#      engine;
#   2. the dedicated engine test file (1-chip bit-identity, dp=4
#      tolerance on planar + tiled paths, dp batch padding, mesh-keyed
#      program cache, lease accounting).
#
# Run locally from the repo root:  scripts/workflows/sharded_serving.sh
set -euo pipefail
cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=4"

echo "sharded-serving: multichip dryrun (4-device CPU mesh)"
python __graft_entry__.py 4

echo "sharded-serving: engine parity + accounting tests"
python -m pytest tests/test_sharded_engine.py -q -p no:cacheprovider
