#!/usr/bin/env bash
# CI job: static-analysis gate (async-safety + JAX tracer-safety).
#
# Blocking: any finding not covered by .analyze-baseline.json fails the
# job.  On pull requests pass the base ref as $1 (e.g. origin/main) to
# scan only changed files — the gate stays fast as the repo grows; the
# push-to-main run does the full scan so baseline drift can't hide.
#
# Run locally from the repo root:  scripts/workflows/analyze.sh
set -euo pipefail
cd "$(dirname "$0")/../.."

BASE_REF="${1:-}"

if [[ -n "$BASE_REF" ]]; then
    echo "analyze: diff-aware scan vs $BASE_REF"
    python -m bioengine_tpu.analysis bioengine_tpu/ apps/ --changed "$BASE_REF"
else
    echo "analyze: full scan"
    python -m bioengine_tpu.analysis bioengine_tpu/ apps/
fi
