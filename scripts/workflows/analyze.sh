#!/usr/bin/env bash
# CI job: static-analysis gate — whole-program, blocking.
#
# Phase 1 indexes every module (process pool, content-hash cache);
# phase 2 runs the cross-module rule families (BE-DIST-2xx contract
# drift, BE-ASYNC-006..008 interprocedural async-safety) over the full
# fact base. Any finding not covered by .analyze-baseline.json fails
# the job.
#
# On pull requests pass the base ref as $1 (e.g. origin/main): module-
# local findings then narrow to changed files while the cross-module
# rules still evaluate the whole project — an unchanged module can
# break a contract a changed one relied on. The push-to-main run does
# the full scan so baseline drift can't hide.
#
# The gate scan includes the BE-PERF-3xx hot-path cost pass and the
# BE-LIFE-4xx lifecycle contract pass — both blocking like every other
# rule family: any unbaselined finding fails the job.
#
# Also emitted:
#   - analyze.sarif        code-scanning annotations (SARIF 2.1.0) —
#     exported BEFORE the job fails, so a red run still annotates
#   - hot-path-report.json the BE-PERF-3xx overhead map (reachable
#     functions ranked by finding count x call-graph depth) — the
#     request_overhead bench's starting point (docs/performance.md)
#   - analyze-stats.json   machine-readable run stats (wall, cache
#     hits, per-pass timings) — the CI perf-budget probe
#   - a docs drift guard: BIOENGINE_* knobs and flight-event/metric
#     catalogs must match the docs (BE-DIST-204/205) with NO baseline
#     escape hatch — the knob tables and docs/observability.md
#     catalogs are operator-facing contracts.
#   - a leak drift guard: BE-LIFE-401 (unswept keyed registry — the
#     PR 8/14 leak class) also runs with NO baseline escape hatch:
#     new registries must be swept or carry an inline justification,
#     never baselined.
#
# Run locally from the repo root:  scripts/workflows/analyze.sh
set -euo pipefail
cd "$(dirname "$0")/../.."

BASE_REF="${1:-}"
SARIF_OUT="${SARIF_OUT:-analyze.sarif}"
HOTPATH_OUT="${HOTPATH_OUT:-hot-path-report.json}"
STATS_OUT="${STATS_OUT:-analyze-stats.json}"

gate_rc=0
if [[ -n "$BASE_REF" ]]; then
    echo "analyze: whole-program scan (module findings vs $BASE_REF)"
    python -m bioengine_tpu.analysis bioengine_tpu/ apps/ \
        --changed "$BASE_REF" --stats \
        --stats-json "$STATS_OUT" \
        --hot-path-report "$HOTPATH_OUT" || gate_rc=$?
else
    echo "analyze: whole-program full scan"
    python -m bioengine_tpu.analysis bioengine_tpu/ apps/ --stats \
        --stats-json "$STATS_OUT" \
        --hot-path-report "$HOTPATH_OUT" || gate_rc=$?
fi
if [[ "$gate_rc" -ge 2 ]]; then
    echo "analyze: analyzer error (rc=$gate_rc)" >&2
    exit "$gate_rc"
fi

# export annotations even when the gate found something — that is
# exactly when a CI consumer needs them (rc 1 = findings, still a
# valid document; rc >= 2 = real error)
echo "analyze: exporting SARIF -> $SARIF_OUT"
sarif_rc=0
python -m bioengine_tpu.analysis bioengine_tpu/ apps/ \
    --format sarif > "$SARIF_OUT" || sarif_rc=$?
if [[ "$sarif_rc" -ge 2 ]]; then
    echo "analyze: SARIF export failed (rc=$sarif_rc)" >&2
    exit "$sarif_rc"
fi
python - "$SARIF_OUT" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["version"] == "2.1.0", "SARIF export is not 2.1.0"
print(f"analyze: SARIF ok ({len(doc['runs'][0]['results'])} result(s))")
EOF

python - "$HOTPATH_OUT" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["schema"] == "bioengine.hot-path-report/v1", doc.get("schema")
assert doc["totals"]["roots"] > 0, "no request-path roots resolved"
print(
    f"analyze: hot-path report ok ({doc['totals']['roots']} roots, "
    f"{doc['totals']['reachable_functions']} reachable, "
    f"{doc['totals']['findings']} finding(s))"
)
EOF

echo "analyze: docs drift guard (env knobs + observability catalogs)"
python -m bioengine_tpu.analysis bioengine_tpu/ apps/ \
    --rule BE-DIST-204 --rule BE-DIST-205 --no-baseline

echo "analyze: leak drift guard (BE-LIFE-401, no baseline escape)"
python -m bioengine_tpu.analysis bioengine_tpu/ apps/ \
    --rule BE-LIFE-401 --no-baseline

if [[ "$gate_rc" -ne 0 ]]; then
    echo "analyze: gate FAILED (new findings above)" >&2
    exit "$gate_rc"
fi
echo "analyze: gate passed"
