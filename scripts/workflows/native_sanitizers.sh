#!/usr/bin/env bash
# CI job: build the native object store under ASan and TSan and run the
# store test suite against each instrumented library.
#
# ASan and TSan cannot share one binary, so this runs the suite twice.
# The python interpreter itself is uninstrumented, so the sanitizer
# runtime must be LD_PRELOADed; CPython's own (intentional) allocation
# leaks would drown the report, so leak detection is off — ASan still
# traps heap overflow / use-after-free in object_store.cpp, and TSan
# reports data races on the shm segment.
#
# Run locally from the repo root:  scripts/workflows/native_sanitizers.sh
set -euo pipefail
cd "$(dirname "$0")/../.."

make -C native sanitizers

run_suite() {
    local san="$1" kfilter="$2" runtime lib
    shift 2
    runtime="$(gcc -print-file-name=lib${san}.so)"
    lib="$PWD/native/build/libbioengine_store_${san}.so"
    # gcc echoes the bare name back when the runtime isn't installed —
    # fail here rather than letting LD_PRELOAD silently no-op
    if [[ "$runtime" != /* ]]; then
        echo "error: lib${san}.so runtime not found (gcc returned '$runtime')" >&2
        exit 1
    fi
    echo "== suite under ${san} (preload ${runtime}): $*"
    # -m 'not slow': the slow sanitizer test spawns its own preloaded
    # subprocess — redundant here where the whole suite already runs
    # against the instrumented library
    env LD_PRELOAD="$runtime" \
        BIOENGINE_STORE_LIB="$lib" \
        ASAN_OPTIONS="detect_leaks=0" \
        TSAN_OPTIONS="halt_on_error=1" \
        JAX_PLATFORMS=cpu \
        python -m pytest "$@" -q -m 'not slow' \
        -k "$kfilter" -p no:cacheprovider
}

# the RPC transport module runs here too: its shm fast path pins,
# maps, releases, and deletes store objects from the wire protocol —
# pin/release misuse must trip ASan, not production
run_suite asan "" tests/test_native_store.py tests/test_rpc_transport.py
# TSan deadlocks in multiprocessing's spawn startup (fork + TSan's
# internal locks), hanging the cross-process test before exec.  TSan's
# job here is intra-process race detection on the shm segment (the
# allocator stress + concurrency tests); cross-process visibility is
# covered by the ASan leg and the regular suite.
run_suite tsan "not cross_process" tests/test_native_store.py
