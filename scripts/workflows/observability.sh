#!/usr/bin/env bash
# Observability gate: tracing/metrics plane tests, flight-recorder +
# incident-bundle tests, process self-metrics — plus a dryrun
# incident-bundle round-trip against the in-process multi-host harness
# (controller + 2 worker hosts over real websockets, a fault-injected
# failure, then `debug_bundle` must return one time-merged artifact).
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu

echo "== observability test suites =="
timeout -k 10 600 python -m pytest \
    tests/test_observability.py tests/test_metrics.py tests/test_flight.py \
    -q -rA -p no:cacheprovider

echo "== dryrun incident-bundle round-trip =="
timeout -k 10 180 python - <<'EOF'
import asyncio, json

from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.serving import DeploymentSpec, RequestOptions, ServeController
from bioengine_tpu.testing import faults
from bioengine_tpu.utils import flight
from bioengine_tpu.worker_host import WorkerHost


class Echo:
    async def ping(self):
        return "pong"


async def main():
    server = RpcServer(host="127.0.0.1", admin_users=["admin"])
    await server.start()
    token = server.issue_token("admin", is_admin=True)
    controller = ServeController(
        ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu")),
        health_check_period=3600,
    )
    controller.attach_rpc(server, admin_users=["admin"])
    hosts = [
        WorkerHost(server_url=server.url, token=token, host_id=f"h{i}")
        for i in (1, 2)
    ]
    for h in hosts:
        await h.start()
    await controller.deploy(
        "bundle-app", [DeploymentSpec(name="entry", instance_factory=Echo)]
    )
    handle = controller.get_handle("bundle-app")
    assert await handle.call("ping") == "pong"
    # one injected transport failure -> failover evidence in the ring
    faults.configure("rpc.client.send", "raise", nth=1, count=1)
    try:
        await hosts[0].connection.call("serve-router", "deregister_host", "nope")
    except Exception:
        faults.clear()
    faults.clear()

    bundle = await controller.debug_bundle()
    for key in ("events", "traces", "metrics", "cluster", "apps", "hosts"):
        assert key in bundle, key
    assert len(bundle["hosts"]) == 2, bundle["hosts"]
    assert all(h["reachable"] for h in bundle["hosts"].values())
    types = {e["type"] for e in bundle["events"]}
    assert "host.join" in types, types
    assert "fault.hit" in types, types
    ts = [e["ts"] for e in bundle["events"]]
    assert ts == sorted(ts), "bundle events are not time-ordered"
    json.dumps(bundle, default=str)  # the artifact must serialize
    print(
        f"bundle OK: {len(bundle['events'])} events, "
        f"{len(bundle['traces'])} spans, {len(bundle['hosts'])} hosts"
    )
    for h in hosts:
        await h.stop()
    await controller.stop()
    await server.stop()


asyncio.run(main())
EOF

echo "observability gate OK"
