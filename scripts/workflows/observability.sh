#!/usr/bin/env bash
# Observability gate: tracing/metrics plane tests, flight-recorder +
# incident-bundle tests, process self-metrics, telemetry history +
# SLO engine tests (incl. the scrape/undeploy race and chaos legs) —
# plus two dryruns against the in-process multi-host harness:
# an incident-bundle round-trip, and an SLO round-trip (inject a
# latency fault -> firing alert with auto-bundle evidence -> JSON-
# serializable get_slo_status).
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu

echo "== observability test suites =="
timeout -k 10 900 python -m pytest \
    tests/test_observability.py tests/test_metrics.py tests/test_flight.py \
    tests/test_telemetry.py tests/test_slo.py \
    -q -rA -p no:cacheprovider

echo "== dryrun incident-bundle round-trip =="
timeout -k 10 180 python - <<'EOF'
import asyncio, json

from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.serving import DeploymentSpec, RequestOptions, ServeController
from bioengine_tpu.testing import faults
from bioengine_tpu.utils import flight
from bioengine_tpu.worker_host import WorkerHost


class Echo:
    async def ping(self):
        return "pong"


async def main():
    server = RpcServer(host="127.0.0.1", admin_users=["admin"])
    await server.start()
    token = server.issue_token("admin", is_admin=True)
    controller = ServeController(
        ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu")),
        health_check_period=3600,
    )
    controller.attach_rpc(server, admin_users=["admin"])
    hosts = [
        WorkerHost(server_url=server.url, token=token, host_id=f"h{i}")
        for i in (1, 2)
    ]
    for h in hosts:
        await h.start()
    await controller.deploy(
        "bundle-app", [DeploymentSpec(name="entry", instance_factory=Echo)]
    )
    handle = controller.get_handle("bundle-app")
    assert await handle.call("ping") == "pong"
    # one injected transport failure -> failover evidence in the ring
    faults.configure("rpc.client.send", "raise", nth=1, count=1)
    try:
        await hosts[0].connection.call("serve-router", "deregister_host", "nope")
    except Exception:
        faults.clear()
    faults.clear()

    bundle = await controller.debug_bundle()
    for key in ("events", "traces", "metrics", "cluster", "apps", "hosts"):
        assert key in bundle, key
    assert len(bundle["hosts"]) == 2, bundle["hosts"]
    assert all(h["reachable"] for h in bundle["hosts"].values())
    types = {e["type"] for e in bundle["events"]}
    assert "host.join" in types, types
    assert "fault.hit" in types, types
    ts = [e["ts"] for e in bundle["events"]]
    assert ts == sorted(ts), "bundle events are not time-ordered"
    json.dumps(bundle, default=str)  # the artifact must serialize
    print(
        f"bundle OK: {len(bundle['events'])} events, "
        f"{len(bundle['traces'])} spans, {len(bundle['hosts'])} hosts"
    )
    for h in hosts:
        await h.stop()
    await controller.stop()
    await server.stop()


asyncio.run(main())
EOF

echo "== dryrun SLO round-trip (latency fault -> firing -> evidence) =="
timeout -k 10 180 python - <<'EOF'
import asyncio, json, time

from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.serving import DeploymentSpec, ServeController, SLOConfig
from bioengine_tpu.serving.slo import SLOEngine
from bioengine_tpu.utils import flight
from bioengine_tpu.utils.telemetry import TelemetryStore
from bioengine_tpu.worker_host import WorkerHost


class SloApp:
    def __init__(self):
        self.delay = 0.0

    async def set_delay(self, delay: float = 0.0):
        self.delay = float(delay)
        return {"delay": self.delay}

    async def infer(self):
        if self.delay:
            await asyncio.sleep(self.delay)
        return {"ok": True}


async def main():
    server = RpcServer(host="127.0.0.1", admin_users=["admin"])
    await server.start()
    token = server.issue_token("admin", is_admin=True)
    controller = ServeController(
        ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu")),
        health_check_period=3600,
    )
    # second-scale rings so burn windows are drivable in a dryrun
    controller.telemetry = TelemetryStore(resolutions=[(0.25, 480)])
    controller.slo = SLOEngine(
        controller.telemetry,
        on_page=controller._slo_page_hook,
        logger=controller.logger,
    )
    controller.attach_rpc(server, admin_users=["admin"])
    hosts = [
        WorkerHost(server_url=server.url, token=token, host_id=f"h{i}")
        for i in (1, 2)
    ]
    for h in hosts:
        await h.start()
    slo = SLOConfig.from_config(
        {"latency_objective_ms": 100, "latency_percentile": 99,
         "window": "60s", "for": "0s"}
    )
    await controller.deploy(
        "slo-dryrun",
        [DeploymentSpec(name="entry", instance_factory=SloApp, slo=slo)],
    )
    handle = controller.get_handle("slo-dryrun")
    controller.telemetry_tick()
    for _ in range(6):
        assert (await handle.call("infer"))["ok"]
    controller.telemetry_tick()

    def alert():
        return controller.get_slo_status()["deployments"][
            "slo-dryrun/entry"]["objectives"]["latency"]["alert"]

    assert alert()["state"] == "inactive", alert()
    # inject the latency fault and burn the budget
    await handle.call("set_delay", 0.25)
    for _ in range(8):
        assert (await handle.call("infer"))["ok"]
    controller.telemetry_tick()   # -> pending
    controller.telemetry_tick()   # -> firing
    a = alert()
    assert a["state"] == "firing" and a["severity"] == "page", a
    types = {e["type"] for e in flight.get_events()}
    assert "slo.firing" in types, types
    for _ in range(40):           # auto-bundle runs in the background
        if controller.slo_bundles:
            break
        await asyncio.sleep(0.05)
    assert controller.slo_bundles, "no auto-captured bundle"
    bundle = controller.slo_bundles[-1]
    assert bundle["slo_alert"]["objective"] == "latency"
    assert len(bundle["hosts"]) == 2
    json.dumps(controller.get_slo_status())  # the verb body serializes
    # fault clears -> resolved
    await handle.call("set_delay", 0.0)
    deadline = time.monotonic() + 8.0
    while time.monotonic() < deadline:
        await handle.call("infer")
        controller.telemetry_tick()
        if alert()["state"] == "resolved":
            break
        await asyncio.sleep(0.1)
    assert alert()["state"] == "resolved", alert()
    print(
        f"slo dryrun OK: firing severity={a['severity']} "
        f"burn_short={a['burn_short']}, bundle events="
        f"{len(bundle['events'])}, resolved after clear"
    )
    for h in hosts:
        await h.stop()
    await controller.stop()
    await server.stop()


asyncio.run(main())
EOF

echo "observability gate OK"
