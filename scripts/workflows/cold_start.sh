#!/usr/bin/env bash
# Cold-start gate: the shared compile-cache tier, streamed weight
# loading, and warm-pool suites (tier entry protocol, persistent-hit
# tagging, streamed-vs-eager bit parity, pool fill/promote/sweep, and
# the preemption chaos test), then a cold_start bench smoke asserting
# the warm-pool path beats the cold path ≥10x, then an in-process
# multi-host DRYRUN proving a second replica start hits the compile
# tier (first replica compiles for real; its entry rides
# host→controller-tier→host and the second replica's compile is tagged
# cache_hit).
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu

echo "== cold-start test suite =="
timeout -k 10 600 python -m pytest tests/test_cold_start.py -q -rA \
    -p no:cacheprovider

echo "== cold_start bench smoke =="
out="$(mktemp)"
timeout -k 10 300 env BENCH_PLATFORM=cpu BENCH_DEADLINE=240 \
    BENCH_CONFIGS=cold_start python bench.py | tail -n1 > "$out"
python - "$out" <<'EOF'
import json
import sys

with open(sys.argv[1]) as f:
    d = json.loads(f.read())
st = d["extra"]["cold_start"]
assert st and st.get("ok"), st
assert st["cold"]["real_compiles"] >= 1, st["cold"]
assert st["warm_cache_hit_observed"], st["warm_cache"]
assert st["warm_pool"]["promoted_from_warm_pool"], st["warm_pool"]
assert st["speedup_warm_pool"] >= 10.0, st["speedup_warm_pool"]
print(
    f"cold_start OK: cold={st['cold']['ttfr_s']}s "
    f"warm_cache={st['warm_cache']['ttfr_s']}s "
    f"warm_pool={st['warm_pool']['ttfr_s']}s "
    f"(speedups {st['speedup_warm_cache']}x / {st['speedup_warm_pool']}x)"
)
EOF

echo "== compile-tier dryrun (second replica start hits the tier) =="
timeout -k 10 300 python - <<'EOF'
import asyncio
import os
import tempfile

root = tempfile.mkdtemp(prefix="coldstart-dryrun-")
dir_a = os.path.join(root, "xla-a")
dir_b = os.path.join(root, "xla-b")
os.makedirs(dir_b)
os.environ["BIOENGINE_COMPILE_CACHE"] = dir_a
# 8 virtual host devices so each in-process "host" can lease 3 chips
# (same forced layout the test suite runs under)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

from bioengine_tpu.utils import flight
from bioengine_tpu.utils.compile_cache import (
    enable_persistent_compilation_cache,
    list_entries,
)

assert enable_persistent_compilation_cache() == dir_a

APP_MANIFEST = """\
name: Cold Start Dryrun
id: coldstart-dryrun
id_emoji: "\\u2744"
description: second replica start must hit the compile tier
type: tpu-serve
version: 1.0.0
deployments:
  - warm_dep:WarmDep
authorized_users: ["*"]
deployment_config:
  warm_dep:
    num_replicas: 2
    min_replicas: 2
    max_replicas: 2
    chips: 3
    autoscale: false
"""

# each replica compiles the same UNet program through its OWN
# CompiledProgramCache: replica 1 pays the real XLA compile (entry
# lands in the persistent dir + the tier), replica 2's "compile" is a
# near-zero persistent-cache read and must be tagged cache_hit
APP_SOURCE = '''\
import jax
import jax.numpy as jnp

from bioengine_tpu.models.unet import UNet2D
from bioengine_tpu.rpc import schema_method
from bioengine_tpu.runtime.program_cache import CompiledProgramCache


class WarmDep:
    async def async_init(self):
        model = UNet2D(features=(8, 16), out_channels=1)
        x = jnp.zeros((1, 64, 64, 1), jnp.float32)
        params = model.init(jax.random.key(0), x)["params"]
        cache = CompiledProgramCache()

        def build():
            f = jax.jit(lambda p, t: model.apply({"params": p}, t))
            f(params, x).block_until_ready()
            return f

        cache.get_or_compile(("dryrun-unet", 64), build)
        self.persistent_hits = cache.stats.persistent_hits

    @schema_method
    async def ping(self, context=None):
        """Liveness."""
        return {"ok": True}
'''


async def main():
    from pathlib import Path

    from bioengine_tpu.apps.builder import AppBuilder
    from bioengine_tpu.cluster.state import ClusterState
    from bioengine_tpu.cluster.topology import TpuTopology
    from bioengine_tpu.rpc.server import RpcServer
    from bioengine_tpu.serving import ServeController
    from bioengine_tpu.serving.compile_tier import CompileCacheTier
    from bioengine_tpu.worker_host import WorkerHost

    server = RpcServer(host="127.0.0.1", admin_users=["admin"])
    await server.start()
    token = server.issue_token("admin", is_admin=True)
    controller = ServeController(
        ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu")),
        health_check_period=3600,
    )
    controller.compile_tier = CompileCacheTier(os.path.join(root, "tier"))
    controller.attach_rpc(server, admin_users=["admin"])
    h1 = WorkerHost(
        server_url=server.url, token=token, host_id="h1",
        workspace_dir=os.path.join(root, "ws1"), compile_cache_dir=dir_a,
    )
    h2 = WorkerHost(
        server_url=server.url, token=token, host_id="h2",
        workspace_dir=os.path.join(root, "ws2"), compile_cache_dir=dir_b,
    )
    await h1.start()
    await h2.start()
    app_dir = Path(root) / "app-src"
    app_dir.mkdir()
    (app_dir / "manifest.yaml").write_text(APP_MANIFEST)
    (app_dir / "warm_dep.py").write_text(APP_SOURCE)
    builder = AppBuilder(workdir_root=Path(root) / "apps")
    built = builder.build(app_id="coldstart-dryrun", local_path=app_dir)
    await controller.deploy("coldstart-dryrun", built.specs)

    compiles = [
        e for e in flight.get_record(limit=2000)["events"]
        if e["type"] == "program.compile"
        and "dryrun-unet" in e["attrs"].get("key", "")
    ]
    assert len(compiles) == 2, compiles
    assert compiles[0]["attrs"]["cache_hit"] is False, compiles[0]
    # THE assertion: the second in-process replica start hit the tier
    assert compiles[1]["attrs"]["cache_hit"] is True, compiles[1]
    tier_stats = controller.compile_tier.stats()
    assert tier_stats["stored"] >= 1, tier_stats
    fetched_b = list_entries(dir_b)
    assert fetched_b, "h2 fetched no tier entries"
    print(
        f"dryrun OK: real_compile={round(compiles[0]['attrs']['seconds'], 3)}s "
        f"tier_hit={round(compiles[1]['attrs']['seconds'], 3)}s "
        f"tier_entries={tier_stats['entries']} "
        f"h2_fetched={len(fetched_b)}"
    )
    await h1.stop()
    await h2.stop()
    await controller.stop()
    await server.stop()


asyncio.run(main())
EOF

echo "cold-start gate OK"
