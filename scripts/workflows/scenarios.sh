#!/usr/bin/env bash
# Scenario-diversity gate (ROADMAP item 5's scenarios.sh job): every
# named synthetic incident — gray failure, preemption storm, diurnal
# wave, blip storm, hot-signature skew, tenant flood — runs with its
# invariants enforced, plus a determinism check (one scenario run twice
# with the same seed must produce identical request outcome sequences
# and invariant verdicts) and the gray-failure acceptance proof (the
# same seed WITHOUT defenses must show the degradation the machinery
# fixes).
#
# Knobs:
#   BIOENGINE_SCENARIO_SEED    workload seed (default 7)
#   BIOENGINE_SCENARIO_CYCLES  repeat the whole suite N times (default 1)
#   BIOENGINE_SCENARIO_SCALE   time-compression stretch for slow CI boxes
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
SEED="${BIOENGINE_SCENARIO_SEED:-7}"
CYCLES="${BIOENGINE_SCENARIO_CYCLES:-1}"

for cycle in $(seq 1 "$CYCLES"); do
    echo "== scenario suite (cycle ${cycle}/${CYCLES}, seed ${SEED}) =="
    for name in preemption_storm diurnal_wave blip_storm hot_signature tenant_flood controller_crash; do
        echo "-- ${name}"
        timeout -k 10 300 python -m bioengine_tpu.cli scenarios run "$name" \
            --seed "$SEED" > /dev/null
    done

    echo "-- slow_replica (defended + determinism double run)"
    timeout -k 10 420 python -m bioengine_tpu.cli scenarios run slow_replica \
        --seed "$SEED" --check-determinism > /dev/null

    echo "-- slow_replica (defenses off: the same seed must SHOW the degradation)"
    out="$(mktemp)"
    timeout -k 10 300 python -m bioengine_tpu.cli scenarios run slow_replica \
        --seed "$SEED" --no-defenses --out "$out" > /dev/null
    python - "$out" <<'EOF'
import json
import sys

with open(sys.argv[1]) as f:
    d = json.load(f)
inv = d["result"]["invariants"]
# undefended leg: traffic still survives (idempotent failover is older
# machinery) but the tail must NOT recover — that asymmetry is the
# proof the scenario detects exactly what probation+hedging fix
assert inv["zero_failed_idempotent"]["ok"], inv["zero_failed_idempotent"]
assert not inv["p99_recovery"]["ok"], (
    "undefended run recovered p99 — the scenario no longer exercises "
    f"the gray failure: {inv['p99_recovery']}"
)
print(
    "undefended degradation confirmed:", inv["p99_recovery"]["detail"]
)
EOF
done

echo "scenarios gate OK"
