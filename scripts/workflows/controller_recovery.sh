#!/usr/bin/env bash
# Durable-control-plane gate: the controller must be crash-restartable
# with zero failed idempotent requests. Three legs:
#   1. the recovery suite (journal units, snapshot+replay, reconcile
#      edge cases, orphan mode, epoch fencing, mesh rebuild, CLI)
#   2. the controller_crash scenario — SIGKILL-equivalent teardown
#      mid-mixed-priority traffic, restart, reconcile — run twice with
#      one seed and required to produce identical outcome sequences
#      and invariant verdicts (determinism double run)
#   3. the real-subprocess leg: an actual controller process is
#      SIGKILLed and restarted on the same port + journal dir, and a
#      live worker host must ride through orphaned -> rejoined with
#      its replica re-adopted in place
#
# Knobs:
#   BIOENGINE_SCENARIO_SEED    workload seed (default 7)
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
SEED="${BIOENGINE_SCENARIO_SEED:-7}"

echo "== controller recovery suite (fast legs) =="
timeout -k 10 600 python -m pytest tests/test_controller_recovery.py \
    -m "not slow" -q -p no:cacheprovider

echo "== controller_crash scenario (determinism double run, seed ${SEED}) =="
timeout -k 10 420 python -m bioengine_tpu.cli scenarios run controller_crash \
    --seed "$SEED" --check-determinism > /dev/null

echo "== real-subprocess kill/restart leg =="
timeout -k 10 600 python -m pytest tests/test_controller_recovery.py \
    -m slow -q -rA -p no:cacheprovider

echo "controller recovery gate OK"
