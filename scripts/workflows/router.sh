#!/usr/bin/env bash
# Router-tier gate (the scale-out routing job): the router unit suite
# (table publication, epoch fencing, gate/kill semantics, the shared-
# contract pins), the router_loss scenario (a router killed mid-traffic
# must lose ZERO idempotent requests — clients hop typed to a sibling
# — while table staleness stays bounded), and a router_scaling bench
# smoke (1→4 routers must scale goodput ≥3x with zero idempotent loss
# across the kill leg).
#
# Knobs:
#   BIOENGINE_SCENARIO_SEED   workload seed (default 7)
#   BIOENGINE_SCENARIO_SCALE  time-compression stretch for slow CI boxes
#   BENCH_ROUTER_LEGS         bench router counts (default here: 1,4)
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS=cpu
SEED="${BIOENGINE_SCENARIO_SEED:-7}"

echo "== router unit suite =="
timeout -k 10 300 python -m pytest tests/test_router.py -q \
    -p no:cacheprovider

echo "== router_loss scenario (seed ${SEED}) =="
out="$(mktemp)"
timeout -k 10 300 python -m bioengine_tpu.cli scenarios run router_loss \
    --seed "$SEED" --out "$out" > /dev/null
python - "$out" <<'EOF'
import json
import sys

with open(sys.argv[1]) as f:
    d = json.load(f)
res = d["result"]
inv = res["invariants"]
for name in (
    "zero_failed_idempotent",
    "router_failover_observed",
    "router_staleness_bounded",
):
    assert inv[name]["ok"], (name, inv[name])
routers = res["routers"]
assert routers["killed"] == ["r1"], routers["killed"]
assert routers["client_failovers"] > 0, "no client ever hopped routers"
print(
    f"router_loss OK: {routers['client_failovers']} failover hop(s), "
    f"max table age {1000 * routers['staleness_max_s']:.0f}ms"
)
EOF

echo "== router_scaling bench smoke =="
BENCH_PLATFORM=cpu BENCH_DEADLINE=240 BENCH_ROUTER_LEGS="${BENCH_ROUTER_LEGS:-1,4}" \
    BENCH_CONFIGS=router_scaling python bench.py \
    | grep '^{' | tail -n 1 > /tmp/_router_bench.json
python - /tmp/_router_bench.json <<'EOF'
import json
import sys

with open(sys.argv[1]) as f:
    st = json.load(f)["extra"]["router_scaling"]
assert st["ok"], st
assert st["router_loss"]["failed_idempotent"] == 0, st["router_loss"]
scaling = st["goodput_scaling_4x_vs_1"]
assert scaling is None or scaling >= 3.0, scaling
print(f"router_scaling OK: 4x-vs-1 goodput ratio {scaling}")
EOF

echo "router gate OK"
