#!/bin/bash
# Start a BioEngine-TPU worker inside Apptainer/Singularity on an HPC
# system — the TPU-native counterpart of the reference's
# scripts/start_hpc_worker.sh (ref :1-306, which launches the Ray-based
# GPU worker). All arguments are passed through to
# `python -m bioengine_tpu.worker`; the script only resolves the
# container runtime + image and sets up the bind mounts the worker
# needs (workspace, datasets, TPU device nodes when present).
#
# Usage:
#   ./scripts/start_hpc_worker.sh --mode slurm --workspace-dir ~/.bioengine \
#       --datasets-dir /proj/data [worker args...]
#
# Environment:
#   BIOENGINE_IMAGE      image URI or SIF path
#                        (default: docker://ghcr.io/bioengine-tpu/worker:latest)
#   BIOENGINE_SIF_CACHE  where to keep the built SIF (default: ~/.bioengine/sif)
#   BIOENGINE_DRY_RUN=1  print the final command instead of exec'ing it

set -euo pipefail

WORKER_ARGS=("$@")

# --- container runtime -------------------------------------------------------
if command -v apptainer &>/dev/null; then
    CONTAINER_CMD="apptainer"
elif command -v singularity &>/dev/null; then
    CONTAINER_CMD="singularity"
else
    echo "❌ Neither Apptainer nor Singularity found on PATH." >&2
    exit 1
fi

# --- helpers -----------------------------------------------------------------
get_arg_value() {
    # get_arg_value --flag default -> value of "--flag VALUE" or "--flag=VALUE"
    local tag="$1" value="$2"
    local i
    for ((i = 0; i < ${#WORKER_ARGS[@]}; i++)); do
        if [[ "${WORKER_ARGS[i]}" == "$tag" ]] && ((i + 1 < ${#WORKER_ARGS[@]})); then
            value="${WORKER_ARGS[i + 1]}"
            break
        elif [[ "${WORKER_ARGS[i]}" == "$tag="* ]]; then
            value="${WORKER_ARGS[i]#*=}"
            break
        fi
    done
    echo "$value"
}

# --- image resolution --------------------------------------------------------
IMAGE="${BIOENGINE_IMAGE:-docker://ghcr.io/bioengine-tpu/worker:latest}"
SIF_CACHE="${BIOENGINE_SIF_CACHE:-$HOME/.bioengine/sif}"

if [[ "$IMAGE" == docker://* ]]; then
    mkdir -p "$SIF_CACHE"
    SIF_NAME="$(echo "${IMAGE#docker://}" | tr '/:' '__').sif"
    SIF_PATH="$SIF_CACHE/$SIF_NAME"
    if [[ ! -f "$SIF_PATH" && "${BIOENGINE_DRY_RUN:-0}" != "1" ]]; then
        echo "Building SIF from $IMAGE (one-time, cached at $SIF_PATH)..."
        "$CONTAINER_CMD" pull "$SIF_PATH" "$IMAGE"
    fi
    IMAGE="$SIF_PATH"
fi

# --- bind mounts -------------------------------------------------------------
WORKSPACE_DIR="$(get_arg_value --workspace-dir "$HOME/.bioengine")"
WORKSPACE_DIR="${WORKSPACE_DIR/#\~/$HOME}"
mkdir -p "$WORKSPACE_DIR"
BINDS=(--bind "$WORKSPACE_DIR:$WORKSPACE_DIR")

DATASETS_DIR="$(get_arg_value --datasets-dir "")"
if [[ -n "$DATASETS_DIR" ]]; then
    BINDS+=(--bind "$DATASETS_DIR:$DATASETS_DIR:ro")
fi

# TPU VM device nodes (present on Cloud TPU hosts; harmless to skip on
# CPU-only login nodes where the worker runs control-plane only)
for dev in /dev/accel* /dev/vfio; do
    if [[ -e "$dev" ]]; then
        BINDS+=(--bind "$dev:$dev")
    fi
done

# --- launch ------------------------------------------------------------------
CMD=("$CONTAINER_CMD" exec --cleanenv
    --env "BIOENGINE_ADMIN_TOKEN=${BIOENGINE_ADMIN_TOKEN:-}"
    --env "HOME=$HOME"
    "${BINDS[@]}"
    "$IMAGE"
    python -m bioengine_tpu.worker "${WORKER_ARGS[@]}")

if [[ "${BIOENGINE_DRY_RUN:-0}" == "1" ]]; then
    printf '%q ' "${CMD[@]}"
    printf '\n'
    exit 0
fi

exec "${CMD[@]}"
