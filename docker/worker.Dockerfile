# BioEngine-TPU worker image — the TPU answer to the reference's
# docker/worker.Dockerfile (CUDA via torch inside Ray runtime envs).
# Runs on Cloud TPU VMs / GKE TPU node pools: jax[tpu] talks to the
# chips through libtpu + /dev/accel*, so the image needs no CUDA stack.
#
#   docker build -f docker/worker.Dockerfile -t bioengine-tpu-worker .
#
# On a TPU VM run with device + shm access:
#   docker run --privileged --network host \
#     -v $HOME/.bioengine:/home/.bioengine bioengine-tpu-worker \
#     python -m bioengine_tpu.worker --mode single-machine

FROM python:3.11-slim

ENV PYTHONUNBUFFERED=1 \
    PYTHONDONTWRITEBYTECODE=1 \
    PIP_NO_CACHE_DIR=1

# build-essential: the native shared-memory object store
# (native/object_store.cpp) compiles in-image so first use never needs
# a toolchain at runtime. curl: compose healthchecks.
RUN apt-get update && apt-get install -y --no-install-recommends \
    build-essential \
    curl \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app

# Dependency layer first — package source changes don't invalidate it.
COPY docker/requirements-worker.txt /app/
RUN pip install -U pip && pip install -r requirements-worker.txt

COPY bioengine_tpu/ /app/bioengine_tpu/
COPY native/ /app/native/
COPY apps/ /app/apps/
COPY pyproject.toml README.md /app/

RUN pip install --no-deps .

# Pre-build the native object store so replicas never race the first
# `make` at runtime.
RUN make -C /app/native

# ---------------------------------------------------------------------------
# jax + libtpu last, controlled by JAX_VERSION: bumping the jax/libtpu
# pair (they must match) rebuilds only this layer, mirroring the
# reference's Ray-last layering trick (ref docker/worker.Dockerfile).
#
#   docker build --build-arg JAX_VERSION=0.4.35 \
#     -f docker/worker.Dockerfile -t bioengine-tpu-worker:dev .
# ---------------------------------------------------------------------------
ARG JAX_VERSION=0.4.35
RUN pip install "jax[tpu]==${JAX_VERSION}" \
    -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

ENV BIOENGINE_JAX_VERSION=${JAX_VERSION}

CMD ["/bin/bash"]
