# BioEngine-TPU datasets server image — serves zarr/file datasets over
# HTTP with Range support (the analog of ref docker/datasets.Dockerfile,
# which ships a FastAPI server; here the server is the framework's own
# aiohttp app, bioengine_tpu/datasets/proxy_server.py).
#
#   docker build -f docker/datasets.Dockerfile -t bioengine-tpu-datasets .
#
# The zarr codecs bind SYSTEM libblosc/zstd/lz4 via ctypes
# (bioengine_tpu/datasets/codecs.py) — no compiled Python wheels needed.

FROM python:3.11-slim

ENV PYTHONUNBUFFERED=1 \
    PYTHONDONTWRITEBYTECODE=1 \
    PIP_NO_CACHE_DIR=1

RUN apt-get update && apt-get install -y --no-install-recommends \
    libblosc1 \
    libzstd1 \
    liblz4-1 \
    curl \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app

COPY docker/requirements-datasets.txt /app/
RUN pip install -U pip && pip install -r requirements-datasets.txt

COPY bioengine_tpu/ /app/bioengine_tpu/
COPY pyproject.toml README.md /app/
RUN pip install --no-deps .

EXPOSE 39527

CMD ["python", "-m", "bioengine_tpu.datasets", "/data", "--port", "39527"]
