# Lightweight overlay over a published BioEngine-TPU worker image:
# swaps only the jax/libtpu pin without rebuilding system packages,
# Python, the native store, or the rest of the dependency tree — the
# analog of the reference's Ray-overlay image
# (ref docker/worker-ray-overlay.Dockerfile: same motivation, a
# version-locked runtime dependency that must match the environment it
# connects to; here it is the jax/libtpu pair that must match the TPU
# VM's driver generation instead of a Ray cluster's version).
#
# Build:
#   docker build \
#       --build-arg BIOENGINE_IMAGE=ghcr.io/OWNER/bioengine-tpu-worker:latest \
#       --build-arg JAX_VERSION=0.4.38 \
#       -f docker/worker-jax-overlay.Dockerfile \
#       -t bioengine-tpu-worker:jax0.4.38 .
#
# BIOENGINE_IMAGE: the published image used as the base.
# JAX_VERSION:     the exact jax release to swap in; libtpu resolves to
#   the matching build from the jax releases index.

ARG BIOENGINE_IMAGE=ghcr.io/aicell-lab/bioengine-tpu-worker:latest
FROM ${BIOENGINE_IMAGE}

ARG JAX_VERSION=0.4.35
RUN pip install --no-cache-dir "jax[tpu]==${JAX_VERSION}" \
    -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

ENV BIOENGINE_JAX_VERSION=${JAX_VERSION}
