"""fibsem-mito-analysis app: post-processing units + the full
app→app composition flow (fibsem → model-runner over the framework
RPC websocket, batched tiled inference, stitching, morphology)."""

import asyncio
import importlib.util
import sys
import time
from pathlib import Path

import numpy as np
import pytest

pytestmark = [pytest.mark.integration, pytest.mark.anyio]

REPO_APPS = Path(__file__).resolve().parent.parent / "apps"
APP_DIR = REPO_APPS / "fibsem-mito-analysis"


def _load_cls():
    spec = importlib.util.spec_from_file_location(
        "fibsem_analysis", APP_DIR / "analysis_deployment.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["fibsem_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod.MitoAnalysis


MitoAnalysis = _load_cls()


def _synthetic_em(size=256, n_mito=6, seed=0):
    """EM-like image with dark elliptical blobs + the true mask."""
    rng = np.random.default_rng(seed)
    img = rng.normal(170, 12, (size, size)).astype(np.float32)
    mask = np.zeros((size, size), bool)
    yy, xx = np.mgrid[:size, :size]
    for _ in range(n_mito):
        cy, cx = rng.integers(40, size - 40, 2)
        ry, rx = rng.integers(10, 22, 2)
        blob = ((yy - cy) / ry) ** 2 + ((xx - cx) / rx) ** 2 < 1
        img[blob] = rng.normal(60, 8, blob.sum())
        mask |= blob
    return img, mask


class TestPostProcessing:
    def test_remove_small(self):
        binary = np.zeros((64, 64), bool)
        binary[2:4, 2:4] = True          # 4 px — removed
        binary[20:45, 20:45] = True      # 625 px — kept
        out = MitoAnalysis._remove_small(binary, min_size=300)
        assert not out[2, 2] and out[30, 30]

    def test_instances_split_touching_blobs(self):
        prob = np.zeros((128, 128), np.float32)
        yy, xx = np.mgrid[:128, :128]
        # two circles overlapping slightly
        prob[((yy - 50) ** 2 + (xx - 50) ** 2) < 18**2] = 0.9
        prob[((yy - 50) ** 2 + (xx - 85) ** 2) < 18**2] = 0.9
        labels = MitoAnalysis._prob_to_instances(prob)
        assert labels.max() == 2

    def test_instances_empty(self):
        labels = MitoAnalysis._prob_to_instances(
            np.zeros((64, 64), np.float32)
        )
        assert labels.max() == 0

    def test_region_properties_circle(self):
        labels = np.zeros((80, 80), np.int32)
        yy, xx = np.mgrid[:80, :80]
        labels[((yy - 40) ** 2 + (xx - 40) ** 2) < 15**2] = 1
        props = MitoAnalysis._region_properties(labels, pixel_um=0.005)
        assert props["label"] == [1]
        area_px = (labels == 1).sum()
        np.testing.assert_allclose(
            props["area_um2"][0], area_px * 0.005**2, rtol=1e-6
        )
        assert props["aspect_ratio"][0] < 1.1   # circle ≈ 1
        assert props["eccentricity"][0] < 0.3
        np.testing.assert_allclose(props["centroid_y"][0], 40, atol=0.5)

    def test_region_properties_ellipse_axes(self):
        labels = np.zeros((120, 120), np.int32)
        yy, xx = np.mgrid[:120, :120]
        labels[(((yy - 60) / 10) ** 2 + ((xx - 60) / 30) ** 2) < 1] = 1
        props = MitoAnalysis._region_properties(labels, pixel_um=1.0)
        np.testing.assert_allclose(
            props["aspect_ratio"][0], 3.0, rtol=0.1
        )
        assert props["eccentricity"][0] > 0.9


# ---- full composition flow --------------------------------------------------


async def deploy(manager, app_dir, **kwargs):
    from bioengine_tpu.utils.permissions import create_context

    result = await manager.deploy_app(
        local_path=str(REPO_APPS / app_dir),
        context=create_context("admin"),
        **kwargs,
    )
    await asyncio.sleep(0.05)
    return result


async def call(server, service_id, method, **kwargs):
    caller = server.validate_token(server.issue_token("user"))
    return await server.call_service_method(
        service_id, method, kwargs=kwargs, caller=caller
    )


@pytest.fixture(scope="module")
def seg_collection(tmp_path_factory):
    """Local model collection with a tiny NHWC segmentation UNet whose
    output is a brightness threshold-ish map (weights trained-free:
    random init is fine — the fibsem flow only needs shape contracts,
    but we bias the final conv so prob maps vary with input)."""
    import jax
    import jax.numpy as jnp
    import yaml

    from bioengine_tpu.models.unet import UNet2D
    from bioengine_tpu.runtime.convert import save_params_npz

    root = tmp_path_factory.mktemp("seg_collection")
    d = root / "tiny-unet"
    d.mkdir()
    model = UNet2D(features=(8, 16), out_channels=1)
    x = np.random.default_rng(0).normal(size=(1, 64, 64, 1)).astype(np.float32)
    params = model.init(jax.random.key(0), jnp.asarray(x))["params"]
    expected = np.asarray(
        jax.jit(lambda p, a: model.apply({"params": p}, a))(
            params, jnp.asarray(x)
        )
    )
    save_params_npz(str(d / "weights.npz"), params)
    np.save(d / "test_input.npy", x)
    np.save(d / "test_output.npy", expected)
    (d / "rdf.yaml").write_text(
        yaml.safe_dump(
            {
                "type": "model",
                "name": "Tiny UNet",
                "description": "tiny segmentation test model",
                "tags": ["segmentation"],
                "inputs": [{"name": "input0", "axes": "byxc"}],
                "outputs": [{"name": "output0", "axes": "byxc"}],
                "test_inputs": ["test_input.npy"],
                "test_outputs": ["test_output.npy"],
                "documentation": "README.md",
                "weights": {
                    "jax_params": {
                        "source": "weights.npz",
                        "architecture": {
                            "name": "unet2d",
                            "kwargs": {
                                "features": [8, 16],
                                "out_channels": 1,
                            },
                        },
                    }
                },
            }
        )
    )
    (d / "README.md").write_text("# Tiny UNet")
    return root


@pytest.fixture
async def fibsem_stack(stack, seg_collection, tmp_path, monkeypatch):
    monkeypatch.setenv("BIOENGINE_LOCAL_MODEL_PATH", str(seg_collection))
    manager, _, server, _ = stack
    mr = await deploy(
        manager,
        "model-runner",
        deployment_kwargs={
            "entry_deployment": {"cache_dir": str(tmp_path / "cache")}
        },
    )
    token = server.issue_token("fibsem-app")
    fibsem = await deploy(
        manager,
        "fibsem-mito-analysis",
        deployment_kwargs={
            "analysis_deployment": {
                "model_runner_service": mr["service_id"],
                "model_id": "tiny-unet",
                "server_url": server.url,
                "batch_size": 4,
                "input_layout": "NHWC",
            }
        },
        env_vars={"BIOENGINE_TOKEN": token},
    )
    return fibsem, server


class TestFibsemApp:
    async def test_ping(self, fibsem_stack):
        result, server = fibsem_stack
        pong = await call(server, result["service_id"], "ping")
        assert pong["status"] == "ok"
        assert pong["model"] == "tiny-unet"

    async def test_analyze_small_image(self, fibsem_stack):
        result, server = fibsem_stack
        img, _ = _synthetic_em(size=128)
        out = await call(
            server, result["service_id"], "analyze",
            image=img, tile_size=512,
        )
        assert out["image_shape"] == [128, 128]
        labels = np.asarray(out["labels"])
        assert labels.shape == (128, 128)
        assert out["n_mitochondria"] == len(out["properties"]["label"])
        assert "processing_time_s" in out

    async def test_analyze_tiled(self, fibsem_stack):
        """Image larger than tile_size exercises batched tiled
        inference + Gaussian stitch."""
        result, server = fibsem_stack
        img, _ = _synthetic_em(size=200)
        out = await call(
            server, result["service_id"], "analyze",
            image=img, tile_size=128, overlap=32,
        )
        assert out["image_shape"] == [200, 200]
        assert np.asarray(out["labels"]).shape == (200, 200)

    async def test_rejects_3d(self, fibsem_stack):
        result, server = fibsem_stack
        with pytest.raises(Exception, match="2-D"):
            await call(
                server, result["service_id"], "analyze",
                image=np.zeros((4, 8, 8)),
            )

    async def test_rejects_bad_overlap(self, fibsem_stack):
        result, server = fibsem_stack
        img, _ = _synthetic_em(size=200)
        with pytest.raises(Exception, match="overlap"):
            await call(
                server, result["service_id"], "analyze",
                image=img, tile_size=128, overlap=128,
            )
