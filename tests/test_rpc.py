import asyncio

import numpy as np
import pytest

from bioengine_tpu.rpc.client import connect_to_server
from bioengine_tpu.rpc.protocol import RemoteError, decode, encode
from bioengine_tpu.rpc.schema import extract_schema, schema_method
from bioengine_tpu.rpc.server import RpcServer

pytestmark = [pytest.mark.integration, pytest.mark.anyio]


class TestProtocol:
    def test_roundtrip_basic(self):
        msg = {"t": "call", "args": [1, "x", 2.5, None, True], "kwargs": {"a": [1, 2]}}
        assert decode(encode(msg)) == msg

    def test_roundtrip_ndarray(self):
        arr = np.random.rand(3, 4).astype(np.float32)
        out = decode(encode({"r": arr}))["r"]
        np.testing.assert_array_equal(out, arr)
        assert out.dtype == np.float32

    def test_roundtrip_exception(self):
        err = decode(encode({"e": ValueError("boom")}))["e"]
        assert isinstance(err, RemoteError)
        assert "boom" in str(err)


class TestSchema:
    def test_extract_schema(self):
        @schema_method
        def infer(model_id: str, batch: int = 4, context=None):
            """Run inference."""

        s = infer.__schema__
        assert s["name"] == "infer"
        assert s["description"] == "Run inference."
        assert s["parameters"]["required"] == ["model_id"]
        assert "context" not in s["parameters"]["properties"]
        assert s["parameters"]["properties"]["batch"]["default"] == 4

    def test_plain_function_schema(self):
        def f(x, y=1):
            pass

        s = extract_schema(f)
        assert set(s["parameters"]["properties"]) == {"x", "y"}


@pytest.fixture
async def server():
    srv = RpcServer(admin_users=["admin"])
    await srv.start()
    yield srv
    await srv.stop()


@pytest.fixture
async def admin_conn(server):
    token = server.issue_token("admin")
    conn = await connect_to_server(
        {"server_url": f"http://127.0.0.1:{server.port}", "token": token}
    )
    yield conn
    await conn.disconnect()


class TestServer:
    async def test_local_service_call_with_context(self, server):
        seen = {}

        def who_am_i(context=None):
            seen.update(context)
            return context["user"]["id"]

        server.register_local_service(
            {
                "id": "test-svc",
                "config": {"require_context": True},
                "who_am_i": who_am_i,
            }
        )
        info = server.issue_token("alice")
        result = await server.call_service_method(
            "test-svc", "who_am_i", caller=server.validate_token(info)
        )
        assert result == "alice"
        assert seen["ws"] == "bioengine"

    async def test_expired_token_rejected(self, server):
        token = server.issue_token("bob", ttl_seconds=-1)
        with pytest.raises(PermissionError, match="expired"):
            server.validate_token(token)

    async def test_unknown_token_rejected(self, server):
        with pytest.raises(PermissionError):
            server.validate_token("nope")

    async def test_remote_client_registers_and_serves(self, server, admin_conn):
        calls = []

        @schema_method
        async def echo(value, context=None):
            """Echo a value back."""
            calls.append(value)
            return {"echoed": value}

        svc = await admin_conn.register_service(
            {
                "id": "echo-svc",
                "name": "Echo",
                "config": {"require_context": True},
                "echo": echo,
            }
        )
        assert svc["id"] == "bioengine/echo-svc"

        # second client calls through the server
        conn2 = await connect_to_server(
            {"server_url": f"http://127.0.0.1:{server.port}"}
        )
        try:
            proxy = await conn2.get_service("echo-svc")
            out = await proxy.echo(value=42)
            assert out == {"echoed": 42}
            assert calls == [42]
        finally:
            await conn2.disconnect()

    async def test_ndarray_over_the_wire(self, server, admin_conn):
        async def double(arr):
            return arr * 2

        await admin_conn.register_service({"id": "math-svc", "double": double})
        conn2 = await connect_to_server(
            {"server_url": f"http://127.0.0.1:{server.port}"}
        )
        try:
            arr = np.arange(12, dtype=np.float32).reshape(3, 4)
            out = await conn2.call("bioengine/math-svc", "double", arr)
            np.testing.assert_array_equal(out, arr * 2)
        finally:
            await conn2.disconnect()

    async def test_remote_error_propagates(self, server, admin_conn):
        async def fail():
            raise ValueError("deliberate")

        await admin_conn.register_service({"id": "fail-svc", "fail": fail})
        with pytest.raises(RemoteError, match="deliberate"):
            await admin_conn.call("bioengine/fail-svc", "fail")

    async def test_generate_token_requires_admin(self, server):
        conn = await connect_to_server(
            {"server_url": f"http://127.0.0.1:{server.port}"}
        )
        try:
            with pytest.raises(Exception, match="admin"):
                await conn.generate_token()
        finally:
            await conn.disconnect()

    async def test_admin_generates_token_for_user(self, server, admin_conn):
        token = await admin_conn.generate_token({"user_id": "app-1"})
        info = server.validate_token(token)
        assert info.user_id == "app-1"
        assert not info.is_admin

    async def test_service_dropped_on_disconnect(self, server, admin_conn):
        conn2 = await connect_to_server(
            {"server_url": f"http://127.0.0.1:{server.port}"}
        )
        await conn2.register_service({"id": "ephemeral", "f": lambda: 1})
        assert any(
            s["id"] == "bioengine/ephemeral" for s in server.list_services()
        )
        await conn2.disconnect()
        await asyncio.sleep(0.2)
        assert not any(
            s["id"] == "bioengine/ephemeral" for s in server.list_services()
        )

    async def test_ping(self, admin_conn):
        ts = await admin_conn.ping()
        assert ts > 0

    async def test_list_services_shapes(self, server, admin_conn):
        @schema_method
        def m(x: int):
            """Doc."""

        await admin_conn.register_service({"id": "s1", "name": "S1", "m": m})
        services = await admin_conn.list_services()
        s1 = next(s for s in services if s["id"] == "bioengine/s1")
        assert s1["name"] == "S1"
        assert "m" in s1["methods"]


class TestTokenIdentityFallback:
    async def test_generate_token_defaults_to_caller_identity(
        self, server, admin_conn
    ):
        token = await admin_conn.generate_token({})
        info = server.validate_token(token)
        assert info.user_id == "admin"
        assert info.workspace == "bioengine"


def test_http_bridge_jsonable_sanitizes_nonfinite():
    """NaN/Inf must become null — browsers' JSON.parse rejects Python's
    bare NaN literals (a diverged loss must not break the frontend)."""
    import json
    import math

    import numpy as np

    from bioengine_tpu.rpc.server import _to_jsonable

    payload = _to_jsonable(
        {
            "loss": float("nan"),
            "losses": [1.0, float("inf"), 2.0],
            "arr": np.array([1.0, np.nan]),
            "ok_arr": np.arange(3),
            "nested": {"v": np.float32("inf")},
        }
    )
    text = json.dumps(payload, allow_nan=False)  # raises if any slipped by
    back = json.loads(text)
    assert back["loss"] is None
    assert back["losses"] == [1.0, None, 2.0]
    assert back["arr"] == [1.0, None]
    assert back["ok_arr"] == [0, 1, 2]
    assert back["nested"]["v"] is None
    assert not any(
        isinstance(v, float) and not math.isfinite(v)
        for v in back["losses"] if v is not None
    )
