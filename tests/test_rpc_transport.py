"""Zero-copy RPC data-plane contract.

Property-style coverage of the out-of-band wire codec (bit identity
across dtypes/layouts, zero-copy receive proven with
``np.shares_memory``), chunked multi-frame reassembly (incl. the
>256 MB round trip the old twin ``max_msg_size`` caps made
impossible), the same-host shm fast path (exactly ONE host copy,
proven by counting store puts and aliasing the decoded array against
the store segment), legacy interop, and pin lifecycle.

This module also runs under the ASan-instrumented native store build
(scripts/workflows/native_sanitizers.sh) so shm pin/release misuse
trips the sanitizer, not production.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from bioengine_tpu.native.store import LocalObjectStore
from bioengine_tpu.rpc import protocol
from bioengine_tpu.rpc.client import connect_to_server
from bioengine_tpu.rpc.protocol import (
    INLINE_LIMIT,
    RemoteError,
    decode,
    decode_oob,
    encode,
    encode_oob,
)
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.rpc.transport import (
    Codec,
    FrameAssembler,
    RpcStats,
    ShmPinTracker,
    TransportConfig,
    chunk_frames,
)

pytestmark = [pytest.mark.integration, pytest.mark.anyio]


def roundtrip(msg: dict, **kw) -> dict:
    return decode_oob(encode_oob(msg, **kw))


DTYPES = [
    np.bool_, np.int8, np.uint8, np.int16, np.uint16, np.int32,
    np.uint32, np.int64, np.uint64, np.float16, np.float32, np.float64,
    np.complex64,
]


class TestOobCodec:
    @pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: np.dtype(d).name)
    def test_dtype_bit_identity(self, dtype):
        rng = np.random.default_rng(0)
        arr = (rng.integers(0, 200, 4096)).astype(dtype)
        out = roundtrip({"a": arr})["a"]
        assert out.dtype == arr.dtype
        assert out.shape == arr.shape
        assert out.tobytes() == arr.tobytes()  # bit identity, NaN-proof

    @pytest.mark.parametrize(
        "shape", [(), (0,), (3, 0, 2), (1,), (5, 7, 3)], ids=str
    )
    def test_odd_shapes(self, shape):
        arr = np.full(shape, 1.5, np.float32)
        out = roundtrip({"a": arr})["a"]
        assert out.shape == arr.shape
        assert out.tobytes() == arr.tobytes()

    def test_noncontiguous_and_fortran_order(self):
        base = np.arange(512 * 512, dtype=np.float32).reshape(512, 512)
        sliced = base[::2, 1::3]          # non-contiguous view
        fortran = np.asfortranarray(base)
        out = roundtrip({"s": sliced, "f": fortran})
        np.testing.assert_array_equal(out["s"], sliced)
        np.testing.assert_array_equal(out["f"], fortran)

    def test_bfloat16_as_uint16(self):
        # numpy has no native bfloat16; the wire convention is a uint16
        # view reinterpreted by the receiver
        import ml_dtypes

        arr = np.linspace(-3, 3, 2048).astype(ml_dtypes.bfloat16)
        out = roundtrip({"a": arr.view(np.uint16)})["a"]
        back = out.view(ml_dtypes.bfloat16)
        assert back.tobytes() == arr.tobytes()

    def test_zero_copy_receive(self):
        arr = np.arange(1 << 18, dtype=np.float32)  # 1 MB, > INLINE_LIMIT
        wire = bytes(encode_oob({"a": arr}))  # what the socket delivers
        out = decode_oob(wire)["a"]
        # the decoded array is a view OVER THE RECEIVED FRAME: zero
        # payload copies on the receive side
        assert np.shares_memory(out, np.frombuffer(wire, np.uint8))
        assert not out.flags.writeable  # views over the wire are RO
        np.testing.assert_array_equal(out, arr)

    def test_small_arrays_stay_inline(self):
        arr = np.arange(8, dtype=np.int16)  # < INLINE_LIMIT
        frame = encode_oob({"a": arr})
        meta_len = int.from_bytes(frame[4:8], "little")
        assert len(frame) <= ((8 + meta_len + 63) & ~63)  # no payload section
        np.testing.assert_array_equal(decode_oob(frame)["a"], arr)

    def test_large_bytes_extracted(self):
        blob = bytes(range(256)) * 4096  # 1 MB
        frame = encode_oob({"b": blob, "small": b"ok"})
        out = decode_oob(frame)
        assert out["b"] == blob
        assert out["small"] == b"ok"

    def test_exception_and_scalars(self):
        out = roundtrip(
            {"e": ValueError("boom"), "i": np.int64(7), "f": np.float32(2.5)}
        )
        assert isinstance(out["e"], RemoteError)
        assert "boom" in str(out["e"])
        assert out["i"] == 7 and out["f"] == 2.5

    def test_nested_structures(self):
        arr = np.arange(1 << 16, dtype=np.float64)
        msg = {"args": [[{"deep": arr}], (1, 2)], "kwargs": {"k": [arr[:10]]}}
        out = roundtrip(msg)
        np.testing.assert_array_equal(out["args"][0][0]["deep"], arr)
        np.testing.assert_array_equal(out["kwargs"]["k"][0], arr[:10])

    def test_legacy_interop_both_directions(self):
        arr = np.arange(1 << 16, dtype=np.float32)
        # pre-oob peer's bytes decode through the new dispatcher
        codec = Codec()
        out = codec.decode(encode({"a": arr}))
        np.testing.assert_array_equal(out["a"], arr)
        # a codec without negotiated oob emits bytes an OLD decode reads
        legacy_codec = Codec()
        assert legacy_codec.oob is False
        frames = legacy_codec.encode_frames({"a": arr})
        assert len(frames) == 1
        np.testing.assert_array_equal(decode(frames[0])["a"], arr)

    def test_magic_cannot_collide_with_legacy(self):
        assert not protocol.is_oob_frame(encode({"t": "ping"}))
        assert protocol.is_oob_frame(encode_oob({"t": "ping"}))


class TestChunking:
    def test_chunk_reassembly(self):
        arr = np.arange(1 << 19, dtype=np.float32)  # 2 MB
        frame = encode_oob({"a": arr})
        chunks = chunk_frames(frame, 256 * 1024)
        assert len(chunks) == (len(frame) + 256 * 1024 - 1) // (256 * 1024)
        asm = FrameAssembler()
        results = [asm.feed(c) for c in chunks]
        assert all(r is None for r in results[:-1])
        out = decode_oob(results[-1])["a"]
        np.testing.assert_array_equal(out, arr)
        assert asm.pending == 0

    def test_interleaved_chunk_streams(self):
        a = np.arange(1 << 17, dtype=np.int32)
        b = (np.arange(1 << 17, dtype=np.int32) * 3)[::-1].copy()
        ca = chunk_frames(encode_oob({"x": a}), 64 * 1024)
        cb = chunk_frames(encode_oob({"x": b}), 64 * 1024)
        asm = FrameAssembler()
        done = []
        # alternate the two streams — concurrent sends interleave at
        # websocket-message granularity exactly like this
        for pair in zip(ca, cb):
            for c in pair:
                whole = asm.feed(c)
                if whole is not None:
                    done.append(decode_oob(whole)["x"])
        for c in ca[len(cb):] + cb[len(ca):]:
            whole = asm.feed(c)
            if whole is not None:
                done.append(decode_oob(whole)["x"])
        assert len(done) == 2
        np.testing.assert_array_equal(done[0], a)
        np.testing.assert_array_equal(done[1], b)

    def test_hostile_chunk_header_rejected_before_allocation(self):
        """A peer-controlled header claiming a huge assembled total
        must be rejected, not allocated (the replacement for the old
        per-message memory bound that chunking removed)."""
        import msgpack as _mp

        asm = FrameAssembler(max_assembled=1024 * 1024)
        hdr = _mp.packb(
            {"id": b"x" * 8, "q": 0, "n": 2, "z": 1 << 40, "o": 0, "c": 2}
        )
        evil = b"".join(
            [protocol.CHUNK_MAGIC, len(hdr).to_bytes(4, "little"), hdr, b"hi"]
        )
        with pytest.raises(ValueError, match="assembled bytes"):
            asm.feed(evil)
        # a duplicated-offset stream (two seqs claiming the same bytes)
        # must not be able to "complete" with zero-filled holes
        hdr2 = _mp.packb(
            {"id": b"y" * 8, "q": 1, "n": 2, "z": 4, "o": 0, "c": 2}
        )
        evil2 = b"".join(
            [protocol.CHUNK_MAGIC, len(hdr2).to_bytes(4, "little"), hdr2, b"hi"]
        )
        with pytest.raises(ValueError, match="inconsistent chunk header"):
            asm.feed(evil2)
        assert asm.pending == 0

    def test_reassembled_frames_are_read_only(self):
        arr = np.arange(1 << 17, dtype=np.float32)
        chunks = chunk_frames(encode_oob({"a": arr}), 64 * 1024)
        asm = FrameAssembler()
        whole = [asm.feed(c) for c in chunks][-1]
        out = decode_oob(whole)["a"]
        # same immutable contract as unchunked (bytes-backed) messages
        assert not out.flags.writeable
        np.testing.assert_array_equal(out, arr)

    def test_stale_partial_streams_expire(self):
        arr = np.arange(1 << 16, dtype=np.float32)
        chunks = chunk_frames(encode_oob({"a": arr}), 16 * 1024)
        asm = FrameAssembler(stale_after=0.0)  # everything is stale
        asm.feed(chunks[0])
        assert asm.pending == 1
        # the next chunk's housekeeping sweep drops the abandoned
        # stream (its own entry is re-created after the sweep)
        asm.feed(chunk_frames(encode_oob({"b": arr}), 16 * 1024)[0])
        assert asm.pending == 1

    def test_codec_chunks_above_frame_limit(self):
        cfg = TransportConfig(frame_limit=128 * 1024)
        enc = Codec(config=cfg)
        enc.oob = True
        dec = Codec(config=cfg)
        arr = np.arange(1 << 18, dtype=np.float32)  # 1 MB -> 9 chunks
        frames = enc.encode_frames({"a": arr})
        assert len(frames) > 1
        assert all(len(f) <= 128 * 1024 + 512 for f in frames)
        outs = [dec.decode(f) for f in frames]
        assert all(o is None for o in outs[:-1])
        np.testing.assert_array_equal(outs[-1]["a"], arr)
        assert enc.stats.chunked_msgs_out == 1
        assert dec.stats.chunked_msgs_in == 1


class _CountingStore(LocalObjectStore):
    """LocalObjectStore that counts the bytes written by put — the
    instrument behind the one-copy proof."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.put_calls: list[int] = []

    def try_put(self, key, data) -> bool:
        ok = super().try_put(key, data)
        if ok:
            self.put_calls.append(len(bytes(data)) if not hasattr(data, "nbytes") else data.nbytes)
        return ok


class TestShmFastPath:
    def _pair(self, store, threshold=1024):
        cfg = TransportConfig(shm_threshold=threshold)
        enc = Codec(config=cfg)
        enc.oob = True
        enc.enable_shm(store)
        dec = Codec(config=cfg)
        dec.oob = True
        dec.enable_shm(store)
        return enc, dec

    def test_64mb_roundtrip_exactly_one_host_copy(self):
        store = _CountingStore("one-copy", capacity=256 * 1024 * 1024)
        enc, dec = self._pair(store)
        arr = np.arange(16 * 1024 * 1024, dtype=np.float32)  # 64 MB
        frames = enc.encode_frames({"t": "call", "a": arr})
        # copy #1 (the only one): the store put
        assert store.put_calls == [arr.nbytes]
        assert len(frames) == 1
        assert len(frames[0]) < 4096, "payload must NOT ride the wire"
        out = dec.decode(frames[0])["a"]
        # receive side: the decoded array aliases the STORE SEGMENT —
        # zero further copies
        key = next(k for k in store._data)
        assert np.shares_memory(out, np.frombuffer(store._data[key], np.uint8))
        np.testing.assert_array_equal(out, arr)
        assert enc.stats.shm_puts == 1 and dec.stats.shm_gets == 1

    def test_native_store_one_copy_roundtrip(self):
        from bioengine_tpu.native.store import (
            SharedObjectStore,
            native_available,
        )

        if not native_available():
            pytest.skip("no native toolchain")
        store = SharedObjectStore(
            "rpc-transport-test", capacity=64 * 1024 * 1024, create=True
        )
        try:
            enc, dec = self._pair(store)
            arr = np.arange(4 * 1024 * 1024, dtype=np.float32)  # 16 MB
            frames = enc.encode_frames({"a": arr})
            assert len(frames[0]) < 4096
            out = dec.decode(frames[0])["a"]
            np.testing.assert_array_equal(out, arr)
            # the decoded array aliases the shm mapping itself
            key = next(iter(dec._tracker._finalizers))
            probe = store.get(key)
            try:
                assert np.shares_memory(out, np.frombuffer(probe, np.uint8))
            finally:
                probe.release()
                store.release(key)
            del out, probe
            dec.drain_pins()
            assert store.stats()["n_objects"] == 0  # released AND deleted
        finally:
            store.destroy()

    def test_fallback_when_store_full(self):
        store = LocalObjectStore("tiny", capacity=1024 * 1024)
        enc, dec = self._pair(store)
        arr = np.arange(1 << 19, dtype=np.float32)  # 2 MB > capacity
        frames = enc.encode_frames({"a": arr})
        assert enc.stats.shm_fallbacks == 1
        assert len(frames[0]) > arr.nbytes  # payload rode the wire
        np.testing.assert_array_equal(dec.decode(frames[0])["a"], arr)

    def test_pin_released_only_after_consumer_drops_views(self):
        store = LocalObjectStore("pins", capacity=64 * 1024 * 1024)
        enc, dec = self._pair(store)
        frames = enc.encode_frames({"a": np.arange(1 << 18, dtype=np.float32)})
        out = dec.decode(frames[0])["a"]
        dec.drain_pins()
        assert store.stats()["n_objects"] == 1  # consumer still holds a view
        del out
        dec.drain_pins()
        assert store.stats()["n_objects"] == 0  # released + deleted

    def test_missing_shm_object_raises_loudly(self):
        store = LocalObjectStore("gone", capacity=64 * 1024 * 1024)
        enc, dec = self._pair(store)
        frames = enc.encode_frames({"a": np.arange(1 << 18, dtype=np.float32)})
        store.clear()  # simulate eviction before consume
        with pytest.raises(KeyError, match="evicted before consume"):
            dec.decode(frames[0])


class TestStats:
    def test_counters_accumulate(self):
        stats = RpcStats()
        codec = Codec(stats=stats)
        codec.oob = True
        arr = np.arange(1 << 18, dtype=np.float32)
        for frame in codec.encode_frames({"a": arr}):
            codec.decode(frame)
        assert stats.msgs_out == 1 and stats.msgs_in == 1
        assert stats.bytes_out == stats.bytes_in > arr.nbytes
        assert stats.encode_seconds > 0 and stats.decode_seconds >= 0
        d = stats.as_dict()
        assert d["shm_hit_rate"] is None  # no shm traffic yet


# ---------------------------------------------------------------------------
# end-to-end over a real websocket server
# ---------------------------------------------------------------------------


@pytest.fixture
async def server_store():
    store = LocalObjectStore("e2e", capacity=512 * 1024 * 1024)
    srv = RpcServer(shm_store=store)
    await srv.start()
    srv.register_local_service({"id": "echo", "echo": lambda a: a})
    yield srv, store
    await srv.stop()


class TestEndToEnd:
    async def test_shm_negotiated_and_used(self, server_store):
        srv, store = server_store
        conn = await connect_to_server(
            {"server_url": f"http://127.0.0.1:{srv.port}", "shm_store": store}
        )
        try:
            assert conn.codec.shm_store is not None
            arr = np.arange(1 << 19, dtype=np.float32)  # 2 MB > threshold
            out = await conn.call("bioengine/echo", "echo", arr)
            np.testing.assert_array_equal(out, arr)
            assert conn.codec.stats.shm_puts >= 1   # request rode the store
            assert conn.codec.stats.shm_gets >= 1   # result rode the store
        finally:
            await conn.disconnect()

    async def test_legacy_client_interop(self, server_store):
        srv, _ = server_store
        conn = await connect_to_server(
            {
                "server_url": f"http://127.0.0.1:{srv.port}",
                "protocols": [],       # pre-oob peer
                "shm_store": None,
            }
        )
        try:
            assert conn.codec.oob is False
            arr = np.arange(1 << 18, dtype=np.float32)
            out = await conn.call("bioengine/echo", "echo", arr)
            np.testing.assert_array_equal(out, arr)
            assert conn.codec.stats.legacy_msgs_out >= 1
        finally:
            await conn.disconnect()

    async def test_above_256mb_roundtrip_chunked(self):
        """The acceptance case: a payload ABOVE the old 256 MB twin
        caps round-trips through chunked multi-frame sends."""
        srv = RpcServer(shm_store=None)
        await srv.start()
        srv.register_local_service(
            {"id": "probe", "head_tail_len": lambda a: [
                int(a[0]), int(a[-1]), int(a.size)
            ]}
        )
        conn = await connect_to_server(
            {"server_url": f"http://127.0.0.1:{srv.port}", "shm_store": None}
        )
        try:
            n = 257 * 1024 * 1024  # 257 MB uint8 > the old hard cap
            arr = np.zeros(n, np.uint8)
            arr[0], arr[-1] = 7, 9
            out = await conn.call("bioengine/probe", "head_tail_len", arr)
            assert out == [7, 9, n]
            assert conn.codec.stats.chunked_msgs_out >= 1
        finally:
            await conn.disconnect()
            await srv.stop()
