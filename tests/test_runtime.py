import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bioengine_tpu.runtime.buckets import (
    bucket_batch,
    bucket_dim,
    bucket_shape,
    crop_to,
    pad_to,
)
from bioengine_tpu.runtime.convert import (
    conv_kernel,
    convert_state_dict,
    dinov2_name_map,
    linear_kernel,
)
from bioengine_tpu.runtime.engine import EngineConfig, InferenceEngine
from bioengine_tpu.runtime.program_cache import CompiledProgramCache
from bioengine_tpu.runtime.rdf import (
    apply_processing,
    from_nhwc,
    load_model_rdf,
    to_nhwc,
)

pytestmark = pytest.mark.unit


class TestBuckets:
    def test_bucket_dim_ladder(self):
        assert bucket_dim(200) == 256
        assert bucket_dim(256) == 256
        assert bucket_dim(257) == 384

    def test_bucket_dim_divisor(self):
        assert bucket_dim(100, divisor=8) % 8 == 0

    def test_bucket_fallback_respects_odd_divisor(self):
        # divisor 5 divides no ladder entry: the fallback must still
        # return a multiple of 5 (a downstream shape error otherwise),
        # quantized geometrically so compilations stay bounded
        assert bucket_dim(8, (8, 16, 24, 32), 5) == 10
        assert bucket_dim(101, (8, 16), 5) == 160  # 5 * 2^5
        assert bucket_dim(106, (8, 16), 5) == 160  # same bucket, no recompile
        # power-of-two divisors keep the 128 alignment above the ladder
        assert bucket_dim(3000, (64, 128), 2) == 3072

    def test_bucket_above_ladder(self):
        assert bucket_dim(5000) >= 5000

    def test_bucket_batch(self):
        assert bucket_batch(3) == 4
        assert bucket_batch(64) == 64

    def test_pad_crop_roundtrip(self):
        x = np.random.rand(1, 50, 70, 3).astype(np.float32)
        bh, bw = bucket_shape((50, 70))
        padded = pad_to(x, (bh, bw))
        assert padded.shape == (1, bh, bw, 3)
        np.testing.assert_array_equal(crop_to(padded, (50, 70)), x)

    def test_pad_rejects_oversize(self):
        with pytest.raises(ValueError):
            pad_to(np.zeros((1, 300, 300, 1)), (256, 256))


class TestProgramCache:
    def test_hit_miss_eviction(self):
        cache = CompiledProgramCache(max_programs=2)
        calls = []
        for key in ["a", "b", "a", "c"]:
            cache.get_or_compile(key, lambda k=key: calls.append(k) or k)
        assert calls == ["a", "b", "c"]  # "a" second time was a hit
        assert cache.stats.hits == 1
        assert cache.stats.evictions == 1  # "a" evicted when "c" arrived (LRU=a? no: a was touched)
        assert len(cache) == 2

    def test_concurrent_build_single_compile(self):
        cache = CompiledProgramCache()
        n_builds = []
        barrier = threading.Barrier(4)

        def build():
            n_builds.append(1)
            return "prog"

        def worker():
            barrier.wait()
            assert cache.get_or_compile("k", build) == "prog"

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(n_builds) == 1

    def test_evict_predicate(self):
        cache = CompiledProgramCache()
        cache.get_or_compile(("m1", 256), lambda: 1)
        cache.get_or_compile(("m2", 256), lambda: 2)
        assert cache.evict(lambda k: k[0] == "m1") == 1
        assert cache.keys() == [("m2", 256)]

    def test_eviction_drops_compile_seconds(self):
        """compile_seconds must not keep entries for evicted programs
        (a long-lived replica cycling shapes would leak the dict), on
        BOTH eviction paths; the lifetime total survives."""
        cache = CompiledProgramCache(max_programs=2)
        for key in ["a", "b", "c"]:  # "a" evicted by LRU pressure
            cache.get_or_compile(key, lambda k=key: k)
        assert set(cache.stats.compile_seconds) == {"b", "c"}
        cache.evict(lambda k: k == "b")  # predicate path
        assert set(cache.stats.compile_seconds) == {"c"}
        d = cache.stats.as_dict()
        assert d["total_compile_seconds"] >= d["live_compile_seconds"]
        # lifetime total still counts all three compiles
        assert (
            cache.stats.cumulative_compile_seconds
            > sum(cache.stats.compile_seconds.values()) * 0.99
        )


class TestEngine:
    @pytest.fixture(scope="class")
    def engine(self):
        # identity-ish model: 1x1 conv equivalent via simple lambda
        def apply_fn(params, x):
            return x * params["scale"]

        return InferenceEngine(
            "ident",
            apply_fn,
            {"scale": jnp.asarray(2.0)},
            cache=CompiledProgramCache(),
        )

    def test_predict_exact_bucket(self, engine):
        x = np.ones((1, 64, 64, 1), np.float32)
        out = engine.predict(x)
        np.testing.assert_allclose(out, 2.0 * x)

    def test_predict_odd_shape_cropped_back(self, engine):
        x = np.random.rand(2, 50, 77, 3).astype(np.float32)
        out = engine.predict(x)
        assert out.shape == (2, 50, 77, 3)
        np.testing.assert_allclose(out, 2 * x, rtol=1e-5)

    def test_same_bucket_reuses_program(self, engine):
        engine.predict(np.ones((1, 60, 60, 1), np.float32))
        misses_before = engine.cache.stats.misses
        engine.predict(np.ones((1, 64, 64, 1), np.float32))  # same bucket
        assert engine.cache.stats.misses == misses_before

    def test_tiled_prediction_matches_direct(self):
        def apply_fn(params, x):
            return x + 1.0

        cfg = EngineConfig(max_tile=64, tile=48, tile_overlap=16)
        eng = InferenceEngine(
            "plus1", apply_fn, {}, config=cfg, cache=CompiledProgramCache()
        )
        x = np.random.rand(1, 100, 90, 2).astype(np.float32)
        out = eng.predict(x)
        assert out.shape == x.shape
        np.testing.assert_allclose(out, x + 1.0, rtol=1e-4, atol=1e-5)

    def test_volume_bucketed_predict(self, engine):
        # 5D input routes through the volumetric path; odd sizes pad to
        # the z/xy buckets and crop back
        x = np.random.rand(1, 5, 50, 70, 2).astype(np.float32)
        out = engine.predict(x)
        assert out.shape == x.shape
        np.testing.assert_allclose(out, 2 * x, rtol=1e-5)

    def test_volume_tiled_matches_direct(self):
        def apply_fn(params, x):
            return x * 3.0

        cfg = EngineConfig(
            max_tile=32, tile=24, tile_overlap=8,
            max_tile_z=8, tile_z=6, tile_overlap_z=2,
            ladder_z=(2, 4, 6, 8),
        )
        eng = InferenceEngine(
            "times3-3d", apply_fn, {}, config=cfg,
            cache=CompiledProgramCache(),
        )
        x = np.random.rand(1, 13, 40, 50, 1).astype(np.float32)
        out = eng.predict(x)
        assert out.shape == x.shape
        np.testing.assert_allclose(out, 3 * x, rtol=1e-4, atol=1e-5)

    def test_thin_wide_stack_clamps_z_overlap(self):
        # D smaller than tile_overlap_z: the z tile clamps to D and the
        # overlap clamps below the tile instead of crashing the ramp
        def apply_fn(params, x):
            return x + 2.0

        cfg = EngineConfig(
            max_tile=32, tile=24, tile_overlap=8,
            max_tile_z=16, tile_z=12, tile_overlap_z=8,
        )
        eng = InferenceEngine(
            "plus2-thin", apply_fn, {}, config=cfg,
            cache=CompiledProgramCache(),
        )
        x = np.random.rand(1, 4, 60, 40, 1).astype(np.float32)
        out = eng.predict(x)
        assert out.shape == x.shape
        np.testing.assert_allclose(out, x + 2.0, rtol=1e-4, atol=1e-5)

    def test_tiled_chunks_bound_device_batch(self):
        # tile_batch=2 forces multiple chunks; stitching must still be
        # exact and the largest compiled batch must stay at the chunk cap
        def apply_fn(params, x):
            return x * 2.0

        cfg = EngineConfig(
            max_tile=16, tile=16, tile_overlap=4, tile_batch=2,
            ladder=(16,),
        )
        cache = CompiledProgramCache()
        eng = InferenceEngine(
            "times2-chunk", apply_fn, {}, config=cfg, cache=cache
        )
        x = np.random.rand(1, 50, 50, 1).astype(np.float32)
        out = eng.predict(x)
        np.testing.assert_allclose(out, x * 2.0, rtol=1e-4, atol=1e-5)
        batches = {key[1] for key in cache._programs}  # (model, B, ...)
        assert max(batches) <= 2, batches

    def test_volume_respects_z_divisor(self):
        """A real 3D conv model: padding must land on the pooling
        divisor in every axis or the forward would shape-error."""
        import jax

        from bioengine_tpu.models.unet3d import UNet3D

        model = UNet3D(features=(2, 4), out_channels=1)
        x = np.random.rand(1, 6, 20, 24, 1).astype(np.float32)
        params = model.init(jax.random.key(0), jnp.zeros((1, 8, 32, 32, 1)))[
            "params"
        ]
        eng = InferenceEngine(
            "unet3d-test",
            lambda p, a: model.apply({"params": p}, a),
            params,
            divisor=model.divisor,
            z_divisor=model.z_divisor,
            cache=CompiledProgramCache(),
        )
        out = eng.predict(x)
        assert out.shape == (1, 6, 20, 24, 1)


class TestConvert:
    def test_conv_kernel_layout(self):
        w = np.arange(2 * 3 * 5 * 7).reshape(2, 3, 5, 7).astype(np.float32)
        assert conv_kernel(w).shape == (5, 7, 3, 2)

    def test_linear_kernel(self):
        assert linear_kernel(np.zeros((4, 8))).shape == (8, 4)

    def test_convert_strict_raises_on_unmapped(self):
        with pytest.raises(KeyError):
            convert_state_dict({"weird.key": np.zeros(3)}, {})

    def test_dinov2_map_round_trip_into_vit(self):
        from bioengine_tpu.models.vit import ViT

        depth, dim, heads, patch = 2, 32, 4, 14
        model = ViT(patch_size=patch, dim=dim, depth=depth, num_heads=heads)
        x = jnp.zeros((1, 28, 28, 3))
        ref_params = model.init(jax.random.key(0), x)["params"]

        # Build a fake torch state dict with matching shapes.
        sd = {
            "cls_token": np.zeros((1, 1, dim), np.float32),
            "pos_embed": np.zeros((1, 5, dim), np.float32),
            "patch_embed.proj.weight": np.zeros((dim, 3, patch, patch), np.float32),
            "patch_embed.proj.bias": np.zeros(dim, np.float32),
            "norm.weight": np.ones(dim, np.float32),
            "norm.bias": np.zeros(dim, np.float32),
        }
        for i in range(depth):
            sd.update(
                {
                    f"blocks.{i}.norm1.weight": np.ones(dim, np.float32),
                    f"blocks.{i}.norm1.bias": np.zeros(dim, np.float32),
                    f"blocks.{i}.attn.qkv.weight": np.zeros((3 * dim, dim), np.float32),
                    f"blocks.{i}.attn.qkv.bias": np.zeros(3 * dim, np.float32),
                    f"blocks.{i}.attn.proj.weight": np.zeros((dim, dim), np.float32),
                    f"blocks.{i}.attn.proj.bias": np.zeros(dim, np.float32),
                    f"blocks.{i}.ls1.gamma": np.ones(dim, np.float32),
                    f"blocks.{i}.ls2.gamma": np.ones(dim, np.float32),
                    f"blocks.{i}.norm2.weight": np.ones(dim, np.float32),
                    f"blocks.{i}.norm2.bias": np.zeros(dim, np.float32),
                    f"blocks.{i}.mlp.fc1.weight": np.zeros((4 * dim, dim), np.float32),
                    f"blocks.{i}.mlp.fc1.bias": np.zeros(4 * dim, np.float32),
                    f"blocks.{i}.mlp.fc2.weight": np.zeros((dim, 4 * dim), np.float32),
                    f"blocks.{i}.mlp.fc2.bias": np.zeros(dim, np.float32),
                }
            )
        params = convert_state_dict(sd, dinov2_name_map(depth))
        # Same tree structure as a natively initialized model.
        ref_paths = {"/".join(str(k) for k in p) for p, _ in jax.tree_util.tree_flatten_with_path(ref_params)[0]}
        got_paths = {"/".join(str(k) for k in p) for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]}
        assert ref_paths == got_paths
        # And the converted params actually run through the model.
        out = model.apply({"params": params}, x)
        assert out.shape == (1, dim)


class TestRDF:
    def test_load_and_axes(self, tmp_path):
        rdf = {
            "name": "test-unet",
            "type": "model",
            "inputs": [
                {
                    "name": "raw",
                    "axes": "bcyx",
                    "preprocessing": [
                        {"name": "zero_mean_unit_variance", "kwargs": {}}
                    ],
                }
            ],
            "outputs": [{"name": "mask", "axes": "bcyx"}],
            "weights": {"pytorch_state_dict": {"source": "weights.pt"}},
        }
        p = tmp_path / "rdf.yaml"
        import yaml

        p.write_text(yaml.safe_dump(rdf))
        model = load_model_rdf(p)
        assert model.name == "test-unet"
        fmt, _ = model.preferred_weights
        assert fmt == "pytorch_state_dict"

    def test_to_from_nhwc_roundtrip(self):
        x = np.random.rand(2, 3, 10, 12).astype(np.float32)  # bcyx
        nhwc = to_nhwc(x, "bcyx")
        assert nhwc.shape == (2, 10, 12, 3)
        back = from_nhwc(nhwc, "bcyx")
        np.testing.assert_array_equal(back, x)

    def test_volumetric_axes_roundtrip(self):
        from bioengine_tpu.runtime.rdf import canonical_layout

        assert canonical_layout("bczyx") == "bzyxc"
        assert canonical_layout("byxc") == "byxc"
        x = np.random.rand(2, 3, 5, 10, 12).astype(np.float32)  # bczyx
        vol = to_nhwc(x, "bczyx")
        assert vol.shape == (2, 5, 10, 12, 3)
        back = from_nhwc(vol, "bczyx")
        np.testing.assert_array_equal(back, x)
        # batchless 0.4-style volume: zyx gains batch + channel dims
        y = np.random.rand(4, 6, 8).astype(np.float32)
        vol = to_nhwc(y, "bzyx")  # implicit batch from ndim mismatch
        assert vol.shape == (1, 4, 6, 8, 1)

    def test_unsupported_axes_rejected_loudly(self):
        # a time axis must not be silently misrouted into the
        # volumetric path as if it were z
        x = np.zeros((1, 3, 2, 8, 9), np.float32)
        with pytest.raises(ValueError, match="not support"):
            to_nhwc(x, "btcyx")

    def test_axes_dict_form(self):
        from bioengine_tpu.runtime.rdf import _axes_string

        axes = [
            {"type": "batch"},
            {"type": "channel"},
            {"type": "space", "id": "y"},
            {"type": "space", "id": "x"},
        ]
        assert _axes_string(axes) == "bcyx"

    def test_processing_ops(self):
        x = np.random.rand(1, 8, 8, 1).astype(np.float32) * 100
        out = apply_processing(
            x, [{"name": "zero_mean_unit_variance", "kwargs": {}}]
        )
        assert abs(out.mean()) < 1e-4
        out2 = apply_processing(x, [{"name": "scale_range", "kwargs": {"min_percentile": 1, "max_percentile": 99}}])
        assert out2.min() >= -0.1 and out2.max() <= 1.1
        with pytest.raises(NotImplementedError):
            apply_processing(x, [{"name": "nonexistent_op"}])


class TestFlows:
    def test_masks_to_flows_unit_norm_inside(self):
        from bioengine_tpu.ops.flows import masks_to_flows

        masks = np.zeros((32, 32), np.int32)
        masks[8:24, 8:24] = 1
        flows = masks_to_flows(masks)
        mag = np.sqrt(flows[0] ** 2 + flows[1] ** 2)
        inside = masks > 0
        assert mag[inside].mean() > 0.5
        assert mag[~inside].max() == 0.0

    def test_follow_flows_converges_to_center(self):
        from bioengine_tpu.ops.flows import follow_flows

        H = W = 16
        yy, xx = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
        # flow pointing at center (8, 8)
        dy = np.clip(8 - yy, -1, 1).astype(np.float32)
        dx = np.clip(8 - xx, -1, 1).astype(np.float32)
        p = np.asarray(follow_flows(jnp.stack([jnp.asarray(dy), jnp.asarray(dx)]), n_iter=40))
        assert np.abs(p[0] - 8).max() < 1.5
        assert np.abs(p[1] - 8).max() < 1.5

    def test_masks_from_flows_two_cells(self):
        from bioengine_tpu.ops.flows import masks_from_flows, masks_to_flows

        masks = np.zeros((48, 48), np.int32)
        masks[6:20, 6:20] = 1
        masks[28:44, 28:44] = 2
        flows = masks_to_flows(masks)
        cellprob = np.where(masks > 0, 5.0, -5.0).astype(np.float32)
        rec = masks_from_flows(flows, cellprob, n_iter=100)
        assert rec.max() == 2  # two instances recovered
        # instance regions should match reasonably (IoU > 0.7 each)
        for lbl in (1, 2):
            ref = masks == lbl
            cand = [np.mean((rec == r) & ref) / max(np.mean((rec == r) | ref), 1e-9) for r in range(1, rec.max() + 1)]
            assert max(cand) > 0.7


    def test_follow_flows_3d_converges_to_center(self):
        from bioengine_tpu.ops.flows import follow_flows_3d

        D = H = W = 11
        zz, yy, xx = np.meshgrid(
            np.arange(D), np.arange(H), np.arange(W), indexing="ij"
        )
        flow = np.stack(
            [
                np.clip(5 - zz, -1, 1),
                np.clip(5 - yy, -1, 1),
                np.clip(5 - xx, -1, 1),
            ]
        ).astype(np.float32)
        p = np.asarray(follow_flows_3d(jnp.asarray(flow), n_iter=30))
        assert np.abs(p - 5).max() < 1.5

    def test_aggregate_orthogonal_flows_recovers_field(self):
        """Per-orientation predictions built from a known 3D field must
        aggregate back to exactly that field (each component is the
        mean of two identical contributions)."""
        from bioengine_tpu.ops.flows import aggregate_orthogonal_flows

        rng = np.random.default_rng(0)
        D, H, W = 4, 5, 6
        F = rng.normal(size=(3, D, H, W)).astype(np.float32)  # dz, dy, dx
        cp = rng.normal(size=(D, H, W)).astype(np.float32)
        pred_yx = np.stack([F[1], F[2], cp], axis=-1)  # [z, y, x, c]
        pred_zx = np.transpose(
            np.stack([F[0], F[2], cp], axis=-1), (1, 0, 2, 3)
        )  # -> [y, z, x, c]
        pred_zy = np.transpose(
            np.stack([F[0], F[1], cp], axis=-1), (2, 0, 1, 3)
        )  # -> [x, z, y, c]
        flow, cellprob = aggregate_orthogonal_flows(pred_yx, pred_zx, pred_zy)
        np.testing.assert_allclose(flow, F, rtol=1e-6)
        np.testing.assert_allclose(cellprob, cp, rtol=1e-6)

    def test_masks_from_flows_3d_two_cells(self):
        from bioengine_tpu.ops.flows import masks_from_flows

        D = H = W = 24
        masks = np.zeros((D, H, W), np.int32)
        masks[4:10, 4:10, 4:10] = 1
        masks[14:21, 14:21, 14:21] = 2
        centers = {1: (7.0, 7.0, 7.0), 2: (17.0, 17.0, 17.0)}
        zz, yy, xx = np.meshgrid(
            np.arange(D), np.arange(H), np.arange(W), indexing="ij"
        )
        flow = np.zeros((3, D, H, W), np.float32)
        for lbl, (cz, cy, cx) in centers.items():
            sel = masks == lbl
            vec = np.stack([cz - zz, cy - yy, cx - xx]).astype(np.float32)
            norm = np.sqrt((vec**2).sum(0)) + 1e-6
            for d in range(3):
                flow[d][sel] = (vec[d] / norm)[sel]
        cellprob = np.where(masks > 0, 5.0, -5.0).astype(np.float32)
        rec = masks_from_flows(flow, cellprob, n_iter=60)
        assert rec.max() == 2
        for lbl in (1, 2):
            ref = masks == lbl
            ious = [
                np.mean((rec == r) & ref) / max(np.mean((rec == r) | ref), 1e-9)
                for r in range(1, rec.max() + 1)
            ]
            assert max(ious) > 0.7


class TestPipelinedEngine:
    """The overlapped tiled pipeline (runtime/pipeline.py) against the
    serial baseline: bit-identical results, a bounded in-flight window,
    reusable staging buffers, and the async front door."""

    def _engine(self, apply_fn=None, **cfg_overrides):
        cfg_kw = dict(
            max_tile=64, tile=48, tile_overlap=16, tile_batch=3,
            pipeline_depth=2,
        )
        cfg_kw.update(cfg_overrides)
        return InferenceEngine(
            "pipe",
            apply_fn or (lambda p, x: x * p["scale"] + 0.25),
            {"scale": jnp.asarray(1.7)},
            config=EngineConfig(**cfg_kw),
            cache=CompiledProgramCache(),
        )

    def test_planar_identical_to_serial(self):
        # tile 48 buckets to 64: the staging-buffer pad margins are
        # exercised, and rtol=0 (exact equality) must still hold
        eng = self._engine()
        x = np.random.rand(3, 100, 90, 2).astype(np.float32)
        serial = eng.predict_serial(x)
        piped = eng.predict(x)
        np.testing.assert_allclose(piped, serial, rtol=0, atol=0)
        np.testing.assert_allclose(piped, x * 1.7 + 0.25, rtol=1e-4, atol=1e-5)

    def test_volumetric_identical_to_serial(self):
        eng = InferenceEngine(
            "pipe3d",
            lambda p, x: x * 3.0,
            {},
            config=EngineConfig(
                max_tile=32, tile=24, tile_overlap=8,
                max_tile_z=8, tile_z=6, tile_overlap_z=2,
                ladder_z=(2, 4, 6, 8), tile_batch=2, pipeline_depth=3,
            ),
            cache=CompiledProgramCache(),
        )
        x = np.random.rand(2, 13, 40, 50, 1).astype(np.float32)
        serial = eng.predict_serial(x)
        piped = eng.predict(x)
        np.testing.assert_allclose(piped, serial, rtol=0, atol=0)
        assert piped.shape == x.shape

    def test_staging_reuse_after_direct_path_poisoning(self):
        """A direct (non-tiled) predict shares the staging pool; its
        stale content in a reused buffer's pad margins must never leak
        into tiled results (regression: margins between the clamped
        tile extent and the bucket extent)."""
        eng = self._engine()
        x = np.random.rand(2, 100, 90, 2).astype(np.float32)
        serial = eng.predict_serial(x)
        # direct predict of a (bb, 64, 64, 2)-bucketed batch writes
        # nonzero data beyond the 48-wide tile extent
        eng.predict(np.random.rand(3, 60, 60, 2).astype(np.float32) + 5.0)
        piped = eng.predict(x)
        np.testing.assert_allclose(piped, serial, rtol=0, atol=0)

    def test_in_flight_window_bounded(self):
        for depth in (1, 2, 3):
            eng = self._engine(pipeline_depth=depth, tile_batch=1)
            x = np.random.rand(1, 120, 120, 1).astype(np.float32)
            out = eng.predict(x)
            stats = eng.pipeline_stats
            assert stats.chunks >= 4  # enough chunks to fill any window
            assert stats.max_in_flight <= depth, (depth, stats.as_dict())
            np.testing.assert_allclose(
                out, x * 1.7 + 0.25, rtol=1e-4, atol=1e-5
            )

    def test_depth_zero_disables_pipeline(self):
        eng = self._engine(pipeline_depth=0)
        x = np.random.rand(2, 100, 90, 1).astype(np.float32)
        out = eng.predict(x)
        np.testing.assert_allclose(
            out, eng.predict_serial(x), rtol=0, atol=0
        )
        assert eng.pipeline_stats.runs == 0  # pipeline never engaged

    def test_staging_buffers_are_recycled(self):
        eng = self._engine()
        x = np.random.rand(4, 150, 150, 1).astype(np.float32)
        for _ in range(3):
            eng.predict(x)
        # many chunks over many runs, but the pool only ever allocated
        # what was concurrently outstanding (depth + prefetch bound)
        assert eng.pipeline_stats.chunks >= 12
        cfg = eng.config
        per_shape_bound = cfg.pipeline_depth + cfg.pipeline_prefetch + 2
        # two shape keys (full chunks + the smaller trailing chunk)
        assert eng._staging_pool.allocated <= 2 * per_shape_bound

    def test_stats_accounting(self):
        eng = self._engine()
        x = np.random.rand(2, 100, 100, 1).astype(np.float32)
        eng.predict(x)
        d = eng.pipeline_stats.as_dict()
        assert d["runs"] == 1 and d["items"] == 2 and d["chunks"] > 0
        for stage in ("cut", "put", "dispatch", "readback", "stitch"):
            assert d[f"{stage}_seconds"] >= 0.0
        assert d["wall_seconds"] > 0
        assert 0.0 <= d["overlap_efficiency"] <= 1.5  # clock-skew slack

    def test_error_in_model_propagates_and_pipeline_unwinds(self):
        def bad_fn(params, x):
            raise RuntimeError("trace-time boom")

        eng = self._engine(apply_fn=bad_fn)
        with pytest.raises(RuntimeError, match="boom"):
            eng.predict(np.random.rand(1, 100, 100, 1).astype(np.float32))
        # the pipeline must be reusable after an aborted run
        good = self._engine()
        good.predict(np.random.rand(1, 100, 100, 1).astype(np.float32))

    def test_global_output_raises_in_pipeline(self):
        eng = self._engine(apply_fn=lambda p, x: jnp.mean(x, axis=(1, 2)))
        with pytest.raises(ValueError, match="dense spatial"):
            eng.predict(np.ones((1, 100, 100, 2), np.float32))

    @pytest.mark.anyio
    async def test_predict_async_front_door(self):
        import asyncio

        eng = self._engine()
        try:
            x = np.random.rand(2, 100, 90, 1).astype(np.float32)
            serial = eng.predict_serial(x)
            # concurrent async callers serialize on the dispatch thread
            # and all come back correct
            outs = await asyncio.gather(
                *(eng.predict_async(x) for _ in range(3))
            )
            for out in outs:
                np.testing.assert_allclose(out, serial, rtol=0, atol=0)
        finally:
            eng.close()


class TestGlobalOutputGuard:
    def test_padded_global_output_raises(self):
        def embed_fn(params, x):
            return jnp.mean(x, axis=(1, 2))  # (B, C) global output

        eng = InferenceEngine(
            "emb", embed_fn, {}, cache=CompiledProgramCache()
        )
        # exact bucket size: fine
        out = eng.predict(np.ones((1, 64, 64, 3), np.float32))
        assert out.shape == (1, 3)
        # off-bucket: padding would corrupt the embedding -> raise
        with pytest.raises(ValueError, match="global output"):
            eng.predict(np.ones((1, 60, 60, 3), np.float32))


def test_predictions_to_masks_rescales_network_flows():
    from bioengine_tpu.ops.flows import (
        masks_to_flows,
        predictions_to_masks,
    )

    masks = np.zeros((48, 48), np.int32)
    masks[6:20, 6:20] = 1
    masks[28:44, 28:44] = 2
    flows = masks_to_flows(masks)
    # Simulate a perfectly-trained network: 5x-scaled flows + logits.
    pred = np.concatenate(
        [
            np.moveaxis(flows * 5.0, 0, -1),
            np.where(masks > 0, 5.0, -5.0)[..., None],
        ],
        axis=-1,
    ).astype(np.float32)
    rec = predictions_to_masks(pred, n_iter=100)
    assert rec.max() == 2


class TestCheckpointService:
    """Orbax-backed train-state checkpoints (runtime/checkpoints.py) —
    SURVEY §5's stretch goal beyond the reference's app-level files."""

    def _tiny_state(self, seed=0):
        import jax
        import jax.numpy as jnp
        import optax

        from bioengine_tpu.models.cellpose import CellposeNet, TrainState

        model = CellposeNet(features=(4, 8), in_channels=2)
        params = model.init(
            jax.random.key(seed), jnp.zeros((1, 16, 16, 2), jnp.float32)
        )["params"]
        return model, TrainState.create(
            model.apply, params, optax.adam(1e-3)
        )

    def test_save_restore_roundtrip(self, tmp_path):
        import jax
        import numpy as np

        from bioengine_tpu.runtime.checkpoints import CheckpointService

        model, state = self._tiny_state()
        with CheckpointService(tmp_path / "ckpt") as ckpt:
            assert ckpt.restore_latest(state) is None  # empty dir
            ckpt.save(0, state)
            ckpt.wait()
            restored = ckpt.restore_latest(state)
        for a, b in zip(
            jax.tree.leaves(state.params), jax.tree.leaves(restored.params)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(restored.step) == int(state.step)

    def test_retention_keeps_newest(self, tmp_path):
        from bioengine_tpu.runtime.checkpoints import CheckpointService

        _, state = self._tiny_state()
        with CheckpointService(tmp_path / "ckpt", max_to_keep=2) as ckpt:
            for step in range(5):
                ckpt.save(step, state)
            ckpt.wait()
            assert ckpt.steps() == [3, 4]
            assert ckpt.latest_step() == 4

    def test_restore_onto_mesh_shards(self, tmp_path):
        """Restore with a sharded template lands leaves on the mesh
        (dp-replicated here) without a host gather."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bioengine_tpu.parallel.mesh import make_mesh
        from bioengine_tpu.runtime.checkpoints import CheckpointService

        _, state = self._tiny_state()
        mesh = make_mesh({"dp": 4}, jax.devices("cpu")[:4])
        sharded_template = jax.device_put(state, NamedSharding(mesh, P()))
        with CheckpointService(tmp_path / "ckpt") as ckpt:
            ckpt.save(7, state)
            ckpt.wait()
            restored = ckpt.restore(7, sharded_template)
        leaf = jax.tree.leaves(restored.params)[0]
        assert len(leaf.sharding.device_set) == 4
