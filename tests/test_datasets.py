"""Datasets plane tests: zarr codec, chunk cache, server + client + prefetch.

Hermetic: a real DatasetsServer on localhost over a tmp data dir, stores
written by our own codec layer (no external zarr/Hypha needed) — the
fake-backend tier the reference lacks (SURVEY §4 implication).
"""

import asyncio

import numpy as np
import pytest

from bioengine_tpu.datasets import zarr_codec
from bioengine_tpu.datasets.chunk_cache import ChunkCache
from bioengine_tpu.datasets.datasets import BioEngineDatasets
from bioengine_tpu.datasets.http_zarr_store import HttpZarrStore, RemoteZarrArray
from bioengine_tpu.datasets.prefetch import ZarrBatchLoader, prefetch_to_device
from bioengine_tpu.datasets.proxy_server import DatasetsServer

pytestmark = [pytest.mark.integration, pytest.mark.anyio]


# ---- codec unit tests --------------------------------------------------------


@pytest.mark.parametrize("zarr_format", [2, 3])
@pytest.mark.parametrize("compressor", [None, "gzip", "zlib"])
def test_codec_roundtrip(tmp_path, zarr_format, compressor):
    data = np.arange(7 * 13, dtype=np.float32).reshape(7, 13)
    meta = zarr_codec.write_array(
        tmp_path, "arr", data, chunks=(3, 5),
        compressor=compressor, zarr_format=zarr_format,
    )
    assert meta.chunk_grid == (3, 3)
    chunks = {}
    for idx in meta.chunk_indices():
        raw = (tmp_path / "arr" / meta.chunk_key(idx)).read_bytes()
        chunks[idx] = zarr_codec.decode_chunk(meta, raw)
    np.testing.assert_array_equal(zarr_codec.assemble(meta, chunks), data)


def test_codec_selection(tmp_path):
    data = np.random.default_rng(0).normal(size=(20, 16)).astype(np.float32)
    meta = zarr_codec.write_array(tmp_path, "a", data, chunks=(6, 6))
    sel = (slice(3, 17), slice(5, 16))
    indices = zarr_codec.chunks_for_selection(meta, sel)
    assert set(indices) == {
        (i, j) for i in range(0, 3) for j in range(0, 3)
    }
    chunks = {
        idx: zarr_codec.decode_chunk(
            meta, (tmp_path / "a" / meta.chunk_key(idx)).read_bytes()
        )
        for idx in indices
    }
    np.testing.assert_array_equal(
        zarr_codec.assemble(meta, chunks, sel), data[sel]
    )


def test_codec_missing_chunk_is_fill_value():
    meta = zarr_codec.ArrayMeta(
        shape=(4, 4), chunks=(2, 2), dtype=np.dtype("int32"), fill_value=7
    )
    np.testing.assert_array_equal(
        zarr_codec.decode_chunk(meta, None), np.full((2, 2), 7, np.int32)
    )


def test_codec_strided_selection_rejected(tmp_path):
    data = np.arange(10, dtype=np.float32)
    meta = zarr_codec.write_array(tmp_path, "s", data, chunks=(4,))
    with pytest.raises(ValueError, match="[Ss]trided"):
        zarr_codec.chunks_for_selection(meta, (slice(0, 10, 2),))
    with pytest.raises(ValueError, match="[Ss]trided"):
        zarr_codec.assemble(meta, {}, (slice(None, None, -1),))


# ---- chunk cache -------------------------------------------------------------


async def test_chunk_cache_lru_eviction():
    cache = ChunkCache(max_bytes=100)
    await cache.put("a", b"x" * 40)
    await cache.put("b", b"y" * 40)
    assert await cache.get("a") == b"x" * 40  # refresh a
    await cache.put("c", b"z" * 40)  # evicts b (LRU)
    assert await cache.get("b") is None
    assert await cache.get("a") is not None
    assert await cache.get("c") is not None
    assert cache.size_bytes <= 100
    await cache.resize(40)
    assert len(cache) == 1


async def test_chunk_cache_oversized_item_skipped():
    cache = ChunkCache(max_bytes=10)
    await cache.put("big", b"x" * 100)
    assert await cache.get("big") is None
    assert cache.size_bytes == 0


# ---- server + client ---------------------------------------------------------


@pytest.fixture()
async def data_server(tmp_path):
    data_dir = tmp_path / "data"
    ds_dir = data_dir / "demo"
    ds_dir.mkdir(parents=True)
    (ds_dir / "manifest.yaml").write_text(
        "description: demo dataset\nauthorized_users: ['*']\n"
    )
    rng = np.random.default_rng(1)
    images = (rng.normal(size=(16, 8, 8)) * 100).astype(np.int16)
    zarr_codec.write_group(ds_dir / "images.zarr")
    zarr_codec.write_array(
        ds_dir / "images.zarr", "raw", images, chunks=(4, 8, 8),
        compressor="gzip",
    )
    (ds_dir / "notes.txt").write_bytes(b"hello bioengine")

    # a private dataset to exercise ACL deny
    priv = data_dir / "secret"
    priv.mkdir()
    (priv / "manifest.yaml").write_text(
        "description: private\nauthorized_users: ['alice']\n"
    )

    server = DatasetsServer(
        data_dir, host="127.0.0.1", write_discovery_file=False
    )
    await server.start()
    try:
        yield server, images
    finally:
        await server.stop()


async def test_list_and_acl(data_server):
    server, _ = data_server
    client = BioEngineDatasets(server_url=server.url)
    assert await client.ping()
    names = [d["name"] for d in await client.list_datasets()]
    assert names == ["demo"]  # 'secret' filtered out for anonymous

    files = {f["name"] for f in await client.list_files("demo")}
    assert files == {"images.zarr", "notes.txt"}
    await client.aclose()


async def test_get_file_bytes_and_zarr(data_server):
    server, images = data_server
    client = BioEngineDatasets(server_url=server.url)
    blob = await client.get_file("demo", "notes.txt")
    assert blob == b"hello bioengine"

    group = await client.get_file("demo", "images.zarr")
    arr = await group.array("raw")
    assert arr.shape == (16, 8, 8)
    np.testing.assert_array_equal(await arr.read(), images)
    part = await arr.read((slice(2, 9), slice(1, 5), slice(0, 8)))
    np.testing.assert_array_equal(part, images[2:9, 1:5, :])
    await client.aclose()


async def test_range_requests(data_server):
    server, _ = data_server
    import httpx

    async with httpx.AsyncClient() as http:
        url = f"{server.url}/data/demo/notes.txt"
        r = await http.get(url, headers={"Range": "bytes=6-14"})
        assert r.status_code == 206
        assert r.content == b"bioengine"
        r = await http.get(url, headers={"Range": "bytes=-6"})
        assert r.content == b"engine"
        r = await http.get(url, headers={"Range": "bytes=99-"})
        assert r.status_code == 416


async def test_malformed_range_serves_full_file(data_server):
    server, _ = data_server
    import httpx

    async with httpx.AsyncClient() as http:
        r = await http.get(
            f"{server.url}/data/demo/notes.txt",
            headers={"Range": "bytes=abc-"},
        )
        assert r.status_code == 200
        assert r.content == b"hello bioengine"


async def test_token_validation_and_expiry(tmp_path):
    from bioengine_tpu.datasets.proxy_server import rpc_token_validator
    from bioengine_tpu.rpc.server import RpcServer

    data_dir = tmp_path / "d"
    ds = data_dir / "private-ds"
    ds.mkdir(parents=True)
    (ds / "manifest.yaml").write_text(
        "description: p\nauthorized_users: ['alice']\n"
    )
    (ds / "blob.bin").write_bytes(b"secret")

    rpc = RpcServer(admin_users=["alice"])
    token = rpc.issue_token("alice")
    bad_token = rpc.issue_token("alice", ttl_seconds=-1)  # already expired

    server = DatasetsServer(
        data_dir,
        host="127.0.0.1",
        token_validator=rpc_token_validator(rpc),
        write_discovery_file=False,
    )
    await server.start()
    try:
        import httpx

        async with httpx.AsyncClient() as http:
            url = f"{server.url}/data/private-ds/blob.bin"
            r = await http.get(url, headers={"Authorization": f"Bearer {token}"})
            assert r.status_code == 200 and r.content == b"secret"
            r = await http.get(
                url, headers={"Authorization": f"Bearer {bad_token}"}
            )
            assert r.status_code == 401
            r = await http.get(url)  # anonymous
            assert r.status_code == 403
    finally:
        await server.stop()


async def test_two_servers_no_port_collision(tmp_path):
    (tmp_path / "x").mkdir()
    s1 = DatasetsServer(tmp_path, host="127.0.0.1", write_discovery_file=False)
    s2 = DatasetsServer(tmp_path, host="127.0.0.1", write_discovery_file=False)
    await asyncio.gather(s1.start(), s2.start())
    try:
        assert s1.port != s2.port
    finally:
        await s1.stop()
        await s2.stop()


async def test_store_caching(data_server):
    server, images = data_server
    cache = ChunkCache(max_bytes=10_000_000)
    store = HttpZarrStore(
        f"{server.url}/data/demo/images.zarr", cache=cache
    )
    arr = await RemoteZarrArray.open(store, "raw")
    await arr.read()
    misses_after_first = cache.misses
    await arr.read()
    assert cache.misses == misses_after_first  # fully cached second read
    assert cache.hits > 0
    await store.aclose()


async def test_save_api_and_traversal_protection(data_server):
    server, _ = data_server
    client = BioEngineDatasets(server_url=server.url)
    await client.save_file("results/out.npy", b"\x01\x02", scope="public")
    listing = await client.list_saved(scope="public")
    assert listing == [{"name": "results/out.npy", "size": 2}]
    assert await client.get_saved("results/out.npy", scope="public") == b"\x01\x02"

    import httpx

    async with httpx.AsyncClient() as http:
        r = await http.put(
            f"{server.url}/saved/public/../../evil.txt", content=b"x"
        )
        assert r.status_code in (400, 404)
    await client.aclose()


async def test_file_not_found(data_server):
    server, _ = data_server
    client = BioEngineDatasets(server_url=server.url)
    with pytest.raises(FileNotFoundError):
        await client.get_file("demo", "missing.bin")
    await client.aclose()


# ---- prefetch ----------------------------------------------------------------


def test_prefetch_to_device_order():
    batches = [np.full((2, 2), i, np.float32) for i in range(5)]
    out = list(prefetch_to_device(iter(batches), size=2))
    assert len(out) == 5
    for i, b in enumerate(out):
        np.testing.assert_array_equal(np.asarray(b), batches[i])


async def test_zarr_batch_loader(data_server):
    server, images = data_server
    store = HttpZarrStore(f"{server.url}/data/demo/images.zarr")
    arr = await RemoteZarrArray.open(store, "raw")
    loader = ZarrBatchLoader(arr, batch_size=4, prefetch_batches=2)
    assert len(loader) == 4

    def consume():
        got = [np.asarray(b) for b in loader]
        return got

    got = await asyncio.to_thread(consume)
    assert len(got) == 4
    np.testing.assert_array_equal(np.concatenate(got, axis=0), images)
    await store.aclose()


# ---- retrying HTTP GET (datasets/net.py) -------------------------------------


class TestGetUrlWithRetry:
    """Full-jitter backoff + Retry-After handling (fault-tolerance PR)."""

    def _client(self, handler):
        import httpx

        return httpx.AsyncClient(transport=httpx.MockTransport(handler))

    async def test_retries_5xx_then_succeeds(self, monkeypatch):
        import httpx

        from bioengine_tpu.datasets import net

        calls = {"n": 0}

        def handler(request):
            calls["n"] += 1
            if calls["n"] < 3:
                return httpx.Response(503)
            return httpx.Response(200, text="ok")

        sleeps = []

        async def fake_sleep(s):
            sleeps.append(s)

        monkeypatch.setattr(net.asyncio, "sleep", fake_sleep)
        resp = await net.get_url_with_retry(
            "http://x/u", client=self._client(handler)
        )
        assert resp.status_code == 200
        assert calls["n"] == 3
        # full jitter: each delay uniform in [0, base * 2**attempt]
        assert len(sleeps) == 2
        assert 0 <= sleeps[0] <= net.BACKOFF_SECONDS
        assert 0 <= sleeps[1] <= net.BACKOFF_SECONDS * 2

    async def test_429_honors_retry_after_seconds(self, monkeypatch):
        import httpx

        from bioengine_tpu.datasets import net

        calls = {"n": 0}

        def handler(request):
            calls["n"] += 1
            if calls["n"] == 1:
                return httpx.Response(429, headers={"Retry-After": "1.5"})
            return httpx.Response(200, text="ok")

        sleeps = []

        async def fake_sleep(s):
            sleeps.append(s)

        monkeypatch.setattr(net.asyncio, "sleep", fake_sleep)
        resp = await net.get_url_with_retry(
            "http://x/u", client=self._client(handler)
        )
        assert resp.status_code == 200
        # the server's stated budget is the FLOOR for the delay
        assert sleeps == [1.5]

    async def test_429_retry_after_http_date_and_cap(self, monkeypatch):
        import httpx

        from bioengine_tpu.datasets import net

        calls = {"n": 0}

        def handler(request):
            calls["n"] += 1
            if calls["n"] == 1:
                # hostile/huge delta-seconds must be capped
                return httpx.Response(429, headers={"Retry-After": "9999"})
            return httpx.Response(200, text="ok")

        sleeps = []

        async def fake_sleep(s):
            sleeps.append(s)

        monkeypatch.setattr(net.asyncio, "sleep", fake_sleep)
        await net.get_url_with_retry(
            "http://x/u", client=self._client(handler)
        )
        assert sleeps == [net.RETRY_AFTER_CAP_SECONDS]

    async def test_4xx_not_retried(self):
        import httpx

        from bioengine_tpu.datasets import net

        calls = {"n": 0}

        def handler(request):
            calls["n"] += 1
            return httpx.Response(404)

        with pytest.raises(httpx.HTTPStatusError):
            await net.get_url_with_retry(
                "http://x/u", client=self._client(handler)
            )
        assert calls["n"] == 1

    def test_retry_after_parser(self):
        import httpx

        from bioengine_tpu.datasets.net import _retry_after_seconds

        assert _retry_after_seconds(httpx.Response(429)) is None
        assert (
            _retry_after_seconds(
                httpx.Response(429, headers={"Retry-After": "7"})
            )
            == 7.0
        )
        assert (
            _retry_after_seconds(
                httpx.Response(429, headers={"Retry-After": "garbage"})
            )
            is None
        )
        # HTTP-date in the past clamps to 0, never negative
        assert (
            _retry_after_seconds(
                httpx.Response(
                    429,
                    headers={
                        "Retry-After": "Wed, 21 Oct 2015 07:28:00 GMT"
                    },
                )
            )
            == 0.0
        )
        # '-0000' parses to a NAIVE datetime — must not crash on the
        # aware-naive subtraction (treated as UTC per RFC 7231)
        assert (
            _retry_after_seconds(
                httpx.Response(
                    429,
                    headers={
                        "Retry-After": "Wed, 21 Oct 2015 07:28:00 -0000"
                    },
                )
            )
            == 0.0
        )
