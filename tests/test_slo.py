"""The SLO engine closing the observability loop (ISSUE 10).

End-to-end acceptance on the in-process multi-host harness: a
deployment with a manifest ``slo:`` block under injected latency
faults transitions pending -> firing (flight event, metric,
auto-captured debug bundle) and -> resolved after the fault clears,
with zero failed requests. Plus: the chaos availability leg (host
killed mid-soak), scrape/undeploy races, the clock-skew handshake,
config validation, the anomaly detectors, and the scheduler's
burn-pressure hook.
"""

import asyncio
import json
import time
from pathlib import Path

import aiohttp
import pytest

from bioengine_tpu.apps.builder import AppBuildError, AppBuilder
from bioengine_tpu.apps.manifest import ManifestError, validate_manifest
from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology
from bioengine_tpu.rpc.client import ServerConnection
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.serving import (
    DeploymentSpec,
    SchedulingConfig,
    ServeController,
    SLOConfig,
)
from bioengine_tpu.serving.slo import ResidualDetector, SLOEngine
from bioengine_tpu.utils import flight, metrics
from bioengine_tpu.utils.telemetry import TelemetryStore
from bioengine_tpu.worker_host import WorkerHost

pytestmark = [pytest.mark.integration, pytest.mark.anyio]


def _no_local_chips() -> ClusterState:
    return ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu"))


def _fine_telemetry(controller, step=0.25, slots=480) -> None:
    """Second-scale rings so burn windows are drivable in a test; must
    run BEFORE deploy (the engine holds the store and registrations)."""
    controller.telemetry = TelemetryStore(resolutions=[(step, slots)])
    controller.slo = SLOEngine(
        controller.telemetry,
        on_page=controller._slo_page_hook,
        logger=controller.logger,
    )


SLO_MANIFEST = """\
name: SLO App
id: slo-app
id_emoji: "\U0001F6A8"
description: slo engine proof app
type: tpu-serve
version: 1.0.0
deployments:
  - slo_dep:SloDep
authorized_users: ["*"]
deployment_config:
  slo_dep:
    num_replicas: {num_replicas}
    min_replicas: {num_replicas}
    max_replicas: {num_replicas}
    chips: 2
    autoscale: false
    slo:
      latency_objective_ms: 100
      latency_percentile: 99
      availability: 99.9
      window: 60s
      for: {for_s}
"""

SLO_SOURCE = '''\
import asyncio

from bioengine_tpu.rpc import schema_method


class SloDep:
    async def async_init(self):
        self.delay = 0.0

    @schema_method
    async def set_delay(self, delay: float = 0.0, context=None):
        """Latency fault injection: every subsequent infer sleeps."""
        self.delay = float(delay)
        return {"delay": self.delay}

    @schema_method
    async def infer(self, context=None):
        """One request; succeeds always, slowly under the fault."""
        if self.delay:
            await asyncio.sleep(self.delay)
        return {"ok": True}
'''


def _write_slo_app(tmp_path: Path, num_replicas=1, for_s="0.3s") -> Path:
    app_dir = tmp_path / "slo-src"
    app_dir.mkdir(exist_ok=True)
    (app_dir / "manifest.yaml").write_text(
        SLO_MANIFEST.format(num_replicas=num_replicas, for_s=for_s)
    )
    (app_dir / "slo_dep.py").write_text(SLO_SOURCE)
    return app_dir


@pytest.fixture()
async def slo_plane(tmp_path):
    server = RpcServer(host="127.0.0.1", admin_users=["admin"])
    await server.start()
    token = server.issue_token("admin", is_admin=True)
    controller = ServeController(_no_local_chips(), health_check_period=3600)
    _fine_telemetry(controller)
    controller.attach_rpc(server, admin_users=["admin"])
    hosts = []

    async def spawn_host(host_id: str, rejoin: bool = True) -> WorkerHost:
        host = WorkerHost(
            server_url=server.url,
            token=token,
            host_id=host_id,
            workspace_dir=tmp_path / f"ws-{host_id}",
            rejoin=rejoin,
        )
        await host.start()
        hosts.append(host)
        return host

    try:
        yield server, controller, spawn_host, tmp_path
    finally:
        for host in hosts:
            try:
                await host.stop()
            except Exception:
                pass
        await controller.stop()
        await server.stop()


async def _deploy_slo_app(controller, tmp_path, num_replicas=1, for_s="0.3s"):
    builder = AppBuilder(workdir_root=tmp_path / "apps")
    built = builder.build(
        app_id="slo-app",
        local_path=_write_slo_app(tmp_path, num_replicas, for_s),
    )
    await controller.deploy("slo-app", built.specs)
    return built


def _alert(controller, objective):
    status = controller.get_slo_status()
    return status["deployments"]["slo-app/slo_dep"]["objectives"][objective][
        "alert"
    ]


class TestEndToEndLatencySLO:
    async def test_latency_fault_pending_firing_resolved(self, slo_plane):
        """Acceptance: injected latency -> pending -> firing (flight
        event + slo_alerts_total + auto-captured bundle) -> resolved
        after the fault clears; zero failed requests throughout."""
        server, controller, spawn_host, tmp_path = slo_plane
        await spawn_host("h1")
        built = await _deploy_slo_app(controller, tmp_path)
        spec = next(s for s in built.specs if s.name == "slo_dep")
        assert spec.slo is not None and spec.slo.latency_objective_s == 0.1
        handle = controller.get_handle("slo-app", "slo_dep")
        flight.clear()

        ok = 0
        controller.telemetry_tick()  # delta baseline
        for _ in range(8):
            assert (await handle.call("infer"))["ok"]
            ok += 1
        controller.telemetry_tick()
        assert _alert(controller, "latency")["state"] == "inactive"

        # inject the latency fault: every request now takes 250 ms
        await handle.call("set_delay", 0.25)
        for _ in range(10):
            assert (await handle.call("infer"))["ok"]
            ok += 1
        controller.telemetry_tick()
        alert = _alert(controller, "latency")
        assert alert["state"] == "pending", alert
        assert alert["severity"] == "page"

        # hold past for_s (0.3 s) with the fault still burning
        await asyncio.sleep(0.35)
        for _ in range(3):
            assert (await handle.call("infer"))["ok"]
            ok += 1
        controller.telemetry_tick()
        alert = _alert(controller, "latency")
        assert alert["state"] == "firing", alert

        # the firing left all three artifacts: flight events, the
        # counter, and the auto-captured cross-host bundle
        types = [e["type"] for e in flight.get_events()]
        assert "slo.pending" in types and "slo.firing" in types
        snap = metrics.collect()
        fired = [
            s
            for s in snap["slo_alerts_total"]["series"]
            if s["labels"]
            == {"app": "slo-app", "deployment": "slo_dep", "severity": "page"}
        ]
        assert fired and fired[0]["value"] >= 1
        for _ in range(40):  # the bundle task runs in the background
            if controller.slo_bundles:
                break
            await asyncio.sleep(0.05)
        assert controller.slo_bundles, "no auto-captured bundle"
        bundle = controller.slo_bundles[-1]
        assert bundle["slo_alert"]["objective"] == "latency"
        assert bundle["hosts"]["h1"]["reachable"]
        json.dumps(bundle, default=str)  # incident artifact serializes

        # clear the fault; good traffic drains the short+long windows
        await handle.call("set_delay", 0.0)
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            assert (await handle.call("infer"))["ok"]
            ok += 1
            controller.telemetry_tick()
            if _alert(controller, "latency")["state"] == "resolved":
                break
            await asyncio.sleep(0.1)
        alert = _alert(controller, "latency")
        assert alert["state"] == "resolved", alert
        assert "slo.resolved" in [e["type"] for e in flight.get_events()]
        # every request of the whole proof succeeded
        assert ok >= 21
        # the whole status surface is JSON-able (the get_slo_status verb)
        json.dumps(controller.get_slo_status())


class TestChaosAvailabilitySLO:
    async def test_host_killed_mid_soak_fires_availability_burn(
        self, slo_plane
    ):
        """Chaos leg: sever one host's control-plane connection
        mid-soak; the failed requests burn the availability budget
        (firing + flight event + auto-bundle), and after the host
        rejoins and good traffic resumes the alert resolves."""
        server, controller, spawn_host, tmp_path = slo_plane
        h1 = await spawn_host("h1")
        await spawn_host("h2")
        await _deploy_slo_app(controller, tmp_path, num_replicas=2, for_s="0s")
        handle = controller.get_handle("slo-app", "slo_dep")
        flight.clear()

        controller.telemetry_tick()
        for _ in range(8):
            await handle.call("infer")
        controller.telemetry_tick()
        assert _alert(controller, "availability")["state"] == "inactive"

        # kill h1's websocket MID-SOAK: a slow wave is in flight on
        # both hosts when the connection dies, so the calls executing
        # on h1 fail ambiguously (non-idempotent -> surfaced typed to
        # the caller, never silently retried) — the availability burn.
        # Auto-heal is suppressed so the outage window is deterministic.
        await handle.call("set_delay", 0.1)

        async def one() -> int:
            try:
                await handle.call("infer")
                return 0
            except Exception:
                return 1

        wave = [asyncio.create_task(one()) for _ in range(12)]
        await asyncio.sleep(0.03)   # wave is mid-flight on both hosts
        h1.connection.auto_reconnect = False
        await h1.connection._abort_connection()
        failures = sum(await asyncio.gather(*wave))
        assert failures > 0, "the kill produced no failed requests"
        await handle.call("set_delay", 0.0)
        controller.telemetry_tick()   # -> pending (for: 0s)
        controller.telemetry_tick()   # -> firing on the next pass
        alert = _alert(controller, "availability")
        assert alert["state"] == "firing", alert
        assert "slo.firing" in [e["type"] for e in flight.get_events()]
        for _ in range(40):
            if controller.slo_bundles:
                break
            await asyncio.sleep(0.05)
        assert controller.slo_bundles

        # rejoin: re-run the client's reconnect loop (re-establish +
        # re-register + the host's _rejoin_cluster hook re-announcing
        # its warm replica for re-adoption)
        h1.connection.auto_reconnect = True
        await h1.connection._reconnect_loop()
        assert h1.connection.connected, "host never rejoined"

        # good traffic drains the windows -> resolved
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            await handle.call("infer")
            controller.telemetry_tick()
            if _alert(controller, "availability")["state"] == "resolved":
                break
            await asyncio.sleep(0.1)
        assert _alert(controller, "availability")["state"] == "resolved"


class TestScrapeUndeployRaces:
    async def test_concurrent_scrapes_during_churn_never_error(
        self, slo_plane
    ):
        """GET /metrics + get_app_status + get_telemetry +
        get_slo_status racing a deploy/undeploy loop: no errors, and a
        swept deployment's series never reported as live."""
        server, controller, spawn_host, tmp_path = slo_plane
        await spawn_host("h1")
        errors: list = []
        stop = asyncio.Event()

        async def scraper():
            async with aiohttp.ClientSession() as session:
                while not stop.is_set():
                    try:
                        async with session.get(
                            server.http_url + "/metrics"
                        ) as resp:
                            assert resp.status == 200
                            await resp.text()
                        try:
                            controller.get_app_status("slo-app")
                        except KeyError:
                            pass  # mid-churn: the app may be gone
                        controller.get_telemetry()
                        json.dumps(controller.get_slo_status())
                    except Exception as e:  # noqa: BLE001 — the assertion
                        errors.append(e)
                    await asyncio.sleep(0.01)

        scrape_task = asyncio.create_task(scraper())
        builder = AppBuilder(workdir_root=tmp_path / "apps")
        built = builder.build(
            app_id="slo-app", local_path=_write_slo_app(tmp_path)
        )
        try:
            for _ in range(4):
                await controller.deploy("slo-app", built.specs)
                handle = controller.get_handle("slo-app", "slo_dep")
                for _ in range(3):
                    await handle.call("infer")
                controller.telemetry_tick()
                await controller.undeploy("slo-app")
        finally:
            stop.set()
            await scrape_task
        assert errors == [], errors
        # swept: no live telemetry series for the undeployed app
        telem = controller.get_telemetry()
        assert not [
            k for k in telem["deployments"] if k.startswith("slo-app/")
        ]
        assert "slo-app/slo_dep" not in controller.get_slo_status()[
            "deployments"
        ]


class TestClockSkew:
    async def test_skewed_host_reports_and_corrects(
        self, slo_plane, monkeypatch
    ):
        """Satellite: a host whose clock runs 5 s fast reports
        clock_skew_s at the handshake; the bundle annotates it and the
        merged timeline is ordered on the controller's clock."""
        server, controller, spawn_host, tmp_path = slo_plane

        async def skewed_probe(self, samples: int = 3):
            # the host's wall clock is 5 s AHEAD of the controller's:
            # the RTT-midpoint offset (server - local) comes out -5
            self.clock_offset_s = -5.0
            self.clock_offset_rtt_s = 0.001
            return {"offset_s": -5.0, "rtt_s": 0.001, "samples": samples}

        monkeypatch.setattr(
            ServerConnection, "measure_clock_offset", skewed_probe
        )
        host = await spawn_host("h-skew")
        assert host.clock_skew_s == pytest.approx(5.0)
        assert controller.cluster_state.hosts[
            "h-skew"
        ].clock_skew_s == pytest.approx(5.0)
        record = host.get_flight_record(limit=10)
        assert record["clock_skew_s"] == pytest.approx(5.0)

        bundle = await controller.debug_bundle()
        assert bundle["hosts"]["h-skew"]["clock_skew_s"] == pytest.approx(5.0)

        # push_telemetry de-skews captured_at: a sample stamped by the
        # fast host's clock (now+5) lands in a bucket at ~now, not in a
        # future bucket that would swallow on-time samples behind it
        caller = server.validate_token(
            server.issue_token("admin", is_admin=True)
        )
        now = time.time()
        await server.call_service_method(
            "serve-router",
            "push_telemetry",
            (
                "h-skew",
                {
                    "captured_at": now + 5.0,
                    "source_id": "other-process",
                    "deployments": {"skew-app/dep": {"requests": 3}},
                },
            ),
            caller=caller,
        )
        points = controller.telemetry.series(
            "skew-app", "dep", "request_rate", now=now
        )
        assert points, "push not ingested"
        assert points[-1]["t"] <= now + 0.5  # de-skewed, not future-dated

    def test_merge_records_orders_skewed_events(self):
        """A +-5 s skewed host's events sort where they actually
        happened, with the applied skew annotated per event."""
        base = 1_000_000.0
        controller_rec = {
            "recorder": "ctrl",
            "events": [
                {"recorder": "ctrl", "seq": 1, "ts": base + 0.0, "type": "a"},
                {"recorder": "ctrl", "seq": 2, "ts": base + 1.0, "type": "c"},
            ],
        }
        fast_host = {   # clock 5 s ahead; event really happened at +0.5
            "recorder": "h1",
            "clock_skew_s": 5.0,
            "events": [
                {"recorder": "h1", "seq": 1, "ts": base + 5.5, "type": "b"},
            ],
        }
        slow_host = {   # clock 5 s behind; event really happened at +1.5
            "recorder": "h2",
            "clock_skew_s": -5.0,
            "events": [
                {"recorder": "h2", "seq": 1, "ts": base - 3.5, "type": "d"},
            ],
        }
        merged = flight.merge_records([controller_rec, fast_host, slow_host])
        assert [e["type"] for e in merged] == ["a", "b", "c", "d"]
        corrected = {e["type"]: e for e in merged}
        assert corrected["b"]["ts"] == pytest.approx(base + 0.5)
        assert corrected["b"]["ts_raw"] == pytest.approx(base + 5.5)
        assert corrected["b"]["clock_skew_s"] == 5.0
        assert corrected["d"]["ts"] == pytest.approx(base + 1.5)
        # unskewed events untouched
        assert "ts_raw" not in corrected["a"]


class TestSLOConfig:
    def test_parsing_and_validation(self):
        cfg = SLOConfig.from_config(
            {
                "latency_objective_ms": 250,
                "latency_percentile": 99,
                "availability": 99.9,
                "window": "24h",
                "for": "2m",
            }
        )
        assert cfg.latency_objective_s == 0.25
        assert cfg.window_s == 86400.0
        assert cfg.for_s == 120.0
        assert cfg.objectives() == ["latency", "availability"]
        assert cfg.budget("latency") == pytest.approx(0.01)
        assert cfg.budget("availability") == pytest.approx(0.001)
        with pytest.raises(ValueError, match="unknown slo keys"):
            SLOConfig.from_config({"latency_objective_ms": 1, "typo": 2})
        with pytest.raises(ValueError, match="needs latency_objective"):
            SLOConfig.from_config({"window": "1h"})
        with pytest.raises(ValueError, match="latency_percentile"):
            SLOConfig.from_config(
                {"latency_objective_ms": 1, "latency_percentile": 100}
            )
        # the fraction foot-gun: 0.999 meaning 99.9% must fail the
        # build, not produce an SLO that can never alert
        with pytest.raises(ValueError, match="not 0.999"):
            SLOConfig.from_config({"availability": 0.999})
        with pytest.raises(ValueError, match="not 0.999"):
            SLOConfig.from_config(
                {"latency_objective_ms": 1, "latency_percentile": 0.99}
            )

    def test_status_flags_window_truncation(self):
        """A 30d objective on a store that only holds minutes of
        history must LABEL the truncation, not report a full-window
        budget figure computed from the covered slice."""
        store = TelemetryStore(resolutions=[(1.0, 60)])  # 60s coverage
        engine = SLOEngine(store)
        engine.register(
            "a", "d",
            SLOConfig.from_config({"availability": 99.9, "window": "30d"}),
        )
        status = engine.status()
        o = status["deployments"]["a/d"]["objectives"]["availability"]
        assert o["window_s"] == 30 * 86400.0
        assert o["window_truncated"] is True
        assert o["window_coverage_s"] == 60.0

    def test_manifest_rejects_non_mapping_slo(self):
        data = {
            "name": "x",
            "id": "x",
            "id_emoji": "x",
            "description": "x",
            "type": "tpu-serve",
            "deployments": ["d:D"],
            "deployment_config": {"d": {"slo": "99.9"}},
        }
        with pytest.raises(ManifestError, match="slo must be a mapping"):
            validate_manifest(data)

    def test_builder_fails_typed_on_bad_slo(self, tmp_path):
        app_dir = tmp_path / "bad-slo"
        app_dir.mkdir()
        (app_dir / "manifest.yaml").write_text(
            SLO_MANIFEST.format(num_replicas=1, for_s="0s").replace(
                "latency_objective_ms: 100", "latency_objective_ms: 100\n      bogus_key: 1"
            )
        )
        (app_dir / "slo_dep.py").write_text(SLO_SOURCE)
        builder = AppBuilder(workdir_root=tmp_path / "apps")
        with pytest.raises(AppBuildError, match="slo config"):
            builder.build(app_id="bad", local_path=app_dir)


class TestEscalationWhileFiring:
    def test_ticket_firing_escalating_to_page_refires_with_evidence(self):
        """The slow-then-fast burn: an alert already firing at ticket
        severity that crosses the page threshold must RE-fire — page
        counter incremented, flight event recorded, auto-bundle hook
        invoked — not silently relabel itself."""
        store = TelemetryStore(resolutions=[(0.5, 240)])
        pages: list = []
        engine = SLOEngine(store, on_page=pages.append)
        cfg = SLOConfig.from_config(
            {"latency_objective_ms": 100, "latency_percentile": 99,
             "window": "60s", "for": "0s"}
        )
        engine.register("esc-app", "dep", cfg)
        flight.clear()
        now = time.time()

        def push(t, bad, good):
            store.ingest(
                {
                    "captured_at": t,
                    "deployments": {
                        "esc-app/dep": {
                            "requests": bad + good,
                            "latency_buckets": {
                                "0.1": good, "0.5": bad + good
                            },
                        }
                    },
                }
            )

        # burn 10x (between ticket 6 and page 14.4): 10% bad
        for i in range(4):
            push(now - 2 + i * 0.5, bad=1, good=9)
        engine.evaluate(now=now)      # -> pending (ticket)
        engine.evaluate(now=now)      # -> firing (ticket)
        key = ("esc-app", "dep", "latency")
        assert engine._alerts[key].state == "firing"
        assert engine._alerts[key].severity == "ticket"
        assert pages == []

        # the burn accelerates to 100x: page threshold crossed
        for i in range(4):
            push(now + i * 0.5, bad=10, good=0)
        engine.evaluate(now=now + 2)
        alert = engine._alerts[key]
        assert alert.state == "firing" and alert.severity == "page"
        assert len(pages) == 1, "page hook must run on escalation"
        snap = metrics.collect()
        fired = [
            s
            for s in snap["slo_alerts_total"]["series"]
            if s["labels"]
            == {"app": "esc-app", "deployment": "dep", "severity": "page"}
        ]
        assert fired and fired[0]["value"] >= 1


class TestAnomalyDetection:
    def test_residual_detector_flags_spike_not_noise(self):
        det = ResidualDetector(min_points=8, consecutive=2, min_delta=0.01)
        import random

        rng = random.Random(0)
        for _ in range(50):
            assert not det.observe(0.1 + rng.uniform(-0.005, 0.005))
        # a sustained 10x excursion flags on the 2nd consecutive point
        assert not det.observe(1.0)
        assert det.observe(1.0)
        # ...and a PERSISTENT level shift is one event, not forever:
        # the flagged point inflates the EW variance, so the new level
        # stops flagging and becomes the baseline
        repeat_flags = sum(det.observe(1.0) for _ in range(30))
        assert repeat_flags <= 2, repeat_flags
        # a single blip does not
        det2 = ResidualDetector(min_points=8, consecutive=2, min_delta=0.01)
        for _ in range(50):
            det2.observe(0.1 + rng.uniform(-0.005, 0.005))
        assert not det2.observe(1.0)
        assert not det2.observe(0.1)

    def test_engine_emits_warn_event_on_latency_excursion(self):
        store = TelemetryStore(resolutions=[(1.0, 600)])
        engine = SLOEngine(store)
        engine.register(
            "a", "d", SLOConfig.from_config({"availability": 99.9})
        )
        flight.clear()
        now = time.time()
        t0 = now - 120
        for i in range(100):
            store.ingest(
                {
                    "captured_at": t0 + i,
                    "deployments": {
                        "a/d": {
                            "requests": 10,
                            "latency_buckets": {"0.1": 10, "0.5": 10},
                        }
                    },
                }
            )
        engine.evaluate(now=t0 + 101)
        assert not [
            e for e in flight.get_events() if e["type"] == "anomaly.detect"
        ]
        # p99 jumps 0.1 -> 0.5 for several buckets
        for i in range(4):
            store.ingest(
                {
                    "captured_at": t0 + 100 + i,
                    "deployments": {
                        "a/d": {
                            "requests": 10,
                            "latency_buckets": {"0.1": 0, "0.5": 10},
                        }
                    },
                }
            )
        status = engine.evaluate(now=t0 + 105)
        events = [
            e for e in flight.get_events() if e["type"] == "anomaly.detect"
        ]
        assert events, "excursion not flagged"
        assert events[0]["attrs"]["series"] == "latency_p99"
        assert events[0]["severity"] == "warning"
        assert status["anomalies"]


class TestSchedulerBurnPressure:
    async def test_burn_pressure_forces_scale_up(self):
        """The closed loop (opt-in): page-rate budget burn upgrades a
        'hold' verdict to 'up' on the predictive autoscaler."""

        class App:
            async def infer(self):
                return 1

        controller = ServeController(_no_local_chips(), health_check_period=3600)
        _fine_telemetry(controller)
        spec = DeploymentSpec(
            name="entry",
            instance_factory=App,
            scheduling=SchedulingConfig(slo_pressure=True),
            slo=SLOConfig.from_config(
                {"latency_objective_ms": 100, "window": "60s"}
            ),
        )
        try:
            await controller.deploy("burn-app", [spec])
            scheduler = controller._schedulers[("burn-app", "entry")]
            assert scheduler.pressure_fn is not None
            # no burn: predictor idle -> hold
            decision, proj = scheduler.scale_decision(1)
            assert decision == "hold"
            assert proj["slo_pressure"] == 0.0
            # feed the store an all-bad window -> page-rate burn
            now = time.time()
            for i in range(8):
                controller.telemetry.ingest(
                    {
                        "captured_at": now - 2 + i * 0.25,
                        "deployments": {
                            "burn-app/entry": {
                                "requests": 10,
                                "latency_buckets": {"0.1": 0, "0.5": 10},
                            }
                        },
                    }
                )
            controller.slo.evaluate(now=now)
            assert controller.slo.burn_pressure("burn-app", "entry") >= 1.0
            decision, proj = scheduler.scale_decision(1)
            assert decision == "up"
            assert proj["slo_pressure"] >= 1.0
        finally:
            await controller.stop()

    async def test_pressure_hook_absent_without_opt_in(self):
        class App:
            async def infer(self):
                return 1

        controller = ServeController(_no_local_chips(), health_check_period=3600)
        spec = DeploymentSpec(
            name="entry",
            instance_factory=App,
            scheduling=SchedulingConfig(),   # slo_pressure defaults off
            slo=SLOConfig.from_config({"availability": 99.9}),
        )
        try:
            await controller.deploy("plain-app", [spec])
            assert (
                controller._schedulers[("plain-app", "entry")].pressure_fn
                is None
            )
        finally:
            await controller.stop()
