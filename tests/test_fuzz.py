"""Chaos fuzzer: fault-layer hygiene, the universal invariant library,
the watchdog, the shrinker, artifact replay determinism, and the
end-to-end lease-leak drill.

Tier-1 proves the loop on a KNOWN bug: the trimmed drill hands the
shrinker a multi-event schedule over the armed lease-accounting defect
(BIOENGINE_FUZZ_DRILL=1) and requires a locally-minimal repro; the
checked-in corpus artifact must replay bit-deterministically. The full
budget-boxed search drill lives in scripts/workflows/fuzz.sh (CI's
fuzz job) and in the slow marker here.
"""

import json
from pathlib import Path

import pytest

from bioengine_tpu.testing import faults
from bioengine_tpu.testing import fuzz as fuzzer
from bioengine_tpu.testing.scenarios import FaultEvent, outcome_signature

pytestmark = [pytest.mark.integration, pytest.mark.anyio]

CORPUS_DIR = Path(__file__).parent / "fuzz_corpus"


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# satellite: fault-layer hygiene (snapshot/restore, clear_all, typed parse)
# ---------------------------------------------------------------------------


class TestFaultHygiene:
    def test_clear_all_disarms_everything_and_reports_count(self):
        faults.configure("p1", "raise")
        faults.configure("p2", "delay", delay_s=0.01)
        faults.configure("p2", "drop", scope="h1")
        assert faults.ACTIVE
        assert faults.clear_all() == 3
        assert not faults.ACTIVE
        assert faults._specs == {} and faults._hits == {}
        assert faults.clear_all() == 0  # idempotent

    async def test_snapshot_restore_roundtrips_exactly(self):
        """Armed specs, CONSUMED hit counters, and the ACTIVE flag all
        survive a snapshot/clobber/restore cycle — the fuzz loop's
        between-iterations contract."""
        faults.configure("pt", "raise", nth=3)
        await faults.hit("pt")  # consume one pass (below the window)
        snap = faults.snapshot()

        faults.clear_all()
        faults.configure("other", "raise")
        faults.restore(snap)

        assert set(faults._specs) == {"pt"}
        assert faults.ACTIVE
        assert faults.hits("pt") == 1
        # the restored window continues where it left off: pass 2 is
        # quiet, pass 3 triggers
        await faults.hit("pt")
        with pytest.raises(faults.FaultInjected):
            await faults.hit("pt")

    def test_snapshot_is_isolated_from_later_mutation(self):
        faults.configure("pt", "raise")
        snap = faults.snapshot()
        faults.configure("pt", "delay", delay_s=9.9)
        assert snap["specs"]["pt"].action == "raise"

    def test_restore_of_inactive_snapshot_deactivates(self):
        snap = faults.snapshot()  # empty state
        faults.configure("pt", "raise")
        faults.restore(snap)
        assert not faults.ACTIVE and faults._specs == {}

    @pytest.mark.parametrize(
        "bad",
        [
            "no_equals_sign",
            "=raise",                      # empty point
            "p=explode",                   # unknown action
            "p=raise:zero",                # non-numeric nth
            "p=raise:1:2:x",               # non-numeric delay
            "p=raise:1:2:0.1:1:16:extra",  # too many fields
        ],
    )
    def test_malformed_env_specs_raise_typed(self, bad):
        with pytest.raises(faults.FaultSpecError):
            faults.load_env(bad)

    def test_configure_rejects_bad_windows_and_actions(self):
        with pytest.raises(faults.FaultSpecError):
            faults.configure("p", "raise", nth=0)
        with pytest.raises(faults.FaultSpecError):
            faults.configure("p", "raise", count=0)
        with pytest.raises(faults.FaultSpecError):
            faults.configure("p", "frobnicate")
        with pytest.raises(faults.FaultSpecError):
            faults.configure("", "raise")

    def test_well_formed_env_still_parses(self):
        faults.load_env("p@h1=slow_ramp:1:1000:0.2:42:20")
        spec = faults._specs["p@h1"]
        assert spec.scope == "h1" and spec.seed == 42
        assert spec.ramp_hits == 20


# ---------------------------------------------------------------------------
# schedule generation + repair stay inside the fair envelope
# ---------------------------------------------------------------------------


class TestGenerateAndRepair:
    def test_generated_schedules_are_fair_and_deterministic(self):
        import random

        for seed in range(30):
            a = fuzzer.generate("small_multihost", random.Random(seed))
            b = fuzzer.generate("small_multihost", random.Random(seed))
            assert a == b, "generator must be a pure function of seed"
            assert fuzzer.is_fair("small_multihost", a)

    def test_repair_pairs_controller_kill_with_restart(self):
        import random

        events = [FaultEvent(at_tick=10, action="kill_controller")]
        repaired = fuzzer.repair(
            "small_multihost", events, random.Random(0)
        )
        actions = [e.action for e in repaired]
        assert actions == ["kill_controller", "restart_controller"]
        assert repaired[1].at_tick > repaired[0].at_tick

    def test_repair_never_kills_the_last_host(self):
        import random

        events = [
            FaultEvent(at_tick=5, action="kill_host", host="h1"),
            FaultEvent(at_tick=8, action="kill_host", host="h2"),
        ]
        repaired = fuzzer.repair(
            "small_multihost", events, random.Random(0)
        )
        assert [e.action for e in repaired] == ["kill_host"]

    def test_mutations_stay_fair(self):
        import random

        rng = random.Random(7)
        parent = fuzzer.generate("small_multihost", rng)
        for _ in range(30):
            child = fuzzer.mutate(
                "small_multihost", parent, rng, pool=[parent]
            )
            assert fuzzer.is_fair("small_multihost", child)
            parent = child or parent


# ---------------------------------------------------------------------------
# satellite: the ddmin shrinker (property-tested on synthetic oracles)
# ---------------------------------------------------------------------------


def _ev(tick: int, action: str = "blip", host: str = "h1") -> FaultEvent:
    return FaultEvent(at_tick=tick, action=action, host=host)


class TestShrinker:
    async def test_shrinks_to_the_single_culprit(self):
        culprit = _ev(9, "kill_host", "h2")
        events = [_ev(t) for t in range(1, 8)] + [culprit]

        async def still_fails(cand):
            return culprit in cand

        minimal, runs = await fuzzer.shrink(events, still_fails)
        assert minimal == [culprit]
        assert runs < len(events) * 4

    async def test_minimal_schedule_is_locally_minimal(self):
        """The satellite property: the minimized schedule still fails,
        and removing ANY single remaining event makes it pass."""
        needed = {_ev(3, "kill_host", "h1"), _ev(11, "kill_host", "h2")}
        noise = [_ev(t) for t in (2, 5, 7, 13, 17)]

        async def still_fails(cand):
            return needed <= set(cand)  # fails only with BOTH culprits

        minimal, _ = await fuzzer.shrink(
            list(needed) + noise, still_fails
        )
        assert await still_fails(minimal)
        for i in range(len(minimal)):
            assert not await still_fails(minimal[:i] + minimal[i + 1:]), (
                f"removing event {i} should have made the schedule pass"
            )

    async def test_respects_run_budget(self):
        calls = 0

        async def still_fails(cand):
            nonlocal calls
            calls += 1
            return True  # pathological oracle: everything "fails"

        await fuzzer.shrink([_ev(t) for t in range(1, 20)],
                            still_fails, max_runs=10)
        assert calls <= 10


# ---------------------------------------------------------------------------
# universal invariants ride along on every scenario run
# ---------------------------------------------------------------------------


class TestUniversalInvariants:
    async def test_every_run_carries_the_whole_library(self):
        from bioengine_tpu.testing.invariants import UNIVERSAL_INVARIANTS

        result = await fuzzer.run_schedule("routed_local", [], seed=3)
        for name in UNIVERSAL_INVARIANTS:
            assert name in result["invariants"], name
            v = result["invariants"][name]
            assert v["required"] and v.get("universal")
        assert result["passed"], result["invariants"]
        assert result["flight_event_types"], (
            "coverage signature needs flight event types"
        )

    async def test_watchdog_fails_typed_instead_of_hanging(self):
        """Satellite: a livelocked run is cut at the watchdog, the
        watchdog_timeout invariant goes red, and unresolved requests
        fail typed — the suite never hangs."""
        from dataclasses import replace as dc_replace

        topo = fuzzer.TOPOLOGIES["routed_local"]
        scenario = dc_replace(
            topo, name="fuzz_watchdog_probe",
            ticks=4, service_s=30.0, watchdog_s=0.8, deadline_s=0.9,
        )
        from bioengine_tpu.testing.scenarios import run_scenario_async

        result = await run_scenario_async(scenario, seed=0)
        assert not result["passed"]
        assert not result["invariants"]["watchdog_timeout"]["ok"]
        assert any(
            out and "WatchdogTimeout" in out
            for out in result["outcomes"]
        )


# ---------------------------------------------------------------------------
# the end-to-end drill: find + shrink a KNOWN lease-accounting bug
# ---------------------------------------------------------------------------


class TestDrill:
    async def test_drill_bug_found_and_shrunk_to_minimal_repro(self):
        """The trimmed acceptance drill: hand the shrinker a noisy
        schedule over the armed defect; it must isolate the kill_host
        in <= 3 events (it lands on exactly 1)."""
        noisy = [
            FaultEvent(at_tick=3, action="clock_skew", skew_s=2.0),
            FaultEvent(at_tick=7, action="kill_host", host="h2"),
            FaultEvent(at_tick=9, action="traffic_burst", burst=6),
        ]
        with fuzzer._env_overlay({"BIOENGINE_FUZZ_DRILL": "1"}):
            first = await fuzzer.run_schedule(
                "small_multihost", noisy, seed=5
            )
            red = fuzzer.red_set(first)
            assert "lease_conservation" in red, first["invariants"]

            async def still_fails(cand):
                if not fuzzer.is_fair("small_multihost", cand):
                    return False
                r = await fuzzer.run_schedule(
                    "small_multihost", cand, seed=5
                )
                return red <= fuzzer.red_set(r)

            minimal, _ = await fuzzer.shrink(noisy, still_fails)
        assert len(minimal) <= 3
        assert [e.action for e in minimal] == ["kill_host"]

    async def test_clean_engine_passes_the_drill_schedule(self):
        """Without the flag the same schedule is green — the defect is
        real, gated, and the invariant does not false-positive on an
        ordinary host death."""
        result = await fuzzer.run_schedule(
            "small_multihost",
            [FaultEvent(at_tick=7, action="kill_host", host="h2")],
            seed=5,
        )
        assert result["passed"], result["invariants"]

    @pytest.mark.slow
    async def test_full_search_finds_the_drill_bug(self):
        """The untrimmed loop: coverage-guided search from scratch must
        find the armed defect and shrink it within a CI-sized budget."""
        out = await fuzzer.fuzz(
            topology="small_multihost", seed=1, budget_s=120.0,
            drill=True,
        )
        assert out["artifacts"], out["stats"]
        art = out["artifacts"][0]
        assert art["expect"]["red"] == ["lease_conservation"]
        assert len(art["events"]) <= 3


# ---------------------------------------------------------------------------
# satellite: corpus artifacts replay bit-deterministically
# ---------------------------------------------------------------------------


class TestCorpusReplay:
    def test_corpus_is_present_and_well_formed(self):
        paths = sorted(CORPUS_DIR.glob("*.json"))
        assert paths, "tests/fuzz_corpus must hold at least the drill repro"
        for path in paths:
            art = fuzzer.load_artifact(path)  # validates kind/version
            assert art["events"], path
            assert set(art["env"]) <= set(fuzzer.ARTIFACT_ENV_ALLOWLIST)

    @pytest.mark.parametrize(
        "path", sorted(CORPUS_DIR.glob("*.json")), ids=lambda p: p.stem
    )
    async def test_corpus_artifact_replays_identically_twice(self, path):
        """Satellite determinism gate: two replays of a checked-in
        artifact produce identical outcome_signatures AND the recorded
        red set still reproduces."""
        verdict = await fuzzer.replay_artifact(path, check_determinism=True)
        assert verdict["deterministic"] is True
        assert verdict["matches_expect"], (
            f"{path.name}: red={verdict['red']}"
        )

    async def test_env_overlay_is_scoped_and_allowlisted(self):
        import os

        art = {"BIOENGINE_FUZZ_DRILL": "1", "PATH": "/evil"}
        before = os.environ.get("PATH")
        with fuzzer._env_overlay(art):
            assert os.environ.get("BIOENGINE_FUZZ_DRILL") == "1"
            assert os.environ.get("PATH") == before  # not allowlisted
        assert os.environ.get("BIOENGINE_FUZZ_DRILL") is None

    def test_artifact_roundtrip(self, tmp_path):
        events = [FaultEvent(at_tick=4, action="kill_host", host="h1")]
        art = {
            "kind": fuzzer.ARTIFACT_KIND,
            "version": fuzzer.ARTIFACT_VERSION,
            "topology": "small_multihost",
            "seed": 9,
            "events": fuzzer.schedule_to_json(events),
            "env": {},
            "expect": {"passed": True, "red": []},
            "outcome_signature": "x",
            "note": "",
        }
        path = fuzzer.save_artifact(tmp_path / "a.json", art)
        loaded = fuzzer.load_artifact(path)
        assert fuzzer.schedule_from_json(loaded["events"]) == events

    def test_load_rejects_foreign_json(self, tmp_path):
        p = tmp_path / "x.json"
        p.write_text(json.dumps({"kind": "something-else"}))
        with pytest.raises(fuzzer.FuzzError):
            fuzzer.load_artifact(p)


# ---------------------------------------------------------------------------
# search-loop plumbing that must not regress silently
# ---------------------------------------------------------------------------


class TestSearchLoop:
    async def test_coverage_key_separates_outcome_shapes(self):
        clean = await fuzzer.run_schedule("routed_local", [], seed=3)
        burst = await fuzzer.run_schedule(
            "routed_local",
            [FaultEvent(at_tick=5, action="kill_router", host="r0")],
            seed=3,
        )
        assert fuzzer.coverage_key(clean) != fuzzer.coverage_key(burst)

    async def test_fuzz_rejects_unknown_topology(self):
        with pytest.raises(fuzzer.FuzzError):
            await fuzzer.run_schedule("no_such_topology", [], 0)
        with pytest.raises(fuzzer.FuzzError):
            await fuzzer.fuzz(topology="no_such_topology", budget_s=1)

    async def test_signature_stable_across_back_to_back_runs(self):
        """The substrate's one-seed determinism contract, as consumed
        by the fuzzer: same topology + schedule + seed → identical
        outcome signature, twice in the same process."""
        events = [FaultEvent(at_tick=6, action="kill_router", host="r1")]
        a = await fuzzer.run_schedule("routed_local", events, seed=8)
        b = await fuzzer.run_schedule("routed_local", events, seed=8)
        assert outcome_signature(a) == outcome_signature(b)
