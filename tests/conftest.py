"""Hermetic test fixtures.

All tests run on the CPU XLA backend with 8 virtual devices so sharding
code paths (dp/sp meshes, halo exchange, ring attention) are exercised
without TPU hardware. This must happen before jax is imported anywhere.
"""

import os
import sys
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax  # noqa: E402
import pytest  # noqa: E402

# Some environments install a remote-TPU PJRT plugin from sitecustomize at
# interpreter startup and overwrite the jax_platforms config, ignoring
# JAX_PLATFORMS. Force pure-CPU here (before any backend is initialized)
# so the suite never blocks on remote hardware.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    from bioengine_tpu.parallel.mesh import make_mesh

    return make_mesh(axes={"dp": 2, "sp": 4}, devices=devices)


@pytest.fixture()
def tmp_workspace(tmp_path):
    ws = tmp_path / "workspace"
    ws.mkdir()
    return ws


@pytest.fixture(scope="session")
def anyio_backend():
    # async tests run via the anyio pytest plugin on plain asyncio
    return "asyncio"


REPO_APPS = Path(__file__).resolve().parent.parent / "apps"


@pytest.fixture
async def stack(tmp_path):
    """controller + rpc server + apps manager wired together in-process,
    sharing one artifact store — the hermetic analog of the reference's
    real-cluster session fixture (ref tests/conftest.py:136-161)."""
    from bioengine_tpu.apps.artifacts import LocalArtifactStore
    from bioengine_tpu.apps.builder import AppBuilder
    from bioengine_tpu.apps.manager import AppsManager
    from bioengine_tpu.cluster.state import ClusterState
    from bioengine_tpu.rpc.server import RpcServer
    from bioengine_tpu.serving.controller import ServeController

    server = RpcServer(admin_users=["admin"])
    await server.start()
    controller = ServeController(ClusterState(), health_check_period=3600)
    store = LocalArtifactStore(tmp_path / "store")
    builder = AppBuilder(
        store=store,
        workdir_root=tmp_path / "workdirs",
        admin_users=["admin"],
        log_file="off",
    )
    manager = AppsManager(
        controller=controller,
        server=server,
        store=store,
        builder=builder,
        admin_users=["admin"],
        log_file="off",
    )
    yield manager, controller, server, store
    await controller.stop()
    await server.stop()
