import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bioengine_tpu.models import get_model, list_models
from bioengine_tpu.models.cellpose import (
    CellposeConfig,
    cellpose_loss,
    create_model_and_state,
    make_train_step,
)

pytestmark = pytest.mark.unit


def test_registry_lists_builtins():
    models = list_models()
    assert {"unet2d", "cellpose", "vit-b14", "vit-s14"} <= set(models)
    with pytest.raises(KeyError):
        get_model("no-such-model")


def test_unet_shapes():
    model = get_model("unet2d", features=(8, 16, 32), out_channels=2)
    x = jnp.zeros((2, 64, 64, 1))
    params = model.init(jax.random.key(0), x)["params"]
    y = model.apply({"params": params}, x)
    assert y.shape == (2, 64, 64, 2)
    assert y.dtype == jnp.float32


def test_vit_embedding_shape():
    model = get_model("vit-s14", depth=2, dim=64, num_heads=4)
    x = jnp.zeros((2, 28, 28, 3))
    params = model.init(jax.random.key(0), x)["params"]
    emb = model.apply({"params": params}, x)
    assert emb.shape == (2, 64)
    assert emb.dtype == jnp.float32


def test_cellpose_forward_and_train_step_reduces_loss():
    cfg = CellposeConfig(features=(8, 16, 32), learning_rate=1e-2)
    model, state = create_model_and_state(cfg, jax.random.key(0), (32, 32))
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(2, 32, 32, 2)), jnp.float32)
    flows = jnp.zeros((2, 32, 32, 2))
    cellprob = jnp.zeros((2, 32, 32))

    step = jax.jit(make_train_step())
    state, m0 = step(state, images, flows, cellprob)
    for _ in range(5):
        state, m = step(state, images, flows, cellprob)
    assert float(m["loss"]) < float(m0["loss"])
    assert int(state.step) == 6


def test_cellpose_loss_components():
    pred = jnp.zeros((1, 8, 8, 3))
    flows = jnp.ones((1, 8, 8, 2)) * 0.2
    cellprob = jnp.ones((1, 8, 8))
    loss, parts = cellpose_loss(pred, flows, cellprob)
    assert float(loss) > 0
    assert set(parts) == {"flow_loss", "bce_loss"}


def test_vit_bf16_softmax_matches_f32():
    """The perf default (bf16 softmax, bench.py/embedder) must stay
    faithful to the f32 reference: cosine >= 0.999 per embedding."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bioengine_tpu.models.vit import ViT

    fast = ViT(patch_size=14, dim=128, depth=4, num_heads=4)
    exact = ViT(
        patch_size=14, dim=128, depth=4, num_heads=4,
        softmax_dtype=jnp.float32,
    )
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(4, 56, 56, 3)).astype(np.float32)
    )
    params = fast.init(jax.random.key(1), x)["params"]
    a = np.asarray(fast.apply({"params": params}, x))
    b = np.asarray(exact.apply({"params": params}, x))
    cos = (a * b).sum(-1) / (
        np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1)
    )
    assert (cos >= 0.999).all(), cos
