import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bioengine_tpu.models import get_model, list_models
from bioengine_tpu.models.cellpose import (
    CellposeConfig,
    cellpose_loss,
    create_model_and_state,
    make_train_step,
)

pytestmark = pytest.mark.unit


def test_registry_lists_builtins():
    models = list_models()
    assert {
        "unet2d", "unet3d", "cellpose", "cellpose-sam", "stardist2d",
        "vit-b14", "vit-s14",
    } <= set(models)
    with pytest.raises(KeyError):
        get_model("no-such-model")


def test_unet_shapes():
    model = get_model("unet2d", features=(8, 16, 32), out_channels=2)
    x = jnp.zeros((2, 64, 64, 1))
    params = model.init(jax.random.key(0), x)["params"]
    y = model.apply({"params": params}, x)
    assert y.shape == (2, 64, 64, 2)
    assert y.dtype == jnp.float32


def test_vit_embedding_shape():
    model = get_model("vit-s14", depth=2, dim=64, num_heads=4)
    x = jnp.zeros((2, 28, 28, 3))
    params = model.init(jax.random.key(0), x)["params"]
    emb = model.apply({"params": params}, x)
    assert emb.shape == (2, 64)
    assert emb.dtype == jnp.float32


def test_cellpose_forward_and_train_step_reduces_loss():
    cfg = CellposeConfig(features=(8, 16, 32), learning_rate=1e-2)
    model, state = create_model_and_state(cfg, jax.random.key(0), (32, 32))
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(2, 32, 32, 2)), jnp.float32)
    flows = jnp.zeros((2, 32, 32, 2))
    cellprob = jnp.zeros((2, 32, 32))

    step = jax.jit(make_train_step())
    state, m0 = step(state, images, flows, cellprob)
    for _ in range(5):
        state, m = step(state, images, flows, cellprob)
    assert float(m["loss"]) < float(m0["loss"])
    assert int(state.step) == 6


def test_cellpose_loss_components():
    pred = jnp.zeros((1, 8, 8, 3))
    flows = jnp.ones((1, 8, 8, 2)) * 0.2
    cellprob = jnp.ones((1, 8, 8))
    loss, parts = cellpose_loss(pred, flows, cellprob)
    assert float(loss) > 0
    assert set(parts) == {"flow_loss", "bce_loss"}


def test_vit_bf16_softmax_matches_f32():
    """The perf default (bf16 softmax, bench.py/embedder) must stay
    faithful to the f32 reference: cosine >= 0.999 per embedding."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bioengine_tpu.models.vit import ViT

    fast = ViT(patch_size=14, dim=128, depth=4, num_heads=4)
    exact = ViT(
        patch_size=14, dim=128, depth=4, num_heads=4,
        softmax_dtype=jnp.float32,
    )
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(4, 56, 56, 3)).astype(np.float32)
    )
    params = fast.init(jax.random.key(1), x)["params"]
    a = np.asarray(fast.apply({"params": params}, x))
    b = np.asarray(exact.apply({"params": params}, x))
    cos = (a * b).sum(-1) / (
        np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1)
    )
    assert (cos >= 0.999).all(), cos


def test_cellpose_sam_forward_and_train_step():
    """Transformer-backbone cellpose (models/cellpose_sam.py): same
    output contract as CellposeNet, loss decreases on a toy target."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from bioengine_tpu.models.cellpose import TrainState, make_train_step
    from bioengine_tpu.models.cellpose_sam import CellposeSAM

    model = CellposeSAM(patch_size=4, dim=64, depth=2, num_heads=4)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 32, 32, 2)), jnp.float32)
    flows = jnp.asarray(rng.normal(size=(2, 32, 32, 2)) * 0.2, jnp.float32)
    cellprob = jnp.asarray(rng.integers(0, 2, (2, 32, 32)), jnp.float32)

    params = model.init(jax.random.key(0), x[:1])["params"]
    out = model.apply({"params": params}, x)
    assert out.shape == (2, 32, 32, 3)
    assert out.dtype == jnp.float32
    assert model.divisor == 4

    state = TrainState.create(model.apply, params, optax.adam(1e-3))
    step = jax.jit(make_train_step())
    losses = []
    for _ in range(8):
        state, metrics = step(state, x, flows, cellprob)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_cellpose_sam_variable_tile_sizes():
    """sin-cos positions are computed per grid: one param set serves
    different tile sizes (fine-tune tiles != inference tiles)."""
    import jax
    import jax.numpy as jnp

    from bioengine_tpu.models.cellpose_sam import CellposeSAM

    model = CellposeSAM(patch_size=4, dim=64, depth=1, num_heads=4)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 2))
    )["params"]
    out = model.apply({"params": params}, jnp.zeros((1, 64, 48, 2)))
    assert out.shape == (1, 64, 48, 3)


def test_cellpose_sam_in_registry():
    from bioengine_tpu.models import get_model, list_models

    assert "cellpose-sam" in list_models()
    m = get_model("cellpose-sam", patch_size=4, dim=64, depth=1, num_heads=4)
    assert m.patch_size == 4


def test_unet3d_shapes_isotropic():
    model = get_model("unet3d", features=(4, 8), out_channels=2)
    assert model.divisor == 2
    assert model.z_divisor == 2
    x = jnp.zeros((1, 8, 16, 16, 1))
    params = model.init(jax.random.key(0), x)["params"]
    y = model.apply({"params": params}, x)
    assert y.shape == (1, 8, 16, 16, 2)
    assert y.dtype == jnp.float32


def test_unet3d_anisotropic_z_strides():
    # classic anisotropic recipe: keep z resolution at the first level
    model = get_model("unet3d", features=(4, 8, 16), z_strides=(1, 2))
    assert model.divisor == 4
    assert model.z_divisor == 2
    x = jnp.zeros((1, 4, 16, 16, 1))
    params = model.init(jax.random.key(0), x)["params"]
    y = model.apply({"params": params}, x)
    assert y.shape == (1, 4, 16, 16, 1)
    with pytest.raises(ValueError, match="z_strides"):
        _ = get_model("unet3d", features=(4, 8, 16), z_strides=(1,)).z_divisor


def test_stardist_forward_shapes():
    model = get_model("stardist2d", n_rays=16, features=(8, 16))
    assert model.divisor == 2
    x = jnp.zeros((2, 32, 32, 1))
    params = model.init(jax.random.key(0), x)["params"]
    y = model.apply({"params": params}, x)
    assert y.shape == (2, 32, 32, 17)  # 1 prob logit + 16 ray distances
    assert y.dtype == jnp.float32
    # softplus head: distances strictly positive
    assert float(np.asarray(y[..., 1:]).min()) >= 0.0


def test_stardist_targets_and_reconstruction_roundtrip():
    """Ground-truth targets for two disks must reconstruct the
    instances through the NMS/rasterization pipeline (the same
    round-trip style as the cellpose flow tests)."""
    from bioengine_tpu.ops.stardist import (
        masks_to_stardist,
        polygons_to_masks,
    )

    masks = np.zeros((64, 64), np.int32)
    yy, xx = np.mgrid[:64, :64]
    masks[(yy - 20) ** 2 + (xx - 20) ** 2 < 10**2] = 1
    masks[(yy - 44) ** 2 + (xx - 44) ** 2 < 8**2] = 2
    prob, dist = masks_to_stardist(masks, n_rays=32)
    # disk center rays ~ radius
    assert abs(dist[20, 20].mean() - 10) < 2.5
    assert abs(dist[44, 44].mean() - 8) < 2.5
    rec = polygons_to_masks(prob, dist, prob_threshold=0.5)
    assert rec.max() == 2
    for lbl in (1, 2):
        ref = masks == lbl
        ious = [
            np.mean((rec == r) & ref) / max(np.mean((rec == r) | ref), 1e-9)
            for r in range(1, rec.max() + 1)
        ]
        assert max(ious) > 0.75, (lbl, max(ious))


def test_stardist_border_cells_not_suppressed():
    """Image-border clipping must not count as NMS overlap: a cell
    centered 1 px from the edge loses ~half its analytic polygon area
    to the border but has zero overlap with other instances."""
    from bioengine_tpu.ops.stardist import masks_to_stardist, polygons_to_masks

    masks = np.zeros((48, 48), np.int32)
    yy, xx = np.mgrid[:48, :48]
    masks[(yy - 1) ** 2 + (xx - 24) ** 2 < 81] = 1  # half-disk at top edge
    prob, dist = masks_to_stardist(masks, n_rays=32)
    rec = polygons_to_masks(prob, dist, prob_threshold=0.5)
    assert rec.max() == 1, "border cell was suppressed"
    ref = masks == 1
    iou = np.mean((rec == 1) & ref) / max(np.mean((rec == 1) | ref), 1e-9)
    assert iou > 0.6, iou


_TINY_CPSAM = dict(
    patch_size=8, dim=32, depth=2, num_heads=2, window_size=2,
    global_attn_indexes=(1,), neck_dim=16, pretrain_grid=4,
)


def test_cpsam_forward_shape_and_registry():
    model = get_model("cpsam", **_TINY_CPSAM)
    x = jnp.zeros((2, 32, 32, 3))
    params = model.init(jax.random.key(0), x)["params"]
    y = model.apply({"params": params}, x)
    assert y.shape == (2, 32, 32, 3)
    assert y.dtype == jnp.float32
    assert model.divisor == 8


def test_cpsam_checkpoint_conversion_matches_model_tree():
    """A synthetic checkpoint in the public cpsam layout converts into
    EXACTLY the pytree ``CpSAM.init`` produces (keys + shapes), with
    transposes verified by value — the capability the reference's app
    is built on (fine-tune from pretrained cpsam, ref main.py:2248)."""
    from bioengine_tpu.runtime.convert import (
        convert_state_dict,
        cpsam_name_map,
        flatten_params,
        infer_depth,
        synthetic_cpsam_state_dict,
    )

    sd = synthetic_cpsam_state_dict(**_TINY_CPSAM)
    assert infer_depth(sd) == 2
    params = convert_state_dict(sd, cpsam_name_map(depth=2), strict=True)

    model = get_model("cpsam", **_TINY_CPSAM)
    expect = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    )["params"]
    got = flatten_params(params)
    import jax.tree_util as jtu

    want = {
        "/".join(str(k.key) for k in path): tuple(leaf.shape)
        for path, leaf in jtu.tree_flatten_with_path(expect)[0]
    }
    assert set(got) == set(want), (
        sorted(set(got) ^ set(want))[:8]
    )
    for k, shape in want.items():
        assert got[k].shape == shape, (k, got[k].shape, shape)

    # value spot checks: each torch->flax transform actually applied
    np.testing.assert_array_equal(
        got["encoder/block0/attn/qkv/kernel"],
        sd["encoder.blocks.0.attn.qkv.weight"].T,
    )
    np.testing.assert_array_equal(
        got["encoder/patch_embed/kernel"],
        np.transpose(sd["encoder.patch_embed.proj.weight"], (2, 3, 1, 0)),
    )
    np.testing.assert_array_equal(
        got["out/kernel"],
        np.transpose(sd["out.weight"], (2, 3, 0, 1))[::-1, ::-1],
    )
    np.testing.assert_array_equal(
        got["encoder/pos_embed"], sd["encoder.pos_embed"]
    )

    # converted params drive a real forward
    y = model.apply({"params": params}, jnp.ones((1, 32, 32, 3)) * 0.1)
    assert np.isfinite(np.asarray(y)).all()


def test_cpsam_conversion_strict_mode_names_unmapped_keys():
    from bioengine_tpu.runtime.convert import (
        convert_state_dict,
        cpsam_name_map,
        synthetic_cpsam_state_dict,
    )

    sd = synthetic_cpsam_state_dict(**_TINY_CPSAM)
    sd["encoder.blocks.0.attn.new_thing"] = np.zeros(3, np.float32)
    with pytest.raises(KeyError, match="new_thing"):
        convert_state_dict(sd, cpsam_name_map(depth=2), strict=True)
    # non-strict skips it
    convert_state_dict(sd, cpsam_name_map(depth=2), strict=False)


class TestGoldenCpSAM:
    """cpsam weight conversion pinned against an INDEPENDENT forward
    (tests/generate_golden_cpsam.py: pure numpy/scipy reimplementation
    of the torch cpsam math — torch-layout kernels consumed directly,
    SAM's reference attention/window/rel-pos semantics, zero shared
    code with models/sam.py or the convert transposes). A transposed-
    but-wrong kernel or a swapped rel-pos table passes the structural
    conversion tests and fails HERE against committed activations
    (round-5 ADVICE)."""

    @pytest.fixture(scope="class")
    def golden(self):
        from pathlib import Path

        return np.load(Path(__file__).parent / "fixtures_golden_cpsam.npz")

    _CFG = dict(
        patch_size=8, dim=32, depth=2, num_heads=2, window_size=2,
        global_attn_indexes=(1,), neck_dim=16, pretrain_grid=4,
    )

    def _converted_params(self):
        from bioengine_tpu.runtime.convert import (
            convert_state_dict,
            cpsam_name_map,
            synthetic_cpsam_state_dict,
        )

        sd = synthetic_cpsam_state_dict(**self._CFG)
        return convert_state_dict(sd, cpsam_name_map(depth=2), strict=True)

    def test_encoder_activations_match_independent_forward(self, golden):
        from bioengine_tpu.models.sam import SAMEncoder

        enc = SAMEncoder(**self._CFG, dtype=jnp.float32)
        feats = np.asarray(
            enc.apply(
                {"params": self._converted_params()["encoder"]},
                jnp.asarray(golden["input"]),
            )
        )
        # golden computed in f64; the flax twin runs f32 — agreement to
        # ~1e-6 leaves a 1000x margin below any layout/transpose bug
        np.testing.assert_allclose(
            feats, golden["encoder"], rtol=1e-3, atol=1e-3
        )

    def test_full_readout_matches_independent_forward(self, golden):
        from bioengine_tpu.models.sam import CpSAM

        model = CpSAM(**self._CFG, dtype=jnp.float32)
        out = np.asarray(
            model.apply(
                {"params": self._converted_params()},
                jnp.asarray(golden["input"]),
            )
        )
        assert out.shape == golden["output"].shape
        np.testing.assert_allclose(
            out, golden["output"], rtol=1e-3, atol=2e-3
        )


class TestGoldenFlows:
    """ops/flows.py pinned against an INDEPENDENT implementation
    (tests/generate_golden_flows.py: exact sparse-solve diffusion +
    numpy/map_coordinates Euler integration — zero shared code). Drift
    in target generation, flow following, or sink clustering fails
    here against committed ground truth, not just against itself
    (VERDICT r4 weak #5)."""

    @pytest.fixture(scope="class")
    def golden(self):
        from pathlib import Path

        with np.load(
            Path(__file__).parent / "fixtures_golden_flows.npz"
        ) as d:
            return {k: d[k] for k in d.files}

    def test_target_flows_match_independent_solve(self, golden):
        from bioengine_tpu.ops.flows import masks_to_flows

        masks = golden["masks"].astype(np.int32)
        ours = masks_to_flows(masks)
        theirs = golden["flows"]
        # compare away from instance boundaries (both implementations
        # use one-sided gradients at the rim; direction there is
        # genuinely ambiguous)
        from scipy import ndimage

        interior = ndimage.binary_erosion(masks > 0, iterations=2)
        cos = (ours * theirs).sum(0)[interior]
        assert cos.mean() > 0.97, cos.mean()
        assert np.quantile(cos, 0.1) > 0.85, np.quantile(cos, 0.1)

    def test_follow_flows_matches_independent_euler(self, golden):
        from bioengine_tpu.ops.flows import follow_flows

        ours = np.asarray(follow_flows(jnp.asarray(golden["flows"])))
        fg = golden["masks"] > 0
        err = np.sqrt(((ours - golden["sinks"]) ** 2).sum(0))[fg]
        # sinks are attractors ~instance-radius apart; sub-pixel mean
        # agreement means both integrators converge to the same points
        assert np.median(err) < 1.0, np.median(err)
        assert err.mean() < 2.0, err.mean()

    def test_masks_reconstructed_from_independent_flows(self, golden):
        """The full postprocessing recipe consumes the INDEPENDENT
        flows and must reproduce the committed instance masks."""
        from bioengine_tpu.ops.flows import masks_from_flows

        masks = golden["masks"].astype(np.int32)
        cellprob_logits = np.where(masks > 0, 8.0, -8.0).astype(np.float32)
        rec = masks_from_flows(golden["flows"], cellprob_logits)
        assert rec.max() == masks.max(), (rec.max(), masks.max())
        for lbl in range(1, masks.max() + 1):
            ref = masks == lbl
            ious = [
                np.sum((rec == r) & ref) / max(np.sum((rec == r) | ref), 1)
                for r in range(1, rec.max() + 1)
            ]
            assert max(ious) > 0.8, (lbl, max(ious))


def test_stardist_candidate_overflow_grid_subsamples():
    """When candidates exceed max_candidates, subsampling must be
    SPATIAL (per-grid-cell argmax), not a global prob top-k — every
    instance keeps a candidate, so none are silently dropped (ADVICE
    r4: global truncation lost low-peak cells on dense images)."""
    import warnings

    from bioengine_tpu.ops.stardist import masks_to_stardist, polygons_to_masks

    masks = np.zeros((96, 96), np.int32)
    yy, xx = np.mgrid[:96, :96]
    lbl = 0
    for cy in range(8, 96, 16):
        for cx in range(8, 96, 16):
            lbl += 1
            masks[(yy - cy) ** 2 + (xx - cx) ** 2 < 36] = lbl
    prob, dist = masks_to_stardist(masks, n_rays=16)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        rec = polygons_to_masks(
            prob, dist, prob_threshold=0.1, max_candidates=50
        )
    assert any("grid-subsampled" in str(w.message) for w in caught)
    assert rec.max() == lbl, f"lost instances: {rec.max()} of {lbl}"


def test_stardist_empty_and_logit_paths():
    from bioengine_tpu.ops.stardist import (
        polygons_to_masks,
        predictions_to_masks_stardist,
    )

    empty = polygons_to_masks(
        np.zeros((16, 16), np.float32), np.zeros((16, 16, 8), np.float32)
    )
    assert empty.shape == (16, 16) and empty.max() == 0
    # logit wrapper: big negative logits -> no instances
    pred = np.full((16, 16, 9), -10.0, np.float32)
    assert predictions_to_masks_stardist(pred).max() == 0


def test_stardist_train_step_reduces_loss():
    """Full family parity: targets from masks_to_stardist, loss drops
    over a few adam steps on trivially-learnable data."""
    import optax

    from bioengine_tpu.models.cellpose import TrainState
    from bioengine_tpu.models.stardist import (
        StarDist2D,
        make_stardist_train_step,
    )
    from bioengine_tpu.ops.stardist import masks_to_stardist

    masks = np.zeros((32, 32), np.int32)
    yy, xx = np.mgrid[:32, :32]
    masks[(yy - 16) ** 2 + (xx - 16) ** 2 < 64] = 1
    prob_t, dist_t = masks_to_stardist(masks, n_rays=8)
    rng = np.random.default_rng(0)
    images = jnp.asarray(
        (masks > 0)[None, ..., None] + 0.05 * rng.normal(size=(2, 32, 32, 1)),
        jnp.float32,
    )
    prob = jnp.broadcast_to(jnp.asarray(prob_t), (2, 32, 32))
    dist = jnp.broadcast_to(jnp.asarray(dist_t), (2, 32, 32, 8))

    model = StarDist2D(n_rays=8, features=(8, 16))
    params = model.init(jax.random.key(0), images[:1])["params"]
    state = TrainState.create(model.apply, params, optax.adam(1e-3))
    step = jax.jit(make_stardist_train_step())
    losses = []
    for _ in range(8):
        state, metrics = step(state, images, prob, dist)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert set(metrics) == {"loss", "bce_loss", "dist_loss"}
