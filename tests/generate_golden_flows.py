"""Generate tests/fixtures_golden_flows.npz — an INDEPENDENT
implementation of the cellpose flow recipe used as ground truth by
``tests/test_models.py::test_golden_flows_*``.

Why this exists (VERDICT r4 weak #5): the framework's
``ops/flows.py`` was validated only structurally (round-trips against
itself). This fixture pins it against a second implementation that
shares NO code with it:

- diffusion is solved EXACTLY as a sparse linear system
  (scipy.sparse.linalg.spsolve) instead of ops/flows.py's fixed-point
  iteration — same math the upstream cellpose paper describes (heat
  diffusion from the cell center, flows = normalized gradient), a
  different numerical path;
- flow-following is a numpy Euler loop over
  scipy.ndimage.map_coordinates, independent of the jitted
  ``lax.scan``/bilinear-gather implementation.

The real cellpose package is deliberately NOT a dependency (the TPU
image has no egress and ships without it); this generator is committed
so the fixture is reproducible: ``python tests/generate_golden_flows.py``
rewrites the npz deterministically.

Fixture contents:
  masks   (96, 96)  int16  — 8 instances: disks, ellipses, touching pair
  flows   (2, 96, 96) f32  — exact-solve flows (dy, dx), unit scale
  sinks   (2, 96, 96) f32  — numpy-Euler final positions (200 iters)
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
from scipy import ndimage, sparse
from scipy.sparse.linalg import spsolve

OUT = Path(__file__).parent / "fixtures_golden_flows.npz"


def make_masks() -> np.ndarray:
    masks = np.zeros((96, 96), np.int16)
    yy, xx = np.mgrid[:96, :96]

    def ellipse(cy, cx, ry, rx, lbl, angle=0.0):
        ca, sa = np.cos(angle), np.sin(angle)
        y, x = yy - cy, xx - cx
        u, v = ca * y + sa * x, -sa * y + ca * x
        masks[(u / ry) ** 2 + (v / rx) ** 2 < 1.0] = lbl

    ellipse(18, 20, 9, 9, 1)              # disk
    ellipse(20, 58, 7, 13, 2, 0.5)        # tilted ellipse
    ellipse(52, 16, 12, 6, 3, -0.3)       # tall ellipse
    ellipse(50, 48, 8, 8, 4)              # touching pair left
    ellipse(50, 63, 8, 8, 5)              # touching pair right (overlap
    #                                       resolved by paint order)
    ellipse(80, 30, 6, 10, 6, 1.1)
    ellipse(78, 70, 9, 5, 7, 0.2)
    ellipse(30, 84, 6, 6, 8)
    return masks


def exact_diffusion_flows(masks: np.ndarray) -> np.ndarray:
    """Steady-state of ops/flows.py's iteration, solved directly:
    h = 0.25 * (sum of 4-neighbor h, zero outside the instance) + src
    =>  (I - 0.25 * A) h = src, one sparse solve per instance."""
    H, W = masks.shape
    flows = np.zeros((2, H, W), np.float32)
    for lbl in np.unique(masks[masks > 0]):
        sel = masks == lbl
        ys, xs = np.nonzero(sel)
        n = len(ys)
        index = {(y, x): i for i, (y, x) in enumerate(zip(ys, xs))}
        A = sparse.lil_matrix((n, n))
        for i, (y, x) in enumerate(zip(ys, xs)):
            for dy, dx in ((1, 0), (-1, 0), (0, 1), (0, -1)):
                j = index.get((y + dy, x + dx))
                if j is not None:
                    A[i, j] = 0.25
        src = np.zeros(n)
        cy, cx = int(np.median(ys)), int(np.median(xs))
        # median point may fall outside a concave instance; snap to the
        # nearest instance pixel
        k = int(np.argmin((ys - cy) ** 2 + (xs - cx) ** 2))
        src[k] = 1.0
        h = spsolve(sparse.eye(n).tocsr() - A.tocsr(), src)
        hmap = np.zeros((H, W))
        hmap[ys, xs] = np.log1p(h / h.min() * 1e3)  # scale-free under log
        gy, gx = np.gradient(hmap)
        norm = np.sqrt(gy**2 + gx**2) + 1e-10
        flows[0][sel] = (gy / norm)[sel]
        flows[1][sel] = (gx / norm)[sel]
    return flows


def numpy_follow(flows: np.ndarray, n_iter: int = 200) -> np.ndarray:
    """Independent Euler integration: map_coordinates bilinear sampling."""
    H, W = flows.shape[1:]
    yy, xx = np.mgrid[:H, :W].astype(np.float64)
    p = np.stack([yy, xx])
    for _ in range(n_iter):
        dy = ndimage.map_coordinates(flows[0], p, order=1, mode="nearest")
        dx = ndimage.map_coordinates(flows[1], p, order=1, mode="nearest")
        p[0] = np.clip(p[0] + dy, 0, H - 1)
        p[1] = np.clip(p[1] + dx, 0, W - 1)
    return p.astype(np.float32)


def main() -> None:
    masks = make_masks()
    flows = exact_diffusion_flows(masks)
    sinks = numpy_follow(flows)
    np.savez_compressed(OUT, masks=masks, flows=flows, sinks=sinks)
    print(f"wrote {OUT}: {masks.max()} instances, flows {flows.shape}")


if __name__ == "__main__":
    main()
