import logging
import os
from pathlib import Path

import pytest

from bioengine_tpu.utils.logger import create_logger, read_log_tail
from bioengine_tpu.utils.network import acquire_free_port, get_internal_ip
from bioengine_tpu.utils.permissions import (
    check_permissions,
    create_context,
    is_authorized,
)
from bioengine_tpu.utils.requirements import (
    get_pip_requirements,
    normalize_requirement,
    update_requirements,
)

pytestmark = pytest.mark.unit


class TestPermissions:
    def test_wildcard_allows_any_user(self):
        ctx = create_context("alice")
        check_permissions(ctx, ["*"])

    def test_user_id_match(self):
        ctx = create_context("alice")
        check_permissions(ctx, ["alice"])

    def test_email_match(self):
        ctx = create_context("alice", email="alice@lab.org")
        check_permissions(ctx, ["alice@lab.org"])

    def test_workspace_match(self):
        ctx = create_context("alice", workspace="ws-team")
        check_permissions(ctx, ["ws-team"])

    def test_empty_list_denies(self):
        ctx = create_context("alice")
        with pytest.raises(PermissionError):
            check_permissions(ctx, [])

    def test_mismatch_denies(self):
        ctx = create_context("mallory")
        with pytest.raises(PermissionError):
            check_permissions(ctx, ["alice", "bob"])

    def test_missing_context_denies(self):
        with pytest.raises(PermissionError):
            check_permissions(None, ["*"])

    def test_is_authorized_bool(self):
        assert is_authorized(create_context("a"), ["*"])
        assert not is_authorized(create_context("a"), ["b"])


class TestNetwork:
    def test_internal_ip_is_ipv4(self):
        ip = get_internal_ip()
        parts = ip.split(".")
        assert len(parts) == 4 and all(0 <= int(p) <= 255 for p in parts)

    def test_acquire_os_assigned_port(self):
        port, sock = acquire_free_port()
        assert port > 0 and sock is None

    def test_held_port_stays_bound(self):
        port, sock = acquire_free_port(hold=True)
        try:
            import socket

            s2 = socket.socket()
            with pytest.raises(OSError):
                s2.bind(("0.0.0.0", port))
            s2.close()
        finally:
            sock.close()

    def test_range_scan(self):
        port, _ = acquire_free_port(40000, 40100)
        assert 40000 <= port <= 40100


class TestLogger:
    def test_console_only(self):
        log = create_logger("t1", log_file="off")
        assert log.name == "bioengine.t1"
        assert len(log.handlers) == 1

    def test_file_logging_and_tail(self, tmp_path):
        f = tmp_path / "t2.log"
        log = create_logger("t2", level=logging.DEBUG, log_file=f)
        log.info("hello-world")
        for h in log.handlers:
            h.flush()
        assert "hello-world" in f.read_text()
        assert "hello-world" in read_log_tail("t2")


class TestRequirements:
    def test_normalize_rewrites_operator_keeps_version(self):
        assert normalize_requirement("numpy>=1.26") == "numpy==1.26"
        assert normalize_requirement("pkg~=2.1.0") == "pkg==2.1.0"

    def test_normalize_bare_name_passthrough(self):
        assert normalize_requirement("not-a-real-pkg-xyz") == "not-a-real-pkg-xyz"

    def test_skip_is_exact_name_not_prefix(self):
        reqs = update_requirements(["jaxtyping==0.2.0", "torchmetrics>=1.0"])
        names = [r.split("==")[0] for r in reqs]
        assert "jaxtyping" in names and "torchmetrics" in names

    def test_injection_skips_compute_stack(self):
        reqs = update_requirements(["jax>=0.4", "flax", "somepkg==1.0"])
        names = [r.split("==")[0] for r in reqs]
        assert "jax" not in names and "flax" not in names
        assert "somepkg" in names

    def test_framework_pins_present(self):
        names = [r.split("==")[0] for r in get_pip_requirements()]
        assert "numpy" in names


class TestGeoLocation:
    @pytest.mark.anyio
    async def test_disabled_via_env(self, monkeypatch):
        from bioengine_tpu.utils.geo_location import fetch_geolocation

        monkeypatch.setenv("BIOENGINE_DISABLE_GEOLOCATION", "1")
        geo = await fetch_geolocation()
        assert geo == {
            "region": None, "country_name": None, "country_code": None,
            "latitude": None, "longitude": None, "timezone": None,
        }

    @pytest.mark.anyio
    async def test_fallback_chain(self, monkeypatch):
        """First provider fails -> second provider's answer is used."""
        from bioengine_tpu.utils import geo_location

        async def fail():
            raise ValueError("down")

        async def ok():
            return {
                "region": "Stockholm", "country_name": "Sweden",
                "country_code": "SE", "latitude": 59.3,
                "longitude": 18.1, "timezone": "Europe/Stockholm",
            }

        monkeypatch.setattr(
            geo_location, "PROVIDERS",
            [("down", fail), ("up", ok)],
        )
        geo = await geo_location.fetch_geolocation()
        assert geo["country_code"] == "SE"

    @pytest.mark.anyio
    async def test_all_fail(self, monkeypatch):
        from bioengine_tpu.utils import geo_location

        async def fail():
            raise ValueError("down")

        monkeypatch.setattr(geo_location, "PROVIDERS", [("down", fail)])
        geo = await geo_location.fetch_geolocation()
        assert geo["latitude"] is None

    @pytest.mark.anyio
    async def test_centroid_fallback_when_no_coordinates(self, monkeypatch):
        from bioengine_tpu.utils import geo_location

        async def names_only():
            return {
                "region": "Uppsala", "country_name": "Sweden",
                "country_code": "SE", "latitude": None,
                "longitude": None, "timezone": "Europe/Stockholm",
            }

        async def centroid(country, region=None, logger=None):
            assert country == "Sweden" and region == "Uppsala"
            return {"latitude": 59.9, "longitude": 17.6}

        monkeypatch.setattr(
            geo_location, "PROVIDERS", [("names", names_only)]
        )
        monkeypatch.setattr(
            geo_location, "fetch_centroid_coordinates", centroid
        )
        geo = await geo_location.fetch_geolocation()
        assert geo["latitude"] == 59.9


class TestPackaging:
    """Packaging surface validation (VERDICT r3 missing #2): compose
    config parses with the right healthchecks, Dockerfiles reference
    real paths, the HPC launcher builds a correct command line."""

    REPO = Path(__file__).resolve().parent.parent

    def test_compose_config_validates(self):
        import yaml

        cfg = yaml.safe_load((self.REPO / "docker-compose.yaml").read_text())
        services = cfg["services"]
        assert set(services) == {"data-server", "worker"}
        for name, svc in services.items():
            test_cmd = svc["healthcheck"]["test"]
            assert "/health/liveness" in " ".join(test_cmd)
            dockerfile = self.REPO / svc["build"]["dockerfile"]
            assert dockerfile.is_file(), dockerfile
        # worker waits for a healthy data server
        assert (
            cfg["services"]["worker"]["depends_on"]["data-server"]["condition"]
            == "service_healthy"
        )

    def test_dockerfiles_copy_real_paths(self):
        for df in ("worker.Dockerfile", "datasets.Dockerfile"):
            text = (self.REPO / "docker" / df).read_text()
            for line in text.splitlines():
                if line.startswith("COPY "):
                    src = line.split()[1]
                    if src.startswith("--"):
                        continue
                    assert (self.REPO / src).exists(), f"{df}: {src}"

    def test_requirements_files_installable_names(self):
        import importlib

        for req in ("requirements-worker.txt", "requirements-datasets.txt"):
            for line in (self.REPO / "docker" / req).read_text().splitlines():
                line = line.split("#")[0].strip()
                if not line:
                    continue
                name = (
                    line.split(">=")[0].split("==")[0].strip()
                    .replace("-", "_")
                )
                # every dep must exist in THIS image (they're all baked in)
                importlib.import_module(
                    {"pyyaml": "yaml", "orbax_checkpoint": "orbax.checkpoint"}
                    .get(name, name)
                )

    def test_hpc_launcher_dry_run_command(self, tmp_path, monkeypatch):
        import subprocess as sp

        # fake apptainer on PATH so the launcher resolves a runtime
        fake_bin = tmp_path / "bin"
        fake_bin.mkdir()
        (fake_bin / "apptainer").write_text("#!/bin/sh\nexit 0\n")
        (fake_bin / "apptainer").chmod(0o755)
        env = dict(
            os.environ,
            PATH=f"{fake_bin}:{os.environ['PATH']}",
            HOME=str(tmp_path),
            BIOENGINE_DRY_RUN="1",
            BIOENGINE_IMAGE="docker://example/worker:1.2",
            BIOENGINE_ADMIN_TOKEN="tok",
        )
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        proc = sp.run(
            [
                "bash", str(self.REPO / "scripts" / "start_hpc_worker.sh"),
                "--mode", "slurm",
                "--workspace-dir", str(tmp_path / "ws"),
                "--datasets-dir", str(data_dir),
            ],
            capture_output=True, text=True, env=env, timeout=30,
        )
        assert proc.returncode == 0, proc.stderr
        cmd = proc.stdout.strip()
        assert "apptainer exec" in cmd
        assert "python -m bioengine_tpu.worker" in cmd
        assert "--mode slurm" in cmd
        assert f"{tmp_path}/ws" in cmd          # workspace bind
        assert f"{data_dir}:{data_dir}:ro" in cmd  # datasets bind (ro)
        assert "example_worker_1.2.sif" in cmd  # cached SIF path
        assert (tmp_path / "ws").is_dir()       # created before bind


class TestTracing:
    def test_span_records_duration_and_nesting(self):
        from bioengine_tpu.utils.tracing import clear_spans, get_spans, span

        clear_spans()
        with span("outer", app_id="a"):
            with span("inner"):
                pass
        # spans land on the buffer when they OPEN (satellite: in-flight
        # visibility), so the order is start order — outer first
        spans = get_spans()
        assert [s["name"] for s in spans] == ["outer", "inner"]
        outer, inner = spans
        assert inner["parent_id"] == outer["span_id"]
        assert outer["attrs"] == {"app_id": "a"}
        assert outer["duration_s"] >= inner["duration_s"] >= 0

    def test_open_spans_visible_only_with_include_open(self):
        from bioengine_tpu.utils.tracing import clear_spans, get_spans, span

        clear_spans()
        with span("inflight"):
            assert get_spans() == []  # not closed yet
            (open_s,) = get_spans(include_open=True)
            assert open_s["name"] == "inflight"
            assert "duration_s" not in open_s
        (closed,) = get_spans()
        assert closed["duration_s"] >= 0

    def test_duration_is_monotonic_not_wall(self, monkeypatch):
        """A wall-clock step (NTP slew) must not corrupt durations;
        started_at stays wall time for display."""
        import time as _time

        from bioengine_tpu.utils import tracing

        tracing.clear_spans()
        real_time = _time.time
        with tracing.span("stepped"):
            # jump the wall clock an hour back mid-span
            monkeypatch.setattr(
                _time, "time", lambda: real_time() - 3600.0
            )
        monkeypatch.undo()
        (s,) = tracing.get_spans()
        assert 0 <= s["duration_s"] < 1.0
        assert abs(s["started_at"] - real_time()) < 5.0

    def test_span_failure_recorded_and_reraised(self):
        from bioengine_tpu.utils.tracing import clear_spans, get_spans, span

        clear_spans()
        with pytest.raises(ValueError):
            with span("boom"):
                raise ValueError("x")
        (s,) = get_spans(name="boom")
        assert s["error"] == "ValueError: x"

    def test_filter_and_limit(self):
        from bioengine_tpu.utils.tracing import clear_spans, get_spans, span

        clear_spans()
        for i in range(5):
            with span("a", i=i):
                pass
            with span("b"):
                pass
        assert len(get_spans(name="a")) == 5
        assert len(get_spans(max_spans=3)) == 3
        assert get_spans(name="a")[-1]["attrs"] == {"i": 4}


def test_persistent_compilation_cache(tmp_path):
    """enable_persistent_compilation_cache fills the cache dir and a
    second process reuses it (subprocess: jax config is process-global
    and must not leak into other tests)."""
    import subprocess
    import sys

    prog = f"""
import jax; jax.config.update("jax_platforms", "cpu")
from bioengine_tpu.utils.compile_cache import enable_persistent_compilation_cache
d = enable_persistent_compilation_cache({str(tmp_path)!r})
assert d == {str(tmp_path)!r}, d
# idempotent
assert enable_persistent_compilation_cache("/elsewhere") == d
import jax.numpy as jnp
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.jit(lambda x: (x @ x).sum())(jnp.ones((64, 64))).block_until_ready()
"""
    for _ in range(2):
        r = subprocess.run(
            [sys.executable, "-c", prog], capture_output=True, text=True
        )
        assert r.returncode == 0, r.stderr[-1500:]
    assert any(tmp_path.iterdir()), "cache dir stayed empty"

    # explicit opt-out
    import os

    r = subprocess.run(
        [sys.executable, "-c", (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "from bioengine_tpu.utils.compile_cache import "
            "enable_persistent_compilation_cache\n"
            "assert enable_persistent_compilation_cache() is None"
        )],
        capture_output=True, text=True,
        env={**os.environ, "BIOENGINE_COMPILE_CACHE": "off"},
    )
    assert r.returncode == 0, r.stderr[-1500:]


def test_full_jitter_delay_windows_and_overflow():
    """Shared backoff helper: uniform in [0, min(cap, base*2**n)], and
    absurd attempt counts must clamp instead of overflowing float
    (0.2 * 2**1075 would raise OverflowError)."""
    from bioengine_tpu.utils.backoff import full_jitter_delay

    for attempt, base, cap, window in [
        (0, 0.2, 5.0, 0.2),
        (3, 0.2, 5.0, 1.6),
        (10, 0.2, 5.0, 5.0),       # capped
    ]:
        for _ in range(50):
            d = full_jitter_delay(attempt, base, cap)
            assert 0.0 <= d <= window
    # a partition lasting thousands of attempts must not kill the loop
    assert 0.0 <= full_jitter_delay(100_000, 0.2, 5.0) <= 5.0
    assert 0.0 <= full_jitter_delay(-3, 0.2, 5.0) <= 0.2
