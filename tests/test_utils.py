import logging

import pytest

from bioengine_tpu.utils.logger import create_logger, read_log_tail
from bioengine_tpu.utils.network import acquire_free_port, get_internal_ip
from bioengine_tpu.utils.permissions import (
    check_permissions,
    create_context,
    is_authorized,
)
from bioengine_tpu.utils.requirements import (
    get_pip_requirements,
    normalize_requirement,
    update_requirements,
)

pytestmark = pytest.mark.unit


class TestPermissions:
    def test_wildcard_allows_any_user(self):
        ctx = create_context("alice")
        check_permissions(ctx, ["*"])

    def test_user_id_match(self):
        ctx = create_context("alice")
        check_permissions(ctx, ["alice"])

    def test_email_match(self):
        ctx = create_context("alice", email="alice@lab.org")
        check_permissions(ctx, ["alice@lab.org"])

    def test_workspace_match(self):
        ctx = create_context("alice", workspace="ws-team")
        check_permissions(ctx, ["ws-team"])

    def test_empty_list_denies(self):
        ctx = create_context("alice")
        with pytest.raises(PermissionError):
            check_permissions(ctx, [])

    def test_mismatch_denies(self):
        ctx = create_context("mallory")
        with pytest.raises(PermissionError):
            check_permissions(ctx, ["alice", "bob"])

    def test_missing_context_denies(self):
        with pytest.raises(PermissionError):
            check_permissions(None, ["*"])

    def test_is_authorized_bool(self):
        assert is_authorized(create_context("a"), ["*"])
        assert not is_authorized(create_context("a"), ["b"])


class TestNetwork:
    def test_internal_ip_is_ipv4(self):
        ip = get_internal_ip()
        parts = ip.split(".")
        assert len(parts) == 4 and all(0 <= int(p) <= 255 for p in parts)

    def test_acquire_os_assigned_port(self):
        port, sock = acquire_free_port()
        assert port > 0 and sock is None

    def test_held_port_stays_bound(self):
        port, sock = acquire_free_port(hold=True)
        try:
            import socket

            s2 = socket.socket()
            with pytest.raises(OSError):
                s2.bind(("0.0.0.0", port))
            s2.close()
        finally:
            sock.close()

    def test_range_scan(self):
        port, _ = acquire_free_port(40000, 40100)
        assert 40000 <= port <= 40100


class TestLogger:
    def test_console_only(self):
        log = create_logger("t1", log_file="off")
        assert log.name == "bioengine.t1"
        assert len(log.handlers) == 1

    def test_file_logging_and_tail(self, tmp_path):
        f = tmp_path / "t2.log"
        log = create_logger("t2", level=logging.DEBUG, log_file=f)
        log.info("hello-world")
        for h in log.handlers:
            h.flush()
        assert "hello-world" in f.read_text()
        assert "hello-world" in read_log_tail("t2")


class TestRequirements:
    def test_normalize_rewrites_operator_keeps_version(self):
        assert normalize_requirement("numpy>=1.26") == "numpy==1.26"
        assert normalize_requirement("pkg~=2.1.0") == "pkg==2.1.0"

    def test_normalize_bare_name_passthrough(self):
        assert normalize_requirement("not-a-real-pkg-xyz") == "not-a-real-pkg-xyz"

    def test_skip_is_exact_name_not_prefix(self):
        reqs = update_requirements(["jaxtyping==0.2.0", "torchmetrics>=1.0"])
        names = [r.split("==")[0] for r in reqs]
        assert "jaxtyping" in names and "torchmetrics" in names

    def test_injection_skips_compute_stack(self):
        reqs = update_requirements(["jax>=0.4", "flax", "somepkg==1.0"])
        names = [r.split("==")[0] for r in reqs]
        assert "jax" not in names and "flax" not in names
        assert "somepkg" in names

    def test_framework_pins_present(self):
        names = [r.split("==")[0] for r in get_pip_requirements()]
        assert "numpy" in names


class TestGeoLocation:
    @pytest.mark.anyio
    async def test_disabled_via_env(self, monkeypatch):
        from bioengine_tpu.utils.geo_location import fetch_geolocation

        monkeypatch.setenv("BIOENGINE_DISABLE_GEOLOCATION", "1")
        geo = await fetch_geolocation()
        assert geo == {
            "region": None, "country_name": None, "country_code": None,
            "latitude": None, "longitude": None, "timezone": None,
        }

    @pytest.mark.anyio
    async def test_fallback_chain(self, monkeypatch):
        """First provider fails -> second provider's answer is used."""
        from bioengine_tpu.utils import geo_location

        async def fail():
            raise ValueError("down")

        async def ok():
            return {
                "region": "Stockholm", "country_name": "Sweden",
                "country_code": "SE", "latitude": 59.3,
                "longitude": 18.1, "timezone": "Europe/Stockholm",
            }

        monkeypatch.setattr(
            geo_location, "PROVIDERS",
            [("down", fail), ("up", ok)],
        )
        geo = await geo_location.fetch_geolocation()
        assert geo["country_code"] == "SE"

    @pytest.mark.anyio
    async def test_all_fail(self, monkeypatch):
        from bioengine_tpu.utils import geo_location

        async def fail():
            raise ValueError("down")

        monkeypatch.setattr(geo_location, "PROVIDERS", [("down", fail)])
        geo = await geo_location.fetch_geolocation()
        assert geo["latitude"] is None

    @pytest.mark.anyio
    async def test_centroid_fallback_when_no_coordinates(self, monkeypatch):
        from bioengine_tpu.utils import geo_location

        async def names_only():
            return {
                "region": "Uppsala", "country_name": "Sweden",
                "country_code": "SE", "latitude": None,
                "longitude": None, "timezone": "Europe/Stockholm",
            }

        async def centroid(country, region=None, logger=None):
            assert country == "Sweden" and region == "Uppsala"
            return {"latitude": 59.9, "longitude": 17.6}

        monkeypatch.setattr(
            geo_location, "PROVIDERS", [("names", names_only)]
        )
        monkeypatch.setattr(
            geo_location, "fetch_centroid_coordinates", centroid
        )
        geo = await geo_location.fetch_geolocation()
        assert geo["latitude"] == 59.9
