"""utils/metrics.py — registry, label children, histograms, collectors,
thread-safety, and Prometheus text rendering."""

from __future__ import annotations

import math
import re
import threading

import pytest

from bioengine_tpu.utils import metrics
from bioengine_tpu.utils.metrics import (
    InstanceSet,
    MetricsRegistry,
    Sample,
)

# one sample line: name{labels} value  (labels optional)
_LABEL_VALUE = r'"(\\.|[^"\\])*"'
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=" + _LABEL_VALUE
    + r"(,[a-zA-Z_][a-zA-Z0-9_]*=" + _LABEL_VALUE + r")*\})?"
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$"
)


class TestFamilies:
    def test_counter_labels_and_values(self):
        reg = MetricsRegistry()
        c = reg.counter("reqs_total", "requests", ("app", "outcome"))
        c.labels("a", "ok").inc()
        c.labels("a", "ok").inc(2)
        c.labels("a", "err").inc()
        assert c.labels("a", "ok").value == 3
        assert c.labels("a", "err").value == 1
        with pytest.raises(ValueError):
            c.labels("a", "ok").inc(-1)
        with pytest.raises(ValueError):
            c.labels("only-one")

    def test_reregistration_returns_same_family(self):
        reg = MetricsRegistry()
        a = reg.counter("x_total", "x", ("l",))
        b = reg.counter("x_total", "x", ("l",))
        assert a is b
        with pytest.raises(ValueError):
            reg.gauge("x_total")  # type change
        with pytest.raises(ValueError):
            reg.counter("x_total", "x", ("other",))  # schema change

    def test_gauge_set_inc_dec(self):
        reg = MetricsRegistry()
        g = reg.gauge("depth")
        g.labels().set(5)
        g.labels().inc()
        g.labels().dec(2)
        assert g.labels().value == 4

    def test_histogram_buckets_and_quantiles(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "l", (), buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        snap = h.labels().snapshot()
        assert snap["count"] == 5
        # cumulative; string keys so the snapshot survives msgpack
        assert snap["buckets"] == {"0.01": 2, "0.1": 3, "1": 4}
        assert snap["p50"] == 0.1
        assert snap["p99"] == math.inf  # overflow bucket
        assert snap["sum"] == pytest.approx(5.56)

    def test_histogram_empty_quantiles_none(self):
        reg = MetricsRegistry()
        h = reg.histogram("empty_seconds", "l")
        snap = h.labels().snapshot()
        assert snap["count"] == 0 and snap["p50"] is None


class TestConcurrency:
    def test_concurrent_counter_and_histogram_mutation(self):
        """Satellite: unlocked += would drop increments exactly under
        load — 8 threads x 5000 ops must account exactly."""
        reg = MetricsRegistry()
        c = reg.counter("hits_total", "h", ("t",))
        h = reg.histogram("obs_seconds", "o", (), buckets=(0.5,))
        n_threads, per_thread = 8, 5000

        def work(i):
            child = c.labels(str(i % 2))
            for k in range(per_thread):
                child.inc()
                h.observe(0.25 if k % 2 else 0.75)

        threads = [
            threading.Thread(target=work, args=(i,)) for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = c.labels("0").value + c.labels("1").value
        assert total == n_threads * per_thread
        snap = h.labels().snapshot()
        assert snap["count"] == n_threads * per_thread
        assert snap["buckets"]["0.5"] == n_threads * per_thread // 2


class TestCollectors:
    def test_collector_samples_in_collect_and_render(self):
        reg = MetricsRegistry()
        reg.register_collector(
            "island",
            lambda: [
                Sample("island_bytes", 42, {"dir": "out"}, kind="counter")
            ],
        )
        snap = reg.collect()
        assert snap["island_bytes"]["series"] == [
            {"labels": {"dir": "out"}, "value": 42}
        ]
        text = reg.render_prometheus()
        assert 'bioengine_island_bytes{dir="out"} 42' in text

    def test_bad_collector_never_breaks_scrape(self):
        reg = MetricsRegistry()
        reg.register_collector("boom", lambda: 1 / 0)
        reg.counter("ok_total").inc()
        assert "ok_total" in reg.collect()

    def test_collector_registration_idempotent(self):
        reg = MetricsRegistry()
        reg.register_collector("a", lambda: [Sample("a_val", 1)])
        reg.register_collector("a", lambda: [Sample("a_val", 2)])
        (series,) = reg.collect()["a_val"]["series"]
        assert series["value"] == 2

    def test_instance_set_drops_dead_instances(self):
        class Stats:
            def __init__(self, n):
                self.n = n

        iset = InstanceSet(
            "test_iset_gc",
            lambda items: [Sample("iset_total", sum(i.n for i in items))],
        )
        a, b = Stats(1), Stats(2)
        iset.add(a)
        iset.add(b)
        assert list(iset._collect())[0].value == 3
        del b
        import gc

        gc.collect()
        assert list(iset._collect())[0].value == 1
        metrics.REGISTRY.unregister_collector("test_iset_gc")


class TestPrometheusRendering:
    def test_every_line_is_valid_exposition_format(self):
        reg = MetricsRegistry()
        c = reg.counter("r_total", "requests served", ("app",))
        c.labels('we"ird\napp').inc()
        h = reg.histogram("l_seconds", "latency", ("dep",), buckets=(0.1, 1))
        h.labels("d1").observe(0.05)
        g = reg.gauge("free")
        g.set(3)
        text = reg.render_prometheus()
        assert text.endswith("\n")
        for line in text.splitlines():
            if line.startswith("#"):
                assert line.startswith("# HELP") or line.startswith("# TYPE")
                continue
            assert _SAMPLE_RE.match(line), f"invalid sample line: {line!r}"

    def test_histogram_rendering_contract(self):
        reg = MetricsRegistry()
        h = reg.histogram("q_seconds", "", ("dep",), buckets=(0.1, 1.0))
        h.labels("d").observe(0.05)
        h.labels("d").observe(2.0)
        text = reg.render_prometheus()
        assert '# TYPE bioengine_q_seconds histogram' in text
        assert 'bioengine_q_seconds_bucket{dep="d",le="0.1"} 1' in text
        assert 'bioengine_q_seconds_bucket{dep="d",le="1"} 1' in text
        assert 'bioengine_q_seconds_bucket{dep="d",le="+Inf"} 2' in text
        assert 'bioengine_q_seconds_count{dep="d"} 2' in text
        # bucket counts are cumulative and monotonic
        counts = [
            float(line.rsplit(" ", 1)[1])
            for line in text.splitlines()
            if line.startswith("bioengine_q_seconds_bucket")
        ]
        assert counts == sorted(counts)


class TestProcessRegistry:
    def test_default_registry_absorbs_stats_islands(self):
        """RpcStats / PipelineStats register themselves at construction
        — one live instance is enough for process totals to appear."""
        from bioengine_tpu.rpc.transport import RpcStats
        from bioengine_tpu.runtime.pipeline import PipelineStats

        st = RpcStats()
        with st.lock:
            st.bytes_out += 123
        ps = PipelineStats(depth=2)
        ps.add(compute_seconds=1.5)
        snap = metrics.collect()
        assert any(
            s["value"] >= 123 for s in snap["rpc_bytes_out"]["series"]
        )
        assert any(
            s["value"] >= 1.5
            for s in snap["pipeline_compute_seconds"]["series"]
        )

    def test_metrics_enabled_kill_switch(self, monkeypatch):
        monkeypatch.setenv("BIOENGINE_METRICS", "0")
        metrics.reset_env_cache()
        assert metrics.metrics_enabled() is False
        monkeypatch.delenv("BIOENGINE_METRICS")
        metrics.reset_env_cache()
        assert metrics.metrics_enabled() is True


class TestProcessSelfMetrics:
    """Satellite (PR 7): rss / open-fd / gc / event-loop-lag samples."""

    def _by_name(self, snap, name):
        return snap.get(name, {}).get("series", [])

    def test_rss_fds_and_gc_samples(self):
        import gc

        metrics.install_process_metrics()
        metrics.install_process_metrics()  # idempotent
        gc.collect()  # guarantee at least one recorded collection
        snap = metrics.collect()
        (rss,) = self._by_name(snap, "process_rss_bytes")
        assert rss["value"] > 10 * 1024 * 1024  # a jax process is >10MB
        (fds,) = self._by_name(snap, "process_open_fds")
        assert fds["value"] > 0
        pauses = self._by_name(snap, "gc_pause_seconds_total")
        assert pauses and pauses[0]["value"] >= 0.0
        colls = self._by_name(snap, "gc_collections_total")
        assert colls, "no gc collections recorded after gc.collect()"
        assert any(s["labels"].get("generation") == "2" for s in colls)
        # rendered form is still valid exposition text
        text = metrics.render_prometheus()
        assert "bioengine_process_rss_bytes" in text

    @pytest.mark.anyio
    async def test_event_loop_lag_ticker_updates_gauge(self):
        import asyncio

        metrics.install_process_metrics()
        task = asyncio.get_running_loop().create_task(
            metrics.monitor_event_loop(interval_s=0.02)
        )
        try:
            await asyncio.sleep(0.1)
        finally:
            task.cancel()
        snap = metrics.collect()
        lag = self._by_name(snap, "event_loop_lag_seconds")
        assert lag, "loop-lag ticker produced no samples"
        assert lag[0]["value"] >= 0.0
        (lag_max,) = self._by_name(snap, "event_loop_lag_max_seconds")
        assert lag_max["value"] >= lag[0]["value"] - 1e-9

    @pytest.mark.anyio
    async def test_second_ticker_is_a_noop(self):
        import asyncio

        loop = asyncio.get_running_loop()
        t1 = loop.create_task(metrics.monitor_event_loop(interval_s=0.02))
        await asyncio.sleep(0.05)
        # the singleton guard returns immediately for a second sampler
        await asyncio.wait_for(
            metrics.monitor_event_loop(interval_s=0.02), timeout=1.0
        )
        t1.cancel()


class TestCardinalityGuard:
    """A hostile/buggy caller cannot grow a labeled family without
    bound: children cap at BIOENGINE_METRICS_MAX_LABELS, overflow folds
    into one __overflow__ child, and the drops are counted."""

    def test_bounded_children_under_10k_distinct_labels(self, monkeypatch):
        monkeypatch.setenv("BIOENGINE_METRICS_MAX_LABELS", "50")
        metrics.reset_env_cache()
        try:
            reg = MetricsRegistry()
            fam = reg.counter("rpc_calls_total", "", ("method",))
            dropped_before = metrics.DROPPED_LABELS.labels(
                "rpc_calls_total"
            ).value
            for i in range(10_000):
                fam.labels(f"method-{i}").inc()
            # memory bound: cap + the one overflow child
            assert len(fam.items()) <= 51
            overflow = fam.labels(metrics.OVERFLOW_LABEL)
            assert overflow.value == 10_000 - 50
            dropped = (
                metrics.DROPPED_LABELS.labels("rpc_calls_total").value
                - dropped_before
            )
            assert dropped == 10_000 - 50
            # existing children keep working normally at the cap
            fam.labels("method-0").inc()
            assert fam.labels("method-0").value == 2
            # the overflow child renders/collects like any other
            snap = reg.collect()
            labels = [
                s["labels"]["method"]
                for s in snap["rpc_calls_total"]["series"]
            ]
            assert metrics.OVERFLOW_LABEL in labels
        finally:
            metrics.reset_env_cache()

    def test_unlabeled_families_are_never_capped(self, monkeypatch):
        monkeypatch.setenv("BIOENGINE_METRICS_MAX_LABELS", "1")
        metrics.reset_env_cache()
        try:
            reg = MetricsRegistry()
            g = reg.gauge("uptime_seconds", "")
            g.set(5.0)  # the single unlabeled child must not overflow
            assert g.labels().value == 5.0
        finally:
            metrics.reset_env_cache()

    def test_warns_once_per_family(self, monkeypatch, caplog):
        import logging

        monkeypatch.setenv("BIOENGINE_METRICS_MAX_LABELS", "2")
        metrics.reset_env_cache()
        try:
            reg = MetricsRegistry()
            fam = reg.counter("warn_once_total", "", ("k",))
            with caplog.at_level(logging.WARNING, logger="bioengine.metrics"):
                for i in range(20):
                    fam.labels(str(i)).inc()
            warnings = [
                r
                for r in caplog.records
                if "label-cardinality cap" in r.message
                and "warn_once_total" in r.message
            ]
            assert len(warnings) == 1
        finally:
            metrics.reset_env_cache()
