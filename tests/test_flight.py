"""Flight recorder, incident bundles, chip-seconds accounting, and
on-demand profiling.

The integration layer rides the PR-4 in-process multi-host chaos
harness (real websockets, one event loop): kill a host mid-traffic,
then ``debug_bundle`` must hand back ONE time-ordered artifact holding
the breaker-trip and re-placement evidence from both hosts, the failed
request's trace tree, and a metrics snapshot — and a normal request's
trace root must carry a non-zero ``chip_seconds`` that agrees with the
engine span's wall seconds x mesh width.
"""

import asyncio
import json
import time
from pathlib import Path

import pytest

from bioengine_tpu.apps.builder import AppBuilder
from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.serving import (
    DeploymentSpec,
    ReplicaState,
    RequestOptions,
    ServeController,
)
from bioengine_tpu.serving.replica import CHIP_SECONDS
from bioengine_tpu.testing import faults
from bioengine_tpu.utils import flight, tracing
from bioengine_tpu.worker_host import WorkerHost

pytestmark = [pytest.mark.integration, pytest.mark.anyio]


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(autouse=True)
def _clean_flight():
    flight.clear()
    flight.reset_env_cache()
    yield
    flight.clear()
    flight.reset_env_cache()


@pytest.fixture(autouse=True)
def _sample_everything(monkeypatch):
    monkeypatch.setenv("BIOENGINE_TRACE_SAMPLE", "1.0")
    tracing.reset_env_cache()
    tracing.clear_spans()
    yield
    tracing.reset_env_cache()


# ---------------------------------------------------------------------------
# recorder unit behavior
# ---------------------------------------------------------------------------


class TestRecorder:
    def test_ring_stays_bounded(self):
        cap = flight._events.maxlen
        for i in range(cap + 300):
            flight.record("test.event", i=i)
        events = flight.get_events(limit=None)
        assert len(events) == cap
        # oldest events rolled off, newest survived
        assert events[-1]["attrs"]["i"] == cap + 299
        assert events[0]["attrs"]["i"] == 300

    def test_seq_is_monotonic_and_recorder_stamped(self):
        a = flight.record("test.a")
        b = flight.record("test.b")
        assert b["seq"] == a["seq"] + 1
        assert a["recorder"] == flight.recorder_id()

    def test_disabled_via_env(self, monkeypatch):
        monkeypatch.setenv("BIOENGINE_FLIGHT", "0")
        flight.reset_env_cache()
        assert flight.record("test.event") is None
        assert flight.dump("nope") is None
        assert flight.get_events() == []

    def test_dump_snapshots_and_rate_limits(self, monkeypatch):
        monkeypatch.setenv("BIOENGINE_FLIGHT_DUMP_INTERVAL_S", "3600")
        flight.record("test.before", k=1)
        snap = flight.dump("unit_reason", extra="x")
        assert snap is not None
        assert snap["reason"] == "unit_reason"
        assert any(e["type"] == "test.before" for e in snap["events"])
        # same reason inside the interval: suppressed
        assert flight.dump("unit_reason") is None
        # a different reason is its own budget
        assert flight.dump("other_reason") is not None
        reasons = [d["reason"] for d in flight.get_dumps()]
        assert reasons == ["unit_reason", "other_reason"]
        # dump metadata (not full events) rides get_record
        record = flight.get_record()
        assert [d["reason"] for d in record["dumps"]] == reasons

    def test_dump_persists_to_dir(self, monkeypatch, tmp_path):
        monkeypatch.setenv("BIOENGINE_FLIGHT_DIR", str(tmp_path / "dumps"))
        flight.record("test.evidence")
        flight.dump("disk_reason")
        files = list(
            (tmp_path / "dumps").glob(
                f"flight-*disk_reason-{flight.recorder_id()}.json"
            )
        )
        assert len(files) == 1
        data = json.loads(files[0].read_text())
        assert data["reason"] == "disk_reason"
        assert any(e["type"] == "test.evidence" for e in data["events"])

    def test_get_record_limit_and_since(self):
        for i in range(10):
            flight.record("test.page", i=i)
        events = flight.get_events(limit=None)
        cut = events[6]["ts"]
        rec = flight.get_record(limit=3)
        assert [e["attrs"]["i"] for e in rec["events"]] == [7, 8, 9]
        rec = flight.get_record(limit=None, since=cut)
        assert [e["attrs"]["i"] for e in rec["events"]] == [6, 7, 8, 9]

    def test_merge_dedupes_and_time_orders(self):
        def evt(recorder, seq, ts):
            return {"recorder": recorder, "seq": seq, "ts": ts, "type": "t"}

        rec_a = {"events": [evt("aaa", 1, 10.0), evt("aaa", 2, 30.0)]}
        rec_b = {"events": [evt("bbb", 1, 20.0), evt("aaa", 2, 30.0)]}
        merged = flight.merge_records([rec_a, rec_b, rec_a])
        assert [(e["recorder"], e["seq"]) for e in merged] == [
            ("aaa", 1),
            ("bbb", 1),
            ("aaa", 2),
        ]


# ---------------------------------------------------------------------------
# chip-seconds accounting (local serving path, no RPC)
# ---------------------------------------------------------------------------


def _no_local_chips() -> ClusterState:
    return ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu"))


def _engine_app_factory():
    import numpy as np

    from bioengine_tpu.runtime.engine import EngineConfig, InferenceEngine

    class EngineApp:
        async def async_init(self):
            # tiny tiles force the overlapped tiled pipeline on 40x40
            config = EngineConfig(
                max_tile=16, tile=8, tile_overlap=2, pipeline_depth=2
            )
            self.engine = InferenceEngine(
                model_id="flight-toy",
                apply_fn=lambda params, x: x * params,
                params=np.float32(3.0),
                config=config,
            )

        async def infer(self, size: int = 40):
            x = np.ones((1, size, size, 1), np.float32)
            y = await self.engine.predict_async(x)
            return float(np.asarray(y).sum())

        async def close(self):
            self.engine.close()

    return EngineApp


def _chip_counter_value(app_id: str) -> float:
    return sum(
        child.value
        for key, child in CHIP_SECONDS.items()
        if key[0] == app_id
    )


class TestChipSeconds:
    async def test_root_span_carries_chip_seconds_that_agree(self):
        controller = ServeController(_no_local_chips(), health_check_period=3600)
        try:
            await controller.deploy(
                "cost-app",
                [
                    DeploymentSpec(
                        name="entry", instance_factory=_engine_app_factory()
                    )
                ],
            )
            handle = controller.get_handle("cost-app")
            await handle.call("infer")  # warm: compile outside accounting asserts
            tracing.clear_spans()
            before = _chip_counter_value("cost-app")
            assert await handle.call("infer") == pytest.approx(
                3.0 * 40 * 40, rel=1e-3
            )

            (root,) = tracing.get_spans(name="request")
            cs_root = root["attrs"].get("chip_seconds")
            assert cs_root is not None and cs_root > 0
            engine_spans = tracing.get_spans(
                name="engine.predict", trace_id=root["trace_id"]
            )
            assert engine_spans
            # root chip_seconds == sum of engine spans' chip_seconds
            assert cs_root == pytest.approx(
                sum(s["attrs"]["chip_seconds"] for s in engine_spans),
                abs=1e-5,
            )
            # each engine span: chip_seconds ~= wall duration x width
            for s in engine_spans:
                assert s["attrs"]["devices"] == 1
                assert s["attrs"]["chip_seconds"] == pytest.approx(
                    s["duration_s"] * s["attrs"]["devices"], rel=0.25
                )

            # the always-on counter accumulated the same cost
            counted = _chip_counter_value("cost-app") - before
            assert counted == pytest.approx(cs_root, rel=0.25)

            # surfaces: per-app rollup + per-replica describe
            status = controller.get_app_status("cost-app")
            cost = status["cost"]
            assert cost["chip_seconds_total"] > 0
            assert "entry" in cost["by_deployment"]
            assert cost["by_deployment"]["entry"]["by_method"]["infer"] > 0
            (replica,) = controller.apps["cost-app"].replicas["entry"]
            assert replica.describe()["chip_seconds_total"] == pytest.approx(
                _chip_counter_value("cost-app"), abs=1e-6
            )
        finally:
            await controller.stop()

    async def test_unsampled_requests_still_account(self, monkeypatch):
        monkeypatch.setenv("BIOENGINE_TRACE_SAMPLE", "0.0")
        tracing.reset_env_cache()
        controller = ServeController(_no_local_chips(), health_check_period=3600)
        try:
            await controller.deploy(
                "cost-unsampled",
                [
                    DeploymentSpec(
                        name="entry", instance_factory=_engine_app_factory()
                    )
                ],
            )
            handle = controller.get_handle("cost-unsampled")
            await handle.call("infer")
            tracing.clear_spans()
            before = _chip_counter_value("cost-unsampled")
            await handle.call("infer")
            # no spans minted...
            assert tracing.get_spans(include_open=True) == []
            # ...but the cost was accounted exactly the same
            assert _chip_counter_value("cost-unsampled") - before > 0
        finally:
            await controller.stop()


# ---------------------------------------------------------------------------
# incident bundle: kill a host mid-traffic (PR-4 harness)
# ---------------------------------------------------------------------------

FLIGHT_MANIFEST = """\
name: Flight App
id: flight-app
id_emoji: "\U0001F6A8"
description: engine + idempotent arithmetic for incident tests
type: tpu-serve
version: 1.0.0
deployments:
  - flight_dep:FlightDep
authorized_users: ["*"]
deployment_config:
  flight_dep:
    num_replicas: 2
    min_replicas: 2
    max_replicas: 2
    chips: 2
    autoscale: false
"""

FLIGHT_SOURCE = '''\
import numpy as np

from bioengine_tpu.rpc import schema_method
from bioengine_tpu.runtime.engine import EngineConfig, InferenceEngine


class FlightDep:
    async def async_init(self):
        config = EngineConfig(
            max_tile=16, tile=8, tile_overlap=2, pipeline_depth=2
        )
        self.engine = InferenceEngine(
            model_id="flight-toy",
            apply_fn=lambda params, x: x * params,
            params=np.float32(3.0),
            config=config,
        )

    @schema_method
    async def infer(self, size: int = 40, context=None):
        """Engine prediction through the tiled pipeline."""
        x = np.ones((1, size, size, 1), np.float32)
        y = await self.engine.predict_async(x)
        return {"sum": float(np.asarray(y).sum())}

    @schema_method
    async def add(self, a: int, b: int, context=None):
        """Idempotent arithmetic for chaos traffic."""
        return {"sum": a + b}

    async def close(self):
        self.engine.close()
'''


def _write_flight_app(tmp_path: Path) -> Path:
    app_dir = tmp_path / "flight-src"
    app_dir.mkdir(exist_ok=True)
    (app_dir / "manifest.yaml").write_text(FLIGHT_MANIFEST)
    (app_dir / "flight_dep.py").write_text(FLIGHT_SOURCE)
    return app_dir


@pytest.fixture()
async def flight_plane(tmp_path):
    server = RpcServer(host="127.0.0.1", admin_users=["admin"])
    await server.start()
    token = server.issue_token("admin", is_admin=True)
    # breaker_threshold=2: the dead replica trips deterministically
    # within a handful of failed-over calls
    controller = ServeController(
        _no_local_chips(), health_check_period=3600, breaker_threshold=2
    )
    controller.attach_rpc(server, admin_users=["admin"])
    hosts = []

    async def spawn_host(host_id: str) -> WorkerHost:
        host = WorkerHost(
            server_url=server.url,
            token=token,
            host_id=host_id,
            workspace_dir=tmp_path / f"ws-{host_id}",
        )
        await host.start()
        hosts.append(host)
        return host

    try:
        yield server, controller, spawn_host, tmp_path
    finally:
        for host in hosts:
            try:
                await host.stop()
            except Exception:  # noqa: BLE001 — killed hosts are already down
                pass
        await controller.stop()
        await server.stop()


async def _kill_host(host: WorkerHost) -> None:
    host.rejoin = False
    host.connection.auto_reconnect = False
    host.connection._closing = True
    await host.connection._abort_connection()


async def _deploy_flight_app(controller, tmp_path):
    builder = AppBuilder(workdir_root=tmp_path / "apps")
    built = builder.build(
        app_id="flight-app", local_path=_write_flight_app(tmp_path)
    )
    await controller.deploy("flight-app", built.specs)
    return controller.apps["flight-app"].replicas["flight_dep"]


class TestIncidentBundle:
    async def test_kill_host_mid_traffic_bundle_has_the_evidence(
        self, flight_plane
    ):
        """Acceptance: kill one of two hosts under idempotent traffic;
        ``debug_bundle`` returns one time-ordered artifact containing
        the breaker-trip and re-placement events (attributed to both
        hosts), the failed request's trace tree, and a metrics
        snapshot. A normal request's trace root carries non-zero
        chip_seconds agreeing with engine wall x mesh width."""
        server, controller, spawn_host, tmp_path = flight_plane
        h1 = await spawn_host("h1")
        h2 = await spawn_host("h2")
        replicas = await _deploy_flight_app(controller, tmp_path)
        assert sorted(r.host_id for r in replicas) == ["h1", "h2"]
        handle = controller.get_handle("flight-app")

        # -- the normal request: cost lands on the trace root ----------
        await handle.call("infer")  # warm both compile paths
        tracing.clear_spans()
        result = await handle.call("infer")
        assert result["sum"] == pytest.approx(3.0 * 40 * 40, rel=1e-3)
        (root,) = tracing.get_spans(name="request")
        cs_root = root["attrs"].get("chip_seconds")
        assert cs_root is not None and cs_root > 0
        engine_spans = tracing.get_spans(
            name="engine.predict", trace_id=root["trace_id"]
        )
        assert engine_spans
        assert cs_root == pytest.approx(
            sum(
                s["duration_s"] * s["attrs"]["devices"]
                for s in engine_spans
            ),
            rel=0.25,
        )

        # -- kill h1 mid-traffic ---------------------------------------
        opts = RequestOptions(idempotent=True, deadline_s=20, max_attempts=8)
        failures: list[Exception] = []
        kill_at = asyncio.Event()

        async def traffic(worker_id: int):
            for i in range(15):
                try:
                    r = await handle.call("add", worker_id, i, options=opts)
                    assert r["sum"] == worker_id + i
                except Exception as e:  # noqa: BLE001 — counted, not raised
                    failures.append(e)
                if i == 4 and worker_id == 0:
                    kill_at.set()
                await asyncio.sleep(0.005)

        tasks = [asyncio.create_task(traffic(w)) for w in range(4)]
        await asyncio.wait_for(kill_at.wait(), 10)
        await _kill_host(h1)

        # deterministic breaker evidence: the dead host's replica stays
        # routable until the breaker notices; sequential idempotent
        # calls round-robin onto it, fail over, and feed the breaker
        # past threshold (=2) before the health loop ever runs
        for i in range(20):
            r = await handle.call("add", 100, i, options=opts)
            assert r["sum"] == 100 + i
            if flight.get_events(types=["breaker.trip"]):
                break
        assert flight.get_events(types=["breaker.trip"]), (
            "breaker did not trip on the dead host's replica"
        )

        recovered = False
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            await controller.health_tick()
            reps = controller.apps["flight-app"].replicas["flight_dep"]
            routable = [
                r
                for r in reps
                if r.state in (ReplicaState.HEALTHY, ReplicaState.TESTING)
            ]
            if len(routable) == 2 and all(
                r.host_id == "h2" for r in routable
            ):
                recovered = True
                break
            await asyncio.sleep(0.1)
        await asyncio.gather(*tasks)
        assert failures == []
        assert recovered, "replica was not re-placed on the survivor"

        # -- the artifact ----------------------------------------------
        bundle = await controller.debug_bundle()
        json.dumps(bundle, default=str)  # one JSON artifact

        events = bundle["events"]
        assert events == sorted(
            events, key=lambda e: (e["ts"], e["recorder"], e["seq"])
        ), "bundle timeline is not time-ordered"
        by_type: dict[str, list] = {}
        for e in events:
            by_type.setdefault(e["type"], []).append(e)

        # breaker trip on the dead host's replica
        trips = by_type.get("breaker.trip", [])
        assert trips and any(t["attrs"]["host"] == "h1" for t in trips)
        # host death + re-placement on the survivor
        assert any(
            e["attrs"]["host"] == "h1" for e in by_type.get("host.dead", [])
        )
        placements = by_type.get("replica.place", [])
        assert any(p["attrs"]["host"] == "h2" for p in placements)
        # both hosts appear in the one merged timeline
        hosts_seen = {
            e["attrs"].get("host")
            for e in events
            if e["attrs"].get("host") is not None
        }
        assert {"h1", "h2"} <= hosts_seen
        # replica state transitions recorded (UNHEALTHY on trip)
        assert any(
            e["attrs"].get("to") == "UNHEALTHY"
            for e in by_type.get("replica.state", [])
        )

        # the failed request's trace tree: an errored attempt span with
        # a successful sibling under the same trace_id
        errored = [
            s
            for s in bundle["traces"]
            if s["name"] == "attempt" and "error" in s
        ]
        assert errored, "no failed attempt span in the bundle"
        tree = tracing.build_trace_tree(errored[0]["trace_id"])
        attempts = [
            n
            for n in _flatten(tree["tree"])
            if n["name"] == "attempt"
        ]
        assert len(attempts) >= 2
        assert any("error" not in a for a in attempts)

        # metrics snapshot + mesh/lease state rode along
        assert "request_e2e_seconds" in bundle["metrics"]
        assert "chip_seconds_total" in bundle["metrics"]
        assert bundle["cluster"]["hosts"]["h2"]["alive"] is True
        assert bundle["apps"]["flight-app"]["cost"]["chip_seconds_total"] > 0
        # the dead host is reported unreachable, the survivor gathered
        assert bundle["hosts"]["h1"]["reachable"] is False
        assert bundle["hosts"]["h2"]["reachable"] is True
        assert "metrics" in bundle["hosts"]["h2"]
        # fault-free run: the injected-fault channel stays quiet, but
        # the dumps that the breaker trip triggered are recorded
        assert any(d["reason"] == "breaker_trip" for d in bundle["dumps"])

    async def test_flight_record_verb_and_profiling_round_trip(
        self, flight_plane, tmp_path
    ):
        """The worker-host verbs the bundle/controller use:
        get_flight_record returns this-host events; start/stop
        profiling wraps jax.profiler and writes a trace; memory_profile
        returns device stats."""
        server, controller, spawn_host, tmp_path2 = flight_plane
        host = await spawn_host("h1")
        rec = await controller._call_host(
            host.service_id, "get_flight_record", limit=50
        )
        assert rec["host_id"] == "h1"
        assert rec["recorder"] == flight.recorder_id()  # in-process harness

        trace_dir = tmp_path / "host-trace"
        started = await controller._call_host(
            host.service_id, "start_profiling", trace_dir=str(trace_dir)
        )
        assert started["profiling"] is True
        with pytest.raises(Exception, match="already active"):
            await controller._call_host(host.service_id, "start_profiling")
        import jax.numpy as jnp

        _ = float(jnp.ones((32, 32)).sum())  # give the trace content
        stopped = await controller._call_host(
            host.service_id, "stop_profiling"
        )
        assert stopped["profiling"] is False
        assert stopped["trace_dir"] == str(trace_dir)
        assert any(trace_dir.rglob("*")), "profiler trace dir is empty"

        mem = await controller._call_host(host.service_id, "memory_profile")
        assert mem["host_id"] == "h1"
        assert mem["pprof_b64"]
        assert mem["devices"]


def _flatten(tree_nodes):
    out = []
    stack = list(tree_nodes)
    while stack:
        node = stack.pop()
        out.append(node)
        stack.extend(node["children"])
    return out
