"""The bench artifact contract, suite-guarded.

Round 4 shipped no perf numbers because the bench could be killed
before printing (VERDICT r4 weak #1). These tests pin the guarantees
the rewrite exists to provide, by running ``bench.py`` as a real
subprocess the way the driver does:

- a normal run prints exactly ONE final JSON line and exits 0;
- a worker wedged mid-stage (simulated via a tiny BENCH_STALL against
  a compile-heavy stage) is killed, diagnosed, and the artifact still
  prints with rc 0 — never rc 124;
- the SIGTERM path (the driver's own axe) emits the artifact before
  dying.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

pytestmark = pytest.mark.integration

BENCH = Path(__file__).resolve().parent.parent / "bench.py"


def _run(env_extra: dict, timeout: float = 240.0):
    env = dict(os.environ, BENCH_PLATFORM="cpu", **env_extra)
    proc = subprocess.run(
        [sys.executable, str(BENCH)],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=str(BENCH.parent),
    )
    lines = [
        ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")
    ]
    return proc, lines


def test_normal_run_prints_one_parsed_line():
    proc, lines = _run(
        {
            "BENCH_CONFIGS": "search,pipeline_overlap",
            "BENCH_DEADLINE": "180",
        }
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert len(lines) == 1, proc.stdout
    d = json.loads(lines[0])
    assert d["metric"] == "dinov2_vitb14_embed_images_per_sec_per_chip"
    assert d["extra"]["probe"]["ok"]
    assert d["extra"]["search_latency"]["ok"]
    # the overlapped-pipeline stage must run and emit its schema on CPU
    # (numbers are informational there; the schema is the contract)
    po = d["extra"]["pipeline_overlap"]
    assert po["ok"], po
    for key in (
        "serial_s",
        "pipelined_s",
        "speedup",
        "serial_tiles_per_sec",
        "pipelined_tiles_per_sec",
        "overlap_efficiency",
        "pipeline_stats",
        "depth",
    ):
        assert key in po, key
    assert po["pipeline_stats"]["max_in_flight"] <= po["depth"]
    assert po["pipeline_stats"]["chunks"] > 0


def test_sharded_serving_stage_schema():
    """Pin the sharded_serving artifact schema: 1-chip vs dp-K engine
    throughput on the same bucketed batch workload, the dp scaling
    efficiency, and the parity check. On CPU the stage spawns its own
    --sharded-worker subprocess with 4 forced virtual host devices (the
    flag stays out of the worker every other stage is measured in), so
    the dp leg always runs; throughput numbers there are core-bound and
    informational — the schema plus parity are the contract (the TPU
    round supplies the scaling number)."""
    proc, lines = _run(
        {
            "BENCH_CONFIGS": "sharded_serving",
            "BENCH_DEADLINE": "170",
        },
        timeout=200.0,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    st = json.loads(lines[-1])["extra"]["sharded_serving"]
    assert st["ok"], st
    for key in (
        "batch",
        "image_hw",
        "n_devices",
        "images_per_sec_1chip",
        "images_per_sec_dp",
        "speedup",
        "dp_scaling_efficiency",
        "mesh",
        "parity_max_abs_err",
        "parity_ok",
    ):
        assert key in st, key
    assert st["n_devices"] == 4
    assert st["mesh"] == {"dp": 4}
    assert st["images_per_sec_1chip"] > 0
    assert st["images_per_sec_dp"] > 0
    # the two engines ran the same inputs: outputs must agree
    assert st["parity_ok"], st["parity_max_abs_err"]


def test_multihost_mesh_stage_schema():
    """Pin the multihost_mesh artifact schema: the SAME 2-stage
    pipeline-mesh deployment spec measured on a 1-host mesh vs
    spanning 2 simulated hosts (each leg its own --multihost-worker
    subprocess under a forced 4-device CPU layout). CPU throughput is
    core-bound and informational; the contract is the schema, output
    parity on both legs, and the RpcStats pin that cross-shard
    activation payloads rode the zero-copy OOB path."""
    proc, lines = _run(
        {
            "BENCH_CONFIGS": "multihost_mesh",
            "BENCH_DEADLINE": "170",
        },
        timeout=200.0,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    st = json.loads(lines[-1])["extra"]["multihost_mesh"]
    assert st["ok"], st
    for key in (
        "batch",
        "image_hw",
        "stages",
        "images_per_sec_1host",
        "images_per_sec_2host",
        "scaling_efficiency",
        "cross_host_overhead_ms_per_request",
        "transfer_bytes_per_request",
        "transfer_seconds_per_request",
        "cross_host_1host",
        "cross_host_2host",
        "parity_ok",
        "parity_max_abs_err",
        "oob_payloads_out",
        "legacy_msgs_out",
    ):
        assert key in st, key
    assert st["stages"] == 2
    assert st["images_per_sec_1host"] > 0
    assert st["images_per_sec_2host"] > 0
    assert st["scaling_efficiency"] > 0
    # one leg colocates (1 host joined), the other spans hosts —
    # the same spec, two topologies
    assert st["cross_host_1host"] is False
    assert st["cross_host_2host"] is True
    # both legs ran the same inputs: outputs must agree with the model
    assert st["parity_ok"], st["parity_max_abs_err"]
    # cross-shard activations moved per request…
    assert st["transfer_bytes_per_request"] > 0
    # …and demonstrably as extracted OOB payloads, never legacy packs
    assert st["oob_payloads_out"] > 0
    assert st["legacy_msgs_out"] == 0


def test_cold_start_stage_schema():
    """Pin the cold_start artifact schema: replica TTFR on the
    model-runner path across three legs — cold (fresh process, empty
    compile cache), warm-cache (fresh process against the cache the
    cold leg populated — the shared-tier experience), warm-pool
    (standby promotion) — each with its compile/load/first-request
    breakdown. The acceptance gate is the warm-pool path: promotion
    must beat the cold path by ≥10x even on a loaded CI core (it's a
    list move vs an XLA compile)."""
    proc, lines = _run(
        {
            "BENCH_CONFIGS": "cold_start",
            "BENCH_DEADLINE": "170",
        },
        timeout=200.0,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    st = json.loads(lines[-1])["extra"]["cold_start"]
    assert st["ok"], st
    for key in (
        "cold",
        "warm_cache",
        "warm_pool",
        "speedup_warm_cache",
        "speedup_warm_pool",
        "warm_cache_hit_observed",
    ):
        assert key in st, key
    for leg in ("cold", "warm_cache"):
        for key in (
            "ttfr_s",
            "build_s",
            "first_request_s",
            "weights_s",
            "compile_s",
            "streamed",
            "persistent_cache_hits",
            "real_compiles",
        ):
            assert key in st[leg], (leg, key)
    assert st["cold"]["streamed"] is True        # manifest package streams
    assert st["cold"]["real_compiles"] >= 1       # the cold leg compiled
    assert st["warm_cache_hit_observed"] is True  # the warm leg did not
    wp = st["warm_pool"]
    assert wp["promoted_from_warm_pool"] is True
    assert wp["promotions"] == 1
    assert wp["ttfr_s"] > 0
    # the acceptance ratio: warm-pool TTFR ≥10x faster than cold
    assert st["speedup_warm_pool"] >= 10.0, st["speedup_warm_pool"]


def test_rpc_transport_stage_schema():
    """Pin the rpc_transport artifact schema: three paths (legacy /
    zero-copy oob / shm), per-size e2e + codec round-trip numbers, the
    headline speedups, and the >frame-limit chunked round trip. Sizes
    are shrunk via env so the test exercises the full stage shape —
    including chunking — in seconds."""
    proc, lines = _run(
        {
            "BENCH_CONFIGS": "rpc_transport",
            "BENCH_DEADLINE": "170",
            "BENCH_RPC_SIZES_MB": "1,8",
            "BENCH_RPC_BIG_MB": "24",
            "BIOENGINE_RPC_FRAME_LIMIT_MB": "8",
        },
        timeout=200.0,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    st = json.loads(lines[-1])["extra"]["rpc_transport"]
    assert st["ok"], st
    for key in (
        "sizes_mb",
        "paths",
        "speedup_oob_vs_legacy",
        "codec_roundtrip_speedup_oob_vs_legacy",
        "speedup_shm_vs_legacy",
        "big_roundtrip",
    ):
        assert key in st, key
    for path in ("legacy", "oob", "shm"):
        per_size = st["paths"][path]["mb8"]
        for key in ("p50_ms", "p95_ms", "mb_per_sec", "codec_ms_per_roundtrip"):
            assert key in per_size, (path, key)
    # the leg above the frame limit must have round-tripped chunked
    assert st["big_roundtrip"]["ok"]
    assert st["big_roundtrip"]["chunked"]


def test_observability_overhead_stage_schema():
    """Pin the observability_overhead artifact schema: five interleaved
    legs (disabled / unsampled / flight / telem / sampled) over the
    same live serve path, per-leg p50, the relative + absolute
    overheads, the flight-recorder-vs-unsampled delta, and the
    push-telemetry-vs-flight delta. The <2% (and flight/telem <1%)
    acceptance numbers come from the full-size driver run — a loaded CI
    core would flake a hard threshold here, so the schema and sanity
    ordering are the contract."""
    proc, lines = _run(
        {
            "BENCH_CONFIGS": "observability_overhead",
            "BENCH_DEADLINE": "170",
            "BENCH_OBS_ROUNDS": "2",
            "BENCH_OBS_REQUESTS": "25",
        },
        timeout=200.0,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    st = json.loads(lines[-1])["extra"]["observability_overhead"]
    assert st["ok"], st
    for key in (
        "requests_per_leg",
        "legs",
        "overhead_unsampled_pct",
        "overhead_unsampled_abs_us",
        "overhead_flight_pct",
        "overhead_flight_abs_us",
        "overhead_flight_vs_unsampled_pct",
        "overhead_telem_pct",
        "overhead_telem_abs_us",
        "overhead_telem_vs_flight_pct",
        "telem_interval_s",
        "overhead_sampled_pct",
        "overhead_sampled_abs_us",
    ):
        assert key in st, key
    assert st["requests_per_leg"] == 50
    for leg in ("disabled", "unsampled", "flight", "telem", "sampled"):
        assert st["legs"][leg]["p50_us"] > 0, leg
    # full span recording can't be cheaper than the unsampled path's
    # contextvar reads (sanity on the leg wiring, not a perf threshold)
    assert (
        st["overhead_sampled_abs_us"] >= st["overhead_unsampled_abs_us"] - 50
    )


def test_request_overhead_stage_schema():
    """Pin the request_overhead artifact schema: three interleaved legs
    (baseline = pre-fast1 stack on TCP, fast_tcp = BEFS + inline
    dispatch on the identical wire, fast = same over the unix socket),
    per-leg uncontended/concurrent throughput, the live-stats codec
    bucket, the per-request decomposition, and the paired speedups.
    The >=2x uncontended acceptance number comes from the full-size
    driver run — a loaded CI core would flake a hard threshold here,
    so the schema and fast-frame wiring are the contract."""
    proc, lines = _run(
        {
            "BENCH_CONFIGS": "request_overhead",
            "BENCH_DEADLINE": "170",
            "BENCH_REQ_ROUNDS": "3",
            "BENCH_REQ_N": "40",
            "BENCH_REQ_CALLERS": "4",
            "BENCH_REQ_PER_CALLER": "5",
        },
        timeout=200.0,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    st = json.loads(lines[-1])["extra"]["request_overhead"]
    assert st["ok"], st
    for key in (
        "legs",
        "decomposition_us",
        "uncontended_speedup",
        "concurrent_speedup",
        "threshold_bytes",
    ):
        assert key in st, key
    for leg in ("baseline", "fast_tcp", "fast"):
        lg = st["legs"][leg]
        for key in (
            "transport",
            "uncontended",
            "concurrent",
            "codec_us_per_req",
            "fast_frames",
            "small_frames_out",
            "fast_frame_hit_rate",
        ):
            assert key in lg, (leg, key)
        for key in ("req_per_sec", "p50_us", "p95_us", "median_req_per_sec"):
            assert lg["uncontended"][key] > 0, (leg, key)
        assert lg["concurrent"]["req_per_sec"] > 0, leg
    for key in (
        "codec_us",
        "tracing_ctx_us",
        "scheduler_us",
        "scoring_us",
        "asyncio_hop_us",
        "wire_residual_us",
    ):
        assert key in st["decomposition_us"], key
    # the fast-frame wiring is the contract: the baseline leg must
    # have negotiated NO fast frames and the fast legs must have run
    # entirely on them
    assert st["legs"]["baseline"]["fast_frames"] is False
    assert st["legs"]["baseline"]["small_frames_out"] == 0
    for leg in ("fast_tcp", "fast"):
        assert st["legs"][leg]["fast_frames"] is True
        assert st["legs"][leg]["small_frames_out"] > 0, leg
        assert st["legs"][leg]["fast_frame_hit_rate"] == 1.0, leg
    assert st["legs"]["fast"]["transport"] == "uds"
    assert st["legs"]["fast_tcp"]["transport"] == "tcp"


def test_scheduler_goodput_stage_schema():
    """Pin the scheduler_goodput artifact schema: per-request router vs
    global scheduler on the same mixed-priority workload (goodput, per
    class p50/p99, SLO attainment, batch occupancy) plus the
    interleaved uncontended leg (the <2% scheduler-overhead acceptance
    gate reads overhead_scheduler_pct from the full-size driver run — a
    loaded CI core would flake a hard threshold here, so schema and
    sanity ordering are the contract)."""
    proc, lines = _run(
        {
            "BENCH_CONFIGS": "scheduler_goodput",
            "BENCH_DEADLINE": "170",
            "BENCH_SCHED_ROUNDS": "1",
            "BENCH_SCHED_WAVES": "6",
            "BENCH_SCHED_SOLO": "12",
        },
        timeout=200.0,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    st = json.loads(lines[-1])["extra"]["scheduler_goodput"]
    assert st["ok"], st
    for key in (
        "workload",
        "legs",
        "goodput_speedup",
        "occupancy_gain",
        "uncontended",
    ):
        assert key in st, key
    for leg in ("router", "scheduler"):
        d = st["legs"][leg]
        for key in (
            "goodput_rps",
            "interactive_p50_ms",
            "interactive_p99_ms",
            "interactive_slo_met_pct",
            "bulk_p50_ms",
            "bulk_p99_ms",
            "batch_occupancy",
            "failed",
        ):
            assert key in d, (leg, key)
        assert d["goodput_rps"] > 0, leg
        assert d["failed"] == 0, (leg, d)
    # the same workload ran both ways; coalescing must raise occupancy
    # (the mechanism — the goodput consequence is a hardware number)
    assert (
        st["legs"]["scheduler"]["batch_occupancy"]
        >= st["legs"]["router"]["batch_occupancy"]
    ), st["legs"]
    unc = st["uncontended"]
    for key in (
        "router_p50_us",
        "scheduler_p50_us",
        "overhead_scheduler_pct",
        "overhead_scheduler_abs_us",
    ):
        assert key in unc, key
    assert unc["router_p50_us"] > 0 and unc["scheduler_p50_us"] > 0


def test_gray_failure_stage_schema():
    """Pin the gray_failure artifact schema: the slow_replica scenario
    (seeded slow-ramp on one replica, health checks still passing) run
    without and with probation + hedging. The acceptance gates ride the
    stage's own ok flag: the defended leg recovers tail p99 to within
    2x the healthy baseline with zero failed idempotent requests, and
    the undefended leg shows the degradation (proving the scenario
    still exercises what the machinery fixes)."""
    proc, lines = _run(
        {
            "BENCH_CONFIGS": "gray_failure",
            "BENCH_DEADLINE": "280",
        },
        timeout=320.0,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    st = json.loads(lines[-1])["extra"]["gray_failure"]
    assert st["ok"], st
    for key in (
        "scenario",
        "seed",
        "legs",
        "tail_p99_improvement",
        "goodput_delta_pct",
        "p99_recovered",
        "degradation_shown",
    ):
        assert key in st, key
    assert st["scenario"] == "slow_replica"
    for leg in ("undefended", "defended"):
        d = st["legs"][leg]
        for key in (
            "requests",
            "failed",
            "goodput_rps",
            "p50_ms",
            "p99_ms",
            "baseline_p99_ms",
            "tail_p99_ms",
            "probations",
            "hedges",
            "invariants_ok",
        ):
            assert key in d, (leg, key)
        # zero failed IDEMPOTENT requests in BOTH legs: failover alone
        # keeps traffic alive; the defenses fix the tail, not liveness
        assert d["failed"] == 0, (leg, d)
        assert d["goodput_rps"] > 0, leg
    assert st["p99_recovered"] is True
    assert st["degradation_shown"] is True
    # the machinery actually engaged in the defended leg only
    assert st["legs"]["defended"]["probations"] >= 1
    assert st["legs"]["defended"]["hedges"] > 0
    assert st["legs"]["undefended"]["probations"] == 0
    assert st["legs"]["undefended"]["hedges"] == 0
    # the headline: the defended tail sits well under the undefended
    assert st["tail_p99_improvement"] > 1.0, st


def test_router_scaling_stage_schema():
    """Pin the router_scaling artifact schema: the fleet_scale scenario
    run per router count, goodput capacity-bound per router so the
    4-router leg must reach >= 3x the 1-router goodput; the router_loss
    leg (one of three routers SIGKILL'd mid-traffic) must lose zero
    idempotent requests; and the seam probe reports serial per-request
    overhead through a table-synced standalone router vs the in-process
    controller path. Legs pinned to 1,4 to keep the gate fast — the
    default 1,2,4,8 sweep is the bench-artifact run."""
    proc, lines = _run(
        {
            "BENCH_CONFIGS": "router_scaling",
            "BENCH_ROUTER_LEGS": "1,4",
            "BENCH_DEADLINE": "280",
        },
        timeout=320.0,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    st = json.loads(lines[-1])["extra"]["router_scaling"]
    assert st["ok"], st
    for key in (
        "scenario",
        "seed",
        "legs",
        "goodput_scaling_4x_vs_1",
        "router_loss",
        "per_request_overhead_us",
    ):
        assert key in st, key
    assert st["scenario"] == "fleet_scale"
    for name in ("1", "4"):
        leg = st["legs"][name]
        for key in (
            "routers",
            "offered",
            "served",
            "wall_s",
            "goodput_rps",
            "table_staleness_max_s",
            "invariants_ok",
        ):
            assert key in leg, (name, key)
        assert leg["invariants_ok"] is True, leg
        assert leg["goodput_rps"] > 0, leg
        # bounded staleness is measured, not just asserted green
        assert leg["table_staleness_max_s"] is not None, leg
    # the acceptance gate: aggregate goodput scales near-linearly
    assert st["goodput_scaling_4x_vs_1"] >= 3.0, st
    loss = st["router_loss"]
    for key in (
        "requests",
        "failed_idempotent",
        "client_failovers",
        "killed",
        "table_staleness_max_s",
        "invariants_ok",
    ):
        assert key in loss, key
    # zero idempotent loss across the router kill, and the clients
    # actually hopped to a sibling (the kill engaged)
    assert loss["failed_idempotent"] == 0, loss
    assert loss["client_failovers"] > 0, loss
    assert loss["killed"] == ["r1"], loss
    assert loss["invariants_ok"] is True, loss
    probe = st["per_request_overhead_us"]
    for key in ("controller", "router", "router_delta_us_p50"):
        assert key in probe, key
    for leg in ("controller", "router"):
        assert probe[leg]["p50_us"] > 0, probe


def test_token_streaming_stage_schema():
    """Pin the token_streaming artifact schema: the co-batched
    throughput leg must show real step-level batching (mean occupancy
    above 1, far fewer steps than serial token count), the inter-token
    leg reports the first-class latency SLO numbers, and the
    join-mid-batch leg proves no head-of-line blocking — the short
    interactive stream joined a RUNNING batch and finished while the
    long bulk generation was still going."""
    proc, lines = _run(
        {
            "BENCH_CONFIGS": "token_streaming",
            "BENCH_DEADLINE": "160",
        },
        timeout=200.0,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    st = json.loads(lines[-1])["extra"]["token_streaming"]
    assert st["ok"], st
    tp = st["throughput"]
    for key in (
        "streams",
        "new_tokens_each",
        "tokens_per_sec",
        "tokens_per_sec_per_chip",
        "batch_occupancy",
        "steps",
        "wall_s",
    ):
        assert key in tp, key
    assert tp["tokens_per_sec"] > 0
    assert tp["tokens_per_sec_per_chip"] > 0
    # continuous batching engaged: sequences shared steps
    assert tp["batch_occupancy"] > 1.0, tp
    assert tp["steps"] < tp["streams"] * tp["new_tokens_each"], tp
    it = st["inter_token"]
    for key in ("ttft_ms", "inter_token_p50_ms", "inter_token_p99_ms"):
        assert key in it, key
        assert it[key] > 0, it
    assert it["inter_token_p99_ms"] >= it["inter_token_p50_ms"]
    jm = st["join_mid_batch"]
    for key in (
        "joined_mid_batch",
        "mid_batch_ttft_ms",
        "short_wall_ms",
        "long_still_running",
        "long_tokens",
    ):
        assert key in jm, key
    # the no-HOL-blocking proof rides the artifact, not just a test
    assert jm["joined_mid_batch"] == 1, jm
    assert jm["long_still_running"] == 1, jm
    assert jm["mid_batch_ttft_ms"] > 0, jm
    eng = st["engine"]
    assert eng["n_devices"] >= 1
    assert eng["kv_block_size"] >= 1


def _artifact(vit=1000.0, pipelined=2.0, p50_us=100.0) -> dict:
    """A minimal bench artifact in the real schema, tunable per metric."""
    return {
        "metric": "dinov2_vitb14_embed_images_per_sec_per_chip",
        "value": vit,
        "unit": "images/sec",
        "vs_baseline": round(vit / 500.0, 3),
        "extra": {
            "pipeline_overlap": {
                "ok": True,
                "serial_s": 4.0,
                "pipelined_s": pipelined,
                "speedup": round(4.0 / pipelined, 2),
            },
            "observability_overhead": {
                "ok": True,
                "legs": {"disabled": {"p50_us": p50_us}},
                "overhead_flight_vs_unsampled_pct": 0.5,
            },
            "skipped": {"unet3d": "budget"},
            "attempts": 1,
        },
    }


def test_compare_mode_schema_and_exit_codes(tmp_path):
    """Pin the --compare contract: one JSON line with per-stage deltas
    and direction-aware regression flags; exit 0 when the candidate
    holds, non-zero past the tolerance."""
    a = tmp_path / "a.json"
    b_ok = tmp_path / "b_ok.json"
    b_bad = tmp_path / "b_bad.json"
    a.write_text(json.dumps(_artifact()))
    # candidate within tolerance (slightly slower, under 10%)
    b_ok.write_text(json.dumps(_artifact(vit=950.0, pipelined=2.1)))
    # candidate regressed: headline -30%, pipeline 2x slower
    b_bad.write_text(json.dumps(_artifact(vit=700.0, pipelined=4.0)))

    def run_compare(b_path):
        proc = subprocess.run(
            [sys.executable, str(BENCH), "--compare", str(a), str(b_path)],
            capture_output=True,
            text=True,
            timeout=60,
            cwd=str(BENCH.parent),
        )
        lines = [
            ln
            for ln in proc.stdout.strip().splitlines()
            if ln.startswith("{")
        ]
        assert len(lines) == 1, proc.stdout
        return proc.returncode, json.loads(lines[0])

    rc, ok_report = run_compare(b_ok)
    assert rc == 0
    assert ok_report["ok"] is True
    for key in (
        "mode",
        "tolerance_pct",
        "stages_compared",
        "stages_only_a",
        "stages_only_b",
        "regressions",
        "improvements",
        "stages",
    ):
        assert key in ok_report, key
    assert "pipeline_overlap" in ok_report["stages_compared"]
    assert "headline" in ok_report["stages_compared"]
    entry = ok_report["stages"]["pipeline_overlap"]["pipelined_s"]
    assert entry["direction"] == "lower"
    assert entry["regression"] is False

    rc, bad_report = run_compare(b_bad)
    assert rc == 1
    assert bad_report["ok"] is False
    regressed = {r["metric"] for r in bad_report["regressions"]}
    assert "headline.images_per_sec_per_chip" in regressed
    assert "pipeline_overlap.pipelined_s" in regressed
    # direction inference: the slower pipelined_s also halves speedup —
    # a higher-is-better metric moving DOWN is a regression too
    assert "pipeline_overlap.speedup" in regressed


def test_compare_token_streaming_directions(tmp_path):
    """Direction inference on the streaming metrics: tokens_per_sec /
    batch_occupancy are higher-is-better (a drop regresses), the
    inter-token percentiles are lower-is-better (a rise regresses) —
    so a compare gate catches a co-batching break from either side."""

    def art(tps, occ, p99):
        a = _artifact()
        a["extra"]["token_streaming"] = {
            "ok": True,
            "throughput": {
                "tokens_per_sec": tps,
                "batch_occupancy": occ,
            },
            "inter_token": {"inter_token_p99_ms": p99},
        }
        return a

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(art(2000.0, 8.0, 3.0)))
    # throughput/occupancy DOWN, tail latency UP: all three must flag
    b.write_text(json.dumps(art(1200.0, 4.0, 9.0)))
    proc = subprocess.run(
        [sys.executable, str(BENCH), "--compare", str(a), str(b)],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=str(BENCH.parent),
    )
    assert proc.returncode == 1
    report = json.loads(
        [ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")][-1]
    )
    stage = report["stages"]["token_streaming"]
    assert stage["throughput.tokens_per_sec"]["direction"] == "higher"
    assert stage["throughput.batch_occupancy"]["direction"] == "higher"
    assert stage["inter_token.inter_token_p99_ms"]["direction"] == "lower"
    regressed = {r["metric"] for r in report["regressions"]}
    assert {
        "token_streaming.throughput.tokens_per_sec",
        "token_streaming.throughput.batch_occupancy",
        "token_streaming.inter_token.inter_token_p99_ms",
    } <= regressed


def test_compare_usage_error_is_json_not_traceback(tmp_path):
    proc = subprocess.run(
        [sys.executable, str(BENCH), "--compare", "only-one.json"],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=str(BENCH.parent),
    )
    assert proc.returncode == 2
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["ok"] is False and "usage" in d["error"]


def test_stalled_worker_killed_with_diagnostics_never_rc124():
    # the env-gated 'sleep' stage hangs mid-stage DETERMINISTICALLY (no
    # dependence on compile latency or a warm compilation cache), so a
    # tiny BENCH_STALL always triggers the wedge detector
    proc, lines = _run(
        {
            "BENCH_CONFIGS": "sleep",
            "BENCH_SLEEP_S": "90",
            "BENCH_DEADLINE": "120",
            "BENCH_STALL": "6",
            "BENCH_ATTEMPTS": "1",
        }
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(lines[-1])
    assert d["value"] == 0.0
    diags = d["extra"]["diagnostics"]
    assert any("wedged mid-stage" in (x.get("killed") or "") for x in diags)


def test_sigterm_emits_artifact_before_dying():
    env = dict(
        os.environ,
        BENCH_PLATFORM="cpu",
        BENCH_CONFIGS="sleep",
        BENCH_SLEEP_S="240",
        BENCH_DEADLINE="300",
    )
    proc = subprocess.Popen(
        [sys.executable, str(BENCH)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        cwd=str(BENCH.parent),
    )
    try:
        time.sleep(8)  # worker is deterministically mid-sleep-stage
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()  # never leak a detached bench past the test
    assert proc.returncode == 0
    lines = [ln for ln in out.strip().splitlines() if ln.startswith("{")]
    d = json.loads(lines[-1])
    assert d["extra"].get("deadline_hit") is True
