"""Generate tests/fixtures_golden_decoder.npz — an INDEPENDENT numpy
implementation of the toy char-level decoder used as ground truth by
``tests/test_decode.py::TestGoldenDecoder``.

The DecodeEngine's math (runtime/decode_engine.py: decoder_prefill /
decoder_step) is pinned against this second implementation, which
shares no code with it: plain numpy, a single unbatched full-attention
forward per position, no KV cache, no padding buckets, no jax. If the
engine's bucketed/paged execution diverges from a straightforward
transformer forward — mask bug, KV gather off-by-one, bucket padding
leaking into the softmax — the fixture catches it.

Committed so the fixture is reproducible:
``python tests/generate_golden_decoder.py`` rewrites the npz
deterministically (seeded init, greedy decoding).

Fixture contents:
  prompt          [T]        int32 — the test prompt ("the cell divides")
  prefill_logits  [vocab]    f32   — logits at the last prompt position
  step_logits     [vocab]    f32   — logits after one greedy decode step
  greedy_tokens   [32]       int32 — 32 greedy continuation tokens
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

OUT = Path(__file__).parent / "fixtures_golden_decoder.npz"

PROMPT = "the cell divides"
N_TOKENS = 32

# mirrors DecoderConfig defaults; duplicated on purpose — the fixture
# must not import the module it pins
VOCAB, D_MODEL, N_HEADS, N_LAYERS, D_FF, MAX_LEN = 256, 64, 4, 2, 128, 512
HEAD_DIM = D_MODEL // N_HEADS


def init_params(seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def w(*shape, scale):
        return rng.normal(0.0, scale, size=shape).astype(np.float32)

    params = {
        "tok_emb": w(VOCAB, D_MODEL, scale=0.02),
        "pos_emb": w(MAX_LEN, D_MODEL, scale=0.02),
        "ln_f_g": np.ones((D_MODEL,), np.float32),
        "ln_f_b": np.zeros((D_MODEL,), np.float32),
        "layers": [],
    }
    for _ in range(N_LAYERS):
        params["layers"].append(
            {
                "ln1_g": np.ones((D_MODEL,), np.float32),
                "ln1_b": np.zeros((D_MODEL,), np.float32),
                "wq": w(D_MODEL, D_MODEL, scale=D_MODEL**-0.5),
                "wk": w(D_MODEL, D_MODEL, scale=D_MODEL**-0.5),
                "wv": w(D_MODEL, D_MODEL, scale=D_MODEL**-0.5),
                "wo": w(D_MODEL, D_MODEL, scale=D_MODEL**-0.5),
                "ln2_g": np.ones((D_MODEL,), np.float32),
                "ln2_b": np.zeros((D_MODEL,), np.float32),
                "w1": w(D_MODEL, D_FF, scale=D_MODEL**-0.5),
                "b1": np.zeros((D_FF,), np.float32),
                "w2": w(D_FF, D_MODEL, scale=D_FF**-0.5),
                "b2": np.zeros((D_MODEL,), np.float32),
            }
        )
    return params


def ln(x, g, b):
    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) / np.sqrt(var + 1e-5) * g + b


def gelu(x):
    # jax.nn.gelu default is the tanh approximation
    return 0.5 * x * (
        1.0 + np.tanh(np.sqrt(2.0 / np.pi) * (x + 0.044715 * x**3))
    )


def softmax(x, axis=-1):
    x = x - x.max(axis=axis, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=axis, keepdims=True)


def forward(params: dict, tokens: np.ndarray) -> np.ndarray:
    """Full-sequence causal forward; returns logits at the LAST
    position. No cache, no padding — the simplest correct transformer,
    recomputed from scratch each call."""
    T = len(tokens)
    x = params["tok_emb"][tokens] + params["pos_emb"][:T]
    causal = np.tril(np.ones((T, T), bool))
    mask = np.where(causal, 0.0, -1e30).astype(np.float32)
    for layer in params["layers"]:
        h = ln(x, layer["ln1_g"], layer["ln1_b"])
        q = (h @ layer["wq"]).reshape(T, N_HEADS, HEAD_DIM)
        k = (h @ layer["wk"]).reshape(T, N_HEADS, HEAD_DIM)
        v = (h @ layer["wv"]).reshape(T, N_HEADS, HEAD_DIM)
        scores = (
            np.einsum("qhd,khd->hqk", q, k) * HEAD_DIM**-0.5 + mask[None]
        )
        attn = softmax(scores, axis=-1)
        out = np.einsum("hqk,khd->qhd", attn, v).reshape(T, D_MODEL)
        x = x + out @ layer["wo"]
        h = ln(x, layer["ln2_g"], layer["ln2_b"])
        x = x + gelu(h @ layer["w1"] + layer["b1"]) @ layer["w2"] + layer["b2"]
    x = ln(x, params["ln_f_g"], params["ln_f_b"])
    return x[-1] @ params["tok_emb"].T


def main() -> None:
    params = init_params(0)
    prompt = np.array([ord(c) % 256 for c in PROMPT], np.int32)

    prefill_logits = forward(params, prompt)
    seq = list(prompt)
    greedy = []
    step_logits = None
    for i in range(N_TOKENS):
        logits = prefill_logits if i == 0 else forward(
            params, np.array(seq, np.int32)
        )
        nxt = int(np.argmax(logits))
        greedy.append(nxt)
        seq.append(nxt)
        if i == 1:
            # logits that produced the SECOND generated token — i.e.
            # the engine's first decoder_step output (prefill produces
            # the first)
            step_logits = logits

    np.savez_compressed(
        OUT,
        prompt=prompt,
        prefill_logits=prefill_logits.astype(np.float32),
        step_logits=step_logits.astype(np.float32),
        greedy_tokens=np.array(greedy, np.int32),
    )
    print(f"wrote {OUT} ({OUT.stat().st_size} bytes)")
    print("greedy:", greedy)


if __name__ == "__main__":
    main()
