"""BEFS small-request fast-frame contract (fast1).

Mirrors the oob1 interop suite in test_rpc_transport.py: property-style
round-trip bit-identity against the legacy codec, transparent fallback
for anything a fast frame cannot carry (traces, spans, ndarrays,
oversize values), byte-identical legacy frames for a peer that never
declared fast1, magic dispatch non-collision, hit-rate stats, and
end-to-end negotiation over a real websocket server.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from bioengine_tpu.rpc import protocol
from bioengine_tpu.rpc.client import connect_to_server
from bioengine_tpu.rpc.protocol import (
    CALL,
    ERROR,
    RESULT,
    decode,
    decode_fast,
    encode,
    encode_fast,
    is_fast_frame,
    is_oob_frame,
)
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.rpc.transport import Codec, TransportConfig

pytestmark = [pytest.mark.integration, pytest.mark.anyio]


def call_msg(*args, **kwargs) -> dict:
    return {
        "t": CALL,
        "call_id": "0123456789abcdef",
        "service_id": "ws/client:svc",
        "method": "echo",
        "args": list(args),
        "kwargs": kwargs,
    }


def result_msg(value) -> dict:
    return {"t": RESULT, "call_id": "0123456789abcdef", "result": value}


def assert_identical(a, b) -> None:
    """Equality plus exact-type identity, recursively (1 == 1.0 == True
    under ==, but the wire must preserve which one it was)."""
    assert type(a) is type(b), (a, b)
    if isinstance(a, list):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert_identical(x, y)
    elif isinstance(a, dict):
        assert list(a) == list(b)  # key order preserved like msgpack
        for k in a:
            assert_identical(a[k], b[k])
    elif isinstance(a, float):
        assert a == b or (a != a and b != b)  # NaN-proof
    else:
        assert a == b


def both_roundtrips(msg: dict):
    """Decode msg through BEFS and through the legacy codec."""
    frame = encode_fast(msg)
    assert frame is not None, f"expected fast-eligible: {msg}"
    assert is_fast_frame(frame)
    return decode_fast(frame), decode(encode(msg))


SMALL_PAYLOADS = [
    (),
    (0,),
    (-1, 2**62, -(2**62), 1.5, -0.0),
    ("", "hello", "unié中"),
    (b"", b"\x00\xff" * 16),
    (None, True, False),
    ([1, "a", None], {"k": 1, "j": [2.5]}),
    # the replica_call envelope shape: [replica_id, method, [args], {kwargs}]
    ("rep-0", "forward", [1, "x"], {"scale": 2.0}),
    (float("nan"), float("inf"), -float("inf")),
]


class TestFastCodec:
    @pytest.mark.parametrize("args", SMALL_PAYLOADS, ids=str)
    def test_call_roundtrip_matches_legacy(self, args):
        msg = call_msg(*args, flag=True, n=3)
        fast, legacy = both_roundtrips(msg)
        assert_identical(fast, legacy)
        # and the legacy re-encode of both decodes is byte-identical
        assert encode(fast) == encode(legacy)

    @pytest.mark.parametrize(
        "value",
        [None, True, 0, -7, 3.25, "ok", b"\x01\x02", [1, [2, [3]]],
         {"a": {"b": 1}}, {"ok": True, "v": [1, 2, 3]}],
        ids=str,
    )
    def test_result_roundtrip_matches_legacy(self, value):
        fast, legacy = both_roundtrips(result_msg(value))
        assert_identical(fast, legacy)

    def test_property_random_small_payloads(self):
        rng = random.Random(1234)

        def gen_value(depth: int):
            kinds = ["none", "bool", "int", "float", "str", "bytes"]
            if depth < 3:
                kinds += ["list", "dict"]
            k = rng.choice(kinds)
            if k == "none":
                return None
            if k == "bool":
                return rng.random() < 0.5
            if k == "int":
                return rng.randint(-(2**63), 2**63 - 1)
            if k == "float":
                return rng.uniform(-1e9, 1e9)
            if k == "str":
                return "".join(
                    chr(rng.randint(32, 0x2FF))
                    for _ in range(rng.randint(0, 24))
                )
            if k == "bytes":
                return rng.randbytes(rng.randint(0, 32))
            if k == "list":
                return [gen_value(depth + 1) for _ in range(rng.randint(0, 4))]
            return {
                f"k{i}": gen_value(depth + 1)
                for i in range(rng.randint(0, 4))
            }

        for _ in range(300):
            args = [gen_value(0) for _ in range(rng.randint(0, 4))]
            kwargs = {f"kw{i}": gen_value(0) for i in range(rng.randint(0, 3))}
            msg = call_msg(*args, **kwargs)
            fast, legacy = both_roundtrips(msg)
            assert_identical(fast, legacy)

    def test_tuple_args_become_lists_like_msgpack(self):
        msg = call_msg((1, 2, "x"))
        fast, legacy = both_roundtrips(msg)
        assert_identical(fast, legacy)
        assert fast["args"][0] == [1, 2, "x"]

    @pytest.mark.parametrize(
        "msg",
        [
            call_msg(np.arange(4)),                       # ndarray arg
            call_msg(np.float32(1.5)),                    # np scalar
            call_msg("x" * 5000),                         # over threshold
            call_msg(2**70),                              # >64-bit int
            call_msg(memoryview(b"abc")),                 # non-bytes buffer
            {**call_msg(1), "trace": {"tid": "t", "sid": "s"}},
            {**result_msg(1), "spans": [{"n": "x"}]},
            {"t": ERROR, "call_id": "c", "error": "boom"},
            {"t": protocol.PING},
            result_msg(ValueError("boom")),               # exception result
            {"t": CALL, "call_id": "c", "service_id": "s",
             "method": "m", "args": [1], "kwargs": {1: "non-str key"}},
        ],
        ids=lambda m: str(m.get("t")) + ":" + str(len(str(m)))
        if isinstance(m, dict) else str(m),
    )
    def test_ineligible_messages_fall_back(self, msg):
        assert encode_fast(msg) is None

    def test_threshold_knob(self):
        msg = call_msg("y" * 1000)
        assert encode_fast(msg, limit=256) is None
        assert encode_fast(msg, limit=4096) is not None
        cfg = TransportConfig(fast_threshold=256)
        codec = Codec(config=cfg)
        codec.fast = True
        frames = codec.encode_frames(msg)
        assert not is_fast_frame(frames[0])
        assert codec.stats.fast_fallbacks == 1

    def test_magic_cannot_collide(self):
        legacy = encode(call_msg(1))
        oob = protocol.encode_oob(call_msg(1))
        fast = encode_fast(call_msg(1))
        assert not is_fast_frame(legacy)
        assert not is_fast_frame(oob)
        assert not is_oob_frame(fast)
        assert not protocol.is_chunk_frame(fast)
        assert is_fast_frame(fast)


class TestFastCodecTransport:
    def _pair(self):
        enc = Codec()
        enc.fast = True
        enc.oob = True
        dec = Codec()
        return enc, dec

    def test_codec_fast_path_and_stats(self):
        enc, dec = self._pair()
        msg = call_msg(1, "a", scale=2.0)
        frames = enc.encode_frames(msg)
        assert len(frames) == 1 and is_fast_frame(frames[0])
        out = dec.decode(frames[0])
        assert_identical(out, decode(encode(msg)))
        assert enc.stats.small_frames_out == 1
        assert dec.stats.small_frames_in == 1

    def test_transparent_fallback_keeps_payload_fidelity(self):
        enc, dec = self._pair()
        dec.oob = True
        arr = np.arange(1 << 12, dtype=np.float32)
        frames = enc.encode_frames(call_msg(arr))
        assert not is_fast_frame(frames[0])
        np.testing.assert_array_equal(dec.decode(frames[0])["args"][0], arr)
        assert enc.stats.fast_fallbacks == 1
        assert enc.stats.small_frames_out == 0
        d = enc.stats.as_dict()
        assert d["fast_frame_hit_rate"] == 0.0

    def test_hit_rate_accounting(self):
        enc, _ = self._pair()
        enc.encode_frames(call_msg(1))
        enc.encode_frames(call_msg(1))
        enc.encode_frames(call_msg(np.arange(8)))
        enc.encode_frames({"t": protocol.PING})  # not a hot envelope
        d = enc.stats.as_dict()
        assert enc.stats.small_frames_out == 2
        assert enc.stats.fast_fallbacks == 1
        assert d["fast_frame_hit_rate"] == round(2 / 3, 4)

    def test_legacy_peer_sees_byte_identical_legacy_frames(self):
        """A codec WITHOUT negotiated fast1 (or oob1) must emit exactly
        what a pre-fast1 build would — byte identity, not just value
        identity."""
        plain = Codec()
        assert plain.fast is False and plain.oob is False
        msg = call_msg(1, "a", k=2.5)
        assert plain.encode_frames(msg) == [encode(msg)]
        # a fast-enabled codec falling back on an ineligible message
        # emits the same full-codec bytes too
        fast_codec = Codec()
        fast_codec.fast = True
        ineligible = {**call_msg(2), "trace": {"tid": "t", "sid": "s"}}
        assert fast_codec.encode_frames(ineligible) == [encode(ineligible)]

    async def test_async_encode_skips_payload_walk(self):
        enc, dec = self._pair()
        frames = await enc.encode_frames_async(call_msg(1, 2, 3))
        assert is_fast_frame(frames[0])
        out = await dec.decode_async(frames[0])
        assert out["args"] == [1, 2, 3]


# ---------------------------------------------------------------------------
# end-to-end over a real websocket server
# ---------------------------------------------------------------------------


@pytest.fixture
async def echo_server():
    srv = RpcServer(shm_store=None)
    await srv.start()
    srv.register_local_service(
        {"id": "echo", "echo": lambda a: a, "add": lambda a, b: a + b}
    )
    yield srv
    await srv.stop()


class TestEndToEnd:
    async def test_fast1_negotiated_and_used(self, echo_server):
        conn = await connect_to_server(
            {
                "server_url": f"http://127.0.0.1:{echo_server.port}",
                "shm_store": None,
            }
        )
        try:
            assert conn.codec.fast is True
            assert protocol.PROTO_FAST1 in conn.peer_protocols
            out = await conn.call("bioengine/echo", "add", 2, 3)
            assert out == 5
            # request rode a fast frame, and so did the result
            assert conn.codec.stats.small_frames_out >= 1
            assert conn.codec.stats.small_frames_in >= 1
            assert conn.describe()["fast"] is True
            assert (
                conn.describe()["transport"]["fast_frame_hit_rate"] is not None
            )
        finally:
            await conn.disconnect()

    async def test_fast1_connection_falls_back_for_arrays(self, echo_server):
        conn = await connect_to_server(
            {
                "server_url": f"http://127.0.0.1:{echo_server.port}",
                "shm_store": None,
            }
        )
        try:
            arr = np.arange(1 << 14, dtype=np.float32)
            out = await conn.call("bioengine/echo", "echo", arr)
            np.testing.assert_array_equal(out, arr)
            assert conn.codec.stats.fast_fallbacks >= 1
            # and small calls still use fast frames on the same conn
            assert await conn.call("bioengine/echo", "add", 1, 1) == 2
            assert conn.codec.stats.small_frames_out >= 1
        finally:
            await conn.disconnect()

    async def test_no_fast1_peer_never_receives_befs(self, echo_server):
        conn = await connect_to_server(
            {
                "server_url": f"http://127.0.0.1:{echo_server.port}",
                "protocols": [protocol.PROTO_OOB1],  # pre-fast1 peer
                "shm_store": None,
            }
        )
        try:
            assert conn.codec.fast is False
            assert await conn.call("bioengine/echo", "add", 2, 2) == 4
            assert conn.codec.stats.small_frames_in == 0
            assert conn.codec.stats.small_frames_out == 0
        finally:
            await conn.disconnect()

    async def test_pure_legacy_peer_interop(self, echo_server):
        conn = await connect_to_server(
            {
                "server_url": f"http://127.0.0.1:{echo_server.port}",
                "protocols": [],       # pre-oob, pre-fast peer
                "shm_store": None,
            }
        )
        try:
            assert await conn.call("bioengine/echo", "add", 3, 4) == 7
            assert conn.codec.stats.legacy_msgs_out >= 1
            assert conn.codec.stats.small_frames_in == 0
        finally:
            await conn.disconnect()

    async def test_compat_pre_fast1_uses_legacy_request_path(
        self, echo_server
    ):
        # The bench's baseline leg: legacy protocols keep BEFS off the
        # wire, and compat_pre_fast1 restores the pre-fast1 request
        # bookkeeping (uuid call ids + wait_for timeout) so the leg
        # measures the pre-optimization stack end to end.
        conn = await connect_to_server(
            {
                "server_url": f"http://127.0.0.1:{echo_server.port}",
                "protocols": [protocol.PROTO_OOB1, protocol.PROTO_TRACE1],
                "compat_pre_fast1": True,
                "shm_store": None,
            }
        )
        try:
            assert conn._compat_request is True
            assert conn.codec.fast is False
            assert await conn.call("bioengine/echo", "add", 5, 6) == 11
            assert conn.codec.stats.small_frames_out == 0
            assert conn.codec.stats.msgs_out >= 1
        finally:
            await conn.disconnect()

    async def test_unix_socket_transport(self, tmp_path):
        sock = str(tmp_path / "rpc.sock")
        srv = RpcServer(shm_store=None, uds_path=sock)
        await srv.start()
        srv.register_local_service(
            {"id": "echo", "add": lambda a, b: a + b}
        )
        try:
            conn = await connect_to_server(
                {"server_url": f"unix://{sock}", "shm_store": None}
            )
            try:
                assert conn.codec.fast is True
                assert await conn.call("bioengine/echo", "add", 8, 9) == 17
                assert conn.codec.stats.small_frames_out >= 1
            finally:
                await conn.disconnect()
        finally:
            await srv.stop()
