"""Scenario engine: seeded determinism, invariants, and the
slow-replica acceptance proof (gray failure detected and steered
around end-to-end, zero failed idempotent requests, p99 recovered —
and the SAME seed without defenses shows the degradation).

The heavyweight full-catalog sweep lives in
scripts/workflows/scenarios.sh; tier-1 runs the acceptance scenario,
one determinism double-run, and the engine/fault-layer units.
"""

import pytest

from bioengine_tpu.testing import faults
from bioengine_tpu.testing.scenarios import (
    NAMED_SCENARIOS,
    FaultEvent,
    Stream,
    get_scenario,
    list_scenarios,
    outcome_signature,
    run_scenario_async,
)

pytestmark = [pytest.mark.integration, pytest.mark.anyio]


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# fault layer: seeded slow_ramp + scope targeting (satellite)
# ---------------------------------------------------------------------------


class TestSlowRampFault:
    async def test_slow_ramp_delays_are_seeded_and_replayable(self):
        """The satellite contract: the whole delay sequence is a pure
        function of (seed, hit index) — two armings with the same seed
        replay EXACTLY; a different seed diverges."""

        async def sample(seed, n=6):
            faults.clear()
            faults.configure(
                "p", "slow_ramp", delay_s=0.002, seed=seed, ramp_hits=4
            )
            spec = faults._specs["p"]
            return [spec.ramp_delay(i + 1) for i in range(n)]

        a = await sample(42)
        b = await sample(42)
        c = await sample(43)
        assert a == b
        assert a != c
        # the ramp: delays grow toward delay_s then plateau with jitter
        assert a[0] < a[3] * 2  # early hits are scaled down by the ramp
        assert all(0 < d <= 0.002 * 1.5 for d in a)

    async def test_slow_ramp_slows_but_never_fails(self):
        import time

        faults.configure(
            "p", "slow_ramp", delay_s=0.01, seed=1, ramp_hits=2
        )
        t0 = time.monotonic()
        for _ in range(3):
            await faults.hit("p")  # degraded, not dead: no exception
        assert time.monotonic() - t0 >= 0.005
        assert faults.hits("p") == 3

    async def test_scope_targets_one_party(self):
        """A spec armed for one host's scope must not trigger for its
        siblings — the in-process harness shares this module's state
        across every host."""
        faults.configure("pt", "raise", scope="h1")
        await faults.hit("pt", scope="h2")  # not targeted
        with pytest.raises(faults.FaultInjected):
            await faults.hit("pt", scope="h1")
        assert faults.hits("pt", scope="h1") == 1
        assert faults.hits("pt", scope="h2") == 0

    async def test_scoped_env_syntax(self):
        faults.load_env("a.b@h2=slow_ramp:1:100:0.25:42:20")
        spec = faults._specs["a.b@h2"]
        assert spec.scope == "h2"
        assert spec.action == "slow_ramp"
        assert spec.delay_s == 0.25
        assert spec.seed == 42
        assert spec.ramp_hits == 20

    async def test_clear_sweeps_scoped_specs(self):
        faults.configure("x.y", "raise")
        faults.configure("x.y", "raise", scope="h1")
        faults.clear("x.y")
        assert not faults._specs
        assert not faults.ACTIVE

    async def test_scoped_counter_advances_even_when_scopeless_raises(self):
        """A pass counts for EVERY matching spec before any action
        fires — a scopeless raise must not shift the scoped window."""
        faults.configure("w.z", "raise", nth=1, count=2)
        faults.configure(
            "w.z", "slow_ramp", scope="h1", nth=3, delay_s=0.001, seed=1
        )
        for _ in range(2):
            with pytest.raises(faults.FaultInjected):
                await faults.hit("w.z", scope="h1")
        # both counters saw both passes despite the raises
        assert faults.hits("w.z", scope="h1") == 2
        await faults.hit("w.z", scope="h1")  # 3rd pass: ramp, no raise
        assert faults.hits("w.z", scope="h1") == 3

    async def test_clear_one_scope_keeps_the_others(self):
        """Healing ONE host must not disarm its siblings' faults (or
        the scopeless spec)."""
        faults.configure("x.y", "raise")
        faults.configure("x.y", "raise", scope="h1")
        faults.configure("x.y", "raise", scope="h2")
        faults.clear("x.y@h1")
        assert "x.y@h1" not in faults._specs
        assert "x.y@h2" in faults._specs
        assert "x.y" in faults._specs
        assert faults.ACTIVE


# ---------------------------------------------------------------------------
# engine vocabulary
# ---------------------------------------------------------------------------


class TestScenarioVocabulary:
    def test_streams_are_pure_functions_of_tick(self):
        s = Stream(kind="diurnal", base=1, amplitude=6, period=30)
        first = [s.arrivals(t) for t in range(60)]
        assert first == [s.arrivals(t) for t in range(60)]
        assert max(first) > min(first)  # it actually waves
        burst = Stream(kind="burst", base=1, burst_every=5, burst_size=8)
        assert burst.arrivals(5) == 9
        assert burst.arrivals(6) == 1
        windowed = Stream(base=2, start_tick=10, end_tick=20)
        assert windowed.arrivals(9) == 0
        assert windowed.arrivals(10) == 2
        assert windowed.arrivals(20) == 0

    def test_catalog_is_complete(self):
        names = {s["name"] for s in list_scenarios()}
        assert {
            "slow_replica",
            "preemption_storm",
            "diurnal_wave",
            "blip_storm",
            "hot_signature",
            "tenant_flood",
            "controller_crash",
            "token_streaming",
        } <= names
        assert len(names) >= 5
        with pytest.raises(KeyError, match="unknown scenario"):
            get_scenario("nope")

    def test_slow_replica_declares_the_acceptance_contract(self):
        s = get_scenario("slow_replica")
        assert "zero_failed_idempotent" in s.invariants
        assert "chip_accounting_exact" in s.invariants
        assert "probation_entered" in s.defended_invariants
        assert "p99_recovery" in s.defended_invariants
        assert any(
            ev.action == "slow_ramp" for ev in s.fault_script
        )


# ---------------------------------------------------------------------------
# engine runs (in-process multi-host harness)
# ---------------------------------------------------------------------------


class TestScenarioRuns:
    async def test_determinism_same_seed_same_outcomes(self):
        """Two runs with one seed produce identical request outcome
        sequences and identical invariant verdicts; a different seed
        produces a different REQUEST PLAN (the workload really is
        seed-driven, not fixed)."""
        scenario = get_scenario("hot_signature")
        r1 = await run_scenario_async(scenario, seed=5)
        r2 = await run_scenario_async(scenario, seed=5)
        assert r1["passed"] and r2["passed"]
        assert outcome_signature(r1) == outcome_signature(r2)
        assert r1["requests"] == r2["requests"]

    async def test_slow_replica_acceptance_both_directions(self):
        """THE acceptance criterion: with probation+hedging a seeded
        gray-failing replica (still passing health checks) is detected
        and steered around — zero failed idempotent requests, tail p99
        back within 2x the healthy baseline — and the same seed with
        defenses OFF shows the degradation, proving the scenario
        detects exactly what the machinery fixes."""
        scenario = get_scenario("slow_replica")
        defended = await run_scenario_async(scenario, seed=7, defenses=True)
        inv = defended["invariants"]
        assert inv["zero_failed_idempotent"]["ok"], inv
        assert inv["chip_accounting_exact"]["ok"], inv
        assert inv["probation_entered"]["ok"], inv
        assert inv["p99_recovery"]["ok"], inv
        assert defended["passed"], defended["invariants"]
        assert defended["probations"] >= 1
        assert defended["hedges"] > 0

        undefended = await run_scenario_async(
            scenario, seed=7, defenses=False
        )
        # failover keeps traffic alive either way — the DEGRADATION is
        # what the undefended leg must show
        assert undefended["invariants"]["zero_failed_idempotent"]["ok"]
        assert not undefended["invariants"]["p99_recovery"]["ok"], (
            "undefended run recovered p99 — the scenario no longer "
            "injects a visible gray failure"
        )
        assert undefended["probations"] == 0
        assert undefended["hedges"] == 0
        assert (
            undefended["phases"]["tail_p99_ms"]
            > defended["phases"]["tail_p99_ms"]
        )

    @pytest.mark.slow
    async def test_full_catalog_passes(self):
        """Every named scenario holds its invariants (the scenarios.sh
        sweep, runnable in-process for the slow tier)."""
        for name, scenario in NAMED_SCENARIOS.items():
            result = await run_scenario_async(scenario, seed=11)
            failed = {
                k: v
                for k, v in result["invariants"].items()
                if v["required"] and not v["ok"]
            }
            assert result["passed"], (name, failed)

    async def test_controller_crash_recovers_with_zero_loss(self):
        """PR 15 acceptance: the controller is SIGKILL-equivalently
        torn down mid-mixed-priority traffic, restarted against the
        same journal, and reconciles — zero failed idempotent
        requests, every surviving replica adopted (no re-placement,
        no duplicates), chip accounting exact, and the revived old
        controller's lower-epoch verb fenced. Deterministic across two
        runs for one seed (the CI double-run gate)."""
        scenario = get_scenario("controller_crash")
        r1 = await run_scenario_async(scenario, seed=7)
        inv = r1["invariants"]
        assert inv["zero_failed_idempotent"]["ok"], inv
        assert inv["chip_accounting_exact"]["ok"], inv
        assert inv["no_duplicate_placements"]["ok"], inv
        assert inv["replicas_adopted"]["ok"], inv
        assert inv["epoch_fencing_observed"]["ok"], inv
        assert r1["passed"], inv
        assert r1["counts"] == {"ok": r1["requests"]}
        r2 = await run_scenario_async(scenario, seed=7)
        assert outcome_signature(r1) == outcome_signature(r2)

    async def test_token_streaming_survives_host_kill_with_cobatching(self):
        """The streaming acceptance scenario: mixed interactive/bulk
        token streams over 2 hosts, a host SIGKILL'd mid-generation at
        tick 45. Every request must verify its WHOLE token sequence
        against the client-side decoder mirror (a resumed stream that
        dropped/duplicated a token records wrong_result), co-batching
        must be observed (mid-batch joins), the kill must force real
        mid-stream resumes, and chip accounting stays exact — a
        co-batched stream bills its fair share, not the whole batch.
        Deterministic for one seed (the replay gate)."""
        scenario = get_scenario("token_streaming")
        r1 = await run_scenario_async(scenario, seed=7)
        inv = r1["invariants"]
        assert inv["zero_failed_idempotent"]["ok"], inv
        assert inv["chip_accounting_exact"]["ok"], inv
        assert inv["decode_cobatch_observed"]["ok"], inv
        assert inv["stream_resume_observed"]["ok"], inv
        assert inv["slo_attainment"]["ok"], inv
        assert r1["passed"], inv
        # every stream delivered its exact expected token sequence
        assert r1["counts"] == {"ok": r1["requests"]}
        r2 = await run_scenario_async(scenario, seed=7)
        assert outcome_signature(r1) == outcome_signature(r2)

    async def test_tenant_flood_protects_the_strict_tenant(self):
        result = await run_scenario_async(
            get_scenario("tenant_flood"), seed=3
        )
        assert result["passed"], result["invariants"]
        # the flood was actually shed somewhere (quota pressure is real)
        assert result["invariants"]["flood_shed_observed"]["ok"]
        # protected requests all strict-ok; flood normalized to absorbed
        assert result["counts"].get("absorbed", 0) > 0
        assert "shed" not in result["counts"]  # strict streams never shed
