"""CLI tests: click commands driven against a live in-process worker.

Mirrors the reference's CLI surface (ref bioengine/cli/) but hermetic —
the worker runs in a background thread with its own event loop, the CLI
connects over the real WebSocket control plane.
"""

import asyncio
import json
import threading

import numpy as np
import pytest
from click.testing import CliRunner

from bioengine_tpu.cli.cli import main as cli_main
from bioengine_tpu.cli.utils import coerce_value, parse_kv_args, read_image, write_image

from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent

pytestmark = [pytest.mark.end_to_end]

REPO_APPS = __import__("pathlib").Path(__file__).resolve().parent.parent / "apps"


# ---- pure helpers -----------------------------------------------------------


def test_coerce_value():
    assert coerce_value("3") == 3
    assert coerce_value("3.5") == 3.5
    assert coerce_value("true") is True
    assert coerce_value('{"a": 1}') == {"a": 1}
    assert coerce_value("[1,2]") == [1, 2]
    assert coerce_value("plain text") == "plain text"


def test_parse_kv_args():
    import click

    out = parse_kv_args(("x=1", "name=bob", 'cfg={"k": 2}'))
    assert out == {"x": 1, "name": "bob", "cfg": {"k": 2}}
    with pytest.raises(click.UsageError):
        parse_kv_args(("novalue",))


def test_image_roundtrip(tmp_path):
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    write_image(tmp_path / "a.npy", arr)
    np.testing.assert_array_equal(read_image(tmp_path / "a.npy"), arr)
    write_image(tmp_path / "a.npz", arr)
    np.testing.assert_array_equal(read_image(tmp_path / "a.npz"), arr)
    img = (np.random.default_rng(0).random((5, 5)) * 255).astype(np.uint8)
    write_image(tmp_path / "a.png", img)
    np.testing.assert_array_equal(read_image(tmp_path / "a.png"), img)


# ---- live worker fixture ----------------------------------------------------


@pytest.fixture(scope="module")
def live_worker(tmp_path_factory):
    """A worker running in a daemon thread with its own loop."""
    from bioengine_tpu.worker.worker import BioEngineWorker

    tmp = tmp_path_factory.mktemp("cli-worker")
    holder: dict = {}
    started = threading.Event()

    def _run():
        async def _main():
            worker = BioEngineWorker(
                mode="single-machine",
                workspace_dir=tmp / "ws",
                admin_users=["admin"],
                startup_applications=[
                    {"local_path": str(REPO_APPS / "demo-app")}
                ],
                monitoring_interval_seconds=5.0,
                log_file="off",
            )
            await worker.start()
            holder["worker"] = worker
            holder["url"] = worker.server.url
            holder["token"] = worker.server.issue_token("admin")
            holder["loop"] = asyncio.get_running_loop()
            started.set()
            await worker._stop_event.wait()

        asyncio.run(_main())

    thread = threading.Thread(target=_run, daemon=True)
    thread.start()
    assert started.wait(timeout=60), "worker failed to start"
    yield holder
    asyncio.run_coroutine_threadsafe(
        holder["worker"].stop(), holder["loop"]
    ).result(timeout=30)
    thread.join(timeout=10)


def _cli(live_worker, *args):
    runner = CliRunner()
    return runner.invoke(
        cli_main,
        list(args)
        # `=` form: a generated token may START with "-" (urlsafe
        # base64), which a space-separated parse reads as an option
        + [f"--server-url={live_worker['url']}", f"--token={live_worker['token']}"],
        catch_exceptions=False,
    )


# ---- commands ---------------------------------------------------------------


def test_cli_status(live_worker):
    result = _cli(live_worker, "status")
    assert result.exit_code == 0, result.stdout
    payload = json.loads(result.stdout)
    assert payload["worker"]["ready"] is True


def test_cli_cluster_status(live_worker):
    result = _cli(live_worker, "cluster", "status")
    assert result.exit_code == 0, result.stdout
    payload = json.loads(result.stdout)
    assert payload["topology"]["n_chips"] == 8


def test_cli_call_list_methods(live_worker):
    (app_id,) = live_worker["worker"].apps_manager.records
    result = _cli(live_worker, "call", app_id, "--list-methods")
    assert result.exit_code == 0, result.stdout
    payload = json.loads(result.stdout)
    assert "echo" in payload["methods"]


def test_cli_call_method_with_args(live_worker):
    (app_id,) = live_worker["worker"].apps_manager.records
    result = _cli(live_worker, "call", app_id, "echo", "--arg", "message=hello")
    assert result.exit_code == 0, result.stdout
    payload = json.loads(result.stdout)
    assert payload["echo"] == "hello"


def test_cli_call_args_json(live_worker):
    (app_id,) = live_worker["worker"].apps_manager.records
    result = _cli(
        live_worker, "call", app_id, "echo", "--args", '{"message": "via-json"}'
    )
    assert result.exit_code == 0, result.stdout
    assert json.loads(result.stdout)["echo"] == "via-json"


def test_cli_apps_upload_list_run_stop(live_worker):
    result = _cli(
        live_worker, "apps", "upload", str(REPO_APPS / "demo-app")
    )
    assert result.exit_code == 0, result.stdout
    uploaded = json.loads(result.stdout)
    assert uploaded["artifact_id"] == "demo-app"

    result = _cli(live_worker, "apps", "list")
    assert result.exit_code == 0
    assert any(a["artifact_id"] == "demo-app" for a in json.loads(result.stdout))

    result = _cli(
        live_worker, "apps", "run", "--artifact-id", "demo-app",
        "--deployment-kwargs", '{"demo_deployment": {"greeting": "CLI"}}',
    )
    assert result.exit_code == 0, result.stdout
    app_id = json.loads(result.stdout)["app_id"]

    result = _cli(live_worker, "apps", "status", app_id)
    assert result.exit_code == 0
    assert json.loads(result.stdout)["status"] in ("RUNNING", "DEPLOYING")

    result = _cli(live_worker, "call", app_id, "echo", "--arg", "message=x")
    assert json.loads(result.stdout)["greeting"] == "CLI"

    result = _cli(live_worker, "apps", "logs", app_id)
    assert result.exit_code == 0

    result = _cli(live_worker, "apps", "stop", app_id)
    assert result.exit_code == 0
    assert json.loads(result.stdout)["status"] == "STOPPED"


def test_cli_upload_sends_file_contents(live_worker, tmp_path):
    """Uploads must work from a directory the WORKER cannot see — file
    contents travel over RPC."""
    import shutil

    src = tmp_path / "client-only-app"
    shutil.copytree(REPO_APPS / "demo-app", src)
    manifest = (src / "manifest.yaml").read_text().replace(
        "id: demo-app", "id: client-app"
    )
    (src / "manifest.yaml").write_text(manifest)
    result = _cli(live_worker, "apps", "upload", str(src))
    assert result.exit_code == 0, result.stdout
    assert json.loads(result.stdout)["artifact_id"] == "client-app"
    # the worker stored it in ITS artifact store
    assert "client-app" in live_worker["worker"].apps_manager.store.list_artifacts()


def test_cli_run_local_path_and_raw_env(live_worker, tmp_path):
    result = _cli(
        live_worker, "apps", "run",
        "--local-path", str(REPO_APPS / "demo-app"),
        "--env", "FLAG=true",
    )
    assert result.exit_code == 0, result.stdout
    app_id = json.loads(result.stdout)["app_id"]
    # env value must arrive as the literal string "true", not Python True
    result = _cli(live_worker, "call", app_id, "get_env", "--arg", "key=FLAG")
    assert json.loads(result.stdout)["value"] == "true"
    _cli(live_worker, "apps", "stop", app_id)


def test_cli_bad_json_is_usage_error(live_worker):
    runner = CliRunner()
    result = runner.invoke(
        cli_main,
        ["call", "any", "m", "--args", "{bad", "--server-url", live_worker["url"]],
    )
    assert result.exit_code == 2  # click usage error, not a traceback
    assert "not valid JSON" in result.stderr


def test_cli_missing_server_url(monkeypatch):
    monkeypatch.delenv("BIOENGINE_SERVER_URL", raising=False)
    runner = CliRunner()
    result = runner.invoke(cli_main, ["status"])
    assert result.exit_code != 0
    assert "server" in (result.stderr + str(result)).lower()


@pytest.mark.anyio
class TestStandaloneUploader:
    """scripts/upload_app.py — ref scripts/upload_app.py analog, both
    transports."""

    async def test_http_transport(self, tmp_path):
        import subprocess
        import sys

        from bioengine_tpu.apps.artifact_http import ArtifactHttpService
        from bioengine_tpu.apps.artifacts import LocalArtifactStore
        from bioengine_tpu.rpc.server import RpcServer

        server = RpcServer(admin_users=["admin"])
        await server.start()
        token = server.issue_token("admin", is_admin=True)
        backing = LocalArtifactStore(tmp_path / "store")
        server.attach_artifact_service(ArtifactHttpService(backing, server))
        try:
            proc = await asyncio.to_thread(
                subprocess.run,
                [
                    sys.executable,
                    str(REPO_ROOT / "scripts" / "upload_app.py"),
                    str(REPO_ROOT / "apps" / "demo-app"),
                    "--server-url", server.http_url,
                    # --token=<v>, not two argv entries: token_urlsafe
                    # output can start with '-' (~1.6% of runs), which
                    # argparse then rejects as an option — a latent
                    # whole-suite flake
                    f"--token={token}",
                ],
                capture_output=True, text=True, timeout=60,
            )
            assert proc.returncode == 0, proc.stderr
            assert "uploaded demo-app@1.0.0" in proc.stdout
            assert backing.list_artifacts() == ["demo-app"]
        finally:
            await server.stop()

    async def test_ws_transport_requires_worker(self, tmp_path):
        import subprocess
        import sys

        from bioengine_tpu.worker.worker import BioEngineWorker

        w = BioEngineWorker(
            mode="single-machine",
            workspace_dir=tmp_path / "ws",
            admin_users=["admin"],
            monitoring_interval_seconds=60.0,
            log_file="off",
        )
        await w.start()
        try:
            proc = await asyncio.to_thread(
                subprocess.run,
                [
                    sys.executable,
                    str(REPO_ROOT / "scripts" / "upload_app.py"),
                    str(REPO_ROOT / "apps" / "demo-app"),
                    # `=` form: a token_urlsafe value can start with
                    # "-" and argparse would read it as an option
                    f"--server-url={w.server.url}",
                    f"--token={w.admin_token}",
                ],
                capture_output=True, text=True, timeout=60,
            )
            assert proc.returncode == 0, proc.stderr
            assert "uploaded demo-app@" in proc.stdout
        finally:
            await w.stop()


def test_cli_cluster_traces(live_worker):
    result = _cli(live_worker, "cluster", "traces", "--name", "deploy_app")
    assert result.exit_code == 0, result.stdout
    spans = json.loads(result.stdout)
    # the live_worker fixture deploys a startup app -> one deploy span
    assert spans and spans[-1]["name"] == "deploy_app"
    assert spans[-1]["duration_s"] >= 0


def test_cli_cluster_profile_memory(live_worker):
    result = _cli(live_worker, "cluster", "profile", "--memory")
    assert result.exit_code == 0, result.stdout
    payload = json.loads(result.stdout)
    assert payload["devices"]
    assert payload["pprof_bytes"] > 0


def test_cli_slo_status(live_worker):
    result = _cli(live_worker, "slo", "status")
    assert result.exit_code == 0, result.stdout
    payload = json.loads(result.stdout)
    assert "deployments" in payload
    assert "auto_bundles" in payload


def test_cli_top(live_worker):
    result = _cli(live_worker, "top")
    assert result.exit_code == 0, result.stdout
    payload = json.loads(result.stdout)
    assert "telemetry" in payload and "slo" in payload
    assert "store" in payload["telemetry"]


def test_read_dir_files_skips_hidden_dirs(tmp_path):
    from bioengine_tpu.cli.utils import read_dir_files

    (tmp_path / "manifest.yaml").write_text("x: 1")
    (tmp_path / ".git" / "objects").mkdir(parents=True)
    (tmp_path / ".git" / "objects" / "blob").write_bytes(b"secret")
    (tmp_path / ".env").write_text("TOKEN=x")
    files = read_dir_files(tmp_path)
    assert set(files) == {"manifest.yaml"}


def test_cli_models_list_and_convert(tmp_path):
    """`bioengine models convert --arch cpsam`: torch checkpoint file ->
    flat-npz jax_params consumable by the finetuning app / model-runner
    (covers load_torch_state_dict + name map + npz write end-to-end)."""
    import torch

    from bioengine_tpu.runtime.convert import (
        load_params_npz,
        synthetic_cpsam_state_dict,
    )

    runner = CliRunner()
    result = runner.invoke(cli_main, ["models", "list"])
    assert result.exit_code == 0, result.stdout
    assert "cpsam" in json.loads(result.stdout)

    sd = synthetic_cpsam_state_dict()
    ckpt = tmp_path / "cpsam.pth"
    torch.save({k: torch.from_numpy(v) for k, v in sd.items()}, ckpt)
    out = tmp_path / "cpsam.npz"
    result = runner.invoke(
        cli_main,
        ["models", "convert", str(ckpt), str(out), "--arch", "cpsam"],
    )
    assert result.exit_code == 0, result.output
    info = json.loads(result.stdout.strip().splitlines()[-1])
    assert info["n_params"] > 0 and set(info["top_level"]) == {
        "encoder", "out",
    }
    params = load_params_npz(str(out))
    np.testing.assert_array_equal(
        params["encoder"]["block0"]["attn"]["qkv"]["kernel"],
        sd["encoder.blocks.0.attn.qkv.weight"].T,
    )


# ---- bioengine analyze ------------------------------------------------------


def test_cli_analyze_list_rules():
    result = CliRunner().invoke(cli_main, ["analyze", "--list-rules"])
    assert result.exit_code == 0
    assert "BE-ASYNC-001" in result.output
    assert "BE-JAX-101" in result.output


def test_cli_analyze_clean_file_exits_zero():
    clean = REPO_ROOT / "tests" / "analysis_fixtures" / "fx_clean.py"
    result = CliRunner().invoke(
        cli_main, ["analyze", str(clean), "--no-baseline"]
    )
    assert result.exit_code == 0, result.output


def test_cli_analyze_findings_exit_one():
    seeded = REPO_ROOT / "tests" / "analysis_fixtures" / "fx_async_blocking.py"
    result = CliRunner().invoke(
        cli_main, ["analyze", str(seeded), "--no-baseline"]
    )
    assert result.exit_code == 1
    assert "BE-ASYNC-001" in result.output
