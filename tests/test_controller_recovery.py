"""Durable control plane: journaled controller state, crash/upgrade
recovery with zero-loss reconcile, epoch fencing against split-brain.

Three layers of proof:

- **Journal units** — CRC-guarded append/replay, torn-tail stop,
  atomic snapshot compaction, epoch monotonicity across restarts, and
  the full ``DeploymentSpec`` round trip (scheduling / slo /
  warm_pool / mesh / batching blocks).
- **In-process crash chaos** — the PR-4-style harness (real
  websockets, WorkerHost objects in the test loop): the controller is
  SIGKILL-equivalently torn down mid-idempotent-traffic and restarted
  against the same journal; zero failed idempotent requests, every
  surviving replica re-adopted IN PLACE (same host-side instance
  object — never restarted), chip accounting exact, and a lower-epoch
  verb from the "old" controller rejected typed. Plus the reconcile
  edge cases: unknown-replica drop, re-place from spec with no
  survivors, double-restart from a recovering snapshot, and the
  orphaned host's grace-window self-drain.
- **Real subprocess** (slow) — an actual controller process is
  SIGKILLed and restarted; the in-test worker host rides through
  orphaned and is re-adopted by the second life.
"""

import asyncio
import os
import signal
import socket
import sys
import time
from pathlib import Path

import pytest

from bioengine_tpu.apps.builder import AppBuilder
from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology
from bioengine_tpu.rpc.client import connect_to_server
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.serving import (
    DeploymentSpec,
    MeshConfig,
    RequestOptions,
    SchedulingConfig,
    ServeController,
    SLOConfig,
    StaleEpochError,
    WarmPoolConfig,
)
from bioengine_tpu.serving.journal import (
    ControlJournal,
    redact_secrets,
    spec_from_dict,
    spec_to_dict,
)
from bioengine_tpu.utils import flight
from bioengine_tpu.worker_host import WorkerHost

pytestmark = [pytest.mark.integration, pytest.mark.anyio]


# ---------------------------------------------------------------------------
# journal units
# ---------------------------------------------------------------------------


class TestJournalUnits:
    def test_append_replay_roundtrip(self, tmp_path):
        j = ControlJournal(tmp_path, snapshot_every=1000)
        j.mint_epoch()
        j.append("deploy", {"app_id": "a", "specs": [{"name": "d",
                 "num_replicas": 2}], "acl": ["*"]})
        j.append("scale", {"app_id": "a", "deployment": "d",
                 "num_replicas": 3})
        j.append("deploy", {"app_id": "b", "specs": [{"name": "x"}],
                 "acl": None})
        j.append("undeploy", {"app_id": "b"})

        state = ControlJournal(tmp_path).load()
        assert state.epoch == 1
        assert set(state.apps) == {"a"}
        assert state.apps["a"]["specs"][0]["num_replicas"] == 3
        assert state.apps["a"]["acl"] == ["*"]
        assert not state.torn_tail
        assert state.records_replayed == 5

    def test_torn_tail_stops_cleanly(self, tmp_path):
        """A crash mid-append leaves a truncated final record; replay
        keeps everything before it and flags the tear instead of
        raising or silently absorbing garbage."""
        j = ControlJournal(tmp_path, snapshot_every=1000)
        j.mint_epoch()
        j.append("deploy", {"app_id": "a", "specs": [], "acl": None})
        j.append("deploy", {"app_id": "b", "specs": [], "acl": None})
        raw = j.journal_path.read_bytes()
        # cut the final record mid-json — CRC can no longer match
        j.journal_path.write_bytes(raw[:-10])

        state = ControlJournal(tmp_path).load()
        assert state.torn_tail
        assert set(state.apps) == {"a"}
        assert state.records_replayed == 2  # epoch + first deploy

    def test_corrupt_crc_stops_cleanly(self, tmp_path):
        j = ControlJournal(tmp_path, snapshot_every=1000)
        j.append("deploy", {"app_id": "a", "specs": [], "acl": None})
        raw = j.journal_path.read_bytes()
        j.journal_path.write_bytes(raw[:-5] + b"X" + raw[-4:])
        state = ControlJournal(tmp_path).load()
        assert state.torn_tail
        assert state.apps == {}

    def test_append_after_torn_tail_starts_clean(self, tmp_path):
        """``load()`` truncates the torn bytes, so the NEXT append (the
        restarted controller's minted epoch) lands on a fresh line.
        Without the truncate it would merge onto the partial line, fail
        CRC on the following replay, and take the epoch — the
        split-brain fence — down with it."""
        j = ControlJournal(tmp_path, snapshot_every=1000)
        j.mint_epoch()
        j.append("deploy", {"app_id": "a", "specs": [], "acl": None})
        raw = j.journal_path.read_bytes()
        j.journal_path.write_bytes(raw[:-10])  # crash mid-append

        j2 = ControlJournal(tmp_path, snapshot_every=1000)
        state = j2.load()
        assert state.torn_tail
        assert j2.mint_epoch() == 2

        state3 = ControlJournal(tmp_path).load()
        assert not state3.torn_tail       # the tear was repaired
        assert state3.epoch == 2          # the minted epoch SURVIVES

    def test_unterminated_final_line_is_torn(self, tmp_path):
        """A final line missing only its newline is a torn write even
        when the record body is intact: ``append`` fsyncs the full
        line, so the record was never acked — it must be dropped, not
        merged into by the next append."""
        j = ControlJournal(tmp_path, snapshot_every=1000)
        j.append("deploy", {"app_id": "a", "specs": [], "acl": None})
        j.append("deploy", {"app_id": "b", "specs": [], "acl": None})
        raw = j.journal_path.read_bytes()
        j.journal_path.write_bytes(raw[:-1])  # strip ONLY the newline
        state = ControlJournal(tmp_path).load()
        assert state.torn_tail
        assert set(state.apps) == {"a"}

    def test_snapshot_compaction(self, tmp_path):
        """Every ``snapshot_every`` appends the folded state lands in
        snapshot.json (atomic rename) and the journal restarts empty —
        replay cost is bounded by cadence, not uptime."""
        j = ControlJournal(tmp_path, snapshot_every=3)
        j.mint_epoch()
        j.set_snapshot_state(
            {"a": {"specs": [{"name": "d"}], "acl": None}}, ["admin"]
        )
        j.append("deploy", {"app_id": "a", "specs": [{"name": "d"}],
                 "acl": None})
        j.append("scale", {"app_id": "a", "deployment": "d",
                 "num_replicas": 2})
        assert j.snapshots_written == 1
        assert j.journal_path.stat().st_size == 0
        assert j.snapshot_path.exists()

        state = ControlJournal(tmp_path).load()
        assert state.snapshot_loaded
        assert set(state.apps) == {"a"}
        assert state.admins == ["admin"]
        assert state.epoch == 1

    def test_epoch_monotonic_across_restarts(self, tmp_path):
        epochs = []
        for _ in range(4):
            j = ControlJournal(tmp_path)
            j.load()
            epochs.append(j.mint_epoch())
        assert epochs == [1, 2, 3, 4]

    def test_epoch_survives_snapshot_compaction(self, tmp_path):
        j = ControlJournal(tmp_path, snapshot_every=1)
        j.load()
        j.mint_epoch()           # append triggers an immediate snapshot
        assert j.journal_path.stat().st_size == 0
        j2 = ControlJournal(tmp_path)
        j2.load()
        assert j2.mint_epoch() == 2

    def test_redact_secrets(self):
        doc = {
            "env_vars": {"BIOENGINE_ADMIN_TOKEN": "s3cret", "N": 4},
            "api_key": "xyz",
            "files": {"main.py": "print('hello world')"},
            "nested": [{"password": "p"}],
            "name": "ok",
        }
        red = redact_secrets(doc)
        assert red["env_vars"]["BIOENGINE_ADMIN_TOKEN"] == "***redacted***"
        assert red["env_vars"]["N"] == 4
        assert red["api_key"] == "***redacted***"
        assert red["nested"][0]["password"] == "***redacted***"
        assert "hello" not in str(red["files"])
        assert red["name"] == "ok"

    def test_inspect_tail_and_describe(self, tmp_path):
        j = ControlJournal(tmp_path, snapshot_every=1000)
        j.mint_epoch()
        for i in range(5):
            j.append("deploy", {"app_id": f"a{i}", "specs": [],
                     "acl": None})
        info = j.inspect(tail=3)
        assert info["journal_records"] == 6
        assert len(info["tail"]) == 3
        assert not info["torn_tail"]
        d = j.describe()
        assert d["records_written"] == 6
        assert d["epoch"] == 1


class TestSpecRoundTrip:
    def test_all_config_blocks_roundtrip(self):
        spec = DeploymentSpec(
            name="dep",
            instance_factory=lambda: None,
            num_replicas=3,
            min_replicas=2,
            max_replicas=5,
            chips_per_replica=2,
            max_ongoing_requests=7,
            autoscale=False,
            target_load=0.6,
            max_batch=16,
            max_wait_ms=4.5,
            scheduling=SchedulingConfig(
                max_batch=8, tenant_quota=6, class_weights={"interactive": 8.0}
            ),
            slo=SLOConfig(latency_objective_s=0.25, availability=99.9,
                          window_s=3600.0),
            warm_pool=WarmPoolConfig(size=2, max_size=4,
                                     telemetry_sized=True),
            mesh=MeshConfig(stages=2, chips_per_stage=2, kind="pipeline",
                            entry_methods=("predict",)),
            remote_payload={"app_id": "a", "deployment": "dep",
                            "files": {"m.py": "x = 1"}},
        )
        d = spec_to_dict(spec)
        import json

        d = json.loads(json.dumps(d))  # must survive the journal's JSON trip
        back = spec_from_dict(d, "a")
        assert back.name == "dep"
        assert back.num_replicas == 3
        assert back.chips_per_replica == 2
        assert back.autoscale is False
        assert back.max_batch == 16 and back.max_wait_ms == 4.5
        assert back.scheduling.max_batch == 8
        assert back.scheduling.tenant_quota == 6
        assert back.slo.latency_objective_s == 0.25
        assert back.warm_pool.size == 2 and back.warm_pool.telemetry_sized
        assert back.mesh.stages == 2
        assert back.mesh.entry_methods == ("predict",)
        assert back.remote_payload["files"]["m.py"] == "x = 1"

    def test_local_only_spec_fails_loudly_at_placement(self):
        spec = DeploymentSpec(name="d", instance_factory=lambda: None)
        back = spec_from_dict(spec_to_dict(spec), "a")
        with pytest.raises(RuntimeError, match="redeploy"):
            back.instance_factory()


# ---------------------------------------------------------------------------
# in-process crash/recovery harness (real websockets)
# ---------------------------------------------------------------------------

REC_MANIFEST = """\
name: Recovery App
id: rec-app
id_emoji: "\U0001F9EA"
description: idempotent arithmetic for recovery traffic
type: tpu-serve
version: 1.0.0
deployments:
  - rec_dep:RecDep
authorized_users: ["*"]
deployment_config:
  rec_dep:
    num_replicas: 2
    min_replicas: 2
    max_replicas: 2
    chips: 2
    autoscale: false
"""

REC_SOURCE = '''\
from bioengine_tpu.rpc import schema_method


class RecDep:
    def __init__(self):
        self.calls = 0

    @schema_method
    async def add(self, a: int, b: int, context=None):
        """Idempotent arithmetic."""
        self.calls += 1
        return {"sum": a + b}
'''


def _no_local_chips() -> ClusterState:
    return ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu"))


def _write_rec_app(tmp_path: Path) -> Path:
    app_dir = tmp_path / "rec-src"
    app_dir.mkdir(exist_ok=True)
    (app_dir / "manifest.yaml").write_text(REC_MANIFEST)
    (app_dir / "rec_dep.py").write_text(REC_SOURCE)
    return app_dir


class DurablePlane:
    """Controller + RpcServer pair that can be crashed (SIGKILL
    equivalent: server torn down, controller object abandoned) and
    restarted on the same port/token against the same journal dir."""

    TOKEN = "recovery-admin-token"

    def __init__(self, tmp_path: Path):
        self.tmp_path = tmp_path
        self.control_dir = tmp_path / "control"
        self.server = None
        self.controller = None
        self.port = None
        self.hosts: list[WorkerHost] = []
        self.dead_controllers: list[ServeController] = []

    async def start(self):
        self.server = RpcServer(host="127.0.0.1", admin_users=["admin"])
        await self.server.start()
        self.port = self.server.port
        self.server.issue_token("admin", is_admin=True,
                                token_value=self.TOKEN)
        self.controller = ServeController(
            _no_local_chips(), health_check_period=3600,
            control_dir=str(self.control_dir),
        )
        self.controller.attach_rpc(self.server, admin_users=["admin"])
        return self

    async def spawn_host(self, host_id, rejoin=True, orphan_grace_s=60.0):
        host = WorkerHost(
            server_url=self.server.url,
            token=self.TOKEN,
            host_id=host_id,
            workspace_dir=self.tmp_path / f"ws-{host_id}",
            rejoin=rejoin,
            orphan_grace_s=orphan_grace_s,
        )
        await host.start()
        host.connection.reconnect_max_backoff_s = 0.3
        self.hosts.append(host)
        return host

    async def deploy(self, app_id="rec-app"):
        builder = AppBuilder(workdir_root=self.tmp_path / "apps")
        built = builder.build(
            app_id=app_id, local_path=_write_rec_app(self.tmp_path)
        )
        await self.controller.deploy(app_id, built.specs)
        return self.controller.apps[app_id].replicas["rec_dep"]

    async def crash(self):
        """SIGKILL-equivalent: no drains, no undeploy, no journal
        goodbye — the server vanishes and the object is abandoned."""
        self.dead_controllers.append(self.controller)
        server, self.server = self.server, None
        await server.stop()
        for sched in self.controller._schedulers.values():
            sched.kill()

    async def restart(self, recover=True, grace_s=3.0):
        server = RpcServer(
            host="127.0.0.1", port=self.port, admin_users=["admin"]
        )
        await server.start()
        server.issue_token("admin", is_admin=True, token_value=self.TOKEN)
        controller = ServeController(
            _no_local_chips(), health_check_period=3600,
            control_dir=str(self.control_dir),
        )
        controller.reconcile_grace_s = grace_s
        if recover:
            await controller.recover()
        controller.attach_rpc(server, admin_users=["admin"])
        self.server = server
        self.controller = controller
        return controller

    async def settle(self, timeout=12.0):
        """Drive health ticks until the reconcile flips ACTIVE."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            await self.controller.health_tick()
            if self.controller.phase == "ACTIVE":
                return
            await asyncio.sleep(0.05)
        raise AssertionError(
            f"reconcile never settled (phase={self.controller.phase}, "
            f"report={self.controller.reconcile_report})"
        )

    async def stop(self):
        for host in self.hosts:
            try:
                await host.stop()
            except Exception:
                pass
        if self.controller is not None:
            try:
                await self.controller.stop()
            except Exception:
                pass
        if self.server is not None:
            await self.server.stop()


@pytest.fixture()
async def plane(tmp_path):
    p = DurablePlane(tmp_path)
    await p.start()
    try:
        yield p
    finally:
        await p.stop()


def _host_leases(plane):
    """host_id -> {chip: replica_id} from the CURRENT controller."""
    return {
        h.host_id: dict(h.chips_in_use)
        for h in plane.controller.cluster_state.hosts.values()
        if h.alive
    }


class TestCrashRecovery:
    async def test_crash_restart_mid_traffic_zero_loss(self, plane):
        """THE acceptance: controller SIGKILLed and restarted
        mid-idempotent-traffic → zero failed requests, all surviving
        replicas re-adopted in place (same host-side instance objects,
        never restarted), chip accounting exact, and a lower-epoch
        verb from the old controller rejected typed."""
        t0 = time.time()
        h1 = await plane.spawn_host("h1")
        h2 = await plane.spawn_host("h2")
        replicas = await plane.deploy()
        assert sorted(r.host_id for r in replicas) == ["h1", "h2"]
        old_epoch = plane.controller.epoch
        rids_before = sorted(r.replica_id for r in replicas)
        instances_before = {
            rid: id(r.instance)
            for host in (h1, h2)
            for rid, r in host.replicas.items()
        }
        calls_before = {
            rid: r.instance.calls
            for host in (h1, h2)
            for rid, r in host.replicas.items()
        }

        failures: list = []
        done = [0]

        async def one_call(i: int) -> None:
            deadline = time.monotonic() + 25
            while True:
                try:
                    handle = plane.controller.get_handle("rec-app")
                    r = await handle.call(
                        "add", i, 1,
                        options=RequestOptions(
                            idempotent=True, deadline_s=5, max_attempts=6,
                            backoff_base_s=0.02, backoff_cap_s=0.2,
                        ),
                    )
                    assert r["sum"] == i + 1
                    done[0] += 1
                    return
                except Exception as e:  # noqa: BLE001 — retry across the restart
                    if time.monotonic() > deadline:
                        failures.append((i, e))
                        return
                    await asyncio.sleep(0.05)

        async def traffic():
            tasks = []
            for i in range(60):
                tasks.append(asyncio.create_task(one_call(i)))
                await asyncio.sleep(0.01)
            await asyncio.gather(*tasks)

        traffic_task = asyncio.create_task(traffic())
        await asyncio.sleep(0.15)          # ~15 requests in flight/done
        await plane.crash()
        await asyncio.sleep(0.2)           # hosts notice: ORPHANED
        assert h1._orphaned_since is not None
        controller = await plane.restart(grace_s=5.0)
        assert controller.phase == "RECOVERING"
        assert controller.epoch == old_epoch + 1
        await plane.settle()
        await traffic_task

        # zero failed idempotent requests across the whole restart
        assert failures == [], failures[:3]
        assert done[0] == 60

        # every surviving replica re-adopted IN PLACE: same ids in the
        # new routing set, same instance objects host-side (and their
        # call counters kept counting — never restarted)
        new_replicas = controller.apps["rec-app"].replicas["rec_dep"]
        assert sorted(r.replica_id for r in new_replicas) == rids_before
        report = controller.reconcile_report
        assert report["adopted"] == 2
        assert report["replaced"] == 0
        assert report["dropped"] == 0
        for host in (h1, h2):
            for rid, r in host.replicas.items():
                assert id(r.instance) == instances_before[rid]
                assert r.instance.calls >= calls_before[rid]

        # chip accounting exact: each host leases exactly its adopted
        # replica's chips, nothing else
        leases = _host_leases(plane)
        for r in new_replicas:
            held = sorted(
                c for c, owner in leases[r.host_id].items()
                if owner == r.replica_id
            )
            assert held == sorted(r.device_ids)
        assert sum(len(l) for l in leases.values()) == sum(
            len(r.device_ids) for r in new_replicas
        )

        # the hosts came back under the NEW epoch, with the orphan gap
        # on the incident timeline
        assert h1.controller_epoch == controller.epoch
        events = {
            e["type"] for e in flight.get_events(
                types=("host.orphaned", "host.rejoined_epoch",
                       "controller.recovering", "controller.recovered"),
                since=t0,
            )
        }
        assert events == {
            "host.orphaned", "host.rejoined_epoch",
            "controller.recovering", "controller.recovered",
        }

        # split-brain fence: the dead controller's epoch is rejected
        # typed on every stamped verb
        victim = next(iter(h1.replicas))
        with pytest.raises(StaleEpochError):
            await h1.drain_replica(victim, timeout_s=0.1, epoch=old_epoch)
        with pytest.raises(StaleEpochError):
            await h1.stop_replica(victim, epoch=old_epoch)
        assert h1.replicas[victim].state.value in (
            "HEALTHY", "TESTING"
        )  # the stale verbs did NOT drain/stop anything
        fenced = flight.get_events(types=("host.fenced",), since=t0)
        assert len(fenced) == 2

    async def test_unknown_replica_is_dropped(self, plane):
        """Reconcile edge: a host reports a replica the journal has no
        intent for (here: the journal was wiped — the 'absent from the
        journal' case). Decision pinned: DROP — the journal is the
        intent of record; the host discards its copy and the chips
        lease nothing."""
        h1 = await plane.spawn_host("h1")
        await plane.deploy()
        assert len(h1.replicas) >= 1
        await plane.crash()
        # wipe the journal: the restarted controller knows nothing
        for f in plane.control_dir.iterdir():
            f.unlink()
        controller = await plane.restart(recover=True, grace_s=1.0)
        assert controller.phase == "ACTIVE"  # no journaled apps
        # the host rejoins and is told to drop its now-unowned replica
        deadline = time.monotonic() + 8
        while h1.replicas and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        assert h1.replicas == {}
        assert controller.apps == {}
        leases = _host_leases(plane)
        assert all(not l for l in leases.values()), leases

    async def test_replace_from_spec_when_no_survivors(self, plane):
        """Reconcile edge: journaled intent but every host that served
        it died with the controller — the diff is the whole deployment,
        re-placed from the journaled spec on whatever capacity joins."""
        h1 = await plane.spawn_host("h1")
        await plane.deploy()
        await plane.crash()
        # the serving host dies too — nothing survives to adopt
        h1.rejoin = False
        h1.connection.auto_reconnect = False
        h1.connection._closing = True
        await h1.connection._abort_connection()
        controller = await plane.restart(grace_s=1.5)
        assert controller.phase == "RECOVERING"
        # a FRESH host joins with no warm replicas at all
        await plane.spawn_host("h3")
        await plane.settle()
        report = controller.reconcile_report
        assert report["adopted"] == 0
        assert report["replaced"] == 2
        replicas = controller.apps["rec-app"].replicas["rec_dep"]
        assert len(replicas) == 2
        assert all(r.host_id == "h3" for r in replicas)
        # the re-placed deployment serves
        handle = controller.get_handle("rec-app")
        r = await handle.call("add", 20, 22)
        assert r["sum"] == 42

    async def test_pinned_intent_topped_up_after_blocked_settle(
        self, plane
    ):
        """Reconcile edge: the grace window closes while capacity is
        still gone, so the settle's re-place is blocked and the app
        goes RUNNING under-provisioned. That must not be permanent:
        when capacity returns, the health tick restores a PINNED
        (autoscale=false) deployment to its full ``num_replicas``
        intent — not just the ``min_replicas`` floor."""
        h1 = await plane.spawn_host("h1")
        await plane.deploy()
        await plane.crash()
        h1.rejoin = False
        h1.connection.auto_reconnect = False
        h1.connection._closing = True
        await h1.connection._abort_connection()
        controller = await plane.restart(grace_s=0.4)
        spec = controller.apps["rec-app"].specs["rec_dep"]
        # pinned intent ABOVE the min floor: the old min-only top-up
        # would stop one short
        spec.min_replicas = 1
        assert not spec.autoscale and spec.num_replicas == 2
        await asyncio.sleep(0.5)          # let the grace window lapse
        await controller.health_tick()    # settles; re-place blocked
        assert controller.phase == "ACTIVE"
        app = controller.apps["rec-app"]
        assert len(app.replicas["rec_dep"]) == 0
        # capacity returns AFTER settle
        await plane.spawn_host("h5")
        await controller.health_tick()
        assert len(app.replicas["rec_dep"]) == 2
        handle = controller.get_handle("rec-app")
        r = await handle.call("add", 1, 2)
        assert r["sum"] == 3

    async def test_double_restart_recovers_from_recovering_snapshot(
        self, plane
    ):
        """Reconcile edge: the controller crashes AGAIN mid-recovery.
        recover() compacts a snapshot flagged recovering=True before
        reconcile settles; the third life must recover the same intent
        from that snapshot."""
        h1 = await plane.spawn_host("h1")
        await plane.deploy()
        await plane.crash()
        # keep the host away so the second life CANNOT settle
        h1.rejoin = False
        h1.connection.auto_reconnect = False
        h1.connection._closing = True
        await h1.connection._abort_connection()
        second = await plane.restart(grace_s=60.0)
        assert second.phase == "RECOVERING"
        snap = second.journal._read_snapshot()
        assert snap["recovering"] is True
        assert "rec-app" in snap["apps"]
        # second crash, mid-recovery
        await plane.crash()
        third = await plane.restart(grace_s=1.5)
        assert third.epoch == 3
        assert third.phase == "RECOVERING"
        assert "rec-app" in third.apps
        spec = third.apps["rec-app"].specs["rec_dep"]
        assert spec.num_replicas == 2 and spec.chips_per_replica == 2
        await plane.spawn_host("h4")
        await plane.settle()
        assert third.apps["rec-app"].status == "RUNNING"
        assert len(third.apps["rec-app"].replicas["rec_dep"]) == 2

    async def test_undeploy_and_scale_survive_restart(self, plane):
        """The journal replays undeploy and autoscale intent: an app
        undeployed before the crash must NOT be resurrected."""
        await plane.spawn_host("h1")
        await plane.deploy()
        await plane.controller.undeploy("rec-app")
        await plane.crash()
        controller = await plane.restart(grace_s=1.0)
        assert controller.phase == "ACTIVE"
        assert "rec-app" not in controller.apps


class TestOrphanMode:
    async def test_orphan_grace_self_drain(self, plane):
        """The orphaned-host gap: controller gone and never coming
        back → after BIOENGINE_ORPHAN_GRACE_S the host drains and
        stops its replicas (chips stop serving unowned intent), with
        the host.orphaned / host.orphan_drain evidence pair."""
        t0 = time.time()
        h1 = await plane.spawn_host("h1", orphan_grace_s=0.6)
        await plane.deploy()
        served = dict(h1.replicas)
        assert served
        await plane.crash()
        deadline = time.monotonic() + 8
        while not h1.orphan_drained and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        assert h1.orphan_drained
        assert h1.replicas == {}
        for r in served.values():
            assert r.state.value == "STOPPED"
        types = [
            e["type"]
            for e in flight.get_events(
                types=("host.orphaned", "host.orphan_drain"), since=t0
            )
        ]
        assert types.count("host.orphaned") == 1
        assert types.count("host.orphan_drain") == 1

    async def test_rejoin_within_grace_keeps_replicas(self, plane):
        """The pair event: a host that rejoins inside the grace window
        keeps serving its warm replicas and stamps the rejoin with the
        epoch it came back under."""
        t0 = time.time()
        h1 = await plane.spawn_host("h1", orphan_grace_s=30.0)
        await plane.deploy()
        instances = {rid: id(r.instance) for rid, r in h1.replicas.items()}
        await plane.crash()
        await asyncio.sleep(0.1)
        assert h1._orphaned_since is not None
        await plane.restart(grace_s=4.0)
        await plane.settle()
        assert h1._orphaned_since is None      # watchdog disarmed
        assert not h1.orphan_drained
        assert {rid: id(r.instance) for rid, r in h1.replicas.items()} == (
            instances
        )
        rejoined = flight.get_events(
            types=("host.rejoined_epoch",), since=t0
        )
        assert rejoined
        attrs = rejoined[-1]["attrs"]
        assert attrs["epoch"] == plane.controller.epoch
        assert attrs["orphan_gap_s"] > 0


class TestEpochFencing:
    async def test_check_epoch_ratchet_and_reject(self, tmp_path):
        host = WorkerHost(
            server_url="ws://127.0.0.1:1/ws", host_id="fence-h",
            workspace_dir=tmp_path, orphan_grace_s=0,
        )
        host._check_epoch(None, "start_replica")   # legacy: accepted
        assert host.controller_epoch == 0
        host._check_epoch(3, "start_replica")
        assert host.controller_epoch == 3
        host._check_epoch(3, "drain_replica")      # equal: fine
        with pytest.raises(StaleEpochError) as exc:
            host._check_epoch(2, "drain_replica")
        assert exc.value.seen_epoch == 3
        assert exc.value.got_epoch == 2
        # classified APPLICATION (terminal), never failed over
        from bioengine_tpu.serving.errors import (
            FailureKind,
            classify_exception,
        )

        assert classify_exception(exc.value) is FailureKind.APPLICATION

    async def test_controller_stamps_epoch_on_host_verbs(self, plane):
        await plane.spawn_host("h1")
        seen = {}
        orig = plane.server.call_service_method

        async def spy(full_id, method, args=(), kwargs=None, **kw):
            if method in ("start_replica", "drain_replica", "stop_replica"):
                seen[method] = (kwargs or {}).get("epoch")
            return await orig(full_id, method, args, kwargs, **kw)

        plane.server.call_service_method = spy
        await plane.deploy()
        await plane.controller.undeploy("rec-app")
        assert seen["start_replica"] == plane.controller.epoch
        assert seen["stop_replica"] == plane.controller.epoch

    async def test_epoch_not_stamped_on_pre_epoch1_host(self, tmp_path):
        """Mixed-version fleet: a host that never declared the
        ``epoch1`` capability gets the LEGACY verb signature. Stamping
        the kwarg unconditionally would TypeError every placement on
        un-upgraded hosts the moment the controller is upgraded first
        in a rolling deploy."""
        calls = []

        class FakeRpc:
            def __init__(self, supports):
                self.supports = supports

            def service_peer_supports(self, service_id, capability):
                return self.supports

            async def call_service_method(
                self, service_id, method, args=(), kwargs=None, **kw
            ):
                calls.append((method, dict(kwargs or {})))
                return {}

        c = ServeController(
            _no_local_chips(), health_check_period=3600,
            control_dir=str(tmp_path / "control"),
        )
        c._rpc_server = FakeRpc(False)
        await c._call_host("svc", "start_replica", "rid")
        assert "epoch" not in calls[-1][1]

        c._rpc_server = FakeRpc(True)
        await c._call_host("svc", "start_replica", "rid")
        assert calls[-1][1]["epoch"] == c.epoch


class TestMeshRecovery:
    async def test_mesh_shards_reattach_to_rebuilt_mesh(self, plane):
        """Tentpole mesh leg: a 2-host pipeline mesh survives the
        controller restart — both shard hosts rejoin reporting their
        ``mesh_shard`` inventory, the controller rebuilds ONE
        MeshReplica around them (same mesh id, chips re-leased under
        it, shard instances untouched) and serving output parity
        holds."""
        import numpy as np
        from test_mesh import (
            MESH_MANIFEST,
            _write_mesh_app,
            make_input,
            reference_forward,
        )

        h1 = await plane.spawn_host("h1")
        h2 = await plane.spawn_host("h2")
        builder = AppBuilder(workdir_root=plane.tmp_path / "apps")
        built = builder.build(
            app_id="mesh-app",
            local_path=_write_mesh_app(plane.tmp_path, MESH_MANIFEST),
        )
        await plane.controller.deploy("mesh-app", built.specs)
        mesh = plane.controller.apps["mesh-app"].replicas["mesh_dep"][0]
        mesh_rid = mesh.replica_id
        assert mesh.plan.cross_host
        shard_instances = {
            rid: id(r.instance)
            for host in (h1, h2)
            for rid, r in host.replicas.items()
        }
        assert len(shard_instances) == 2

        await plane.crash()
        await asyncio.sleep(0.15)
        controller = await plane.restart(grace_s=6.0)
        await plane.settle()

        replicas = controller.apps["mesh-app"].replicas["mesh_dep"]
        assert len(replicas) == 1
        rebuilt = replicas[0]
        assert rebuilt.replica_id == mesh_rid
        assert rebuilt is not mesh            # a NEW controller-side object
        assert sorted(rebuilt.plan.hosts) == ["h1", "h2"]
        report = controller.reconcile_report
        assert report["mesh_rebuilt"] == 1
        assert report["replaced"] == 0
        # shard chips re-leased under the mesh id, shard instances kept
        for host_id in ("h1", "h2"):
            rec = controller.cluster_state.hosts[host_id]
            assert list(rec.chips_in_use.values()) == [mesh_rid] * 2
        for host in (h1, h2):
            for rid, r in host.replicas.items():
                assert id(r.instance) == shard_instances[rid]

        x = make_input()
        handle = controller.get_handle("mesh-app", "mesh_dep")
        out = np.asarray(await handle.call("predict", x))
        np.testing.assert_allclose(
            out, reference_forward(x), rtol=1e-4, atol=1e-5
        )


class TestSurplusMeshSweep:
    async def test_surplus_complete_mesh_swept_at_settle(self, tmp_path):
        """Intent says ONE mesh but TWO complete warm meshes report at
        recovery (the old controller died between planning a
        replacement and stopping the degraded original). The second
        mesh's early stages were answered "kept" before the surplus
        was knowable — the settle sweep must stop them host-side, not
        leave them serving unrouted on leased chips forever."""
        control = tmp_path / "control"
        spec = DeploymentSpec(
            name="dep", instance_factory=lambda: None,
            num_replicas=1, min_replicas=1, chips_per_replica=2,
            autoscale=False, mesh=MeshConfig(stages=2),
        )
        seed = ControlJournal(control)
        seed.mint_epoch()
        seed.append(
            "deploy",
            {"app_id": "m-app", "specs": [spec_to_dict(spec)],
             "acl": None},
        )
        controller = ServeController(
            _no_local_chips(), health_check_period=3600,
            control_dir=str(control),
        )
        stops = []

        async def fake_call_host(service_id, verb, *args, **kwargs):
            stops.append((service_id, verb, args))
            return {}

        controller._call_host = fake_call_host
        await controller.recover()
        assert controller.phase == "RECOVERING"
        for n in range(1, 5):
            controller.cluster_state.register_host(
                f"fh{n}", f"svc-fh{n}", {"n_chips": 2}
            )

        def report(mesh_rid, stage, host_n):
            return controller._adopt_reported_replica(
                f"fh{host_n}", f"svc-fh{host_n}",
                {
                    "app_id": "m-app", "deployment": "dep",
                    "replica_id": f"{mesh_rid}-s{stage}",
                    "state": "healthy",
                    "device_ids": [0, 1],
                    "mesh_shard": {
                        "mesh_replica_id": mesh_rid, "stage": stage,
                    },
                },
            )

        # mesh A completes first and satisfies the intent
        assert report("meshA", 0, 1)
        assert report("meshA", 1, 2)
        assert len(controller.apps["m-app"].replicas["dep"]) == 1
        # mesh B: stage 0 is answered "kept" (siblings may complete
        # it); stage 1 reveals the surplus and is told to drop
        assert report("meshB", 0, 3)
        assert not report("meshB", 1, 4)
        assert "meshB" in controller._surplus_mesh_shards
        await controller._reconcile_settle()
        # the already-kept stage-0 shard was stopped host-side
        assert ("svc-fh3", "stop_replica", ("meshB-s0",)) in stops
        assert controller._surplus_mesh_shards == {}
        assert controller.reconcile_report["dropped"] == 1
        assert controller.reconcile_report["mesh_rebuilt"] == 1


class TestReReportRelease:
    """A re-registering host gets a FRESH HostRecord (empty lease
    table): every "keep your replica" answer during recovery must
    re-establish the chip lease, or the ledger shows the devices free
    and a later placement double-leases them."""

    def _recovered_controller(self, tmp_path, spec):
        control = tmp_path / "control"
        seed = ControlJournal(control)
        seed.mint_epoch()
        seed.append(
            "deploy",
            {"app_id": "rr-app", "specs": [spec_to_dict(spec)],
             "acl": None},
        )
        return ServeController(
            _no_local_chips(), health_check_period=3600,
            control_dir=str(control),
        )

    async def test_rebuilt_mesh_shard_rereport_releases_chips(
        self, tmp_path
    ):
        spec = DeploymentSpec(
            name="dep", instance_factory=lambda: None,
            num_replicas=1, min_replicas=1, chips_per_replica=2,
            autoscale=False, mesh=MeshConfig(stages=2),
        )
        controller = self._recovered_controller(tmp_path, spec)

        async def fake_call_host(*a, **k):
            return {}

        controller._call_host = fake_call_host
        await controller.recover()
        for n in (1, 2):
            controller.cluster_state.register_host(
                f"fh{n}", f"svc-fh{n}", {"n_chips": 2}
            )

        def report(stage, host_n):
            return controller._adopt_reported_replica(
                f"fh{host_n}", f"svc-fh{host_n}",
                {
                    "app_id": "rr-app", "deployment": "dep",
                    "replica_id": f"meshA-s{stage}",
                    "state": "healthy", "device_ids": [0, 1],
                    "mesh_shard": {
                        "mesh_replica_id": "meshA", "stage": stage,
                    },
                },
            )

        assert report(0, 1) and report(1, 2)   # mesh rebuilt
        # host fh1 blips and re-registers: fresh record, empty leases
        controller.cluster_state.register_host(
            "fh1", "svc-fh1", {"n_chips": 2}
        )
        assert controller.cluster_state.hosts["fh1"].chips_in_use == {}
        # the re-report is kept AND the lease is restored
        assert report(0, 1)
        assert controller.cluster_state.hosts["fh1"].chips_in_use == {
            0: "meshA", 1: "meshA",
        }

    async def test_replica_rereport_releases_chips(self, tmp_path):
        spec = DeploymentSpec(
            name="dep", instance_factory=lambda: None,
            num_replicas=1, min_replicas=1, chips_per_replica=2,
            autoscale=False,
            remote_payload={"app_id": "rr-app", "deployment": "dep",
                            "files": {}},
        )
        controller = self._recovered_controller(tmp_path, spec)
        await controller.recover()
        controller.cluster_state.register_host(
            "fh1", "svc-fh1", {"n_chips": 2}
        )
        info = {
            "app_id": "rr-app", "deployment": "dep",
            "replica_id": "rep-1", "state": "HEALTHY",
            "device_ids": [0, 1],
        }
        assert controller._adopt_reported_replica("fh1", "svc-fh1", info)
        # blip re-register: fresh record, empty leases
        controller.cluster_state.register_host(
            "fh1", "svc-fh1", {"n_chips": 2}
        )
        assert controller._adopt_reported_replica("fh1", "svc-fh1", info)
        assert controller.cluster_state.hosts["fh1"].chips_in_use == {
            0: "rep-1", 1: "rep-1",
        }
        # the same replica id reported by a DIFFERENT host is dropped
        controller.cluster_state.register_host(
            "fh9", "svc-fh9", {"n_chips": 2}
        )
        assert not controller._adopt_reported_replica(
            "fh9", "svc-fh9", info
        )


class TestJournalCli:
    def test_debug_journal_offline_dump_redacts_tokens(self, tmp_path):
        """``bioengine debug journal`` reads a (dead) controller's
        directory with no server and masks secret-shaped payload
        values — the runbook's second read after the epoch."""
        from click.testing import CliRunner

        from bioengine_tpu.cli.cli import main as cli_main

        j = ControlJournal(tmp_path, snapshot_every=1000)
        j.mint_epoch()
        j.append(
            "deploy",
            {
                "app_id": "demo",
                "specs": [
                    {
                        "name": "dep",
                        "num_replicas": 2,
                        "remote_payload": {
                            "env_vars": {"API_TOKEN": "sup3rsecret"},
                            "files": {"m.py": "sourcecode here"},
                        },
                    }
                ],
                "acl": None,
            },
        )
        result = CliRunner().invoke(
            cli_main, ["debug", "journal", "--dir", str(tmp_path)]
        )
        assert result.exit_code == 0, result.output
        assert "demo" in result.output
        assert "sup3rsecret" not in result.output
        assert "sourcecode" not in result.output
        assert "***redacted***" in result.output

    def test_debug_journal_missing_dir_errors(self):
        from click.testing import CliRunner

        from bioengine_tpu.cli.cli import main as cli_main

        result = CliRunner().invoke(
            cli_main, ["debug", "journal", "--dir", "/nonexistent-xyz"]
        )
        assert result.exit_code != 0


class TestManagerRecoveryAdoption:
    async def test_record_recovery_reattaches_to_journaled_intent(
        self, plane
    ):
        """Worker-restart collision: the control journal AND the apps
        manager's record file cover the SAME app. Life 2's record
        recovery must re-attach the rebuilt app to the journal-
        recovered controller intent — live instance factories swapped
        in, service proxy registered, record kept — instead of dying
        on 'already deployed' and silently dropping the app from the
        state file."""
        from bioengine_tpu.apps.manager import AppsManager
        from bioengine_tpu.serving.journal import PayloadInstanceFactory
        from bioengine_tpu.utils.permissions import create_context

        admin = create_context("admin")
        state_file = plane.tmp_path / "deployed.json"
        app_dir = _write_rec_app(plane.tmp_path)
        await plane.spawn_host("h1")
        manager1 = AppsManager(
            controller=plane.controller, server=plane.server,
            builder=AppBuilder(workdir_root=plane.tmp_path / "apps"),
            admin_users=["admin"], state_file=state_file,
            can_scale_out=True,   # capacity comes from joined hosts
        )
        await manager1.deploy_app(
            local_path=str(app_dir), app_id="rec-app", context=admin
        )
        assert "rec-app" in manager1.records

        await plane.crash()
        await asyncio.sleep(0.15)
        controller2 = await plane.restart(grace_s=6.0)
        manager2 = AppsManager(
            controller=controller2, server=plane.server,
            builder=AppBuilder(workdir_root=plane.tmp_path / "apps2"),
            admin_users=["admin"], state_file=state_file,
        )
        recovered = await manager2.recover_deployed_applications()
        assert len(recovered) == 1     # no 'already deployed' collision
        assert "rec-app" in manager2.records
        app = controller2.apps["rec-app"]
        # reconcile still owns the app — record recovery did not
        # short-circuit the RECOVERING phase
        assert app.status == "RECOVERING"
        # the rebuilt specs' LIVE factories replaced the payload stubs
        spec = app.specs["rec_dep"]
        assert not isinstance(spec.instance_factory, PayloadInstanceFactory)
        await plane.settle()
        # the rejoined host's warm replicas were adopted, and the app
        # serves through the re-registered service proxy
        assert len(app.replicas["rec_dep"]) == 2
        out = await plane.server.call_service_method(
            recovered[0]["service_id"], "add",
            kwargs={"a": 2, "b": 3},
            caller=plane.server.validate_token(
                plane.server.issue_token("anyone")
            ),
        )
        assert out["sum"] == 5


class TestWorkerStartRecovers:
    async def test_production_worker_start_replays_journal(
        self, tmp_path, monkeypatch
    ):
        """The PRODUCTION startup path recovers: BioEngineWorker.start
        with ``BIOENGINE_CONTROL_DIR`` set must replay the previous
        life's journaled intent into the RECOVERING phase before the
        router verbs exist — not just the test harnesses that call
        ``recover()`` by hand."""
        from bioengine_tpu.worker.worker import BioEngineWorker

        control_dir = tmp_path / "control"
        seed = ControlJournal(control_dir)
        seed.mint_epoch()
        spec = DeploymentSpec(
            name="dep", instance_factory=lambda: None, num_replicas=1
        )
        seed.append(
            "deploy",
            {
                "app_id": "ghost-app",
                "specs": [spec_to_dict(spec)],
                "acl": None,
            },
        )
        monkeypatch.setenv("BIOENGINE_CONTROL_DIR", str(control_dir))
        w = BioEngineWorker(
            mode="single-machine",
            workspace_dir=tmp_path / "ws",
            admin_users=["admin"],
            log_file="off",
        )
        await w.start()
        try:
            assert "ghost-app" in w.controller.apps
            assert w.controller.apps["ghost-app"].status == "RECOVERING"
            assert w.controller.phase == "RECOVERING"
            # the second life out-epochs the seed life's epoch 1
            assert w.controller.epoch == 2
        finally:
            await w.stop()


# ---------------------------------------------------------------------------
# real subprocess: an actual controller process SIGKILLed + restarted
# ---------------------------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def _wait_marker(proc, marker: str, timeout: float = 60.0) -> str:
    deadline = time.monotonic() + timeout
    while True:
        remaining = deadline - time.monotonic()
        assert remaining > 0, f"'{marker}' never printed"
        line = await asyncio.wait_for(
            proc.stdout.readline(), timeout=remaining
        )
        assert line, f"controller proc exited before '{marker}'"
        text = line.decode().strip()
        if text.startswith(marker):
            return text


@pytest.mark.slow
class TestRealSubprocessCrash:
    async def test_kill_and_restart_real_controller_process(self, tmp_path):
        """An ACTUAL controller process (RpcServer + journaled
        ServeController) is SIGKILLed and restarted on the same port +
        journal dir; the in-test worker host rides through orphaned,
        rejoins the second life, and its replica is re-adopted without
        a restart."""
        port = _free_port()
        control_dir = tmp_path / "control"
        app_dir = _write_rec_app(tmp_path)
        token = "subproc-admin-token"
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "BIOENGINE_ADMIN_TOKEN": token,
            "BIOENGINE_RECONCILE_GRACE_S": "10",
        }

        async def spawn(extra):
            return await asyncio.create_subprocess_exec(
                sys.executable, "-m",
                "bioengine_tpu.testing.controller_proc",
                "--port", str(port), "--control-dir", str(control_dir),
                *extra,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL,
                env=env,
            )

        proc1 = await spawn(
            ["--deploy-dir", str(app_dir), "--app-id", "rec-app"]
        )
        host = None
        proc2 = None
        try:
            ready = await _wait_marker(proc1, "READY")
            assert "epoch=1" in ready
            host = WorkerHost(
                server_url=f"ws://127.0.0.1:{port}/ws",
                token=token,
                host_id="sub-h1",
                workspace_dir=tmp_path / "ws-sub-h1",
                rejoin=True,
                orphan_grace_s=120.0,
            )
            await host.start()
            host.connection.reconnect_max_backoff_s = 0.3
            await _wait_marker(proc1, "DEPLOYED")
            assert len(host.replicas) == 2
            instances = {
                rid: id(r.instance) for rid, r in host.replicas.items()
            }

            client = await connect_to_server(
                {"server_url": f"ws://127.0.0.1:{port}/ws", "token": token}
            )
            r = await client.call(
                "serve-router", "route_call", "rec-app", "rec_dep",
                "add", [2, 3], {},
            )
            assert r["sum"] == 5
            await client.disconnect()

            # SIGKILL the real process mid-life
            proc1.send_signal(signal.SIGKILL)
            await proc1.wait()
            deadline = time.monotonic() + 10
            while host._orphaned_since is None and (
                time.monotonic() < deadline
            ):
                await asyncio.sleep(0.05)
            assert host._orphaned_since is not None

            proc2 = await spawn(["--recover"])
            ready2 = await _wait_marker(proc2, "READY")
            assert "epoch=2" in ready2 and "phase=RECOVERING" in ready2
            reconciled = await _wait_marker(proc2, "RECONCILED")
            assert "adopted=2" in reconciled
            assert "replaced=0" in reconciled

            # the host kept its instances (no restart) and serves
            # under the new epoch
            assert {
                rid: id(r.instance) for rid, r in host.replicas.items()
            } == instances
            deadline = time.monotonic() + 10
            while host.controller_epoch < 2 and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert host.controller_epoch == 2

            client = await connect_to_server(
                {"server_url": f"ws://127.0.0.1:{port}/ws", "token": token}
            )
            r = await client.call(
                "serve-router", "route_call", "rec-app", "rec_dep",
                "add", [40, 2], {},
            )
            assert r["sum"] == 42
            await client.disconnect()
        finally:
            if host is not None:
                await host.stop()
            for proc in (proc1, proc2):
                if proc is not None and proc.returncode is None:
                    proc.kill()
                    await proc.wait()
