"""Decode path units: paged KV cache, step-level continuous batching,
and the golden-activation pin on the toy decoder.

Three layers, bottom-up:

- ``PagedKVCache``: block-table allocation, append across block
  boundaries, gather round-trip, LRU eviction of idle sequences (typed
  ``KVCacheFull`` when everything is pinned).
- ``DecodeLoop`` over a pure-python deterministic backend: co-batching
  occupancy, no head-of-line blocking (a short generation joins and
  leaves a running batch), the interactive admission reserve,
  ``resume_from`` emitting exactly the missing suffix, and consumer
  cancellation releasing the slot and the backend state.
- ``TestGoldenDecoder``: the jax decoder math pinned bit-for-bit
  against ``tests/fixtures_golden_decoder.npz`` — an INDEPENDENT numpy
  implementation (see ``tests/generate_golden_decoder.py``) — through
  prefill logits, one decode step's logits, the engine's 32-token
  greedy continuation, and the dp-mesh parity unlock (same tokens on
  1 chip and a forced 4-device CPU mesh).
"""

from __future__ import annotations

import asyncio
import time
from pathlib import Path

import numpy as np
import pytest

from bioengine_tpu.runtime.kv_cache import KVCacheFull, PagedKVCache
from bioengine_tpu.serving.decode import DecodeLoop
from bioengine_tpu.utils import flight

pytestmark = pytest.mark.integration

FIXTURE = Path(__file__).parent / "fixtures_golden_decoder.npz"


# ---------------------------------------------------------------------------
# paged KV cache
# ---------------------------------------------------------------------------


class TestPagedKVCache:
    def _rand_kv(self, rng, n_layers, T, n_heads, head_dim):
        return (
            rng.normal(size=(n_layers, T, n_heads, head_dim)).astype(np.float32),
            rng.normal(size=(n_layers, T, n_heads, head_dim)).astype(np.float32),
        )

    def test_prefill_gather_roundtrip(self):
        """KV written as a prefix comes back exactly through the
        block-table indirection, zero-padded to the bucket."""
        rng = np.random.default_rng(0)
        cache = PagedKVCache(2, 4, 16, num_blocks=8, block_size=4)
        k, v = self._rand_kv(rng, 2, 6, 4, 16)  # 6 tokens -> 2 blocks
        cache.write_prefill("s", k, v)
        assert cache.sequence_length("s") == 6
        K, V, lengths = cache.gather(["s"], pad_len=8)
        assert K.shape == (2, 1, 8, 4, 16)
        np.testing.assert_array_equal(K[:, 0, :6], k)
        np.testing.assert_array_equal(V[:, 0, :6], v)
        assert not K[:, 0, 6:].any()  # padding stays zero
        assert lengths.tolist() == [6]

    def test_append_crosses_block_boundary(self):
        rng = np.random.default_rng(1)
        cache = PagedKVCache(1, 2, 8, num_blocks=8, block_size=4)
        k, v = self._rand_kv(rng, 1, 3, 2, 8)
        cache.write_prefill("s", k, v)
        steps = []
        for _ in range(4):  # 3 -> 7 tokens: crosses the 4-token block edge
            ks = rng.normal(size=(1, 2, 8)).astype(np.float32)
            vs = rng.normal(size=(1, 2, 8)).astype(np.float32)
            cache.append("s", ks, vs)
            steps.append((ks, vs))
        assert cache.sequence_length("s") == 7
        K, V, _ = cache.gather(["s"], pad_len=8)
        for i, (ks, vs) in enumerate(steps):
            np.testing.assert_array_equal(K[:, 0, 3 + i], ks)
            np.testing.assert_array_equal(V[:, 0, 3 + i], vs)

    def test_free_returns_blocks_and_is_idempotent(self):
        rng = np.random.default_rng(2)
        cache = PagedKVCache(1, 2, 8, num_blocks=4, block_size=4)
        k, v = self._rand_kv(rng, 1, 8, 2, 8)
        cache.write_prefill("s", k, v)
        assert cache.stats["blocks_in_use"] == 2
        assert cache.free("s") == 2
        assert cache.free("s") == 0
        assert cache.stats["blocks_in_use"] == 0
        assert len(cache) == 0

    def test_eviction_reclaims_idle_lru_victim(self):
        """Pool exhaustion evicts the least-recently-touched UNPINNED
        sequence (flight-marked); an all-pinned pool sheds typed."""
        rng = np.random.default_rng(3)
        cache = PagedKVCache(1, 2, 8, num_blocks=2, block_size=4)
        k, v = self._rand_kv(rng, 1, 4, 2, 8)
        cache.write_prefill("a", k, v)
        cache.unpin("a")  # idle: eviction candidate
        t0 = time.time()
        cache.write_prefill("b", k, v)  # needs the pool's other block... fine
        # third sequence must evict 'a'
        cache.write_prefill("c", k, v)
        assert not cache.has_sequence("a")
        assert cache.has_sequence("b") and cache.has_sequence("c")
        evs = flight.get_events(types=("decode.kv_evict",), since=t0)
        assert evs and evs[-1]["attrs"]["seq"] == "a"
        # b and c are pinned: a fourth admission has no victim
        with pytest.raises(KVCacheFull):
            cache.write_prefill("d", k, v)


# ---------------------------------------------------------------------------
# decode loop over a deterministic pure-python backend
# ---------------------------------------------------------------------------


class _FakeBackend:
    """Deterministic toy decoder: token i of a sequence is
    ``(sum(prompt) + i) % 97``. Tracks finish() calls so tests can
    assert resource release."""

    chip_width = 2  # exercised by fair-share accounting

    def __init__(self, step_s: float = 0.0):
        self.step_s = step_s
        self.state: dict[str, list[int]] = {}
        self.finished: list[str] = []

    def prefill(self, seq_id, tokens):
        import time as _t

        if self.step_s:
            _t.sleep(self.step_s)
        base = sum(tokens) % 97
        self.state[seq_id] = [base, 1]
        return base

    def step(self, seq_ids, tokens):
        import time as _t

        if self.step_s:
            _t.sleep(self.step_s)
        out = []
        for sid in seq_ids:
            base, n = self.state[sid]
            out.append((base + n) % 97)
            self.state[sid][1] += 1
        return out

    def finish(self, seq_id):
        self.state.pop(seq_id, None)
        self.finished.append(seq_id)


def _expected(prompt, n):
    base = sum(prompt) % 97
    return [(base + i) % 97 for i in range(n)]


async def _drain(stream):
    return [t async for t in stream.tokens()]


@pytest.mark.anyio
class TestDecodeLoop:
    async def test_tokens_are_deterministic_and_complete(self):
        loop = DecodeLoop(_FakeBackend(), name="t-det", max_active=4)
        try:
            toks = await _drain(loop.submit([1, 2, 3], 8))
            assert toks == _expected([1, 2, 3], 8)
        finally:
            await loop.close()

    async def test_cobatching_occupancy(self):
        """Concurrent sequences share decode steps: N streams drain in
        ~L steps, not N*L, and the occupancy window shows the co-batch."""
        be = _FakeBackend()
        loop = DecodeLoop(be, name="t-occ", max_active=4, interactive_reserve=0)
        try:
            streams = [loop.submit([i], 12, klass="bulk") for i in range(4)]
            results = await asyncio.gather(*(_drain(s) for s in streams))
            for i, toks in enumerate(results):
                assert toks == _expected([i], 12)
            s = loop.stats
            assert s["occupancy"]["max"] == 4
            # 4 sequences x 12 tokens on a full co-batch: ~11 steps
            # (token 1 comes from prefill), nowhere near 4 x 11 serial
            assert s["steps"] <= 2 * 11
            assert be.finished and len(be.finished) == 4
        finally:
            await loop.close()

    async def test_short_generation_not_blocked_by_long(self):
        """THE continuous-batching contract: a short sequence submitted
        while a long one is mid-generation joins the RUNNING batch
        (mid-batch join flag), finishes, and leaves — while the long one
        is still going. Request-level batching would chain it to the
        long one's completion."""
        be = _FakeBackend(step_s=0.001)
        loop = DecodeLoop(be, name="t-hol", max_active=4)
        try:
            long_stream = loop.submit([5], 200, klass="bulk")
            long_task = asyncio.ensure_future(_drain(long_stream))
            while loop.stats["tokens"] < 5:  # long is visibly generating
                await asyncio.sleep(0.001)
            short = loop.submit([9], 4, klass="interactive")
            toks = await _drain(short)
            assert toks == _expected([9], 4)
            assert short.joined_mid_batch
            assert not long_task.done()  # no head-of-line blocking
            assert await long_task == _expected([5], 200)
            assert short.chip_seconds > 0  # fair share was booked
        finally:
            await loop.close()

    async def test_interactive_reserve_blocks_bulk_admits_interactive(self):
        """With the reserve, bulk can never occupy the whole batch:
        the last slot stays empty for interactive while bulk waits."""
        be = _FakeBackend(step_s=0.001)
        loop = DecodeLoop(be, name="t-res", max_active=2, interactive_reserve=1)
        try:
            b1 = asyncio.ensure_future(_drain(loop.submit([1], 100, klass="bulk")))
            while loop.stats["tokens"] < 3:
                await asyncio.sleep(0.001)
            b2 = asyncio.ensure_future(_drain(loop.submit([2], 100, klass="bulk")))
            await asyncio.sleep(0.02)
            s = loop.stats
            assert s["active"] == 1 and s["waiting"] == 1  # reserve held
            toks = await _drain(loop.submit([3], 4, klass="interactive"))
            assert toks == _expected([3], 4)  # took the reserved slot
            assert await b1 == _expected([1], 100)
            assert await b2 == _expected([2], 100)  # admitted after b1 left
        finally:
            await loop.close()

    async def test_resume_from_emits_exact_suffix(self):
        loop = DecodeLoop(_FakeBackend(), name="t-res2", max_active=2)
        try:
            full = await _drain(loop.submit([7, 7], 10))
            resumed = await _drain(loop.submit([7, 7], 10, resume_from=6))
            assert resumed == full[6:]
        finally:
            await loop.close()

    async def test_consumer_break_releases_slot_and_backend(self):
        """A consumer abandoning its stream (disconnect) retires the
        sequence at the next step boundary: slot freed, backend
        finish() called, loop keeps serving others."""
        be = _FakeBackend(step_s=0.001)
        loop = DecodeLoop(be, name="t-cancel", max_active=4)
        try:
            t0 = time.time()
            stream = loop.submit([4], 500, klass="bulk")
            got = 0
            async for _ in stream.tokens():
                got += 1
                if got == 3:
                    break  # generator aclose -> loop.cancel
            for _ in range(200):
                if stream.seq_id in be.finished:
                    break
                await asyncio.sleep(0.005)
            assert stream.seq_id in be.finished
            assert loop.stats["active"] == 0
            leaves = flight.get_events(types=("decode.leave",), since=t0)
            assert any(
                e["attrs"]["reason"] == "cancelled" for e in leaves
            )
            # the loop is still alive for new work
            assert await _drain(loop.submit([1], 3)) == _expected([1], 3)
        finally:
            await loop.close()


# ---------------------------------------------------------------------------
# golden-activation pin on the jax decoder + the engine + the mesh unlock
# ---------------------------------------------------------------------------


class TestGoldenDecoder:
    @pytest.fixture(scope="class")
    def fx(self):
        return dict(np.load(FIXTURE))

    @pytest.fixture(scope="class")
    def engine_parts(self):
        from bioengine_tpu.runtime.decode_engine import (
            DecoderConfig,
            init_decoder_params,
        )

        return DecoderConfig(), init_decoder_params(0)

    def test_prefill_logits_match_independent_numpy(self, fx, engine_parts):
        """The jax prefill (padded, masked, KV-emitting) agrees with a
        from-scratch numpy full-attention forward to float32 tolerance."""
        from bioengine_tpu.runtime.decode_engine import decoder_prefill

        config, params = engine_parts
        prompt = fx["prompt"].astype(np.int32)
        logits, K, V = decoder_prefill(
            params, config, prompt, np.int32(len(prompt))
        )
        np.testing.assert_allclose(
            np.asarray(logits), fx["prefill_logits"], rtol=2e-4, atol=2e-4
        )
        assert K.shape == (config.n_layers, len(prompt), config.n_heads, config.head_dim)

    def test_step_logits_match_independent_numpy(self, fx, engine_parts):
        """One cached decode step (gathered KV + the token's own KV)
        equals the no-cache numpy forward over the extended sequence."""
        from bioengine_tpu.runtime.decode_engine import (
            decoder_prefill,
            decoder_step,
        )

        config, params = engine_parts
        prompt = fx["prompt"].astype(np.int32)
        T = len(prompt)
        logits0, K, V = decoder_prefill(
            params, config, prompt, np.int32(T)
        )
        tok0 = int(np.argmax(np.asarray(logits0)))
        assert tok0 == int(fx["greedy_tokens"][0])
        step_logits, _, _ = decoder_step(
            params,
            config,
            np.asarray([tok0], np.int32),
            np.asarray([T], np.int32),
            np.asarray(K)[:, None, :T],
            np.asarray(V)[:, None, :T],
            np.asarray([T], np.int32),
        )
        np.testing.assert_allclose(
            np.asarray(step_logits)[0], fx["step_logits"], rtol=2e-4, atol=2e-4
        )

    def _engine_greedy(self, engine, prompt, n):
        toks = [engine.prefill("golden", list(prompt))]
        while len(toks) < n:
            toks.extend(engine.step(["golden"], [toks[-1]]))
        engine.finish("golden")
        return toks

    def test_engine_greedy_tokens_bit_exact(self, fx):
        """The full engine path — bucketed prefill, paged KV, batched
        steps across KV-bucket growth — reproduces the fixture's 32
        greedy tokens EXACTLY."""
        from bioengine_tpu.runtime.decode_engine import DecodeEngine

        engine = DecodeEngine(model_id="golden-1chip")
        toks = self._engine_greedy(engine, fx["prompt"], 32)
        assert toks == fx["greedy_tokens"].tolist()
        assert engine.kv.stats["sequences"] == 0  # finish released KV

    def test_mesh_parity_same_tokens_on_dp_mesh(self, fx):
        """The sharded-decoder unlock: the SAME model over a forced
        4-device CPU dp mesh produces bit-identical greedy tokens —
        scaling the decode batch is a manifest edit, not a math change."""
        import jax

        from bioengine_tpu.runtime.decode_engine import DecodeEngine

        if len(jax.devices()) < 4:
            pytest.skip("needs 4 forced host devices (conftest XLA_FLAGS)")
        engine = DecodeEngine(
            model_id="golden-dp4",
            devices=jax.devices()[:4],
            mesh_axes={"dp": -1},
        )
        assert engine.mesh_shape == {"dp": 4}
        assert engine.chip_width == 4
        toks = self._engine_greedy(engine, fx["prompt"], 32)
        assert toks == fx["greedy_tokens"].tolist()

    def test_mesh_rejects_unsupported_axes(self):
        import jax

        from bioengine_tpu.runtime.decode_engine import DecodeEngine

        if len(jax.devices()) < 2:
            pytest.skip("needs multiple host devices")
        with pytest.raises(ValueError, match="dp"):
            DecodeEngine(
                devices=jax.devices()[:2], mesh_axes={"tp": -1}
            )

    def test_prompt_length_validated(self):
        from bioengine_tpu.runtime.decode_engine import DecodeEngine

        engine = DecodeEngine(model_id="golden-val")
        with pytest.raises(ValueError, match="prompt length"):
            engine.prefill("bad", [])
        with pytest.raises(ValueError, match="prompt length"):
            engine.prefill("bad", [1] * 1000)
