"""Converter ↔ published-checkpoint layout contract.

The key→shape manifests (``tests/fixtures_manifest_*.json``, derived
from the upstream model definitions — see
``generate_checkpoint_manifests.py`` for provenance) stand in for the
published cpsam and DINOv2 ViT-B/14 checkpoint files, which CI cannot
download. The name maps must cover each manifest EXACTLY: an unmapped
checkpoint key (upstream added/renamed something) fails, and a mapped
key missing from the manifest (the map invents keys the published file
doesn't have) fails too — drift in either direction breaks the suite
without any egress.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pytest

from bioengine_tpu.runtime.convert import (
    convert_state_dict,
    cpsam_name_map,
    dinov2_name_map,
    flatten_params,
    infer_depth,
)

FIXTURES = Path(__file__).resolve().parent

CASES = {
    "dinov2_vitb14": (
        "fixtures_manifest_dinov2_vitb14.json", dinov2_name_map, 12,
    ),
    "cpsam_vitl": (
        "fixtures_manifest_cpsam_vitl.json", cpsam_name_map, 24,
    ),
}


def _load(case):
    fname, map_fn, depth = CASES[case]
    manifest = json.loads((FIXTURES / fname).read_text())
    return manifest, map_fn(depth), depth


@pytest.mark.parametrize("case", sorted(CASES))
def test_name_map_covers_manifest_exactly(case):
    manifest, name_map, _ = _load(case)
    missing = sorted(set(manifest) - set(name_map))
    phantom = sorted(set(name_map) - set(manifest))
    assert not missing, (
        f"checkpoint keys with no conversion rule (upstream layout "
        f"drift?): {missing[:5]} (+{max(len(missing) - 5, 0)} more)"
    )
    assert not phantom, (
        f"conversion rules for keys the published checkpoint does not "
        f"carry: {phantom[:5]} (+{max(len(phantom) - 5, 0)} more)"
    )


@pytest.mark.parametrize("case", sorted(CASES))
def test_manifest_converts_strict(case):
    """A manifest-shaped state dict converts under strict=True and the
    transforms produce the Flax-side layouts (conv kernels HWIO,
    linear kernels (in, out))."""
    manifest, name_map, depth = _load(case)
    # np.zeros is lazy (calloc) — the ViT-L manifest is ~1.2 GB virtual
    # but each transform only materializes one tensor at a time
    sd = {k: np.zeros(shape, np.float32) for k, shape in manifest.items()}
    assert infer_depth(sd) == depth
    params = convert_state_dict(sd, name_map, strict=True)
    flat = flatten_params(params)

    if case == "dinov2_vitb14":
        # mask_token is a known-drop: present in the checkpoint, absent
        # from the converted tree (the ViT never masks at inference)
        assert not any("mask_token" in k for k in flat)
        assert flat["patch_embed/kernel"].shape == (14, 14, 3, 768)
        assert flat["block0/attn/qkv/kernel"].shape == (768, 2304)
        assert flat["cls_token"].shape == (1, 1, 768)
    else:
        assert flat["encoder/patch_embed/kernel"].shape == (8, 8, 3, 1024)
        assert flat["encoder/neck_conv1/kernel"].shape == (1, 1, 1024, 256)
        # ConvTranspose: (in, out, kH, kW) -> (kH, kW, in, out), flipped
        assert flat["out/kernel"].shape == (8, 8, 256, 3)
        assert flat["encoder/block0/mlp_lin1/kernel"].shape == (1024, 4096)
        # windowed vs global relative-position table sizes
        assert flat["encoder/block0/attn/rel_pos_h"].shape == (27, 64)
        assert flat["encoder/block5/attn/rel_pos_h"].shape == (63, 64)

    # every non-dropped rule landed exactly one leaf
    n_dropped = sum(1 for v in name_map.values() if v is None)
    assert len(flat) == len(manifest) - n_dropped


def test_manifest_matches_synthetic_generator_layout():
    """The synthetic cpsam generator (what the conversion/CLI tests
    feed) and the published-checkpoint manifest must agree on the key
    set at matching hyperparameters — otherwise the suite validates a
    layout the real file doesn't have."""
    from bioengine_tpu.runtime.convert import synthetic_cpsam_state_dict

    manifest, _, _ = _load("cpsam_vitl")
    sd = synthetic_cpsam_state_dict(
        patch_size=8,
        dim=16,               # tiny dim: only the KEY SET is compared
        depth=24,
        num_heads=2,
        window_size=14,
        global_attn_indexes=(5, 11, 17, 23),
        neck_dim=8,
        pretrain_grid=32,
    )
    assert set(sd) == set(manifest)
