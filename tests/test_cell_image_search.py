"""cell-image-search app: index variants, normalizer, crop extraction,
ingestion sessions, search — hermetic on the CPU backend (the embedder
is the randomly-initialized ViT in pipeline-shape mode)."""

import asyncio
import importlib.util
import sys
import time
from pathlib import Path

import numpy as np
import pytest

pytestmark = [pytest.mark.integration, pytest.mark.anyio]

REPO_APPS = Path(__file__).resolve().parent.parent / "apps"
APP_DIR = REPO_APPS / "cell-image-search"


def _load(stem):
    """Import an app module the way the builder does (bare stem name)."""
    if stem in sys.modules:
        return sys.modules[stem]
    spec = importlib.util.spec_from_file_location(stem, APP_DIR / f"{stem}.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[stem] = mod
    spec.loader.exec_module(mod)
    return mod


normalizer = _load("normalizer")
ingestion = _load("ingestion")
index_mod = _load("index")


class TestNormalizer:
    def test_grayscale(self):
        img = np.random.default_rng(0).normal(100, 30, (64, 64))
        out = normalizer.to_model_input(img)
        assert out.shape == (224, 224, 3)
        assert out.dtype == np.float32

    @pytest.mark.parametrize("c", [1, 2, 3, 4, 5])
    def test_channel_counts(self, c):
        img = np.random.default_rng(c).integers(
            0, 65535, (48, 48, c)
        ).astype(np.uint16)
        rgb = normalizer.to_rgb_uint8(img)
        assert rgb.shape == (48, 48, 3)
        assert rgb.dtype == np.uint8

    def test_channels_first(self):
        img = np.random.default_rng(1).normal(size=(5, 48, 48))
        rgb = normalizer.to_rgb_uint8(img)
        assert rgb.shape == (48, 48, 3)

    def test_percentile_stretch_outliers(self):
        img = np.full((32, 32), 100.0)
        img[0, 0] = 1e9  # hot pixel must not crush the range
        out = normalizer.percentile_stretch(img)
        assert out.max() <= 255 and out.min() >= 0

    def test_decode_roundtrip(self):
        import io

        from PIL import Image

        arr = np.random.default_rng(2).integers(
            0, 255, (32, 32, 3)
        ).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="PNG")
        out = normalizer.decode_image_bytes(buf.getvalue())
        np.testing.assert_array_equal(out, arr)


class TestCropExtraction:
    def test_finds_blobs(self):
        _, img = next(
            iter(ingestion.make_synthetic_images(n_images=1, size=512))
        )
        crops = ingestion.extract_cell_crops(img, crop_size=96, n_crops=20)
        assert len(crops) >= 5
        assert all(c.shape[:2] == (96, 96) for c in crops)

    def test_grid_fallback_crop_size_near_image_size(self):
        """Regression: the fallback grid double-offset its centers by
        half a window, so a crop_size close to the image size yielded
        ZERO crops from a perfectly valid image ('No cells found')."""
        img = np.random.default_rng(0).normal(40, 5, (256, 256)).astype(
            np.float32
        )
        crops = ingestion.extract_cell_crops(img, crop_size=224)
        assert len(crops) >= 1
        assert all(c.shape[:2] == (224, 224) for c in crops)

    def test_grid_fallback_on_flat_image(self):
        img = np.random.default_rng(0).normal(10, 0.1, (300, 300))
        crops = ingestion.extract_cell_crops(img, crop_size=64, n_crops=9)
        assert len(crops) >= 4


def _random_unit(n, d=768, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    return x / np.linalg.norm(x, axis=1, keepdims=True)


class TestIndexVariants:
    def test_flat_exact(self, tmp_path):
        import pandas as pd

        emb = _random_unit(500)
        df = pd.DataFrame({"compound": [f"c{i % 7}" for i in range(500)]})
        stats = index_mod.build_index(emb, df, tmp_path)
        assert stats["index_type"] == "FlatIP"
        idx, meta, info = index_mod.load_index(tmp_path)
        results = index_mod.search_index(idx, meta, emb[42], top_k=5)
        assert results[0]["index_id"] == 42  # self-match first
        assert results[0]["score"] > 0.99
        assert results[0]["compound"] == "c0"

    def test_ivfflat_recall(self, tmp_path):
        emb = _random_unit(3000, seed=1)
        idx = index_mod.IVFFlatIndex.build(emb, nlist=32, nprobe=8)
        hits = 0
        for q in range(50):
            _, ids = idx.search(emb[q], 1)
            hits += int(ids[0, 0] == q)
        assert hits >= 45  # self-recall@1 with 8/32 probes

    def test_ivfpq_recall(self, tmp_path):
        emb = _random_unit(4000, seed=2)
        idx = index_mod.IVFPQIndex.build(emb, nlist=16, nprobe=8)
        hits = 0
        for q in range(30):
            _, ids = idx.search(emb[q], 10)
            hits += int(q in ids[0])
        assert hits >= 24  # PQ is lossy; self-recall@10 stays high

    def test_pqflat_exact_scan_recall_and_batch(self, tmp_path):
        """Device-resident PQ flat scan: exact over ALL codes, so
        self-recall@10 must be at least as good as probed IVFPQ; batch
        queries return per-row results through the jitted scan."""
        emb = _random_unit(600, seed=5)
        idx = index_mod.PQFlatIndex.build(emb)
        assert idx.ntotal == 600
        hits = 0
        for q in range(30):
            _, ids = idx.search(emb[q], 10)
            hits += int(q in ids[0])
        assert hits >= 26, hits  # no probe misses — PQ loss only
        s, i = idx.search(emb[:8], 5)
        assert s.shape == (8, 5) and i.shape == (8, 5)
        # batch rows match single-query results (same jitted scan)
        s1, i1 = idx.search(emb[3], 5)
        np.testing.assert_array_equal(i[3], i1[0])
        rec = idx.reconstruct(np.array([0, 7]))
        assert rec.shape == (2, emb.shape[1])
        # quantized reconstruction stays close in angle
        cos = (rec[0] / np.linalg.norm(rec[0])) @ emb[0]
        assert cos > 0.8, cos

    def test_save_load_roundtrip(self, tmp_path):
        emb = _random_unit(1200, seed=3)
        for built in (
            index_mod.FlatIPIndex(emb),
            index_mod.IVFFlatIndex.build(emb, nlist=8),
            index_mod.IVFPQIndex.build(emb, nlist=4),
            index_mod.PQFlatIndex.build(emb),
        ):
            p = tmp_path / f"{built.kind}.npz"
            built.save(p)
            with np.load(p) as data:
                loaded = index_mod._KINDS[str(data["kind"])].load(data)
            assert loaded.ntotal == 1200
            s1, i1 = built.search(emb[7], 3)
            s2, i2 = loaded.search(emb[7], 3)
            np.testing.assert_array_equal(i1, i2)

    def test_auto_selection_thresholds(self, tmp_path):
        import pandas as pd

        emb = _random_unit(200)
        df = pd.DataFrame({"label": ["x"] * 200})
        stats = index_mod.build_index(
            emb, df, tmp_path, n_cells_total=200_000
        )
        assert stats["index_type"] == "IVFFlat"

    def test_projection_and_query(self, tmp_path):
        import pandas as pd

        emb = _random_unit(800, seed=4)
        df = pd.DataFrame({"compound": [f"c{i % 5}" for i in range(800)]})
        index_mod.build_index(emb, df, tmp_path)
        proj = index_mod.compute_projection(tmp_path, n_samples=200)
        assert len(proj["x"]) == 200
        assert proj["n_total"] == 800
        # cached second call
        t0 = time.time()
        proj2 = index_mod.compute_projection(tmp_path, n_samples=200)
        assert time.time() - t0 < 0.5
        assert proj2["x"] == proj["x"]
        pos = index_mod.project_query(tmp_path, emb[0])
        assert set(pos) == {"x", "y"}


class TestKnnOp:
    def test_matches_numpy(self):
        from bioengine_tpu.ops.knn import topk_inner_product

        import jax.numpy as jnp

        corpus = _random_unit(300, seed=5)
        q = _random_unit(4, seed=6)
        s, i = topk_inner_product(jnp.asarray(corpus), jnp.asarray(q), 7)
        ref = np.argsort(-(q @ corpus.T), axis=1)[:, :7]
        np.testing.assert_array_equal(np.asarray(i), ref)

    def test_sharded_matches_flat(self, mesh8):
        from bioengine_tpu.ops.knn import ShardedKnnIndex

        corpus = _random_unit(1000, seed=7)
        q = _random_unit(3, seed=8)
        flat = ShardedKnnIndex(corpus, mesh=None, dtype=np.float32)
        sharded = ShardedKnnIndex(
            corpus, mesh=mesh8, axis="dp", dtype=np.float32
        )
        s1, i1 = flat.search(q, 9)
        s2, i2 = sharded.search(q, 9)
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_allclose(s1, s2, atol=1e-5)

    def test_sharded_uneven_corpus(self, mesh8):
        from bioengine_tpu.ops.knn import ShardedKnnIndex

        corpus = _random_unit(37, seed=9)  # not divisible by shards
        q = _random_unit(2, seed=10)
        idx = ShardedKnnIndex(corpus, mesh=mesh8, axis="dp", dtype=np.float32)
        s, i = idx.search(q, 40)  # k > n clamps
        assert i.shape == (2, 37)
        assert (i < 37).all() and (i >= 0).all()


# ---- full app flow through the serving stack --------------------------------


async def deploy(manager, app_dir, **kwargs):
    from bioengine_tpu.utils.permissions import create_context

    result = await manager.deploy_app(
        local_path=str(REPO_APPS / app_dir),
        context=create_context("admin"),
        **kwargs,
    )
    await asyncio.sleep(0.05)
    return result


async def call(server, service_id, method, **kwargs):
    caller = server.validate_token(server.issue_token("user"))
    return await server.call_service_method(
        service_id, method, kwargs=kwargs, caller=caller
    )


@pytest.fixture
async def search_app(stack, tmp_path):
    manager, _, server, _ = stack
    result = await deploy(
        manager,
        "cell-image-search",
        deployment_kwargs={
            "main": {
                "workspace_dir": str(tmp_path / "ws"),
                "batch_bucket": 8,
                "crop_size": 64,
                "n_crops_per_image": 8,
            }
        },
    )
    return result, server


class TestCellImageSearchApp:
    async def test_full_flow(self, search_app):
        result, server = search_app
        sid = result["service_id"]

        pong = await call(server, sid, "ping")
        assert pong["status"] == "ok"

        stats = await call(server, sid, "get_index_stats")
        assert stats["loaded"] is False

        added = await call(
            server, sid, "add_dataset",
            name="demo", source="synthetic", n_images=2, image_size=256,
        )
        assert added["added"]

        datasets = await call(server, sid, "list_datasets")
        assert any(d["name"] == "demo" for d in datasets["registered"])

        started = await call(
            server, sid, "start_ingestion",
            dataset_name="demo", session_id="s1",
        )
        assert started["status"] == "started"

        deadline = time.time() + 600
        status = {}
        while time.time() < deadline:
            status = await call(
                server, sid, "get_ingestion_status", session_id="s1"
            )
            if status["status"] in ("completed", "failed"):
                break
            await asyncio.sleep(0.3)
        assert status["status"] == "completed", status
        assert status["n_embedded"] > 0

        stats = await call(server, sid, "get_index_stats")
        assert stats["loaded"] and stats["n_cells"] == status["n_embedded"]
        assert stats["index_type"] == "FlatIP"

        query = np.random.default_rng(0).normal(100, 20, (64, 64))
        found = await call(server, sid, "search", image=query, top_k=5)
        assert found["n_results"] == 5
        assert found["results"][0]["rank"] == 1
        assert found["results"][0]["dataset"] == "demo"

        preview = await call(server, sid, "get_umap_preview", n_samples=10)
        assert len(preview["x"]) == min(10, status["n_embedded"])
        pos = await call(
            server, sid, "project_query_onto_umap", image=query
        )
        assert set(pos) == {"x", "y"}

        sessions = await call(server, sid, "get_active_sessions")
        assert "s1" in sessions

    async def test_stop_ingestion(self, search_app):
        result, server = search_app
        sid = result["service_id"]
        await call(
            server, sid, "add_dataset",
            name="big", source="synthetic", n_images=50, image_size=256,
        )
        await call(
            server, sid, "start_ingestion",
            dataset_name="big", session_id="s2",
        )
        await call(server, sid, "stop_ingestion", session_id="s2")
        deadline = time.time() + 600
        while time.time() < deadline:
            status = await call(
                server, sid, "get_ingestion_status", session_id="s2"
            )
            if status["status"] in ("stopped", "completed", "failed"):
                break
            await asyncio.sleep(0.3)
        assert status["status"] in ("stopped", "completed")

    async def test_unknown_dataset_rejected(self, search_app):
        result, server = search_app
        sid = result["service_id"]
        with pytest.raises(Exception, match="not registered"):
            await call(
                server, sid, "start_ingestion", dataset_name="nope"
            )
