"""Generate tests/fixtures_golden_cpsam.npz — an INDEPENDENT forward
pass of the tiny-config cpsam checkpoint, used as ground truth by
``tests/test_models.py::TestGoldenCpSAM``.

Why this exists (round-5 ADVICE): the cpsam weight conversion was
validated only structurally — ``cpsam_name_map`` produces the right
pytree keys/shapes and spot-checked transposes, but nothing pinned the
*activations* of the converted model. A transposed-but-wrong kernel,
a swapped rel-pos table, or an attention-reshape mismatch would pass
every structural test and silently fine-tune from garbage.

This generator reimplements the public cpsam forward
(``cellpose.vit_sam.Transformer`` = segment-anything ImageEncoderViT +
transposed-conv readout) in pure numpy/scipy, straight from the
TORCH-layout state dict and torch operator semantics:

- Conv2d / ConvTranspose2d are computed from the (O, I, kH, kW) /
  (I, O, kH, kW) torch kernels directly — no flax-layout transposes
  shared with ``runtime/convert.py``;
- attention follows SAM's reference math (qkv reshape/permute,
  decomposed relative-position bias, window partition) as written in
  the segment-anything paper repo, not the flax twin's einsum layout;
- LayerNorm eps = 1e-6 (SAM pins it), exact erf GELU.

The real cellpose/torch packages are deliberately NOT dependencies
(the TPU image has no egress); this generator is committed so the
fixture is reproducible: ``python tests/generate_golden_cpsam.py``
rewrites the npz deterministically. Weights come from
``synthetic_cpsam_state_dict`` — weights are shared DATA; the forward
MATH shares no code with ``models/sam.py``.

Fixture contents (tiny config: patch 8, dim 32, depth 2, heads 2,
window 2, global (1,), neck 16, grid 4):
  input    (1, 32, 32, 3)  f32 — deterministic N(0,1) image, NHWC
  encoder  (1, 4, 4, 16)   f32 — neck features (post 2nd LayerNorm)
  output   (1, 32, 32, 3)  f32 — full cpsam readout
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np
from scipy.special import erf

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bioengine_tpu.runtime.convert import synthetic_cpsam_state_dict  # noqa: E402

OUT = Path(__file__).parent / "fixtures_golden_cpsam.npz"

CONFIG = dict(
    patch_size=8, dim=32, depth=2, num_heads=2, window_size=2,
    global_attn_indexes=(1,), neck_dim=16, pretrain_grid=4,
)
EPS = 1e-6  # SAM pins LayerNorm eps=1e-6 everywhere


def layer_norm(x: np.ndarray, w: np.ndarray, b: np.ndarray) -> np.ndarray:
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + EPS) * w + b


def gelu(x: np.ndarray) -> np.ndarray:
    return 0.5 * x * (1.0 + erf(x / np.sqrt(2.0)))


def softmax(x: np.ndarray) -> np.ndarray:
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def get_rel_pos(q_size: int, k_size: int, rel_pos: np.ndarray) -> np.ndarray:
    """SAM's get_rel_pos; the tiny config stores tables at exactly
    2*max(q,k)-1 so no interpolation branch is needed."""
    assert rel_pos.shape[0] == 2 * max(q_size, k_size) - 1
    coords = (
        np.arange(q_size)[:, None] * max(k_size / q_size, 1.0)
        - np.arange(k_size)[None, :] * max(q_size / k_size, 1.0)
        + (k_size - 1) * max(q_size / k_size, 1.0)
    )
    return rel_pos[coords.astype(np.int64)]


def attention(x: np.ndarray, sd: dict, prefix: str, num_heads: int) -> np.ndarray:
    """SAM Attention over a (B, H, W, C) token grid, torch semantics."""
    B, H, W, C = x.shape
    hd = C // num_heads
    qkv = x.reshape(B, H * W, C) @ sd[f"{prefix}.qkv.weight"].T
    qkv = qkv + sd[f"{prefix}.qkv.bias"]
    qkv = qkv.reshape(B, H * W, 3, num_heads, hd).transpose(2, 0, 3, 1, 4)
    q, k, v = qkv.reshape(3, B * num_heads, H * W, hd)
    attn = (q * hd**-0.5) @ k.transpose(0, 2, 1)
    Rh = get_rel_pos(H, H, sd[f"{prefix}.rel_pos_h"])
    Rw = get_rel_pos(W, W, sd[f"{prefix}.rel_pos_w"])
    r_q = q.reshape(B * num_heads, H, W, hd)
    rel_h = np.einsum("bhwc,hkc->bhwk", r_q, Rh)
    rel_w = np.einsum("bhwc,wkc->bhwk", r_q, Rw)
    attn = attn.reshape(B * num_heads, H, W, H, W)
    attn = attn + rel_h[:, :, :, :, None] + rel_w[:, :, :, None, :]
    attn = softmax(attn.reshape(B * num_heads, H * W, H * W))
    out = (attn @ v).reshape(B, num_heads, H * W, hd)
    out = out.transpose(0, 2, 1, 3).reshape(B, H, W, C)
    return out @ sd[f"{prefix}.proj.weight"].T + sd[f"{prefix}.proj.bias"]


def window_partition(x: np.ndarray, ws: int) -> np.ndarray:
    B, H, W, C = x.shape
    assert H % ws == 0 and W % ws == 0  # tiny config: no padding branch
    x = x.reshape(B, H // ws, ws, W // ws, ws, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(-1, ws, ws, C)


def window_unpartition(x: np.ndarray, ws: int, H: int, W: int) -> np.ndarray:
    B = x.shape[0] // ((H // ws) * (W // ws))
    x = x.reshape(B, H // ws, W // ws, ws, ws, -1)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H, W, -1)


def block(x: np.ndarray, sd: dict, i: int, num_heads: int, ws: int) -> np.ndarray:
    p = f"encoder.blocks.{i}"
    shortcut = x
    x = layer_norm(x, sd[f"{p}.norm1.weight"], sd[f"{p}.norm1.bias"])
    if ws > 0:
        H, W = x.shape[1:3]
        win = window_partition(x, ws)
        win = attention(win, sd, f"{p}.attn", num_heads)
        x = window_unpartition(win, ws, H, W)
    else:
        x = attention(x, sd, f"{p}.attn", num_heads)
    x = shortcut + x
    y = layer_norm(x, sd[f"{p}.norm2.weight"], sd[f"{p}.norm2.bias"])
    y = gelu(y @ sd[f"{p}.mlp.lin1.weight"].T + sd[f"{p}.mlp.lin1.bias"])
    y = y @ sd[f"{p}.mlp.lin2.weight"].T + sd[f"{p}.mlp.lin2.bias"]
    return x + y


def encoder_forward(img: np.ndarray, sd: dict) -> np.ndarray:
    cfg = CONFIG
    p = cfg["patch_size"]
    B, H, W, _ = img.shape
    gh, gw = H // p, W // p
    # torch Conv2d(stride=p, kernel=p): each patch is one matmul row
    Wp = sd["encoder.patch_embed.proj.weight"]  # (dim, 3, p, p)
    kern = Wp.transpose(2, 3, 1, 0).reshape(p * p * 3, -1)  # (a,b,c)->dim
    patches = img.reshape(B, gh, p, gw, p, 3).transpose(0, 1, 3, 2, 4, 5)
    x = patches.reshape(B, gh, gw, p * p * 3) @ kern
    x = x + sd["encoder.patch_embed.proj.bias"]
    x = x + sd["encoder.pos_embed"]  # stored (1, grid, grid, dim); grid == gh
    for i in range(cfg["depth"]):
        ws = (
            0 if i in cfg["global_attn_indexes"] else cfg["window_size"]
        )
        x = block(x, sd, i, cfg["num_heads"], ws)
    # neck: 1x1 conv (no bias), LN, 3x3 SAME conv (no bias), LN —
    # LayerNorm2d over channels == last-axis LN in this NHWC layout
    W0 = sd["encoder.neck.0.weight"][:, :, 0, 0]  # (neck, dim)
    x = x @ W0.T
    x = layer_norm(x, sd["encoder.neck.1.weight"], sd["encoder.neck.1.bias"])
    W2 = sd["encoder.neck.2.weight"]  # (neck, neck, 3, 3)
    xpad = np.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    y = np.zeros_like(x)
    for a in range(3):
        for b in range(3):
            y = y + xpad[:, a : a + gh, b : b + gw, :] @ W2[:, :, a, b].T
    return layer_norm(
        y, sd["encoder.neck.3.weight"], sd["encoder.neck.3.bias"]
    )


def readout(feats: np.ndarray, sd: dict) -> np.ndarray:
    """torch ConvTranspose2d(kernel=stride=p): each input pixel paints
    one disjoint p x p output block."""
    p = CONFIG["patch_size"]
    Wt = sd["out.weight"]  # (in, out=3, p, p)
    B, gh, gw, _ = feats.shape
    t = np.tensordot(feats, Wt, axes=([3], [0]))  # (B, gh, gw, 3, p, p)
    out = t.transpose(0, 1, 4, 2, 5, 3).reshape(B, gh * p, gw * p, 3)
    return out + sd["out.bias"]


def main() -> None:
    sd = {
        k: v.astype(np.float64)
        for k, v in synthetic_cpsam_state_dict(**CONFIG).items()
    }
    rng = np.random.default_rng(42)
    img = rng.standard_normal((1, 32, 32, 3))
    feats = encoder_forward(img, sd)
    out = readout(feats, sd)
    np.savez_compressed(
        OUT,
        input=img.astype(np.float32),
        encoder=feats.astype(np.float32),
        output=out.astype(np.float32),
    )
    print(
        f"wrote {OUT}: encoder {feats.shape} "
        f"(|mean|={abs(feats.mean()):.4f}), output {out.shape}"
    )


if __name__ == "__main__":
    main()
