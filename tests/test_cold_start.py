"""Cold-start elimination: shared compile-cache tier, streamed weight
loading, and the preemption-tolerant warm pool.

Covers the three coordinated pieces end to end:
- utils/compile_cache.py — failure-verdict caching and the tier entry
  file protocol (list/read/atomic-write, unsafe names rejected);
- serving/compile_tier.py + worker_host sync — hosts publish compiled
  programs at join/replica-start and a later host FETCHES them, with
  ``program.cache_fetch`` flight evidence;
- runtime/program_cache.py — persistent-cache hits tagged apart from
  real compiles (``cache_hit`` on the program.compile flight event and
  in engine.describe()["programs"]);
- runtime/weight_stream.py + model-runner — manifest-driven streamed
  loading with BIT-IDENTICAL outputs vs eager, transparent fallback
  when no manifest exists, loud failure on a layout mismatch;
- serving/warm_pool.py + controller — pool fill/promote/refill/sweep,
  and the acceptance chaos test: a preempted host's replica is absorbed
  by a standby within the request deadline, zero failed idempotent
  requests, exact chip accounting, and ``warmpool.promote`` sits
  between ``host.dead`` and ``replica.place`` in the flight record.
"""

import asyncio
import importlib.util
import json
import time
from pathlib import Path

import numpy as np
import pytest

from bioengine_tpu.utils import compile_cache, flight

pytestmark = [pytest.mark.integration, pytest.mark.anyio]

REPO_APPS = Path(__file__).resolve().parent.parent / "apps"


def _load_model_runner():
    spec = importlib.util.spec_from_file_location(
        "cold_start_mr_rt", REPO_APPS / "model-runner" / "runtime_deployment.py"
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _make_package(root: Path, with_manifest: bool = True) -> Path:
    """Tiny jax_params UNet package (model-runner layout), optionally
    with the key→shape streaming manifest."""
    import jax
    import jax.numpy as jnp
    import yaml

    from bioengine_tpu.models.unet import UNet2D
    from bioengine_tpu.runtime.convert import flatten_params, save_params_npz
    from bioengine_tpu.runtime.weight_stream import write_manifest

    d = root / ("pkg-manifest" if with_manifest else "pkg-plain")
    d.mkdir(parents=True, exist_ok=True)
    model = UNet2D(features=(4, 8), out_channels=1)
    x = np.random.default_rng(0).normal(size=(1, 64, 64, 1)).astype(np.float32)
    params = model.init(jax.random.key(0), jnp.asarray(x))["params"]
    save_params_npz(str(d / "weights.npz"), params)
    if with_manifest:
        write_manifest(d / "weights.npz", flatten_params(params))
    np.save(d / "test_input.npy", x)
    (d / "rdf.yaml").write_text(
        yaml.safe_dump(
            {
                "type": "model",
                "name": "ColdStart Test UNet",
                "description": "cold-start test model",
                "inputs": [{"name": "input0", "axes": "byxc"}],
                "outputs": [{"name": "output0", "axes": "byxc"}],
                "test_inputs": ["test_input.npy"],
                "documentation": "README.md",
                "weights": {
                    "jax_params": {
                        "source": "weights.npz",
                        "architecture": {
                            "name": "unet2d",
                            "kwargs": {"features": [4, 8], "out_channels": 1},
                        },
                    }
                },
            }
        )
    )
    (d / "README.md").write_text("docs")
    return d


# ---------------------------------------------------------------------------
# compile_cache: failure-verdict caching + tier entry file protocol
# ---------------------------------------------------------------------------


class TestCompileCache:
    def test_failure_verdict_cached_and_logged_once(
        self, tmp_path, monkeypatch, caplog
    ):
        blocker = tmp_path / "a-file"
        blocker.write_text("not a directory")
        monkeypatch.setenv(
            "BIOENGINE_COMPILE_CACHE", str(blocker / "sub" / "dir")
        )
        compile_cache.reset_for_tests()
        try:
            import logging

            with caplog.at_level(
                logging.WARNING, logger="bioengine_tpu.utils.compile_cache"
            ):
                assert compile_cache.enable_persistent_compilation_cache() is None
                assert compile_cache.enable_persistent_compilation_cache() is None
                assert compile_cache.enable_persistent_compilation_cache() is None
            warnings = [
                r for r in caplog.records if "unavailable" in r.getMessage()
            ]
            # the verdict is cached: one attempt, one warning — not one
            # mkdir+warning per call on a read-only FS
            assert len(warnings) == 1
            assert compile_cache._failed is True
        finally:
            compile_cache.reset_for_tests()

    def test_off_switch(self, monkeypatch):
        monkeypatch.setenv("BIOENGINE_COMPILE_CACHE", "off")
        compile_cache.reset_for_tests()
        try:
            assert compile_cache.enable_persistent_compilation_cache() is None
            assert compile_cache._failed is False  # off is not a failure
        finally:
            compile_cache.reset_for_tests()

    def test_entry_io_roundtrip_and_safety(self, tmp_path):
        d = tmp_path / "cache"
        d.mkdir()
        name = "jit_fn-abc123-cache"
        assert compile_cache.write_entry(name, b"program-bytes", d)
        # idempotent: an existing entry is never overwritten
        assert not compile_cache.write_entry(name, b"other", d)
        assert compile_cache.read_entry(name, d) == b"program-bytes"
        assert compile_cache.list_entries(d) == {name: 13}
        # atime bookkeeping files and foreign files never list
        (d / "jit_fn-abc123-atime").write_bytes(b"x")
        (d / "random.txt").write_bytes(b"x")
        assert list(compile_cache.list_entries(d)) == [name]
        # names cross the RPC plane: traversal/dotfiles/suffix rejected
        for bad in ("../evil-cache", "a/b-cache", ".hidden-cache", "x"):
            assert not compile_cache.write_entry(bad, b"x", d)
            assert compile_cache.read_entry(bad, d) is None


class TestCompileTierStore:
    def test_publish_fetch_list_stats(self, tmp_path):
        from bioengine_tpu.serving.compile_tier import CompileCacheTier

        tier = CompileCacheTier(tmp_path / "tier", max_bytes=10_000)
        assert tier.fetch("jit_a-1-cache") is None  # miss counted
        assert tier.publish("jit_a-1-cache", b"A" * 100)
        assert not tier.publish("jit_a-1-cache", b"B" * 100)  # first copy kept
        assert tier.fetch("jit_a-1-cache") == b"A" * 100
        assert tier.list() == {"jit_a-1-cache": 100}
        st = tier.stats()
        assert st["entries"] == 1
        assert st["served"] == 1 and st["missed"] == 1
        assert st["hit_rate"] == 0.5
        assert not tier.publish("../evil-cache", b"x")

    def test_size_bound_evicts_lru(self, tmp_path):
        from bioengine_tpu.serving.compile_tier import CompileCacheTier

        tier = CompileCacheTier(tmp_path / "tier", max_bytes=250)
        tier.publish("jit_a-1-cache", b"A" * 100)
        time.sleep(0.02)
        tier.publish("jit_b-2-cache", b"B" * 100)
        time.sleep(0.02)
        tier.publish("jit_c-3-cache", b"C" * 100)  # 300 bytes > 250
        listing = tier.list()
        assert sum(listing.values()) <= 250
        assert "jit_c-3-cache" in listing  # newest survives
        assert tier.stats()["evicted"] >= 1


# ---------------------------------------------------------------------------
# program cache: persistent-hit tagging
# ---------------------------------------------------------------------------


class TestCacheHitTagging:
    def test_fast_build_with_persistent_cache_tags_hit(self, monkeypatch):
        from bioengine_tpu.runtime.program_cache import CompiledProgramCache

        monkeypatch.setattr(compile_cache, "_enabled_dir", "/tmp/fake-cache")
        monkeypatch.setenv("BIOENGINE_COMPILE_HIT_THRESHOLD_S", "10")
        flight.clear()
        cache = CompiledProgramCache()
        cache.get_or_compile(("m", 1), lambda: (lambda *a: None))
        assert cache.stats.persistent_hits == 1
        info = cache.compile_info_snapshot()
        assert info[str(("m", 1))]["cache_hit"] is True
        events = [
            e
            for e in flight.get_record()["events"]
            if e["type"] == "program.compile"
        ]
        assert events and events[-1]["attrs"]["cache_hit"] is True

    def test_no_persistent_cache_means_no_hit_tag(self, monkeypatch):
        from bioengine_tpu.runtime.program_cache import CompiledProgramCache

        monkeypatch.setattr(compile_cache, "_enabled_dir", None)
        monkeypatch.setenv("BIOENGINE_COMPILE_HIT_THRESHOLD_S", "10")
        cache = CompiledProgramCache()
        cache.get_or_compile(("m", 1), lambda: (lambda *a: None))
        assert cache.stats.persistent_hits == 0
        assert cache.compile_info_snapshot()[str(("m", 1))]["cache_hit"] is False

    def test_slow_build_is_a_real_compile(self, monkeypatch):
        from bioengine_tpu.runtime.program_cache import CompiledProgramCache

        monkeypatch.setattr(compile_cache, "_enabled_dir", "/tmp/fake-cache")
        monkeypatch.setenv("BIOENGINE_COMPILE_HIT_THRESHOLD_S", "0.01")

        def build():
            time.sleep(0.05)
            return lambda *a: None

        cache = CompiledProgramCache()
        cache.get_or_compile(("m", 2), build)
        assert cache.stats.persistent_hits == 0


# ---------------------------------------------------------------------------
# streamed weight loading
# ---------------------------------------------------------------------------


class TestWeightStreaming:
    def test_streamed_outputs_bit_identical_to_eager(self, tmp_path, monkeypatch):
        rt = _load_model_runner()
        pkg = _make_package(tmp_path, with_manifest=True)
        x = np.load(pkg / "test_input.npy")
        streamed = rt.Pipeline(pkg)
        assert streamed.load_info["streamed"] is True
        y_streamed = streamed.predict(x)["output0"]
        monkeypatch.setenv("BIOENGINE_WEIGHT_STREAMING", "0")
        eager = rt.Pipeline(pkg)
        assert eager.load_info["streamed"] is False
        y_eager = eager.predict(x)["output0"]
        # parity pin: same checkpoint, same programs — BIT identical
        assert np.array_equal(y_streamed, y_eager)
        info = streamed.cold_start_info()
        assert info["stream_done"] is True
        assert info["bytes_loaded"] > 0
        streamed.close()
        eager.close()

    def test_missing_manifest_falls_back_to_eager(self, tmp_path):
        rt = _load_model_runner()
        pkg = _make_package(tmp_path, with_manifest=False)
        x = np.load(pkg / "test_input.npy")
        p = rt.Pipeline(pkg)
        assert p.load_info["streamed"] is False
        assert p.predict(x)["output0"].shape == (1, 64, 64, 1)
        p.close()

    def test_manifest_shape_mismatch_fails_loudly(self, tmp_path):
        rt = _load_model_runner()
        pkg = _make_package(tmp_path, with_manifest=True)
        mpath = pkg / "weights.npz.manifest.json"
        manifest = json.loads(mpath.read_text())
        key = next(iter(manifest))
        manifest[key]["shape"] = [
            int(d) + 1 for d in manifest[key]["shape"]
        ]
        mpath.write_text(json.dumps(manifest))
        x = np.load(pkg / "test_input.npy")
        p = rt.Pipeline(pkg)
        with pytest.raises(RuntimeError, match="stream"):
            p.predict(x)
        p.close()

    def test_engine_gate_blocks_until_complete(self):
        import jax
        import jax.numpy as jnp

        from bioengine_tpu.models.unet import UNet2D
        from bioengine_tpu.runtime.engine import EngineConfig, InferenceEngine
        from bioengine_tpu.runtime.program_cache import CompiledProgramCache

        model = UNet2D(features=(4, 8), out_channels=1)
        x = np.random.default_rng(1).normal(size=(1, 64, 64, 1)).astype(
            np.float32
        )
        params = model.init(jax.random.key(0), jnp.asarray(x))["params"]
        zeros = jax.tree.map(np.zeros_like, params)

        eager = InferenceEngine(
            "gate-eager",
            lambda p, t: model.apply({"params": p}, t),
            params,
            divisor=model.divisor,
            config=EngineConfig(max_tile=64),
            cache=CompiledProgramCache(),
        )
        streamed = InferenceEngine(
            "gate-streamed",
            lambda p, t: model.apply({"params": p}, t),
            zeros,
            divisor=model.divisor,
            config=EngineConfig(max_tile=64),
            cache=CompiledProgramCache(),
        )
        streamed.begin_param_streaming()
        assert not streamed.params_resident
        # complete on a timer thread while predict blocks on the gate
        import threading

        threading.Timer(
            0.15, streamed.complete_param_streaming, args=(params,)
        ).start()
        t0 = time.perf_counter()
        y_streamed = streamed.predict(x)
        assert time.perf_counter() - t0 >= 0.1  # it actually waited
        assert streamed.params_resident
        y_eager = eager.predict(x)
        assert np.array_equal(y_streamed, y_eager)
        d = streamed.describe()
        assert d["params_resident"] is True
        eager.close()
        streamed.close()

    def test_loader_error_surfaces_on_predict(self):
        import jax
        import jax.numpy as jnp

        from bioengine_tpu.models.unet import UNet2D
        from bioengine_tpu.runtime.engine import EngineConfig, InferenceEngine
        from bioengine_tpu.runtime.program_cache import CompiledProgramCache

        model = UNet2D(features=(4, 8), out_channels=1)
        x = np.zeros((1, 64, 64, 1), np.float32)
        params = model.init(jax.random.key(0), jnp.asarray(x))["params"]
        engine = InferenceEngine(
            "gate-error",
            lambda p, t: model.apply({"params": p}, t),
            params,
            divisor=model.divisor,
            config=EngineConfig(max_tile=64),
            cache=CompiledProgramCache(),
        )
        engine.begin_param_streaming()
        engine.fail_param_streaming(ValueError("manifest mismatch"))
        with pytest.raises(RuntimeError, match="manifest mismatch"):
            engine.predict(x)
        engine.close()

    def test_manifest_helpers(self, tmp_path):
        from bioengine_tpu.runtime.weight_stream import (
            group_keys,
            load_manifest,
            manifest_path_for,
            skeleton_from_manifest,
            write_manifest,
        )

        weights = tmp_path / "w.npz"
        flat = {
            "enc/conv/kernel": np.zeros((3, 3, 1, 4), np.float32),
            "enc/conv/bias": np.zeros((4,), np.float16),
            "dec/out": np.zeros((4, 1), np.float32),
        }
        np.savez(weights, **flat)
        p = write_manifest(weights, flat)
        assert p == manifest_path_for(weights)
        manifest = load_manifest(weights)
        assert manifest == {
            "enc/conv/kernel": {"shape": [3, 3, 1, 4], "dtype": "float32"},
            "enc/conv/bias": {"shape": [4], "dtype": "float16"},
            "dec/out": {"shape": [4, 1], "dtype": "float32"},
        }
        assert sorted(group_keys(manifest)) == ["dec", "enc"]
        skel = skeleton_from_manifest(manifest)
        assert skel["enc"]["conv"]["kernel"].shape == (3, 3, 1, 4)
        # the skeleton carries the checkpoint's dtypes — a wrong-dtype
        # skeleton would warm executables the real params retrace past
        assert skel["enc"]["conv"]["bias"].dtype == np.float16
        # legacy shape-only manifests (the PR 3 committed fixtures'
        # format) normalize with dtype float32
        legacy = tmp_path / "legacy.npz"
        np.savez(legacy, **{"a/b": np.zeros((2, 2), np.float32)})
        (tmp_path / "legacy.npz.manifest.json").write_text(
            json.dumps({"a/b": [2, 2]})
        )
        assert load_manifest(legacy) == {
            "a/b": {"shape": [2, 2], "dtype": "float32"}
        }
        # absent manifest → None (the eager-fallback trigger)
        assert load_manifest(tmp_path / "other.npz") is None


# ---------------------------------------------------------------------------
# warm pool: fill / promote / refill / sweep, and status surfaces
# ---------------------------------------------------------------------------


class PingApp:
    async def async_init(self):
        pass

    async def ping(self):
        return "ok"


def _warm_spec(size=1, refill=True, name="e"):
    from bioengine_tpu.serving import DeploymentSpec, WarmPoolConfig

    return DeploymentSpec(
        name=name,
        instance_factory=PingApp,
        num_replicas=1,
        max_replicas=4,
        autoscale=False,
        warm_pool=WarmPoolConfig(size=size, refill=refill),
    )


async def _wait_for(predicate, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


class TestWarmPool:
    async def test_deploy_fills_pool_and_scale_up_promotes(self):
        from bioengine_tpu.cluster.state import ClusterState
        from bioengine_tpu.serving import ServeController

        flight.clear()
        controller = ServeController(ClusterState(), health_check_period=3600)
        spec = _warm_spec(size=1)
        app = await controller.deploy("wp", [spec])
        pool = controller._warm_pools[("wp", "e")]
        assert len(app.replicas["e"]) == 1            # serving set
        assert len(pool.standbys) == 1                # standby OUT of it
        standby_id = pool.standbys[0].replica_id
        status = controller.get_app_status("wp")
        cold = status["deployments"]["e"]["cold_start"]
        assert cold["warm_pool"]["occupancy"] == 1
        assert cold["warm_pool"]["promotions"] == 0

        # scale-up: the standby is PROMOTED, not cold-started
        promoted = await controller._add_replica(app, spec)
        assert promoted.replica_id == standby_id
        assert promoted.promoted_from_warm_pool is True
        assert promoted in app.replicas["e"]
        assert "standby_seconds" in promoted.ttfr
        # a promoted replica serves immediately and records its TTFR
        assert await promoted.call("ping") == "ok"
        assert promoted.ttfr["ttfr_seconds"] < 1.0
        types = [e["type"] for e in flight.get_record()["events"]]
        assert "warmpool.fill" in types
        assert "warmpool.promote" in types
        assert "replica.first_request" in types
        # background refill restores the pool
        await _wait_for(
            lambda: len(pool.standbys) == 1, msg="warm-pool refill"
        )
        status = controller.get_app_status("wp")
        cold = status["deployments"]["e"]["cold_start"]
        assert cold["warm_pool"]["promotions"] == 1
        assert cold["last_replica_ttfr"]["promoted_from_warm_pool"] is True
        await controller.stop()
        assert controller._warm_pools == {}

    async def test_unhealthy_replica_restart_promotes_standby(self):
        from bioengine_tpu.cluster.state import ClusterState
        from bioengine_tpu.serving import ReplicaState, ServeController

        flight.clear()
        controller = ServeController(ClusterState(), health_check_period=3600)
        spec = _warm_spec(size=1, refill=False)
        app = await controller.deploy("wp2", [spec])
        pool = controller._warm_pools[("wp2", "e")]
        standby_id = pool.standbys[0].replica_id
        victim = app.replicas["e"][0]
        victim.state = ReplicaState.UNHEALTHY
        await controller.health_tick()
        ids = [r.replica_id for r in app.replicas["e"]]
        assert standby_id in ids and victim.replica_id not in ids
        assert pool.standbys == []  # refill=False → pool spent
        events = flight.get_record()["events"]
        promote = [e for e in events if e["type"] == "warmpool.promote"]
        place = [
            e
            for e in events
            if e["type"] == "replica.place"
            and e["attrs"].get("warm_pool") is True
        ]
        assert promote and place
        await controller.stop()

    async def test_dead_standby_is_released_and_refilled(self):
        from bioengine_tpu.cluster.state import ClusterState
        from bioengine_tpu.serving import ReplicaState, ServeController

        controller = ServeController(ClusterState(), health_check_period=3600)
        spec = _warm_spec(size=1)
        app = await controller.deploy("wp3", [spec])
        pool = controller._warm_pools[("wp3", "e")]
        dead = pool.standbys[0]
        dead.state = ReplicaState.UNHEALTHY
        await controller.health_tick()
        # the tick releases the dead standby immediately; the refill is
        # a cold start and runs OFF the health loop (background task)
        await _wait_for(
            lambda: len(pool.standbys) == 1
            and pool.standbys[0].replica_id != dead.replica_id,
            msg="dead standby replaced",
        )
        assert dead.state == ReplicaState.STOPPED
        await controller.stop()

    async def test_undeploy_sweeps_standbys(self):
        from bioengine_tpu.cluster.state import ClusterState
        from bioengine_tpu.serving import ReplicaState, ServeController

        controller = ServeController(ClusterState(), health_check_period=3600)
        spec = _warm_spec(size=2)
        await controller.deploy("wp4", [spec])
        pool = controller._warm_pools[("wp4", "e")]
        standbys = list(pool.standbys)
        assert len(standbys) == 2
        await controller.undeploy("wp4")
        assert ("wp4", "e") not in controller._warm_pools
        assert all(r.state == ReplicaState.STOPPED for r in standbys)
        await controller.stop()

    def test_target_size_follows_telemetry(self):
        from bioengine_tpu.serving import WarmPool, WarmPoolConfig

        class RisingRate:
            def series(self, app, dep, name):
                assert name == "request_rate"
                return [{"t": 0, "value": v} for v in (1.0, 1.0, 5.0)]

        class FlatRate:
            def series(self, app, dep, name):
                return [{"t": 0, "value": 1.0}] * 4

        pool = WarmPool(
            "a", "d", WarmPoolConfig(size=1, max_size=2, telemetry_sized=True)
        )
        assert pool.target_size(RisingRate()) == 2   # burst → deepen
        assert pool.target_size(FlatRate()) == 1     # steady → configured
        assert pool.target_size(None) == 1
        capped = WarmPool(
            "a", "d", WarmPoolConfig(size=2, max_size=2, telemetry_sized=True)
        )
        assert capped.target_size(RisingRate()) == 2  # never past max_size

    def test_builder_parses_warm_pool_block(self, tmp_path):
        import yaml

        from bioengine_tpu.apps.builder import AppBuilder, AppBuildError

        def write_app(warm_pool):
            d = tmp_path / "app-src"
            d.mkdir(exist_ok=True)
            (d / "manifest.yaml").write_text(
                yaml.safe_dump(
                    {
                        "name": "WP App",
                        "id": "wp-app",
                        "id_emoji": "x",
                        "description": "d",
                        "type": "tpu-serve",
                        "version": "1.0.0",
                        "deployments": ["dep:Dep"],
                        "authorized_users": ["*"],
                        "deployment_config": {
                            "dep": {"warm_pool": warm_pool}
                        },
                    }
                )
            )
            (d / "dep.py").write_text(
                "from bioengine_tpu.rpc import schema_method\n\n\n"
                "class Dep:\n"
                "    @schema_method\n"
                "    async def ping(self, context=None):\n"
                '        """Ping."""\n'
                "        return 'ok'\n"
            )
            return d

        builder = AppBuilder(workdir_root=tmp_path / "apps")
        built = builder.build(
            app_id="wp-app",
            local_path=write_app({"size": 2, "telemetry_sized": True}),
        )
        spec = built.specs[0]
        assert spec.warm_pool is not None
        assert spec.warm_pool.size == 2
        assert spec.warm_pool.telemetry_sized is True
        with pytest.raises(AppBuildError, match="warm_pool"):
            builder.build(
                app_id="wp-app-bad",
                local_path=write_app({"pool_size": 2}),
            )


# ---------------------------------------------------------------------------
# shared compile-cache tier over the in-process multi-host control plane
# ---------------------------------------------------------------------------

WARM_CHAOS_MANIFEST = """\
name: Warm Chaos App
id: warm-chaos-app
id_emoji: "\\U0001F525"
description: idempotent arithmetic for warm-pool chaos traffic
type: tpu-serve
version: 1.0.0
deployments:
  - chaos_dep:ChaosDep
authorized_users: ["*"]
deployment_config:
  chaos_dep:
    num_replicas: 2
    min_replicas: 2
    max_replicas: 3
    chips: 3
    autoscale: false
    warm_pool:
      size: 1
      refill: false
"""

CHAOS_SOURCE = '''\
from bioengine_tpu.rpc import schema_method


class ChaosDep:
    def __init__(self):
        self.calls = 0

    @schema_method
    async def add(self, a: int, b: int, context=None):
        """Idempotent arithmetic."""
        self.calls += 1
        return {"sum": a + b}
'''


def _no_local_chips():
    from bioengine_tpu.cluster.state import ClusterState
    from bioengine_tpu.cluster.topology import TpuTopology

    return ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu"))


@pytest.fixture()
async def control_plane(tmp_path):
    from bioengine_tpu.rpc.server import RpcServer
    from bioengine_tpu.serving import ServeController
    from bioengine_tpu.worker_host import WorkerHost

    server = RpcServer(host="127.0.0.1", admin_users=["admin"])
    await server.start()
    token = server.issue_token("admin", is_admin=True)
    controller = ServeController(_no_local_chips(), health_check_period=3600)
    # per-test tier directory (the default is a real home-dir path)
    from bioengine_tpu.serving.compile_tier import CompileCacheTier

    controller.compile_tier = CompileCacheTier(tmp_path / "tier")
    controller.attach_rpc(server, admin_users=["admin"])
    hosts = []

    async def spawn_host(host_id: str, **kwargs) -> WorkerHost:
        host = WorkerHost(
            server_url=server.url,
            token=token,
            host_id=host_id,
            workspace_dir=tmp_path / f"ws-{host_id}",
            **kwargs,
        )
        await host.start()
        hosts.append(host)
        return host

    try:
        yield server, controller, spawn_host, tmp_path
    finally:
        for host in hosts:
            try:
                await host.stop()
            except Exception:
                pass
        await controller.stop()
        await server.stop()


class TestCompileTierSync:
    async def test_join_publishes_and_later_host_fetches(
        self, control_plane
    ):
        """h1 joins with two locally-compiled entries → they land in
        the controller tier; h2 joins with an empty directory → the
        entries are fetched into it (a fresh autoscaled host starts
        with the fleet's programs), with program.cache_fetch flight
        evidence and tier hit accounting."""
        server, controller, spawn_host, tmp_path = control_plane
        flight.clear()
        dir_a = tmp_path / "xla-a"
        dir_a.mkdir()
        (dir_a / "jit_model-k1-cache").write_bytes(b"P1" * 600)
        (dir_a / "jit_model-k2-cache").write_bytes(b"P2" * 600)
        (dir_a / "jit_model-k1-atime").write_bytes(b"t")  # local-only
        dir_b = tmp_path / "xla-b"
        dir_b.mkdir()

        h1 = await spawn_host("h1", compile_cache_dir=dir_a)
        assert h1.tier_published_count == 2
        assert set(controller.compile_tier.list()) == {
            "jit_model-k1-cache",
            "jit_model-k2-cache",
        }

        h2 = await spawn_host("h2", compile_cache_dir=dir_b)
        assert h2.tier_fetched == 2
        assert compile_cache.list_entries(dir_b) == {
            "jit_model-k1-cache": 1200,
            "jit_model-k2-cache": 1200,
        }
        assert (dir_b / "jit_model-k1-cache").read_bytes() == b"P1" * 600
        # the fetch is flight-recorded (the trace of WHY a cold compile
        # became a disk read)
        fetches = [
            e
            for e in flight.get_record()["events"]
            if e["type"] == "program.cache_fetch"
        ]
        assert len(fetches) == 2
        assert all(e["attrs"]["host"] == "h2" for e in fetches)
        stats = controller.compile_tier.stats()
        assert stats["served"] == 2 and stats["stored"] == 2
        assert stats["hit_rate"] == 1.0
        # host describe carries the sync counters
        assert h2.describe()["compile_tier"]["fetched"] == 2
        assert h1.describe()["compile_tier"]["published"] == 2

    async def test_replica_start_resyncs_and_publishes(
        self, control_plane
    ):
        """Entries published AFTER a host joined are pulled before its
        next replica build, and entries the build compiles are pushed
        back — the start_replica hook, proven at file level."""
        from pathlib import Path

        from bioengine_tpu.apps.builder import AppBuilder
        from bioengine_tpu.serving import RequestOptions

        server, controller, spawn_host, tmp_path = control_plane
        dir_a = tmp_path / "xla-h1"
        dir_a.mkdir()
        h1 = await spawn_host("h1", compile_cache_dir=dir_a)
        # a LATER publisher (another host's compile)
        controller.compile_tier.publish("jit_late-k9-cache", b"LATE" * 300)

        app_dir = tmp_path / "app-src"
        app_dir.mkdir()
        (app_dir / "manifest.yaml").write_text(WARM_CHAOS_MANIFEST.replace(
            "num_replicas: 2", "num_replicas: 1"
        ).replace("min_replicas: 2", "min_replicas: 1").replace(
            "    warm_pool:\n      size: 1\n      refill: false\n", ""
        ))
        (app_dir / "chaos_dep.py").write_text(CHAOS_SOURCE)
        builder = AppBuilder(workdir_root=tmp_path / "apps")
        built = builder.build(app_id="warm-chaos-app", local_path=app_dir)
        await controller.deploy("warm-chaos-app", built.specs)
        # the pre-build sync installed the late entry locally
        assert "jit_late-k9-cache" in compile_cache.list_entries(dir_a)
        # and a "compile" this replica produced locally is published back
        (Path(dir_a) / "jit_fresh-k5-cache").write_bytes(b"F" * 100)
        await h1._publish_compile_cache()
        assert "jit_fresh-k5-cache" in controller.compile_tier.list()
        handle = controller.get_handle("warm-chaos-app")
        r = await handle.call(
            "add", 1, 2, options=RequestOptions(idempotent=True)
        )
        assert r["sum"] == 3


# ---------------------------------------------------------------------------
# acceptance: preemption chaos with a warm pool
# ---------------------------------------------------------------------------


class TestWarmPoolChaos:
    async def test_preemption_absorbed_by_standby(self, control_plane):
        """Kill the host serving a replica mid-traffic: the warm
        standby absorbs the loss within the request deadline — ZERO
        failed idempotent requests, chip accounting exact, and the
        flight record shows warmpool.promote between host.dead and
        replica.place."""
        from bioengine_tpu.apps.builder import AppBuilder
        from bioengine_tpu.serving import ReplicaState, RequestOptions

        server, controller, spawn_host, tmp_path = control_plane
        flight.clear()
        h1 = await spawn_host("h1")
        h2 = await spawn_host("h2")
        app_dir = tmp_path / "chaos-src"
        app_dir.mkdir()
        (app_dir / "manifest.yaml").write_text(WARM_CHAOS_MANIFEST)
        (app_dir / "chaos_dep.py").write_text(CHAOS_SOURCE)
        builder = AppBuilder(workdir_root=tmp_path / "apps")
        built = builder.build(app_id="warm-chaos-app", local_path=app_dir)
        await controller.deploy("warm-chaos-app", built.specs)
        app = controller.apps["warm-chaos-app"]
        replicas = app.replicas["chaos_dep"]
        assert sorted(r.host_id for r in replicas) == ["h1", "h2"]
        pool = controller._warm_pools[("warm-chaos-app", "chaos_dep")]
        assert len(pool.standbys) == 1
        standby = pool.standbys[0]
        # kill the host that serves a replica but does NOT hold the
        # standby — the standby must survive to absorb the preemption
        victim_host = next(
            h for h in (h1, h2)
            if h.host_id != standby.host_id
            and any(r.host_id == h.host_id for r in replicas)
        )
        survivor = h1 if victim_host is h2 else h2

        handle = controller.get_handle("warm-chaos-app")
        opts = RequestOptions(idempotent=True, deadline_s=20, max_attempts=8)
        failures: list = []
        successes = [0]
        kill_at = asyncio.Event()

        async def traffic(worker_id: int):
            for i in range(25):
                try:
                    r = await handle.call("add", worker_id, i, options=opts)
                    assert r["sum"] == worker_id + i
                    successes[0] += 1
                except Exception as e:  # noqa: BLE001 — counted, not raised
                    failures.append(e)
                if i == 6 and worker_id == 0:
                    kill_at.set()
                await asyncio.sleep(0.004)

        tasks = [asyncio.create_task(traffic(w)) for w in range(4)]
        await asyncio.wait_for(kill_at.wait(), 10)
        # the in-process analog of SIGKILL/preemption (test_chaos)
        victim_host.rejoin = False
        victim_host.connection.auto_reconnect = False
        victim_host.connection._closing = True
        await victim_host.connection._abort_connection()

        t_kill = time.monotonic()
        recovered = False
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            await controller.health_tick()
            reps = app.replicas["chaos_dep"]
            routable = [
                r
                for r in reps
                if r.state in (ReplicaState.HEALTHY, ReplicaState.TESTING)
            ]
            if len(routable) == 2:
                recovered = True
                break
            await asyncio.sleep(0.05)
        recovery_s = time.monotonic() - t_kill
        await asyncio.gather(*tasks)

        assert failures == []          # ZERO failed idempotent requests
        assert successes[0] == 100
        assert recovered, "standby was not promoted in time"
        assert recovery_s < 15.0       # well inside the request deadline
        # the standby WAS the absorber
        ids = [r.replica_id for r in app.replicas["chaos_dep"]]
        assert standby.replica_id in ids
        assert standby.promoted_from_warm_pool is True
        assert pool.standbys == []     # refill=false → pool spent

        # flight timeline: host.dead → warmpool.promote → replica.place
        events = flight.get_record(limit=2000)["events"]
        i_dead = next(
            i for i, e in enumerate(events)
            if e["type"] == "host.dead"
            and e["attrs"].get("host") == victim_host.host_id
        )
        i_promote = next(
            i for i, e in enumerate(events)
            if e["type"] == "warmpool.promote"
            and e["attrs"].get("replica") == standby.replica_id
        )
        i_place = next(
            i for i, e in enumerate(events)
            if e["type"] == "replica.place"
            and e["attrs"].get("replica") == standby.replica_id
            and e["attrs"].get("warm_pool") is True
        )
        assert i_dead < i_promote < i_place

        # chip accounting exact: the dead host leaks nothing; the
        # survivor holds its original replica + the promoted standby
        # (2 leases x 3 chips), no double lease
        state = controller.cluster_state
        assert state.hosts[victim_host.host_id].chips_in_use == {}
        assert not state.hosts[victim_host.host_id].alive
        surviving = state.hosts[survivor.host_id].chips_in_use
        assert len(surviving) == 6
        assert len(set(surviving.values())) == 2

        # the cold-start status surface reports the promotion
        cold = controller.get_app_status("warm-chaos-app")["deployments"][
            "chaos_dep"
        ]["cold_start"]
        assert cold["warm_pool"]["promotions"] == 1
        assert cold["warm_pool"]["occupancy"] == 0
