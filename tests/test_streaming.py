"""Token streaming end to end: the ``generate`` app through the
serving plane, mid-stream failover with exactly-once resume, the RPC
stream1 plane, and the mesh-manifest parity unlock.

Layer map (bottom-up):

- ``TestRpcStreamPlane`` — streaming calls over a REAL websocket:
  per-item ordering, typed mid-stream application errors, and the
  provider-generator lifecycle pin (abandoning a stream closes the
  provider's async generator deterministically — its ``finally`` runs
  NOW, not at GC; that is what keeps replica ongoing-counts and decode
  slots from stranding until drain timeouts).
- ``TestStreamFailover`` — ``DeploymentHandle.call_stream`` resumes an
  idempotent stream on another replica with ``resume_from=<yielded>``
  after a mid-stream transport failure: the consumer sees an
  uninterrupted exactly-once sequence and ``decode.stream_resume``
  marks the seam. Non-idempotent streams fail typed instead.
- ``TestGenerateApp`` — the shipped ``apps/generate`` manifest deployed
  unmodified: stream == unary == the golden fixture's greedy tokens,
  ``resume_from`` emits exactly the missing suffix.
- ``TestMeshManifestParity`` — the SAME app sources with a ``mesh:``
  block (1 stage x 2 chips, dp axes) deployed over a real worker-host
  plane: bit-identical greedy tokens to the 1-chip deploy (both pin the
  golden fixture), streaming included — the sharded-decoder unlock is a
  manifest edit.
"""

from __future__ import annotations

import asyncio
import time
from pathlib import Path

import numpy as np
import pytest

from bioengine_tpu.apps.builder import AppBuilder
from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology
from bioengine_tpu.rpc.client import connect_to_server
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.serving import (
    DeploymentSpec,
    RequestOptions,
    ServeController,
)
from bioengine_tpu.serving.errors import RetryableTransportError
from bioengine_tpu.utils import flight
from bioengine_tpu.worker_host import WorkerHost

pytestmark = [pytest.mark.integration, pytest.mark.anyio]

APP_DIR = Path(__file__).resolve().parent.parent / "apps" / "generate"
FIXTURE = Path(__file__).parent / "fixtures_golden_decoder.npz"
GOLDEN_PROMPT = "the cell divides"


# ---------------------------------------------------------------------------
# RPC stream plane
# ---------------------------------------------------------------------------


@pytest.fixture
async def rpc_server():
    srv = RpcServer(admin_users=["admin"])
    await srv.start()
    yield srv
    await srv.stop()


@pytest.fixture
async def rpc_conn(rpc_server):
    token = rpc_server.issue_token("admin")
    conn = await connect_to_server(
        {"server_url": f"http://127.0.0.1:{rpc_server.port}", "token": token}
    )
    yield conn
    await conn.disconnect()


class TestRpcStreamPlane:
    async def test_remote_stream_items_arrive_in_order(
        self, rpc_server, rpc_conn
    ):
        async def countdown(n: int = 5, context=None):
            for i in range(n):
                await asyncio.sleep(0.001)
                yield {"i": i}

        await rpc_conn.register_service(
            {"id": "gen-svc", "countdown": countdown}
        )
        items = [
            item
            async for item in rpc_conn.call_stream("gen-svc", "countdown", n=7)
        ]
        assert [it["i"] for it in items] == list(range(7))

    async def test_mid_stream_application_error_is_raised(
        self, rpc_server, rpc_conn
    ):
        async def explode(context=None):
            yield 1
            yield 2
            raise ValueError("boom mid-stream")

        await rpc_conn.register_service({"id": "boom-svc", "explode": explode})
        got = []
        with pytest.raises(Exception, match="boom mid-stream"):
            async for item in rpc_conn.call_stream("boom-svc", "explode"):
                got.append(item)
        assert got == [1, 2]

    async def test_abandoned_stream_closes_provider_generator(
        self, rpc_server
    ):
        """The resource-lifecycle pin: a consumer that stops consuming
        (disconnect, break, send failure) must close the provider's
        generator NOW — the generator's ``finally`` is what releases
        decode slots and replica ongoing-counts, and leaving it to GC
        is exactly the stranded-drain leak this pins against."""
        closed = asyncio.Event()

        async def infinite(context=None):
            try:
                i = 0
                while True:
                    yield i
                    i += 1
                    await asyncio.sleep(0)
            finally:
                closed.set()

        rpc_server.register_local_service(
            {"id": "leak-svc", "infinite": infinite}
        )
        caller = rpc_server.validate_token(rpc_server.issue_token("admin"))
        agen = rpc_server.call_service_stream(
            "leak-svc", "infinite", (), {}, caller=caller
        )
        got = []
        async for item in agen:
            got.append(item)
            if len(got) == 3:
                break
        await agen.aclose()
        assert got == [0, 1, 2]
        await asyncio.wait_for(closed.wait(), 5.0)


# ---------------------------------------------------------------------------
# handle-level mid-stream failover
# ---------------------------------------------------------------------------

# module-level so both replicas' instances share the arming state: the
# FIRST stream attempt (whichever replica the router picks) dies
# mid-stream, the resumed attempt completes
_FLAKY = {"armed": False}


def _flaky_tokens(n: int) -> list:
    return [(i * i) % 101 for i in range(n)]


class _FlakyGen:
    async def gen(self, n: int = 10, resume_from: int = 0):
        full = _flaky_tokens(n)
        for i in range(int(resume_from or 0), n):
            await asyncio.sleep(0.001)
            yield {"token": full[i], "index": i}
            if _FLAKY["armed"] and i == 2:
                _FLAKY["armed"] = False
                raise RetryableTransportError(
                    "simulated transport drop mid-stream"
                )


@pytest.fixture
async def flaky_controller():
    c = ServeController(ClusterState(), health_check_period=3600)
    await c.deploy(
        "app",
        [
            DeploymentSpec(
                name="dep",
                instance_factory=_FlakyGen,
                num_replicas=2,
                min_replicas=2,
                max_replicas=2,
                autoscale=False,
            )
        ],
    )
    yield c
    _FLAKY["armed"] = False
    await c.stop()


class TestStreamFailover:
    async def test_idempotent_stream_resumes_exactly_once(
        self, flaky_controller
    ):
        """Mid-stream transport failure after 3 yielded items: the
        handle fails over with ``resume_from=3``, the consumer sees the
        full sequence exactly once, and the seam is flight-marked."""
        _FLAKY["armed"] = True
        t0 = time.time()
        handle = flaky_controller.get_handle("app", "dep")
        items = [
            item
            async for item in handle.call_stream(
                "gen",
                n=10,
                options=RequestOptions(idempotent=True, deadline_s=30),
            )
        ]
        assert [it["token"] for it in items] == _flaky_tokens(10)
        assert [it["index"] for it in items] == list(range(10))
        assert not _FLAKY["armed"]  # the failure really fired
        evs = flight.get_events(types=("decode.stream_resume",), since=t0)
        assert evs, "resume must be flight-marked"
        assert evs[-1]["attrs"]["resume_from"] == 3
        assert evs[-1]["attrs"]["attempt"] == 1

    async def test_non_idempotent_stream_fails_typed_after_items(
        self, flaky_controller
    ):
        _FLAKY["armed"] = True
        handle = flaky_controller.get_handle("app", "dep")
        got = []
        with pytest.raises(RetryableTransportError, match="non-idempotent"):
            async for item in handle.call_stream(
                "gen",
                n=10,
                options=RequestOptions(idempotent=False, deadline_s=30),
            ):
                got.append(item)
        assert len(got) == 3  # items before the drop were delivered

    async def test_clean_stream_no_resume_events(self, flaky_controller):
        t0 = time.time()
        handle = flaky_controller.get_handle("app", "dep")
        items = [
            item
            async for item in handle.call_stream(
                "gen", n=6, options=RequestOptions(idempotent=True)
            )
        ]
        assert [it["token"] for it in items] == _flaky_tokens(6)
        assert not flight.get_events(
            types=("decode.stream_resume",), since=t0
        )


# ---------------------------------------------------------------------------
# the shipped generate app (unmodified manifest, local 1-chip replica)
# ---------------------------------------------------------------------------


@pytest.fixture
async def generate_controller(tmp_path):
    controller = ServeController(ClusterState(), health_check_period=3600)
    builder = AppBuilder(
        workdir_root=tmp_path / "apps", admin_users=["admin"], log_file="off"
    )
    built = builder.build(app_id="generate", local_path=str(APP_DIR))
    await controller.deploy("generate", built.specs)
    for _ in range(600):
        reps = controller.apps["generate"].replicas["generate_deployment"]
        if reps and all(r.state.value == "HEALTHY" for r in reps):
            break
        await asyncio.sleep(0.05)
    else:
        raise RuntimeError("generate replicas never became healthy")
    yield controller
    await controller.stop()


class TestGenerateApp:
    async def test_stream_equals_unary_equals_golden_and_resumes(
        self, generate_controller
    ):
        """One deploy, the full contract: the streamed token sequence
        equals the unary drain, both equal the golden fixture's greedy
        continuation (the app really serves the pinned decoder), and a
        ``resume_from`` call emits exactly the missing suffix."""
        golden = np.load(FIXTURE)["greedy_tokens"].tolist()
        handle = generate_controller.get_handle("generate")
        opts = RequestOptions(idempotent=True, deadline_s=120)

        unary = await handle.call(
            "generate", prompt=GOLDEN_PROMPT, max_new_tokens=16, options=opts
        )
        assert unary["tokens"] == golden[:16]

        streamed = []
        async for item in handle.call_stream(
            "generate_stream",
            prompt=GOLDEN_PROMPT,
            max_new_tokens=16,
            options=opts,
        ):
            streamed.append(item["token"])
        assert streamed == unary["tokens"]

        resumed = []
        async for item in handle.call_stream(
            "generate_stream",
            prompt=GOLDEN_PROMPT,
            max_new_tokens=16,
            resume_from=11,
            options=opts,
        ):
            resumed.append(item["token"])
            assert item["index"] >= 11
        assert resumed == golden[11:16]

        st = await handle.call("describe_engine", options=opts)
        assert st["engine"]["n_devices"] == 1
        assert st["loop"]["tokens"] >= 32
        # every finished stream released its KV blocks
        assert st["engine"]["kv"]["sequences"] == 0


# ---------------------------------------------------------------------------
# mesh-manifest parity over a real worker-host plane
# ---------------------------------------------------------------------------

MESH_GENERATE_MANIFEST = """\
name: Generate (mesh)
id: generate-mesh
id_emoji: "✒️"
description: the generate app over a forced multi-device dp mesh
type: tpu-serve
version: 1.0.0
deployments:
  - generate_deployment:GenerateDeployment
authorized_users: ["*"]
deployment_config:
  generate_deployment:
    num_replicas: 1
    min_replicas: 1
    max_replicas: 1
    autoscale: false
    mesh:
      stages: 1
      chips_per_stage: 2
      kind: dp
      axes:
        dp: -1
"""


class TestMeshManifestParity:
    async def test_mesh_decoder_matches_golden_tokens(self, tmp_path):
        """The sharded-decoder unlock: the SAME deployment source with a
        ``mesh:`` block (1 stage x 2 chips, dp over the step batch)
        deployed over a real worker-host plane produces BIT-IDENTICAL
        greedy tokens to the 1-chip deploy — both pin the golden
        fixture — and streams through the mesh replica's stream bridge.
        Scaling the decoder is a manifest edit, not a code change."""
        golden = np.load(FIXTURE)["greedy_tokens"].tolist()

        server = RpcServer(host="127.0.0.1", admin_users=["admin"])
        await server.start()
        token = server.issue_token("admin", is_admin=True)
        controller = ServeController(
            ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu")),
            health_check_period=3600,
        )
        controller.attach_rpc(server, admin_users=["admin"])
        host = WorkerHost(
            server_url=server.url,
            token=token,
            host_id="h1",
            workspace_dir=tmp_path / "ws-h1",
            rejoin=True,
        )
        await host.start()
        try:
            app_dir = tmp_path / "generate-mesh-src"
            app_dir.mkdir()
            (app_dir / "manifest.yaml").write_text(MESH_GENERATE_MANIFEST)
            (app_dir / "generate_deployment.py").write_text(
                (APP_DIR / "generate_deployment.py").read_text()
            )
            builder = AppBuilder(workdir_root=tmp_path / "apps")
            built = builder.build(
                app_id="generate-mesh", local_path=app_dir
            )
            await controller.deploy("generate-mesh", built.specs)
            replicas = controller.apps["generate-mesh"].replicas[
                "generate_deployment"
            ]
            assert len(replicas) == 1
            mesh = replicas[0]
            # the lease is real: 2 chips on the joined host, billed to
            # the mesh replica
            rec = controller.cluster_state.hosts["h1"]
            assert list(rec.chips_in_use.values()) == [mesh.replica_id] * 2

            handle = controller.get_handle("generate-mesh")
            opts = RequestOptions(idempotent=True, deadline_s=180)
            out = await handle.call(
                "generate",
                prompt=GOLDEN_PROMPT,
                max_new_tokens=16,
                options=opts,
            )
            assert out["tokens"] == golden[:16], (
                "dp-mesh decoder diverged from the golden greedy tokens"
            )

            st = await handle.call("describe_engine", options=opts)
            assert st["engine"]["n_devices"] == 2
            assert st["engine"]["mesh"] == {"dp": 2}

            # streaming rides the mesh stream bridge end to end
            streamed = []
            async for item in handle.call_stream(
                "generate_stream",
                prompt=GOLDEN_PROMPT,
                max_new_tokens=12,
                options=opts,
            ):
                streamed.append(item["token"])
            assert streamed == golden[:12]
        finally:
            try:
                await host.stop()
            except Exception:
                pass
            await controller.stop()
            await server.stop()
