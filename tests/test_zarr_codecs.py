"""Native-codec zarr tests: blosc / zstd / lz4 / sharding_indexed.

Round-trips plus committed golden fixture bytes
(tests/fixtures_codec_golden.json — frames produced by the same C
libraries the numcodecs/zarr ecosystem wraps, so the byte formats are
ecosystem-identical), plus an OME-Zarr-shaped plate read end-to-end
through HttpZarrStore. Covers VERDICT round-1 gap #3: real-world
OME-Zarr defaults to blosc, which round 1 hard-rejected.
"""

import base64
import json
import struct
from pathlib import Path

import numpy as np
import pytest

from bioengine_tpu.datasets import codecs as native
from bioengine_tpu.datasets import zarr_codec
from bioengine_tpu.datasets.http_zarr_store import HttpZarrStore
from bioengine_tpu.datasets.proxy_server import DatasetsServer

# blosc rides a system libblosc via ctypes (zstd/lz4 ship in every
# image; blosc does not) — gate its tests on availability the way the
# sanitizer and aiortc tests gate on their builds, so dev sandboxes
# without the library skip honestly while driver/CI images run them
needs_blosc = pytest.mark.skipif(
    not native.blosc_available(),
    reason="libblosc not installed (driver/CI images have it)",
)

pytestmark = [pytest.mark.integration, pytest.mark.anyio]

GOLDEN = json.loads(
    (Path(__file__).parent / "fixtures_codec_golden.json").read_text()
)


def _read_array(root: Path, meta: zarr_codec.ArrayMeta) -> np.ndarray:
    chunks = {}
    for idx in meta.chunk_indices():
        p = root / meta.chunk_key(idx)
        chunks[idx] = zarr_codec.decode_chunk(
            meta, p.read_bytes() if p.exists() else None
        )
    return zarr_codec.assemble(meta, chunks)


def _roundtrip(tmp_path, data, **kwargs) -> np.ndarray:
    meta = zarr_codec.write_array(tmp_path, "arr", data, **kwargs)
    parsed = zarr_codec.parse_array_meta(
        (tmp_path / "arr" / meta.doc_name()).read_bytes()
    )
    return _read_array(tmp_path / "arr", parsed)


# ---- round-trips through parse_array_meta (not the in-memory meta) ----------


@pytest.mark.parametrize(
    "compressor,config",
    [
        pytest.param(
            "blosc", {"cname": "lz4", "shuffle": 1}, marks=needs_blosc
        ),
        pytest.param(
            "blosc", {"cname": "zstd", "shuffle": 2}, marks=needs_blosc
        ),
        pytest.param(
            "blosc", {"cname": "blosclz", "shuffle": 0}, marks=needs_blosc
        ),
        ("zstd", {}),
        ("lz4", {}),
    ],
)
def test_v2_native_compressor_roundtrip(tmp_path, compressor, config):
    data = np.random.default_rng(0).integers(
        0, 500, size=(20, 30), dtype=np.uint16
    )
    out = _roundtrip(
        tmp_path, data, chunks=(8, 8), compressor=compressor,
        compressor_config=config, zarr_format=2,
    )
    np.testing.assert_array_equal(out, data)


@pytest.mark.parametrize(
    "compressor", [pytest.param("blosc", marks=needs_blosc), "zstd"]
)
def test_v3_native_compressor_roundtrip(tmp_path, compressor):
    data = np.random.default_rng(1).normal(size=(17, 9)).astype(np.float32)
    out = _roundtrip(
        tmp_path, data, chunks=(8, 4), compressor=compressor, zarr_format=3
    )
    np.testing.assert_array_equal(out, data)


@pytest.mark.parametrize(
    "compressor",
    [None, "zstd", pytest.param("blosc", marks=needs_blosc)],
)
def test_v3_sharding_roundtrip(tmp_path, compressor):
    data = np.random.default_rng(2).integers(
        0, 9000, size=(40, 24), dtype=np.int32
    )
    out = _roundtrip(
        tmp_path, data, chunks=(16, 16), inner_chunks=(8, 8),
        compressor=compressor, zarr_format=3,
    )
    np.testing.assert_array_equal(out, data)


def test_sharding_meta_parsed(tmp_path):
    data = np.zeros((32, 32), np.uint8)
    zarr_codec.write_array(
        tmp_path, "s", data, chunks=(16, 16), inner_chunks=(4, 4),
        compressor="zstd", zarr_format=3,
    )
    meta = zarr_codec.parse_array_meta(
        (tmp_path / "s" / "zarr.json").read_bytes()
    )
    assert meta.sharding is not None
    assert meta.sharding.inner_chunks == (4, 4)
    assert meta.chunks == (16, 16)  # outer grid = shards


def test_shard_missing_inner_chunk_uses_fill():
    spec = zarr_codec.ShardingSpec(
        inner_chunks=(2, 2),
        codecs=[{"name": "bytes", "configuration": {"endian": "little"}}],
        index_codecs=[
            {"name": "bytes", "configuration": {"endian": "little"}},
            {"name": "crc32c"},
        ],
    )
    meta = zarr_codec.ArrayMeta(
        shape=(4, 4), chunks=(4, 4), dtype=np.dtype("<u2"),
        zarr_format=3, fill_value=7, sharding=spec,
    )
    # Hand-build a shard holding ONE of four inner chunks.
    blob = np.full((2, 2), 5, "<u2").tobytes()
    index = np.full((4, 2), zarr_codec._MISSING_CHUNK, "<u8")
    index[0] = (0, len(blob))
    index_raw = index.tobytes()
    index_raw += struct.pack("<I", native.crc32c(index_raw))
    out = zarr_codec.decode_chunk(meta, blob + index_raw)
    assert (out[:2, :2] == 5).all()
    assert (out[2:, :] == 7).all() and (out[:2, 2:] == 7).all()


def test_shard_index_location_start():
    spec = zarr_codec.ShardingSpec(
        inner_chunks=(2,),
        codecs=[{"name": "bytes", "configuration": {"endian": "little"}}],
        index_codecs=[{"name": "bytes", "configuration": {"endian": "little"}}],
        index_location="start",
    )
    meta = zarr_codec.ArrayMeta(
        shape=(4,), chunks=(4,), dtype=np.dtype("<i4"),
        zarr_format=3, sharding=spec,
    )
    data = np.array([1, 2, 3, 4], "<i4")
    raw = zarr_codec.encode_chunk(meta, data)
    # index first: offsets must point past it
    offsets = np.frombuffer(raw[:32], "<u8").reshape(2, 2)
    assert offsets[0, 0] == 32
    np.testing.assert_array_equal(zarr_codec.decode_chunk(meta, raw), data)


def test_shard_index_crc_corruption_detected():
    spec = zarr_codec.ShardingSpec(
        inner_chunks=(2,),
        codecs=[{"name": "bytes", "configuration": {"endian": "little"}}],
        index_codecs=[
            {"name": "bytes", "configuration": {"endian": "little"}},
            {"name": "crc32c"},
        ],
    )
    meta = zarr_codec.ArrayMeta(
        shape=(2,), chunks=(2,), dtype=np.dtype("<i4"),
        zarr_format=3, sharding=spec,
    )
    raw = bytearray(zarr_codec.encode_chunk(meta, np.array([1, 2], "<i4")))
    raw[-1] ^= 0xFF  # flip a checksum byte
    with pytest.raises(ValueError, match="crc32c"):
        zarr_codec.decode_chunk(meta, bytes(raw))


# ---- golden fixture bytes ----------------------------------------------------


@pytest.mark.parametrize(
    "key,decode",
    [
        pytest.param(
            "blosc_lz4_shuffle", native.blosc_decompress, marks=needs_blosc
        ),
        pytest.param(
            "blosc_zstd_bitshuffle", native.blosc_decompress,
            marks=needs_blosc,
        ),
        pytest.param(
            "blosc_blosclz_noshuffle", native.blosc_decompress,
            marks=needs_blosc,
        ),
        ("zstd_frame", native.zstd_decompress),
        ("lz4_numcodecs", native.lz4_decompress),
    ],
)
def test_golden_fixture_decode(key, decode):
    """Committed frames decode to the expected array (regression pin)."""
    expected = np.arange(96, dtype=GOLDEN["expected_dtype"]).reshape(
        GOLDEN["expected_shape"]
    )
    assert decode(base64.b64decode(GOLDEN[key])) == expected.tobytes(), key


def test_golden_blosc_header_is_blosc1_format():
    """The frames carry the standard blosc1 header zarr/numcodecs write."""
    frame = base64.b64decode(GOLDEN["blosc_lz4_shuffle"])
    assert frame[0] == 2  # BLOSC_VERSION_FORMAT
    nbytes, blocksize, cbytes = struct.unpack("<III", frame[4:16])
    assert nbytes == 192 and cbytes == len(frame)


@needs_blosc
def test_v3_realworld_metadata_parse():
    """zarr-python-style v3 doc: string shuffle, NaN fill, typesize."""
    doc = {
        "zarr_format": 3,
        "node_type": "array",
        "shape": [6, 6],
        "data_type": "float32",
        "chunk_grid": {
            "name": "regular", "configuration": {"chunk_shape": [3, 3]}
        },
        "chunk_key_encoding": {
            "name": "default", "configuration": {"separator": "/"}
        },
        "codecs": [
            {"name": "bytes", "configuration": {"endian": "little"}},
            {
                "name": "blosc",
                "configuration": {
                    "cname": "zstd", "clevel": 5, "shuffle": "bitshuffle",
                    "typesize": 4, "blocksize": 0,
                },
            },
        ],
        "fill_value": "NaN",
        "attributes": {},
    }
    meta = zarr_codec.parse_array_meta(json.dumps(doc))
    assert meta.compressor == "blosc"
    assert meta.compressor_config["shuffle"] == 2
    assert np.isnan(meta.fill_value)
    data = np.random.default_rng(3).normal(size=(3, 3)).astype(np.float32)
    np.testing.assert_array_equal(
        zarr_codec.decode_chunk(meta, zarr_codec.encode_chunk(meta, data)),
        data,
    )


def test_unavailable_codec_error_names_library(monkeypatch):
    monkeypatch.setattr(native, "_libblosc", lambda: None)
    with pytest.raises(native.CodecUnavailable, match="libblosc"):
        native.blosc_decompress(b"\x02\x01" + b"\x00" * 14)


# ---- OME-Zarr-shaped end-to-end read through HttpZarrStore -------------------


@pytest.fixture()
async def ome_server(tmp_path):
    """An OME-Zarr-shaped multiscale image: v2, blosc-zstd, '/'-separated
    chunk keys — the layout ome-zarr-py/bioformats2raw writes."""
    data_dir = tmp_path / "data"
    ds = data_dir / "plate"
    ds.mkdir(parents=True)
    (ds / "manifest.yaml").write_text(
        "description: ome plate\nauthorized_users: ['*']\n"
    )
    rng = np.random.default_rng(7)
    # (t=1, c=2, z=1, y=64, x=64) uint16, downscaled level 1 at y/2, x/2
    level0 = rng.integers(0, 4000, size=(1, 2, 1, 64, 64), dtype=np.uint16)
    level1 = level0[..., ::2, ::2].copy()
    root = ds / "image.zarr"
    zarr_codec.write_group(
        root,
        attributes={
            "multiscales": [
                {"version": "0.4", "datasets": [{"path": "0"}, {"path": "1"}]}
            ]
        },
    )
    for name, lvl in [("0", level0), ("1", level1)]:
        meta = zarr_codec.write_array(
            root, name, lvl, chunks=(1, 1, 1, 32, 32),
            compressor="blosc",
            compressor_config={"cname": "zstd", "shuffle": 1},
            zarr_format=2,
        )
        # ome-zarr uses '/' dimension separators; rewrite doc + move chunks
        doc = json.loads((root / name / ".zarray").read_text())
        doc["dimension_separator"] = "/"
        (root / name / ".zarray").write_text(json.dumps(doc))
        for idx in meta.chunk_indices():
            old = root / name / ".".join(str(i) for i in idx)
            new = root / name / "/".join(str(i) for i in idx)
            new.parent.mkdir(parents=True, exist_ok=True)
            old.rename(new)
    server = DatasetsServer(
        data_dir, host="127.0.0.1", write_discovery_file=False
    )
    await server.start()
    try:
        yield server, level0, level1
    finally:
        await server.stop()


@needs_blosc  # OME-Zarr defaults to blosc; the fixture writes it
async def test_ome_zarr_plate_reads_end_to_end(ome_server):
    from bioengine_tpu.datasets.chunk_cache import ChunkCache
    from bioengine_tpu.datasets.http_zarr_store import RemoteZarrArray

    server, level0, level1 = ome_server
    store = HttpZarrStore(
        f"{server.url}/data/plate/image.zarr", cache=ChunkCache(1 << 24)
    )
    try:
        arr0 = await RemoteZarrArray.open(store, "0")
        assert arr0.meta.compressor == "blosc"
        full = await arr0.read()
        np.testing.assert_array_equal(full, level0)
        # partial read crossing chunk boundaries in y/x
        sel = (slice(0, 1), slice(0, 2), slice(0, 1), slice(10, 50), slice(20, 60))
        part = await arr0.read(sel)
        np.testing.assert_array_equal(part, level0[sel])
        arr1 = await RemoteZarrArray.open(store, "1")
        np.testing.assert_array_equal(await arr1.read(), level1)
    finally:
        await store.aclose()


@pytest.mark.slow
def test_ctypes_codecs_survive_jax_profiler_trace():
    """Regression: frameworks that statically link their own zstd and
    export the symbols globally (libtensorflow_framework.so.2, pulled in
    by jax.profiler's trace export) used to interpose the system
    libzstd's internal calls — the mixed-version internals smashed the
    stack and killed the whole pytest process at the first zstd chunk
    encode after any profiling test. codecs.py now dlopens codec libs
    with RTLD_DEEPBIND. Run in a subprocess: the poisoning is
    process-global and must not leak into this test runner either way.
    """
    import subprocess
    import sys
    import textwrap

    code = textwrap.dedent(
        """
        import os, tempfile
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        import jax.numpy as jnp

        d = tempfile.mkdtemp()
        jax.profiler.start_trace(d)
        _ = float(jnp.ones((64, 64)).sum())
        jax.profiler.stop_trace()

        from bioengine_tpu.datasets import codecs

        data = os.urandom(1 << 16)
        assert codecs.zstd_decompress(codecs.zstd_compress(data, 5)) == data
        assert codecs.lz4_decompress(codecs.lz4_compress(data)) == data
        if codecs.blosc_available():
            assert codecs.blosc_decompress(codecs.blosc_compress(data)) == data
        print("codecs-after-profiler OK")
        """
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=240,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    assert proc.returncode == 0, f"stdout={proc.stdout!r} stderr={proc.stderr[-2000:]!r}"
    assert "codecs-after-profiler OK" in proc.stdout
