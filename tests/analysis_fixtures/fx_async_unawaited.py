"""Seeded violations for BE-ASYNC-004 (un-awaited coroutine)."""

import asyncio


async def flush():
    await asyncio.sleep(0.1)


class Service:
    async def persist(self):
        await asyncio.sleep(0.1)

    async def bad_method_call(self):
        self.persist()  # <- BE-ASYNC-004

    async def good_method_call(self):
        await self.persist()


async def bad_bare_call():
    flush()  # <- BE-ASYNC-004


# --- negatives -------------------------------------------------------------


async def awaited_is_fine():
    await flush()


async def tasked_is_fine():
    t = asyncio.create_task(flush())
    await t


def sync_caller_is_not_checked():
    # sync context: asyncio.run / runner's responsibility, other linters
    # (and the runtime warning) cover it
    asyncio.run(flush())
