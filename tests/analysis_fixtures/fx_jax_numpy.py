"""Seeded violations for BE-JAX-102 (host numpy on traced values)."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_np_abs(x):
    return np.abs(x)  # <- BE-JAX-102


@jax.jit
def bad_np_keyword(x):
    return np.sum(x, axis=0)  # <- BE-JAX-102


def bad_call_style(batch):
    return np.mean(batch)  # <- BE-JAX-102


bad_call_style_jitted = jax.jit(bad_call_style)


# --- negatives -------------------------------------------------------------


@jax.jit
def jnp_is_fine(x):
    return jnp.abs(x)


@jax.jit
def np_on_static_metadata_is_fine(x):
    pad = np.zeros(x.shape)  # shapes are concrete at trace time
    return x + pad


def host_side_np_is_fine(batch):
    return np.mean(batch)  # never jitted: ordinary host numpy
