"""A fully clean module: idiomatic async + jitted code, zero findings.

The negative control for tests/test_analysis.py — every rule must stay
silent here.
"""

import asyncio
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("downsample",))
def embed(images, downsample):
    x = images.reshape(images.shape[0], -1)
    if downsample > 1:  # static argument: plain python at trace time
        x = x[:, ::downsample]
    return x / jnp.linalg.norm(x, axis=-1, keepdims=True)


async def serve_embeddings(queue: asyncio.Queue, batcher):
    lock = asyncio.Lock()
    while True:
        batch = await queue.get()
        async with lock:
            result = await asyncio.to_thread(batcher, batch)
        await asyncio.sleep(0)
        queue.task_done()
        if result is None:
            break


async def supervised_background(coro_factory):
    task = asyncio.create_task(coro_factory())
    task.add_done_callback(lambda t: t.cancelled() or t.exception())
    return task
