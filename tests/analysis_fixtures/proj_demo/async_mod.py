"""Fixture: interprocedural async-safety (BE-ASYNC-006..008).

Markers follow the flat-fixture ``# <- RULE-ID`` convention.
"""

import asyncio
import threading
import time


def slow_helper():
    time.sleep(0.5)


def indirect_helper():
    slow_helper()


class Service:
    def __init__(self):
        self._tlock = threading.Lock()
        self._alock = asyncio.Lock()
        self.counter = 0
        self.guarded = 0
        self.loop_only = 0

    # --- BE-ASYNC-006: blocking reachable through sync callees -------

    async def handle(self):
        self._sync_step()  # <- BE-ASYNC-006

    def _sync_step(self):
        indirect_helper()

    async def handle_offloaded(self):
        # function handed to a thread: not a loop-context edge
        await asyncio.to_thread(self._sync_step)

    async def handle_suppressed(self):
        # reviewed: only runs in the CLI one-shot path
        # bioengine: ignore[BE-ASYNC-006]
        self._sync_step()

    # --- BE-ASYNC-007: loop/thread shared mutation -------------------

    def start_worker(self):
        t = threading.Thread(target=self._worker, daemon=True)
        t.start()
        return t

    def _worker(self):
        self.counter += 1  # <- BE-ASYNC-007
        with self._tlock:
            self.guarded += 1

    async def on_loop(self):
        self.counter = 0
        with self._tlock:
            self.guarded = 0
        # written on the loop only: never a finding
        self.loop_only += 1

    # --- BE-ASYNC-008: blocking lock acquisition in async ------------

    async def bad_async_with(self):
        with self._alock:  # <- BE-ASYNC-008
            return self.counter

    async def good_async_with(self):
        async with self._alock:
            return self.counter

    async def bad_acquire(self):
        self._tlock.acquire()  # <- BE-ASYNC-008
        try:
            return self.counter
        finally:
            self._tlock.release()
