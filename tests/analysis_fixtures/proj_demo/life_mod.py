"""Seeded resource-lifecycle contract sites for the BE-LIFE-4xx pass.

Per rule: a positive (marked), a suppressed twin, and negative twins
covering the clean idioms — close-path sweep (direct and delegated
through a helper), self-bounding cache, guarded alias cancel,
try/finally release, and the cross-function permit handoff.
All sync on purpose: BE-ASYNC-008 owns blocking acquires in ``async
def``, and these classes must not cross-fire it.
"""

import threading


def spawn_supervised(fn):
    """Stand-in for the supervised-task spawner (leaf-name match)."""
    return fn


# ---- BE-LIFE-401: keyed registry vs the close-path sweep ------------------


class LeakyRegistry:
    """Insert site, close path, no sweep anywhere: fires."""

    def __init__(self):
        self._items = {}

    def add(self, key, value):
        self._items[key] = value  # <- BE-LIFE-401

    def close(self):
        return None


class SweptRegistry:
    """close() clears the map: clean."""

    def __init__(self):
        self._items = {}

    def add(self, key, value):
        self._items[key] = value

    def close(self):
        self._items.clear()


class DelegatedSweepRegistry:
    """The sweep sits behind a helper reachable from close(): clean."""

    def __init__(self):
        self._items = {}

    def add(self, key, value):
        self._items[key] = value

    def _evict(self, key):
        self._items.pop(key, None)

    def close(self):
        self._evict("all")


class SelfBoundedCache:
    """The inserting function evicts its own entries: clean."""

    def __init__(self):
        self._cache = {}

    def add(self, key, value):
        if len(self._cache) > 8:
            self._cache.pop(next(iter(self._cache)), None)
        self._cache[key] = value

    def close(self):
        return None


class SuppressedRegistry:
    """Deliberately unswept (bounded by design): suppressed."""

    def __init__(self):
        self._seen = {}

    def add(self, key, value):
        # bounded by construction — keys are a fixed enum
        # bioengine: ignore[BE-LIFE-401]
        self._seen[key] = value

    def close(self):
        return None


# ---- BE-LIFE-402: supervised task handle vs the close-path cancel ---------


class LeakyWorker:
    """Spawn stored on self, stop() never cancels: fires."""

    def __init__(self):
        self._task = None

    def start(self):
        self._task = spawn_supervised(self._run)  # <- BE-LIFE-402

    def _run(self):
        return None

    def stop(self):
        return None


class OrphanWorker:
    """No close-path method at all: fires (different detail)."""

    def start(self):
        self._task = spawn_supervised(self._run)  # <- BE-LIFE-402

    def _run(self):
        return None


class CancelledWorker:
    """stop() cancels the handle directly: clean."""

    def start(self):
        self._task = spawn_supervised(self._run)

    def _run(self):
        return None

    def stop(self):
        if self._task is not None:
            self._task.cancel()


class AliasCancelledWorker:
    """Guarded cancel through a local alias: clean."""

    def start(self):
        self._task = spawn_supervised(self._run)

    def _run(self):
        return None

    def stop(self):
        task = self._task
        if task is not None:
            task.cancel()


class SuppressedWorker:
    """Fire-and-forget by design (task exits on its own): suppressed."""

    def start(self):
        # bioengine: ignore[BE-LIFE-402]
        self._task = spawn_supervised(self._run)

    def _run(self):
        return None

    def stop(self):
        return None


# ---- BE-LIFE-403: acquire without an exception-safe release ---------------


class PermitLedger:
    """One semaphore per case so the module-wide handoff check can't
    mask a genuine leak."""

    def __init__(self):
        self._leak_sem = threading.Semaphore(4)
        self._bare_sem = threading.Semaphore(4)
        self._safe_sem = threading.Semaphore(4)
        self._handoff_sem = threading.Semaphore(4)
        self._quiet_sem = threading.Semaphore(4)

    def never_returned(self):
        self._leak_sem.acquire()  # <- BE-LIFE-403
        return 1

    def returned_outside_finally(self):
        self._bare_sem.acquire()  # <- BE-LIFE-403
        work = 1
        self._bare_sem.release()
        return work

    def returned_in_finally(self):
        """Exception-safe pairing: clean."""
        self._safe_sem.acquire()
        try:
            return 1
        finally:
            self._safe_sem.release()

    def take_permit(self):
        """Cross-function handoff: give_back() returns it — skipped."""
        self._handoff_sem.acquire()

    def give_back(self):
        self._handoff_sem.release()

    def deliberate_hold(self):
        # permit retired on purpose (capacity fencing)
        # bioengine: ignore[BE-LIFE-403]
        self._quiet_sem.acquire()
        return 1
