"""Fixture: observability-catalog + env-knob contracts (BE-DIST-204/205)."""

import os

from bioengine_tpu.utils import flight, metrics

DOCUMENTED = metrics.counter("demo_requests_total", "in the catalog")
UNDOCUMENTED = metrics.counter(  # <- BE-DIST-204
    "demo_undocumented_total", "missing from the catalog"
)


def emit_events():
    flight.record("demo.documented", ok=True)
    flight.record("demo.undocumented", ok=False)  # <- BE-DIST-204


def read_knobs():
    a = os.environ.get("BIOENGINE_DEMO_DOCUMENTED", "1")
    b = os.environ.get("BIOENGINE_DEMO_SECRET_KNOB")  # <- BE-DIST-205
    c = os.environ["BIOENGINE_DEMO_SUBSCRIPT"]  # <- BE-DIST-205
    # deliberate test-only knob
    # bioengine: ignore[BE-DIST-205]
    d = os.environ.get("BIOENGINE_DEMO_SUPPRESSED")
    return a, b, c, d
