"""Fixture: verb registration + capability definition/offer sites.

Project-rule markers use the same ``# <- RULE-ID`` convention as the
flat fixtures; tests/test_analysis_project.py asserts the finding set
equals the marker set exactly.
"""

PROTO_DEMO1 = "demo1"    # offered AND gated: in sync, no finding
PROTO_UNGATED1 = "ungated1"  # <- BE-DIST-203 (offered, never gated)
PROTO_UNOFFERED1 = "unoffered1"  # <- BE-DIST-203 (gated, never offered)
# offered; gated only through the SERVER-side helper (token is the
# second arg) — in sync, no finding
PROTO_SRVGATED1 = "srvgated1"

HANDSHAKE_PROTOCOLS = [PROTO_DEMO1, PROTO_UNGATED1, PROTO_SRVGATED1]


class DemoServer:
    def __init__(self, rpc):
        self.rpc = rpc

    def plan(self, service_id):
        # gate on what the ws peer that OWNS service_id declared
        return self.rpc.service_peer_supports(service_id, PROTO_SRVGATED1)

    def ping(self):
        return "pong"

    def describe(self):
        return {"ok": True}

    def orphan_verb(self):
        return None

    def register(self):
        self.rpc.register_service(
            {
                "id": "demo-service",
                "name": "Demo",
                "config": {"require_context": False},
                "ping": self.ping,
                "describe": self.describe,
                "orphan_verb": self.orphan_verb,  # <- BE-DIST-202
            }
        )


class JustifiedServer:
    """A deliberately-external verb suppressed at the registration."""

    def external_only(self):
        return None

    def register(self, rpc):
        rpc.register_service(
            {
                "id": "justified-service",
                # external clients call this; suppression keeps it quiet
                # bioengine: ignore[BE-DIST-202]
                "external_only": self.external_only,
            }
        )
