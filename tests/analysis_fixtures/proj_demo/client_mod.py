"""Fixture: verb-call sites + capability gate sites."""

from tests.analysis_fixtures.proj_demo.server_mod import (
    PROTO_DEMO1,
    PROTO_UNOFFERED1,
)


class DemoClient:
    def __init__(self, conn):
        self.conn = conn
        self.peer_protocols = []

    async def good_call(self):
        # registered verb: no finding
        return await self.conn.call("demo-service", "ping")

    async def bad_call(self):
        return await self.conn.call("demo-service", "pingg")  # <- BE-DIST-201

    async def check(self, svc):
        # attribute-call keeps the registered `describe` verb alive
        return await svc.describe()

    async def gates(self):
        # PROTO_DEMO1 offered + gated -> in sync
        if PROTO_DEMO1 in self.peer_protocols:
            pass
        # PROTO_UNOFFERED1 gated but never offered anywhere
        return PROTO_UNOFFERED1 in self.peer_protocols
