"""Seeded hot-path cost sites for the BE-PERF-3xx pass.

``handle_request`` opts in as a request-path root via the
``# analyze: hot-path-root`` marker (the catalog-free extension
mechanism); everything it calls is on the hot path.  Each rule has a
positive (marked), a suppressed twin, and a negative twin — the
negatives cover the memo-guard, the level-guard, lazy ``%s`` args,
module-level compilation, and plain unreachability.
"""

import logging
import os
import re
import uuid

log = logging.getLogger(__name__)

# compiled once at import — the 304-clean idiom
_WORD_RE = re.compile(r"\w+")

_CACHED_LIMIT = None


class _Family:
    """Stand-in labeled-metric family (labels -> child with .inc())."""

    def labels(self, *values):
        return self

    def inc(self, amount=1):
        return amount


REQUESTS = _Family()


# analyze: hot-path-root
def handle_request(payload):
    """Marker-declared request-path root."""
    rid = mint_request_id()
    limit = read_limit_per_call()
    cached = read_limit_cached()
    count_request()
    tokens = tokenize(payload)
    trace(rid, tokens, limit, cached)
    trace_guarded(rid)
    suppressed_sites()
    return rid, tokens


def mint_request_id():
    return uuid.uuid4().hex  # <- BE-PERF-302


def read_limit_per_call():
    return int(os.environ.get("DEMO_REQUEST_LIMIT", "8"))  # <- BE-PERF-301


def read_limit_cached():
    """Memo-guarded read: miss-branch env reads are cached, not
    per-request — no finding."""
    global _CACHED_LIMIT
    if _CACHED_LIMIT is None:
        _CACHED_LIMIT = int(os.environ.get("DEMO_CACHED_LIMIT", "8"))
    return _CACHED_LIMIT


def count_request():
    REQUESTS.labels("demo").inc()  # <- BE-PERF-303


def tokenize(text):
    pattern = re.compile(r"[a-z0-9]+")  # <- BE-PERF-304
    return pattern.findall(text) + _WORD_RE.findall(text)


def trace(rid, tokens, limit, cached):
    log.debug(f"req {rid}: {len(tokens)} tok {limit}/{cached}")  # <- BE-PERF-305


def trace_guarded(rid):
    """Level-guarded + lazy formatting: both clean."""
    if log.isEnabledFor(logging.DEBUG):
        log.debug(f"req {rid} (guarded, renders only when DEBUG is on)")
    log.debug("req %s (lazy args never render eagerly)", rid)


def suppressed_sites():
    """One suppressed twin per BE-PERF-3xx rule."""
    # bootstrap session id: crypto-random by design, once per session
    # bioengine: ignore[BE-PERF-302]
    sid = uuid.uuid4().hex
    # bioengine: ignore[BE-PERF-301]
    flag = os.environ.get("DEMO_SUPPRESSED_FLAG")
    # bioengine: ignore[BE-PERF-303]
    REQUESTS.labels("suppressed").inc()
    # bioengine: ignore[BE-PERF-304]
    pattern = re.compile(r"x+")
    # bioengine: ignore[BE-PERF-305]
    log.debug(f"suppressed {sid} {flag} {pattern.pattern}")
    return sid


def cold_path_rebuild():
    """Same cost classes, but not reachable from any root — the
    hot-path pass must stay quiet here."""
    key = os.environ.get("DEMO_COLD_KEY", "cold")
    pattern = re.compile(key)
    log.debug(f"cold rebuild {key}")
    return uuid.uuid4().hex, pattern
