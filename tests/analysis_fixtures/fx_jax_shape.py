"""Seeded violations for BE-JAX-105 (traced value as a shape argument)."""

import functools

import jax
import jax.numpy as jnp


@jax.jit
def bad_zeros(x, n):
    return x + jnp.zeros(n)  # <- BE-JAX-105


@jax.jit
def bad_reshape(x, n):
    return x.reshape(n, -1)  # <- BE-JAX-105


@jax.jit
def bad_broadcast(x, n):
    return jnp.broadcast_to(x, (n, 4))  # <- BE-JAX-105


# --- negatives -------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(1,))
def static_argnums_is_fine(x, n):
    return x + jnp.zeros(n)  # n is static: concrete python int


@functools.partial(jax.jit, static_argnames=("n",))
def static_argnames_is_fine(x, n):
    return x.reshape(n, -1)


@jax.jit
def shape_metadata_is_fine(x):
    flat = x.reshape(x.shape[0], -1)  # shape tuple is concrete
    return jnp.zeros(x.shape) + flat.sum()
