"""Seeded violations for BE-JAX-103 (concretizing coercion under jit)."""

import jax
import jax.numpy as jnp


@jax.jit
def bad_float(x):
    return float(x)  # <- BE-JAX-103


@jax.jit
def bad_int(x):
    return int(jnp.sum(x))  # <- BE-JAX-103


@jax.jit
def bad_item(x):
    return x.item()  # <- BE-JAX-103


@jax.jit
def bad_bool(x):
    return bool(x)  # <- BE-JAX-103


# --- negatives -------------------------------------------------------------


@jax.jit
def astype_is_fine(x):
    return x.astype(jnp.float32)


@jax.jit
def float_of_shape_is_fine(x):
    return x * float(x.shape[0])  # static metadata: concrete


def host_item_is_fine(arr):
    return arr.item()  # not jitted: host-side coercion is normal
