"""Seeded violations for BE-ASYNC-002 (threading lock across await)."""

import asyncio
import threading

_lock = threading.Lock()
_alock = asyncio.Lock()


class Holder:
    def __init__(self):
        self._mutex = threading.RLock()
        self._state = {}

    async def bad_method(self):
        with self._mutex:  # <- BE-ASYNC-002
            await asyncio.sleep(0.1)
            self._state["k"] = 1


async def bad_module_lock():
    with _lock:  # <- BE-ASYNC-002
        await asyncio.sleep(0.1)


# --- negatives -------------------------------------------------------------


async def asyncio_lock_is_fine():
    async with _alock:
        await asyncio.sleep(0.1)


async def lock_without_await_is_fine():
    with _lock:
        pass  # held only across sync work: no suspension point


def sync_lock_is_fine():
    with _lock:
        pass
