"""Seeded BE-OBS-001 violations: wall-clock subtraction as a duration.

Negative cases: monotonic deltas, timestamp arithmetic with constants,
expiry comparisons, and wall time stored for display.
"""

import time


def measures_duration_with_wall_clock():
    started = time.time()
    do_work()
    return time.time() - started  # <- BE-OBS-001


def subtracts_two_wall_timestamps():
    t0 = time.time()
    do_work()
    t1 = time.time()
    return t1 - t0  # <- BE-OBS-001


class Tracker:
    def __init__(self):
        self.started_at = time.time()

    def age(self):
        return time.time() - self.started_at  # <- BE-OBS-001


def direct_call_minus_foreign_attr(workload):
    # one side is a direct time.time() call — flagged even though the
    # other operand's origin is unknown
    return time.time() - workload.submitted_at  # <- BE-OBS-001


# ---- negative cases: none of these may fire -------------------------------


def measures_duration_correctly():
    t0 = time.monotonic()
    do_work()
    return time.monotonic() - t0


def computes_past_timestamp():
    # constant operand: a timestamp (an hour ago), not a duration
    return time.time() - 3600


def computes_expiry_deadline(ttl):
    return time.time() + ttl


def compares_against_deadline(expires_at):
    return time.time() > expires_at


def stores_wall_time_for_display():
    record = {"started_at": time.time()}
    return record


def subtracts_unrelated_names(a, b):
    return a - b


def do_work():
    pass
