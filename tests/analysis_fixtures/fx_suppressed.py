"""Suppression-comment fixture: every seeded violation is ignored.

Exercises all three suppression forms; the analyzer must report zero
findings for this file.
"""

import asyncio
import time

# bioengine: ignore-file[BE-ASYNC-005]
from pathlib import Path


async def same_line_suppression():
    time.sleep(0.1)  # bioengine: ignore[BE-ASYNC-001]


async def line_above_suppression():
    # bioengine: ignore[BE-ASYNC-003]
    asyncio.create_task(asyncio.sleep(0.1))


async def file_wide_suppression():
    return Path("status.json").read_text()  # covered by ignore-file above
