"""Seeded BE-OBS-002 violations: broad exception handlers whose whole
body is ``pass`` — the failure leaves no log line, no flight event, no
re-raise.

Negative cases: narrow types, handlers that log / re-raise / return a
fallback, and an ellipsis-free body with real work.
"""

import logging

logger = logging.getLogger("fixture")


def swallows_exception_silently():
    try:
        do_work()
    except Exception:  # <- BE-OBS-002
        pass


def swallows_with_bare_except():
    try:
        do_work()
    except:  # noqa: E722  # <- BE-OBS-002
        pass


def swallows_base_exception_with_ellipsis():
    try:
        do_work()
    except BaseException:  # <- BE-OBS-002
        ...


def swallows_in_broad_tuple():
    try:
        do_work()
    except (ValueError, Exception):  # <- BE-OBS-002
        pass


# ---- negative cases: none of these may fire -------------------------------


def ignores_a_narrow_expected_condition():
    try:
        do_work()
    except OSError:
        pass  # a named, expected condition — a decision, not a swallow


def ignores_a_narrow_tuple():
    try:
        do_work()
    except (KeyError, StopIteration):
        pass


def logs_before_moving_on():
    try:
        do_work()
    except Exception as e:  # noqa: BLE001
        logger.debug(f"tolerated: {e}")


def reraises_after_cleanup():
    try:
        do_work()
    except Exception:
        cleanup()
        raise


def falls_back_to_default():
    try:
        return do_work()
    except Exception:
        return None


def cleanup():
    pass


def do_work():
    pass
