"""Seeded violations for BE-ASYNC-003 (fire-and-forget create_task)."""

import asyncio


async def work():
    await asyncio.sleep(0.1)


async def bad_fire_and_forget():
    asyncio.create_task(work())  # <- BE-ASYNC-003


async def bad_ensure_future():
    asyncio.ensure_future(work())  # <- BE-ASYNC-003


async def bad_loop_create_task():
    loop = asyncio.get_running_loop()
    loop.create_task(work())  # <- BE-ASYNC-003


# --- negatives -------------------------------------------------------------


async def kept_reference_is_fine():
    task = asyncio.create_task(work())
    await task


async def done_callback_is_fine():
    asyncio.create_task(work()).add_done_callback(lambda t: t.exception())


async def stored_in_set_is_fine():
    tasks = set()
    tasks.add(asyncio.create_task(work()))
