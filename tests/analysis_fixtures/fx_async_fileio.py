"""Seeded violations for BE-ASYNC-005 (blocking file I/O in async def)."""

import asyncio
from pathlib import Path


async def bad_open():
    with open("config.json") as f:  # <- BE-ASYNC-005
        return f.read()


async def bad_path_read():
    return Path("status.json").read_text()  # <- BE-ASYNC-005


async def bad_path_write(payload: bytes):
    Path("out.bin").write_bytes(payload)  # <- BE-ASYNC-005


# --- negatives -------------------------------------------------------------


def sync_open_is_fine():
    with open("config.json") as f:
        return f.read()


async def to_thread_read_is_fine():
    return await asyncio.to_thread(Path("status.json").read_text)
