"""Seeded violations for BE-JAX-104 (closure/global mutation under jit)."""

import jax
import jax.numpy as jnp

_CACHE = {}
_TRACE_LOG = []
_counter = 0


@jax.jit
def bad_append(x):
    _TRACE_LOG.append("called")  # <- BE-JAX-104
    return x * 2


@jax.jit
def bad_dict_write(x):
    _CACHE["last"] = x  # <- BE-JAX-104
    return x


@jax.jit
def bad_global(x):
    global _counter  # <- BE-JAX-104
    _counter += 1
    return x


# --- negatives -------------------------------------------------------------


@jax.jit
def local_mutation_is_fine(x):
    parts = []
    parts.append(x)  # local list: trace-time only, but self-contained
    acc = {}
    acc["x"] = x
    return jnp.concatenate(parts), acc["x"]


def host_side_cache_is_fine(key, value):
    _CACHE[key] = value  # never jitted: ordinary host mutation
