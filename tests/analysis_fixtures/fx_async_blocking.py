"""Seeded violations for BE-ASYNC-001 (blocking call in async def).

Marker comments (``# <- RULE-ID``) name the line each rule must fire
on; tests/test_analysis.py parses them and asserts exact positions.
"""

import asyncio
import subprocess
import time


async def bad_sleep():
    time.sleep(1.0)  # <- BE-ASYNC-001


async def bad_subprocess():
    subprocess.run(["ls"])  # <- BE-ASYNC-001


async def bad_requests():
    import requests

    requests.get("http://example.com")  # <- BE-ASYNC-001


# --- negatives -------------------------------------------------------------


def sync_sleep_is_fine():
    time.sleep(1.0)  # sync context: not the event loop's problem


async def async_sleep_is_fine():
    await asyncio.sleep(1.0)


async def to_thread_is_fine():
    # function *reference* passed to a thread — not called in the loop
    await asyncio.to_thread(time.sleep, 1.0)


async def nested_sync_def_is_fine():
    def helper():
        time.sleep(0.1)  # runs wherever helper is called, not here

    await asyncio.to_thread(helper)
