"""Seeded violations for BE-JAX-101 (Python control flow on traced values)."""

import functools

import jax
import jax.numpy as jnp


@jax.jit
def bad_if(x):
    if x > 0:  # <- BE-JAX-101
        return x
    return -x


@jax.jit
def bad_while(x):
    while x > 1:  # <- BE-JAX-101
        x = x / 2
    return x


def call_style(x):
    if x.sum() > 0:  # <- BE-JAX-101
        return x
    return -x


call_style_jitted = jax.jit(call_style)


# --- negatives -------------------------------------------------------------


@jax.jit
def shape_branch_is_fine(x):
    if x.shape[0] > 2:  # static metadata, resolved at trace time
        return x[:2]
    return x


@jax.jit
def none_check_is_fine(x, mask=None):
    if mask is None:  # identity check on a python-level default
        return x
    return x * mask


@functools.partial(jax.jit, static_argnames=("mode",))
def static_arg_branch_is_fine(x, mode):
    if mode == "train":  # mode is static: concrete at trace time
        return x * 2
    return x


def never_jitted(x):
    if x > 0:  # plain numpy-style function, not traced
        return x
    return -x


@jax.jit
def lax_cond_is_fine(x):
    return jax.lax.cond(jnp.sum(x) > 0, lambda v: v, lambda v: -v, x)
