"""End-to-end request tracing + the unified metrics plane.

Rides the in-process multi-host chaos harness (real websockets, one
event loop): a sampled request minted in DeploymentHandle.call crosses
the RPC plane to a worker host, through the replica semaphore, the
continuous batcher, and the engine's overlapped pipeline — and comes
back as ONE reconstructable span tree whose stage durations account
for the observed end-to-end latency. Plus: legacy-peer negotiation
(no trace bytes on the wire without ``trace1``), failover under one
trace_id, and the Prometheus scrape surface.
"""

import asyncio
import re
import time
from pathlib import Path

import aiohttp
import pytest

from bioengine_tpu.apps.builder import AppBuilder
from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology
from bioengine_tpu.rpc.client import connect_to_server
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.serving import (
    DeploymentSpec,
    RequestOptions,
    ServeController,
)
from bioengine_tpu.testing import faults
from bioengine_tpu.utils import metrics, tracing
from bioengine_tpu.worker_host import WorkerHost

pytestmark = [pytest.mark.integration, pytest.mark.anyio]


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(autouse=True)
def _sample_everything(monkeypatch):
    """Deterministic head sampling for these tests; production default
    stays ~1%."""
    monkeypatch.setenv("BIOENGINE_TRACE_SAMPLE", "1.0")
    tracing.reset_env_cache()
    tracing.clear_spans()
    yield
    tracing.reset_env_cache()


# ---------------------------------------------------------------------------
# the observability app: batcher + tiled engine pipeline behind a verb
# ---------------------------------------------------------------------------

OBS_MANIFEST = """\
name: Obs App
id: obs-app
id_emoji: "\U0001F50E"
description: batcher + engine pipeline for trace tests
type: tpu-serve
version: 1.0.0
deployments:
  - obs_dep:ObsDep
authorized_users: ["*"]
deployment_config:
  obs_dep:
    num_replicas: {num_replicas}
    min_replicas: {num_replicas}
    max_replicas: {num_replicas}
    chips: 2
    autoscale: false
"""

OBS_SOURCE = '''\
import asyncio

import numpy as np

from bioengine_tpu.rpc import schema_method
from bioengine_tpu.runtime.engine import EngineConfig, InferenceEngine
from bioengine_tpu.serving import ContinuousBatcher


class ObsDep:
    async def async_init(self):
        # tiny tiles force the overlapped tiled pipeline on a 40x40 input
        config = EngineConfig(
            max_tile=16, tile=8, tile_overlap=2, pipeline_depth=2
        )
        self.engine = InferenceEngine(
            model_id="obs-toy",
            apply_fn=lambda params, x: x * params,
            params=np.float32(2.0),
            config=config,
        )
        self.batcher = ContinuousBatcher(
            self._run_batch, max_batch=4, max_wait_ms=5.0
        )

    async def _run_batch(self, signature, payloads):
        merged = np.concatenate(payloads, axis=0)
        out = await self.engine.predict_async(merged)
        res, start = [], 0
        for p in payloads:
            res.append(out[start : start + len(p)])
            start += len(p)
        return res

    @schema_method
    async def infer(self, n: int = 1, size: int = 40, context=None):
        """One request through batcher + tiled engine pipeline."""
        x = np.ones((n, size, size, 1), np.float32)
        y = await self.batcher.submit(("obs", x.shape[1:]), x)
        # a deliberate, dominant stage so the tree's duration math is
        # assertable without depending on CPU compile noise
        await asyncio.sleep(0.15)
        return {"sum": float(np.asarray(y).sum())}

    async def close(self):
        await self.batcher.close()
        self.engine.close()
'''


def _write_obs_app(tmp_path: Path, num_replicas: int = 1) -> Path:
    app_dir = tmp_path / "obs-src"
    app_dir.mkdir(exist_ok=True)
    (app_dir / "manifest.yaml").write_text(
        OBS_MANIFEST.format(num_replicas=num_replicas)
    )
    (app_dir / "obs_dep.py").write_text(OBS_SOURCE)
    return app_dir


def _no_local_chips() -> ClusterState:
    return ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu"))


@pytest.fixture()
async def obs_plane(tmp_path):
    server = RpcServer(host="127.0.0.1", admin_users=["admin"])
    await server.start()
    token = server.issue_token("admin", is_admin=True)
    controller = ServeController(_no_local_chips(), health_check_period=3600)
    controller.attach_rpc(server, admin_users=["admin"])
    hosts = []

    async def spawn_host(host_id: str) -> WorkerHost:
        host = WorkerHost(
            server_url=server.url,
            token=token,
            host_id=host_id,
            workspace_dir=tmp_path / f"ws-{host_id}",
        )
        await host.start()
        hosts.append(host)
        return host

    try:
        yield server, controller, spawn_host, tmp_path
    finally:
        for host in hosts:
            try:
                await host.stop()
            except Exception:
                pass
        await controller.stop()
        await server.stop()


async def _deploy_obs_app(controller, tmp_path, num_replicas: int = 1):
    builder = AppBuilder(workdir_root=tmp_path / "apps")
    built = builder.build(
        app_id="obs-app",
        local_path=_write_obs_app(tmp_path, num_replicas),
    )
    await controller.deploy("obs-app", built.specs)
    return controller.apps["obs-app"].replicas["obs_dep"]


def _flatten(tree_nodes):
    out = []
    stack = list(tree_nodes)
    while stack:
        node = stack.pop()
        out.append(node)
        stack.extend(node["children"])
    return out


class TestFullPathTrace:
    async def test_span_tree_accounts_for_e2e_latency(self, obs_plane):
        """Acceptance: one sampled request client -> controller ->
        remote replica -> batcher -> engine pipeline yields ONE span
        tree under one trace_id whose stage durations sum to ~= the
        observed end-to-end latency."""
        server, controller, spawn_host, tmp_path = obs_plane
        await spawn_host("h1")
        await _deploy_obs_app(controller, tmp_path)
        handle = controller.get_handle("obs-app")

        # warmup: compile the engine programs outside the timed request
        await handle.call("infer", n=1)
        tracing.clear_spans()

        t0 = time.monotonic()
        result = await handle.call("infer", n=1)
        e2e = time.monotonic() - t0
        # 40x40 input, every pixel doubled, ramp-blend stitching is
        # weight-normalized
        assert result["sum"] == pytest.approx(2.0 * 40 * 40, rel=1e-3)

        (root_span,) = tracing.get_spans(name="request")
        trace_id = root_span["trace_id"]
        tree = tracing.build_trace_tree(trace_id)
        assert tree["trace_id"] == trace_id
        (root,) = tree["tree"]
        assert root["name"] == "request"

        nodes = _flatten(tree["tree"])
        names = {n["name"] for n in nodes}
        # the full stage ladder is present in ONE tree: routing,
        # attempt, the RPC hop, host-side handling, semaphore park,
        # execution, batch queue wait, and the engine pipeline
        assert {
            "request",
            "route",
            "attempt",
            "remote.call",
            "rpc.call",
            "rpc.handle",
            "replica.park",
            "replica.execute",
            "batch.queue",
            "engine.predict",
        } <= names
        # every span belongs to this one trace
        assert all(n.get("trace_id") == trace_id for n in nodes)

        # duration accounting: the root span tracks the observed e2e,
        # and its direct children (route + attempt) cover it without
        # exceeding it
        assert root["duration_s"] == pytest.approx(e2e, rel=0.35)
        child_sum = sum(c["duration_s"] for c in root["children"])
        assert child_sum <= root["duration_s"] * 1.05
        assert child_sum >= root["duration_s"] * 0.6
        # the deliberate 150 ms stage dominates replica.execute
        execute = next(n for n in nodes if n["name"] == "replica.execute")
        assert execute["duration_s"] >= 0.14
        # the engine pipeline span carries the per-stage breakdown
        engine_span = next(n for n in nodes if n["name"] == "engine.predict")
        stage_seconds = engine_span["attrs"]["stage_seconds"]
        assert {
            "cut", "put", "dispatch", "compute", "readback", "stitch"
        } <= set(stage_seconds)
        # get_traces(trace_id=...) rollup matches the tree
        assert tree["stage_seconds"]["request"] == root["duration_s"]

    async def test_local_path_batch_queue_stays_in_one_tree(self, tmp_path):
        """A single-process deployment (no RPC hop) using the batcher:
        the retroactive batch.queue span must parent under the
        submitter's replica.execute span, not orphan a second root —
        ctx.span_id is None for locally-minted contexts."""
        import numpy as np

        from bioengine_tpu.serving import ContinuousBatcher

        class LocalApp:
            async def async_init(self):
                self.batcher = ContinuousBatcher(
                    self._run, max_batch=4, max_wait_ms=5.0
                )

            async def _run(self, sig, payloads):
                return [p * 2 for p in payloads]

            async def infer(self):
                out = await self.batcher.submit("k", np.ones(4))
                return float(out.sum())

            async def close(self):
                await self.batcher.close()

        controller = ServeController(_no_local_chips(), health_check_period=3600)
        try:
            await controller.deploy(
                "local-app",
                [DeploymentSpec(name="entry", instance_factory=LocalApp)],
            )
            handle = controller.get_handle("local-app")
            await handle.call("infer")
            tracing.clear_spans()
            assert await handle.call("infer") == 8.0
            (root_span,) = tracing.get_spans(name="request")
            tree = tracing.build_trace_tree(root_span["trace_id"])
            assert len(tree["tree"]) == 1, tree["tree"]
            (bq,) = tracing.get_spans(
                name="batch.queue", trace_id=root_span["trace_id"]
            )
            (execute,) = tracing.get_spans(
                name="replica.execute", trace_id=root_span["trace_id"]
            )
            assert bq["parent_id"] == execute["span_id"]
            # started_at is back-dated to the enqueue, so the span
            # sorts where the wait happened
            assert bq["started_at"] <= execute["started_at"] + execute[
                "duration_s"
            ]
        finally:
            await controller.stop()

    async def test_unsampled_request_leaves_no_spans(
        self, obs_plane, monkeypatch
    ):
        server, controller, spawn_host, tmp_path = obs_plane
        await spawn_host("h1")
        await _deploy_obs_app(controller, tmp_path)
        handle = controller.get_handle("obs-app")
        await handle.call("infer", n=1)  # warm (sampled — autouse env)
        monkeypatch.setenv("BIOENGINE_TRACE_SAMPLE", "0.0")
        tracing.reset_env_cache()
        tracing.clear_spans()
        await handle.call("infer", n=1)
        assert tracing.get_spans(include_open=True) == []


class TestFailoverTrace:
    async def test_failed_attempt_and_failover_share_one_trace(
        self, obs_plane
    ):
        """Satellite: kill the first routed replica call mid-request —
        the trace shows the failed attempt AND the successful failover
        attempt under one trace_id."""
        server, controller, spawn_host, tmp_path = obs_plane
        await spawn_host("h1")
        await spawn_host("h2")
        replicas = await _deploy_obs_app(controller, tmp_path, num_replicas=2)
        assert sorted(r.host_id for r in replicas) == ["h1", "h2"]
        handle = controller.get_handle("obs-app")
        await handle.call("infer", n=1)  # warm both engines? (one is enough)

        tracing.clear_spans()
        faults.configure("host.replica_call", "raise", nth=1, count=1)
        result = await handle.call(
            "infer", n=1, options=RequestOptions(idempotent=True)
        )
        assert result["sum"] == pytest.approx(2.0 * 40 * 40, rel=1e-3)

        (root_span,) = tracing.get_spans(name="request")
        attempts = tracing.get_spans(
            name="attempt", trace_id=root_span["trace_id"]
        )
        assert len(attempts) == 2
        first, second = attempts
        assert "error" in first and "error" not in second
        assert first["attrs"]["replica"] != second["attrs"]["replica"]
        assert first["attrs"]["attempt"] == 1
        assert second["attrs"]["attempt"] == 2


class TestLegacyNegotiation:
    async def test_no_trace_fields_without_trace1(self, obs_plane):
        """Satellite: a peer that does not advertise ``trace1`` never
        sees trace fields on the wire; a trace1 peer sees them exactly
        when the request is sampled."""
        server, controller, spawn_host, tmp_path = obs_plane

        async def make_echo_client(name, protocols):
            conn = await connect_to_server(
                {"server_url": server.url, "protocols": protocols}
            )
            seen = []
            orig = conn._handle_incoming_call

            async def spy(msg):
                seen.append(msg)
                await orig(msg)

            conn._handle_incoming_call = spy
            conn._seen = seen
            trace_state = []

            def echo(x):
                trace_state.append(tracing.current_trace())
                return x

            conn._trace_state = trace_state
            # forwarded CALLs carry the caller's service id verbatim, so
            # address each peer by the FULL id REGISTER handed back
            reg = await conn.register_service({"id": name, "echo": echo})
            return conn, reg["id"]

        legacy, legacy_id = await make_echo_client("legacy-svc", ["oob1"])
        modern, modern_id = await make_echo_client("modern-svc", None)
        try:
            ctx = tracing.maybe_start_trace(sample=True)
            token = tracing.activate(ctx)
            try:
                await server.call_service_method(legacy_id, "echo", (1,))
                await server.call_service_method(modern_id, "echo", (1,))
            finally:
                tracing.deactivate(token)

            (legacy_msg,) = legacy._seen
            (modern_msg,) = modern._seen
            assert "trace" not in legacy_msg  # legacy wire: byte-identical
            assert modern_msg["trace"]["tid"] == ctx.trace_id
            assert legacy._trace_state == [None]
            (remote_ctx,) = modern._trace_state
            assert remote_ctx is not None
            assert remote_ctx.trace_id == ctx.trace_id

            # unsampled requests put nothing on the wire even for
            # trace1 peers (near-zero unsampled cost)
            modern._seen.clear()
            ctx2 = tracing.maybe_start_trace(sample=False)
            token = tracing.activate(ctx2)
            try:
                await server.call_service_method(modern_id, "echo", (1,))
            finally:
                tracing.deactivate(token)
            (msg2,) = modern._seen
            assert "trace" not in msg2
        finally:
            await legacy.disconnect()
            await modern.disconnect()


_PROM_LINE = re.compile(
    r"^(# (HELP|TYPE) .+|[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? "
    r"(-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN))$"
)


class TestMetricsSurface:
    async def test_prometheus_endpoint_serves_request_histograms(
        self, obs_plane
    ):
        """Acceptance: GET /metrics on the worker serves valid
        Prometheus text including request-latency histograms labeled
        by deployment and replica."""
        server, controller, spawn_host, tmp_path = obs_plane
        await spawn_host("h1")
        await _deploy_obs_app(controller, tmp_path)
        handle = controller.get_handle("obs-app")
        await handle.call("infer", n=1)

        async with aiohttp.ClientSession() as session:
            async with session.get(server.http_url + "/metrics") as resp:
                assert resp.status == 200
                assert "version=0.0.4" in resp.headers["Content-Type"]
                body = await resp.text()

        for line in body.splitlines():
            assert _PROM_LINE.match(line), f"invalid line: {line!r}"
        # request-latency histogram labeled by deployment (+ method/app)
        assert re.search(
            r'bioengine_request_e2e_seconds_bucket\{app="obs-app",'
            r'deployment="obs_dep",le="\+Inf",method="infer"\} \d+',
            body,
        ), body[:2000]
        # per-replica execution histogram (host runs in this process)
        assert re.search(
            r'bioengine_replica_request_seconds_bucket\{app="obs-app",'
            r'deployment="obs_dep",le="\+Inf",replica="obs_dep-[0-9a-f]+"\}',
            body,
        )
        # absorbed islands: transport counters + serving gauges
        assert "bioengine_rpc_bytes_out" in body
        assert "bioengine_serve_replicas" in body
        assert "bioengine_chips_free" in body
        assert "bioengine_batcher_requests_total" in body

    async def test_get_metrics_verb_and_describe_agree(self, obs_plane):
        """Satellite: describe() keeps its schema but is backed by the
        registry — the same number shows up in both surfaces."""
        server, controller, spawn_host, tmp_path = obs_plane
        host = await spawn_host("h1")
        await _deploy_obs_app(controller, tmp_path)
        handle = controller.get_handle("obs-app")
        for _ in range(3):
            await handle.call("infer", n=1)

        replica = host.replicas[next(iter(host.replicas))]
        desc = replica.describe()
        assert desc["total_requests"] == 3
        assert desc["uptime_seconds"] > 0

        # the host's get_metrics verb (over RPC) sees the same counter
        snap = await controller._call_host(host.service_id, "get_metrics")
        series = snap["replica_requests_total"]["series"]
        mine = [
            s
            for s in series
            if s["labels"]["replica"] == replica.replica_id
        ]
        assert mine and mine[0]["value"] == 3

        # worker status["rpc"] shape is fed by the same RpcStats the
        # registry scrapes
        rpc_desc = server.describe()
        assert rpc_desc["transport"]["msgs_in"] > 0
        prom = await controller._call_host(
            host.service_id, "get_metrics", prometheus=True
        )
        assert isinstance(prom, str) and "bioengine_rpc_msgs_in" in prom


class TestTracingDisabled:
    async def test_metrics_and_slow_log_survive_tracing_off(
        self, monkeypatch, caplog
    ):
        """BIOENGINE_TRACING=0 is the *tracing* kill-switch — metrics
        (own knob: BIOENGINE_METRICS) and slow-request logging (own
        knob: BIOENGINE_SLOW_REQUEST_MS) keep working, with
        trace_id=- in the log line."""
        import logging

        monkeypatch.setenv("BIOENGINE_TRACING", "0")
        monkeypatch.setenv("BIOENGINE_SLOW_REQUEST_MS", "10")
        tracing.reset_env_cache()

        class App:
            async def infer(self):
                await asyncio.sleep(0.05)
                return 1

        controller = ServeController(_no_local_chips(), health_check_period=3600)
        serving_logger = logging.getLogger("bioengine.serving")
        serving_logger.addHandler(caplog.handler)
        try:
            await controller.deploy(
                "off-app",
                [DeploymentSpec(name="entry", instance_factory=App)],
            )
            handle = controller.get_handle("off-app")
            tracing.clear_spans()
            for _ in range(3):
                await handle.call("infer")
        finally:
            serving_logger.removeHandler(caplog.handler)
            await controller.stop()
            tracing.reset_env_cache()

        # no request-path spans minted at all
        assert tracing.get_spans(name="request", include_open=True) == []
        # but the e2e histogram and outcome counter still counted
        snap = metrics.collect()
        mine = [
            s
            for s in snap["request_e2e_seconds"]["series"]
            if s["labels"]["app"] == "off-app"
        ]
        assert mine and mine[0]["count"] == 3
        outcomes = [
            s
            for s in snap["requests_total"]["series"]
            if s["labels"]["app"] == "off-app"
        ]
        assert outcomes and outcomes[0]["value"] == 3
        # and the slow log fired, un-correlatable but present
        slow = [r for r in caplog.records if "slow_request" in r.message]
        assert slow and "trace_id=-" in slow[-1].message


class TestTraceBufferHardening:
    async def test_span_ring_stays_bounded_under_sustained_sampled_load(
        self,
    ):
        """Satellite: 100%-sampled load three times the ring size never
        grows the buffer past MAX_SPANS — the ring is the memory
        ceiling, not the request rate."""
        ctx = tracing.maybe_start_trace(sample=True)
        token = tracing.activate(ctx)
        try:
            for i in range(tracing.MAX_SPANS * 3):
                with tracing.trace_span("load.span", i=i):
                    pass
        finally:
            tracing.deactivate(token)
        spans = tracing.get_spans(
            max_spans=tracing.MAX_SPANS * 10, include_open=True
        )
        assert len(spans) <= tracing.MAX_SPANS
        # newest survived, oldest rolled off
        assert spans[-1]["attrs"]["i"] == tracing.MAX_SPANS * 3 - 1

    async def test_get_spans_since_and_limit_paginate(self):
        ctx = tracing.maybe_start_trace(sample=True)
        token = tracing.activate(ctx)
        try:
            for i in range(10):
                with tracing.trace_span("page.span", i=i):
                    time.sleep(0.002)  # distinct wall started_at stamps
        finally:
            tracing.deactivate(token)
        all_spans = tracing.get_spans(name="page.span", max_spans=100)
        assert len(all_spans) == 10
        # limit: newest N
        assert [
            s["attrs"]["i"] for s in tracing.get_spans(
                name="page.span", max_spans=3
            )
        ] == [7, 8, 9]
        # since: wall-clock cursor (inclusive)
        cut = all_spans[6]["started_at"]
        assert [
            s["attrs"]["i"]
            for s in tracing.get_spans(
                name="page.span", max_spans=100, since=cut
            )
        ] == [6, 7, 8, 9]


class TestSlowRequestLog:
    async def test_slow_request_logged_with_trace_id(
        self, obs_plane, monkeypatch, caplog
    ):
        server, controller, spawn_host, tmp_path = obs_plane
        await spawn_host("h1")
        await _deploy_obs_app(controller, tmp_path)
        monkeypatch.setenv("BIOENGINE_SLOW_REQUEST_MS", "50")
        tracing.reset_env_cache()
        handle = controller.get_handle("obs-app")
        import logging

        # bioengine loggers set propagate=False, so caplog's root
        # handler never sees them — attach its handler directly
        serving_logger = logging.getLogger("bioengine.serving")
        serving_logger.addHandler(caplog.handler)
        try:
            await handle.call("infer", n=1)  # sleeps 150 ms > 50 ms
        finally:
            serving_logger.removeHandler(caplog.handler)
        slow = [r for r in caplog.records if "slow_request" in r.message]
        assert slow, caplog.records
        msg = slow[-1].message
        assert re.search(r"trace_id=[0-9a-f]{32}", msg)
        assert "app=obs-app" in msg
        assert "deployment=obs_dep" in msg
        assert re.search(r"duration_ms=\d+", msg)
