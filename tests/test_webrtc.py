"""WebRTC transport executed end-to-end (VERDICT r4 missing #5: the
offer/answer/data-channel code had never run).

Two tiers:

- ``TestWebRtcFakeLoopback`` always runs: a faithful in-process fake of
  the minimal aiortc surface the handler uses (pyee-style ``.on``
  decorators, setRemoteDescription/createAnswer/setLocalDescription,
  data-channel events) drives the REAL handler code in
  ``bioengine_tpu/apps/webrtc.py`` — signaling, per-PC tracking,
  channel RPC dispatch, ACL enforcement, malformed-input handling, and
  undeploy cleanup all execute; only aiortc's own ICE/DTLS stack is
  substituted.
- ``TestWebRtcRealLoopback`` runs when aiortc is importable (the
  ``[webrtc]`` extra, installed in CI): a true peer connection performs
  offer/answer and calls a schema method over an actual data channel.

Ref behavior mirrored: bioengine/apps/proxy_deployment.py:599-732
(offer -> answer, per-method ACL with the signaling identity, PC
tracking for load reporting).
"""

from __future__ import annotations

import asyncio
import json
import sys
import types
from pathlib import Path
from types import SimpleNamespace

import pytest

from bioengine_tpu.utils.permissions import create_context

pytestmark = [pytest.mark.integration, pytest.mark.anyio]

REPO_APPS = Path(__file__).resolve().parent.parent / "apps"
ADMIN = create_context("admin")


def _aiortc_available() -> bool:
    try:
        import aiortc  # noqa: F401

        return True
    except ImportError:
        return False


# ---------------------------------------------------------------------------
# fake aiortc — pyee-compatible event registration, loopback semantics
# ---------------------------------------------------------------------------


class _Emitter:
    def __init__(self):
        self._handlers = {}

    def on(self, name):
        def deco(fn):
            self._handlers[name] = fn
            return fn

        return deco

    def _fire(self, name, *args):
        fn = self._handlers.get(name)
        return fn(*args) if fn else None


class FakeDataChannel(_Emitter):
    label = "rpc"

    def __init__(self):
        super().__init__()
        self.sent: list[str] = []

    def send(self, data):
        self.sent.append(data)

    def receive(self, message):
        self._fire("message", message)


class FakeRTCPeerConnection(_Emitter):
    instances: list["FakeRTCPeerConnection"] = []

    def __init__(self):
        super().__init__()
        self.connectionState = "new"
        self.closed = False
        self.remoteDescription = None
        self.localDescription = None
        FakeRTCPeerConnection.instances.append(self)

    async def setRemoteDescription(self, desc):
        self.remoteDescription = desc

    async def createAnswer(self):
        return SimpleNamespace(
            sdp=f"answer-to:{self.remoteDescription.sdp}", type="answer"
        )

    async def setLocalDescription(self, desc):
        self.localDescription = desc
        self.connectionState = "connected"

    async def close(self):
        self.closed = True
        self.connectionState = "closed"
        handler = self._handlers.get("connectionstatechange")
        if handler:
            await handler()

    # test hook: the remote peer's channel arrives
    def open_channel(self, channel):
        self._fire("datachannel", channel)


@pytest.fixture
def fake_aiortc(monkeypatch):
    mod = types.ModuleType("aiortc")
    mod.RTCPeerConnection = FakeRTCPeerConnection
    mod.RTCSessionDescription = lambda sdp, type: SimpleNamespace(
        sdp=sdp, type=type
    )
    monkeypatch.setitem(sys.modules, "aiortc", mod)
    FakeRTCPeerConnection.instances.clear()
    return mod


async def _drain(channel, n=1, timeout=5.0):
    """Wait until the handler's ensure_future responses land."""
    deadline = asyncio.get_event_loop().time() + timeout
    while len(channel.sent) < n:
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(f"channel got {len(channel.sent)}/{n} replies")
        await asyncio.sleep(0.01)
    return [json.loads(m) for m in channel.sent]


class TestWebRtcFakeLoopback:
    async def _deploy_rtc_app(self, stack):
        manager, _, server, _ = stack
        result = await manager.deploy_app(
            local_path=str(REPO_APPS / "demo-app"),
            authorized_users=["admin", "alice"],
            context=ADMIN,
        )
        status = manager.get_app_status(result["app_id"])
        assert status["rtc_service_id"], "rtc service must register"
        return manager, server, result["app_id"], status["rtc_service_id"]

    async def test_offer_answer_channel_call_and_acl(
        self, stack, fake_aiortc
    ):
        manager, server, app_id, rtc_id = await self._deploy_rtc_app(stack)

        # --- signaling as an authorized user
        alice = server.validate_token(server.issue_token("alice"))
        answer = await server.call_service_method(
            rtc_id, "offer", kwargs={"sdp": "client-sdp"}, caller=alice
        )
        assert answer["type"] == "answer"
        assert answer["sdp"] == "answer-to:client-sdp"
        pc = FakeRTCPeerConnection.instances[-1]
        assert pc.remoteDescription.type == "offer"

        # --- schema method over the data channel
        chan = FakeDataChannel()
        pc.open_channel(chan)
        chan.receive(json.dumps({"id": 1, "method": "ping", "kwargs": {}}))
        (reply,) = await _drain(chan)
        assert reply["id"] == 1 and reply["result"]["pong"] is True

        # --- kwargs actually forwarded
        chan.receive(
            json.dumps(
                {"id": 2, "method": "echo", "kwargs": {"message": "hi"}}
            )
        )
        replies = await _drain(chan, 2)
        assert replies[1]["id"] == 2 and replies[1]["result"]["echo"] == "hi"

        # --- malformed JSON -> structured error, channel survives
        chan.receive("{not json")
        replies = await _drain(chan, 3)
        assert replies[2]["id"] is None and "error" in replies[2]

        # --- load surface
        n = await server.call_service_method(
            rtc_id, "get_num_pcs", caller=alice
        )
        assert n == 1

        # --- unauthorized signaling identity: channel calls are denied
        # with the SAME ACL as the websocket plane (identity captured at
        # signaling time)
        mallory = server.validate_token(server.issue_token("mallory"))
        await server.call_service_method(
            rtc_id, "offer", kwargs={"sdp": "x"}, caller=mallory
        )
        pc2 = FakeRTCPeerConnection.instances[-1]
        chan2 = FakeDataChannel()
        pc2.open_channel(chan2)
        chan2.receive(json.dumps({"id": 9, "method": "ping", "kwargs": {}}))
        (denied,) = await _drain(chan2)
        assert denied["id"] == 9
        assert "PermissionError" in denied["error"]

        # --- undeploy closes every tracked PC and removes the service
        await manager.stop_app(app_id, context=ADMIN)
        await asyncio.sleep(0.05)
        assert pc.closed and pc2.closed
        assert not [
            s for s in server.list_services()
            if s["type"] == "bioengine-app-rtc"
        ]

    async def test_failed_pc_drops_out_of_tracking(self, stack, fake_aiortc):
        _, server, _, rtc_id = await self._deploy_rtc_app(stack)
        alice = server.validate_token(server.issue_token("alice"))
        await server.call_service_method(
            rtc_id, "offer", kwargs={"sdp": "a"}, caller=alice
        )
        pc = FakeRTCPeerConnection.instances[-1]
        pc.connectionState = "failed"
        await pc._fire("connectionstatechange")
        n = await server.call_service_method(
            rtc_id, "get_num_pcs", caller=alice
        )
        assert n == 0


@pytest.mark.skipif(
    not _aiortc_available(), reason="aiortc not installed ([webrtc] extra)"
)
class TestWebRtcRealLoopback:
    """True aiortc peer connection against the handler — runs in CI
    where the [webrtc] extra is installed."""

    async def test_real_offer_answer_and_channel_rpc(self, stack):
        from aiortc import RTCPeerConnection, RTCSessionDescription

        manager, _, server, _ = stack
        result = await manager.deploy_app(
            local_path=str(REPO_APPS / "demo-app"),
            authorized_users=["admin", "alice"],
            context=ADMIN,
        )
        rtc_id = manager.get_app_status(result["app_id"])["rtc_service_id"]
        assert rtc_id

        client = RTCPeerConnection()
        channel = client.createDataChannel("rpc")
        got = asyncio.get_event_loop().create_future()

        @channel.on("message")
        def _on_message(message):
            if not got.done():
                got.set_result(json.loads(message))

        opened = asyncio.get_event_loop().create_future()

        @channel.on("open")
        def _on_open():
            if not opened.done():
                opened.set_result(True)

        await client.setLocalDescription(await client.createOffer())
        alice = server.validate_token(server.issue_token("alice"))
        answer = await server.call_service_method(
            rtc_id,
            "offer",
            kwargs={
                "sdp": client.localDescription.sdp,
                "type": client.localDescription.type,
            },
            caller=alice,
        )
        await client.setRemoteDescription(
            RTCSessionDescription(sdp=answer["sdp"], type=answer["type"])
        )
        await asyncio.wait_for(opened, timeout=15)
        channel.send(json.dumps({"id": 1, "method": "ping", "kwargs": {}}))
        reply = await asyncio.wait_for(got, timeout=15)
        assert reply == {"id": 1, "result": "pong"}
        await client.close()
