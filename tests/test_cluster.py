import os
import subprocess
import time

import pytest

from bioengine_tpu.cluster.cluster import ClusterLockError, TpuCluster
from bioengine_tpu.cluster.provisioner import (
    NullProvisioner,
    ScalingPolicy,
    SlurmProvisioner,
)
from bioengine_tpu.cluster.state import ClusterState, PendingWorkload
from bioengine_tpu.cluster.topology import detect_topology

pytestmark = pytest.mark.unit


class FakeRunner:
    """Records commands; scripted stdout per verb."""

    def __init__(self):
        self.commands = []
        self.job_states: dict[str, str] = {}
        self._next_id = 100

    def __call__(self, cmd):
        self.commands.append(cmd)
        verb = cmd[0]
        if verb == "sbatch":
            job_id = str(self._next_id)
            self._next_id += 1
            self.job_states[job_id] = "RUNNING"
            return subprocess.CompletedProcess(cmd, 0, stdout=f"{job_id}\n", stderr="")
        if verb == "squeue":
            job_id = cmd[cmd.index("-j") + 1]
            state = self.job_states.get(job_id, "")
            return subprocess.CompletedProcess(cmd, 0, stdout=f"{state}\n", stderr="")
        if verb == "scancel":
            self.job_states.pop(cmd[1], None)
            return subprocess.CompletedProcess(cmd, 0, stdout="", stderr="")
        return subprocess.CompletedProcess(cmd, 0, stdout="", stderr="")


class TestTopology:
    def test_detect_on_cpu_backend(self):
        topo = detect_topology()
        assert topo.n_chips == 8  # virtual CPU devices from conftest
        assert topo.platform == "cpu"
        assert topo.default_mesh_axes() == {"dp": 8}

    def test_as_dict_shape(self):
        d = detect_topology().as_dict()
        assert set(d) == {"platform", "n_chips", "n_hosts", "chips"}
        assert len(d["chips"]) == d["n_chips"]


class TestClusterState:
    def test_snapshot_and_history_ring(self):
        state = ClusterState()
        for _ in range(105):
            state.snapshot()
        assert len(state.history()) == 100
        snap = state.history()[-1]
        assert snap["n_chips_free"] == 8

    def test_chip_accounting(self):
        state = ClusterState()
        taken = state.acquire_chips("replica-1", 3)
        assert len(taken) == 3
        assert state.free_chips() == 5
        with pytest.raises(RuntimeError):
            state.acquire_chips("replica-2", 6)
        state.release_chips("replica-1")
        assert state.free_chips() == 8

    def test_replica_registry_and_dead_logs(self):
        state = ClusterState()
        state.register_replica("app-1", "entry", "r1", [0])
        state.append_replica_log("r1", "hello")
        state.append_replica_log("r1", "world")
        state.mark_replica_dead("r1")
        logs = state.get_replica_logs("app-1")
        assert list(logs) == ["entry/r1 (dead)"]
        assert logs["entry/r1 (dead)"] == ["hello", "world"]
        assert state.get_replica_logs("app-1", include_dead=False) == {}

    def test_pending_queue(self):
        state = ClusterState()
        state.add_pending("w1", {"chips": 2})
        assert [p.workload_id for p in state.pending()] == ["w1"]
        state.remove_pending("w1")
        assert state.pending() == []


class TestSlurmProvisioner:
    def make(self, **kw):
        runner = FakeRunner()
        policy = ScalingPolicy(
            max_workers=2, cooldown_seconds=0.0, idle_window_snapshots=3
        )
        prov = SlurmProvisioner(runner=runner, policy=policy, **kw)
        return prov, runner

    def pending(self, n=1):
        return [
            PendingWorkload(f"w{i}", {"chips": 4, "cpus": 8}, time.time())
            for i in range(n)
        ]

    def test_scale_up_on_pending(self):
        prov, runner = self.make()
        actions = prov.check_scaling(self.pending(), [])
        assert len(actions["scaled_up"]) == 1
        assert runner.commands[0][0] == "sbatch"
        w = prov.active_workers()[0]
        assert w.resources["chips"] == 4

    def test_max_workers_cap(self):
        prov, _ = self.make()
        prov.check_scaling(self.pending(), [])
        prov.check_scaling(self.pending(), [])
        actions = prov.check_scaling(self.pending(), [])
        assert actions["scaled_up"] == []
        assert len(prov.active_workers()) == 2

    def test_cooldown_blocks_rapid_scale_up(self):
        runner = FakeRunner()
        prov = SlurmProvisioner(
            runner=runner,
            policy=ScalingPolicy(max_workers=5, cooldown_seconds=9999),
        )
        prov.check_scaling(self.pending(), [])
        actions = prov.check_scaling(self.pending(), [])
        assert actions["scaled_up"] == []

    def test_scale_down_requires_full_idle_window(self):
        prov, runner = self.make()
        prov.check_scaling(self.pending(), [])
        worker_id = prov.active_workers()[0].worker_id
        # idle but history window too short: no scale-down
        actions = prov.check_scaling([], [{}], {worker_id})
        assert actions["scaled_down"] == []
        # full window: scale down
        actions = prov.check_scaling([], [{}] * 3, {worker_id})
        assert actions["scaled_down"] == [worker_id]
        assert any(c[0] == "scancel" for c in runner.commands)

    def test_sbatch_script_contents(self):
        prov, _ = self.make(
            partition="tpu-v5e", container_image="bioengine.sif"
        )
        script = prov.build_sbatch_script({"cpus": 4, "memory_gb": 16}, "abc")
        assert "#SBATCH --partition=tpu-v5e" in script
        assert "#SBATCH --cpus-per-task=4" in script
        assert "#SBATCH --mem=16G" in script
        assert "apptainer exec" in script
        assert "--worker-tag abc" in script

    def test_close_all_cancels(self):
        prov, runner = self.make()
        prov.check_scaling(self.pending(), [])
        prov.close_all()
        assert prov.active_workers() == []
        assert any(c[0] == "scancel" for c in runner.commands)


class TestTpuCluster:
    def test_start_stop_and_status(self, tmp_path):
        cluster = TpuCluster(
            mode="single-machine", workspace_dir=tmp_path, log_file="off"
        )
        cluster.start()
        try:
            assert cluster.is_ready
            assert cluster.check_connection()
            st = cluster.status
            assert st["mode"] == "single-machine"
            assert st["topology"]["n_chips"] == 8
            actions = cluster.monitor_cluster()
            assert actions == {"scaled_up": [], "scaled_down": []}
        finally:
            cluster.stop()
        assert not cluster.is_ready
        assert not (tmp_path / "cluster.lock").exists()

    def test_lock_prevents_second_manager(self, tmp_path):
        c1 = TpuCluster(mode="single-machine", workspace_dir=tmp_path, log_file="off")
        c1.start()
        try:
            c2 = TpuCluster(
                mode="single-machine", workspace_dir=tmp_path, log_file="off"
            )
            with pytest.raises(ClusterLockError):
                c2.start()
        finally:
            c1.stop()

    def test_stale_lock_reclaimed(self, tmp_path):
        (tmp_path / "cluster.lock").write_text("999999999")
        cluster = TpuCluster(
            mode="single-machine", workspace_dir=tmp_path, log_file="off"
        )
        cluster.start()
        try:
            assert cluster.is_ready
            assert (tmp_path / "cluster.lock").read_text() == str(os.getpid())
        finally:
            cluster.stop()

    def test_invalid_mode_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="mode"):
            TpuCluster(mode="kubernetes", workspace_dir=tmp_path)

    def test_slurm_mode_uses_provisioner(self, tmp_path):
        runner = FakeRunner()
        prov = SlurmProvisioner(
            runner=runner, policy=ScalingPolicy(cooldown_seconds=0)
        )
        cluster = TpuCluster(
            mode="slurm",
            workspace_dir=tmp_path,
            provisioner=prov,
            log_file="off",
        )
        cluster.start()
        try:
            cluster.state.add_pending("w1", {"chips": 8})
            actions = cluster.monitor_cluster()
            assert len(actions["scaled_up"]) == 1
        finally:
            cluster.stop()


class FakeGcloudRunner:
    """Records gcloud invocations; queued-resources become ACTIVE."""

    def __init__(self):
        self.commands = []
        self.resources: dict[str, str] = {}

    def __call__(self, cmd):
        self.commands.append(cmd)
        if cmd[:5] == ["gcloud", "compute", "tpus", "queued-resources", "create"]:
            self.resources[cmd[5]] = "ACTIVE"
            return subprocess.CompletedProcess(cmd, 0, stdout="", stderr="")
        if cmd[:5] == ["gcloud", "compute", "tpus", "queued-resources", "describe"]:
            state = self.resources.get(cmd[5], "")
            return subprocess.CompletedProcess(cmd, 0, stdout=f"{state}\n", stderr="")
        if cmd[:5] == ["gcloud", "compute", "tpus", "queued-resources", "delete"]:
            self.resources.pop(cmd[5], None)
            return subprocess.CompletedProcess(cmd, 0, stdout="", stderr="")
        return subprocess.CompletedProcess(cmd, 0, stdout="", stderr="")


class TestGkeProvisioner:
    """VERDICT r3 weak #4/#5: provisioned nodes must be able to JOIN,
    and idle joined hosts must map back to cancellable backend jobs."""

    def make(self):
        from bioengine_tpu.cluster.provisioner import GkeProvisioner

        runner = FakeGcloudRunner()
        prov = GkeProvisioner(
            project="proj", zone="us-central2-b",
            policy=ScalingPolicy(
                max_workers=2, cooldown_seconds=0.0, idle_window_snapshots=2
            ),
            runner=runner,
        )
        prov.set_join_info("ws://head:1234/ws", "sekret-token")
        return prov, runner

    def pending(self):
        return [PendingWorkload("w0", {"chips": 8}, time.time())]

    def test_create_carries_join_info_and_tag(self):
        prov, runner = self.make()
        actions = prov.check_scaling(self.pending(), [])
        assert len(actions["scaled_up"]) == 1
        create = runner.commands[0]
        assert create[4] == "create"
        meta = next(a for a in create if a.startswith("--metadata=startup-script="))
        script = meta.split("=", 2)[2]
        assert "BIOENGINE_SERVER_URL=ws://head:1234/ws" in script
        assert "BIOENGINE_ADMIN_TOKEN=sekret-token" in script
        w = prov.active_workers()[0]
        assert w.worker_tag and f"--worker-tag {w.worker_tag}" in script
        assert "worker_host" in script

    def test_worker_tag_recorded_and_job_named_after_it(self):
        prov, runner = self.make()
        prov.check_scaling(self.pending(), [])
        w = prov.active_workers()[0]
        assert w.backend_job_id == f"bioengine-{w.worker_tag}"

    def test_idle_joined_host_maps_to_cancelled_job(self, tmp_path):
        """Full loop: provision -> host joins with the tag -> host goes
        idle -> the policy cancels exactly that backend job."""
        prov, runner = self.make()
        cluster = TpuCluster(
            mode="gke", workspace_dir=tmp_path, provisioner=prov,
            log_file="off",
        )
        cluster.start()
        try:
            cluster.state.add_pending("app/dep", {"chips": 8})
            cluster.monitor_cluster()
            w = prov.active_workers()[0]
            # the provisioned VM boots and joins, reporting its tag
            cluster.state.register_host(
                "host-a", "svc-a",
                {"n_chips": 8, "chips": [{"device_id": i} for i in range(8)]},
                worker_tag=w.worker_tag,
            )
            cluster.state.remove_pending("app/dep")
            # a replica lands on it: NOT idle, no scale-down
            cluster.state.register_replica(
                "app", "dep", "r1", host_id="host-a"
            )
            for _ in range(3):
                actions = cluster.monitor_cluster()
            assert actions["scaled_down"] == []
            # replica dies; host idle across the window -> cancel ITS job
            cluster.state.mark_replica_dead("r1")
            down = []
            for _ in range(3):
                down += cluster.monitor_cluster()["scaled_down"]
            assert down == [w.worker_id]
            deletes = [c for c in runner.commands if c[4] == "delete"]
            assert deletes and deletes[0][5] == w.backend_job_id
        finally:
            cluster.stop()

    def test_local_replicas_do_not_block_host_scale_down(self, tmp_path):
        """A busy CONTROLLER (host_id=None replicas) must not keep an
        idle remote host alive."""
        prov, runner = self.make()
        cluster = TpuCluster(
            mode="gke", workspace_dir=tmp_path, provisioner=prov,
            log_file="off",
        )
        cluster.start()
        try:
            cluster.state.add_pending("a/d", {"chips": 8})
            cluster.monitor_cluster()
            w = prov.active_workers()[0]
            cluster.state.register_host(
                "host-b", "svc-b", {"n_chips": 8, "chips": []},
                worker_tag=w.worker_tag,
            )
            cluster.state.remove_pending("a/d")
            cluster.state.register_replica("a", "d", "r-local", host_id=None)
            down = []
            for _ in range(3):
                down += cluster.monitor_cluster()["scaled_down"]
            assert down == [w.worker_id]
        finally:
            cluster.stop()
