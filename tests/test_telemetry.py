"""Telemetry history: the fixed-memory multi-resolution store and the
registry-delta sampler (utils/telemetry.py).

Acceptance pins (ISSUE 10): the store is fixed-memory under 3x
sustained push load, and ``get_telemetry`` reconstructs rate/p99
series that agree with the live registry within quantile-bucket error.
"""

import time

import pytest

from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology
from bioengine_tpu.serving import DeploymentSpec, ServeController
from bioengine_tpu.utils import metrics
from bioengine_tpu.utils.telemetry import (
    RegistrySampler,
    TelemetryStore,
    quantile_from_buckets,
)

pytestmark = pytest.mark.anyio


def _snap(t, key="app/dep", requests=10, errors=0, buckets=None, **extra):
    return {
        "captured_at": t,
        "deployments": {
            key: {
                "requests": requests,
                "errors": errors,
                "latency_buckets": buckets
                or {"0.1": requests, "0.25": requests, "0.5": requests},
                **extra,
            }
        },
    }


class TestStoreBounds:
    def test_rings_stay_fixed_under_3x_push_load(self):
        """3x the coarsest ring's capacity in pushes: every ring stays
        at its maxlen, nothing grows with the push count."""
        store = TelemetryStore(resolutions=[(1.0, 30), (5.0, 20)])
        t0 = time.time()
        n_pushes = 3 * 20 * 5  # 3x the coarse ring's span in 1s steps
        for i in range(n_pushes):
            store.ingest(_snap(t0 + i), host_id=f"h{i % 3}")
        s = store._series[("app", "dep")]
        for step, ring in s.rings:
            assert len(ring) == ring.maxlen, step
        # series reads stay bounded too
        assert len(store.series("app", "dep", "request_rate")) <= 30

    def test_deployment_key_set_is_lru_bounded(self):
        store = TelemetryStore(
            resolutions=[(1.0, 10)], max_series=8
        )
        t0 = time.time()
        for i in range(100):
            store.ingest(_snap(t0 + i, key=f"app{i}/dep"))
        assert len(store.keys()) == 8
        # newest keys survived
        assert ("app99", "dep") in store.keys()

    def test_malformed_push_is_rejected_not_raised(self):
        store = TelemetryStore(resolutions=[(1.0, 10)])
        assert store.ingest(None) == 0
        assert store.ingest({"deployments": "nope"}) == 0
        assert store.ingest({"deployments": {"a/b": "nope"}}) == 0
        assert store.keys() == []

    def test_sweep_drops_dead_deployment_series(self):
        store = TelemetryStore(resolutions=[(1.0, 10)])
        t = time.time()
        store.ingest(_snap(t, key="a/x"))
        store.ingest(_snap(t, key="a/y"))
        store.ingest(_snap(t, key="b/x"))
        store.sweep("a", "x")
        assert store.keys() == [("a", "y"), ("b", "x")]
        store.sweep("a")
        assert store.keys() == [("b", "x")]


class TestSeriesReconstruction:
    def test_rates_and_quantiles_from_deltas(self):
        store = TelemetryStore(resolutions=[(1.0, 60)])
        t0 = time.time() - 10
        for i in range(10):
            store.ingest(
                _snap(
                    t0 + i,
                    requests=20,
                    errors=2,
                    buckets={"0.1": 10, "0.25": 19, "0.5": 20},
                    queue_depth=4,
                    chip_seconds=1.5,
                    shed=1,
                )
            )
        rate = store.series("app", "dep", "request_rate")
        assert rate[-1]["value"] == 20.0
        assert store.series("app", "dep", "error_rate")[-1]["value"] == 2.0
        assert store.series("app", "dep", "error_ratio")[-1]["value"] == 0.1
        assert store.series("app", "dep", "shed_rate")[-1]["value"] == 1.0
        assert store.series("app", "dep", "queue_depth")[-1]["value"] == 4
        assert store.series("app", "dep", "chip_seconds")[-1]["value"] == 1.5
        # p50 lands in the first bucket that covers half the requests
        assert store.series("app", "dep", "latency_p50")[-1]["value"] == 0.1
        assert store.series("app", "dep", "latency_p99")[-1]["value"] == 0.5

    def test_window_aggregate_folds_buckets(self):
        store = TelemetryStore(resolutions=[(1.0, 60)])
        now = time.time()
        for i in range(20):
            store.ingest(_snap(now - 20 + i, requests=5))
        agg = store.window_aggregate("app", "dep", 10.0, now=now)
        # ~10 buckets of 5 requests (edge alignment may include one more)
        assert 45 <= agg["requests"] <= 55
        assert agg["latency_buckets"]["0.5"] == agg["requests"]

    def test_resolution_selection_prefers_finest_that_covers(self):
        store = TelemetryStore(resolutions=[(1.0, 10), (10.0, 10)])
        now = time.time()
        for i in range(100):
            store.ingest(_snap(now - 100 + i))
        fine = store.series("app", "dep", "request_rate", resolution=1.0)
        coarse = store.series(
            "app", "dep", "request_rate", since=now - 90
        )
        # a 90s window cannot come from the 10-slot 1s ring
        assert len(coarse) >= 9
        assert all(p["value"] == 10.0 for p in fine)
        # edge buckets are partial depending on wall-clock alignment;
        # every interior bucket holds the full 10 req/s
        assert all(0 < p["value"] <= 10.0 for p in coarse)
        assert all(p["value"] == 10.0 for p in coarse[1:-1])

    def test_unknown_series_name_is_none_not_crash(self):
        store = TelemetryStore(resolutions=[(1.0, 10)])
        store.ingest(_snap(time.time()))
        assert store.series("app", "dep", "latency_p95")[-1]["value"] == 0.1

    def test_quantile_estimator_matches_registry_convention(self):
        buckets = {"0.1": 50, "0.25": 90, "0.5": 100}
        assert quantile_from_buckets(buckets, 100, 0.5) == 0.1
        assert quantile_from_buckets(buckets, 100, 0.95) == 0.5
        assert quantile_from_buckets({}, 0, 0.5) is None


class TestRegistrySampler:
    def test_deltas_between_snapshots(self):
        reg = metrics.MetricsRegistry()
        outcomes = reg.counter(
            "requests_total", "", ("app", "deployment", "outcome")
        )
        e2e = reg.histogram(
            "request_e2e_seconds", "", ("app", "deployment", "method"),
            buckets=(0.1, 0.5),
        )
        sampler = RegistrySampler(registry=reg)
        assert sampler.sample() is None  # baseline
        outcomes.labels("a", "d", "ok").inc(5)
        outcomes.labels("a", "d", "transport_error").inc(2)
        e2e.labels("a", "d", "infer").observe(0.05)
        e2e.labels("a", "d", "infer").observe(0.3)
        snap = sampler.sample()
        d = snap["deployments"]["a/d"]
        assert d["requests"] == 7
        assert d["errors"] == 2
        assert d["latency_buckets"] == {"0.1": 1, "0.5": 2}
        # second sample with no traffic: nothing to report
        assert sampler.sample() is None
        # snapshots are stamped with the process identity (the
        # controller drops same-process pushes by it)
        assert snap["source_id"]

    async def test_live_registry_roundtrip_agrees_within_bucket_error(self):
        """Acceptance: drive a real deployment, tick telemetry, and the
        reconstructed rate/p99 agree with the live registry within
        quantile-bucket error."""
        import asyncio

        class App:
            async def infer(self):
                await asyncio.sleep(0.012)
                return 1

        controller = ServeController(
            ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu")),
            health_check_period=3600,
        )
        try:
            controller.telemetry = TelemetryStore(resolutions=[(0.5, 240)])
            await controller.deploy(
                "telem-app",
                [DeploymentSpec(name="entry", instance_factory=App)],
            )
            handle = controller.get_handle("telem-app")
            controller.telemetry_tick()   # baseline
            n = 12
            for _ in range(n):
                await handle.call("infer")
            controller.telemetry_tick()

            telem = controller.get_telemetry(app="telem-app")
            series = telem["deployments"]["telem-app/entry"]
            total = sum(
                p["value"] * 0.5
                for p in series["request_rate"]
                if p["value"]
            )
            assert total == pytest.approx(n, abs=0.5)

            # live registry truth
            snap = metrics.collect()
            live = next(
                s
                for s in snap["request_e2e_seconds"]["series"]
                if s["labels"]["app"] == "telem-app"
            )
            stored_p99 = max(
                p["value"]
                for p in series["latency_p99"]
                if p["value"] is not None
            )
            assert stored_p99 == live["p99"]  # same bucket edge
        finally:
            await controller.stop()

    async def test_get_telemetry_validates_series_names(self):
        controller = ServeController(
            ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu")),
            health_check_period=3600,
        )
        try:
            with pytest.raises(ValueError, match="unknown telemetry series"):
                controller.get_telemetry(series="nope")
            assert controller.get_telemetry(series="request_rate") is not None
        finally:
            await controller.stop()
