"""Gray-failure defense: latency-outlier probation + request hedging.

The failure mode under test is the one PR 4's breaker CANNOT see: a
replica that still answers health checks while serving far slower than
its siblings. Detection (EWMA vs deployment lower-median), the
PROBATION state machine (soft-eject, trickle probe, self-correcting
recovery), and request hedging (p95-delay second attempt, loser
cancelled WITHOUT feeding the breaker or the EWMA) are pinned here;
the end-to-end proof over real websockets lives in the scenario
engine's ``slow_replica`` scenario (tests/test_scenarios.py).
"""

import asyncio
import time

import pytest

from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.serving import (
    DeploymentSpec,
    OutlierConfig,
    ReplicaState,
    RequestOptions,
    ServeController,
)
from bioengine_tpu.serving.outlier import DeploymentLatencyTracker
from bioengine_tpu.utils import flight

pytestmark = [pytest.mark.anyio]


def make_tracker(**overrides) -> DeploymentLatencyTracker:
    cfg = OutlierConfig(
        enabled=True,
        ewma_alpha=0.5,
        ratio=3.0,
        recovery_ratio=1.5,
        excursion_s=0.5,
        min_samples=4,
        probe_every=4,
        hedge_streak_limit=5,
        **overrides,
    )
    return DeploymentLatencyTracker("app", "dep", cfg)


class TestOutlierDetector:
    def test_outlier_enters_probation_after_persistence(self):
        t = make_tracker()
        now = 100.0
        # healthy baseline on three replicas
        for i in range(6):
            for rid in ("r1", "r2", "r3"):
                t.note(rid, 0.01, now=now + i * 0.01)
        now += 1.0
        # r1 excursions: first over-threshold note STARTS the clock
        assert t.note("r1", 0.2, now=now) == []
        # still inside the persistence window: no verdict
        assert t.note("r1", 0.2, now=now + 0.2) == []
        # past excursion_s: probation
        transitions = t.note("r1", 0.2, now=now + 0.6)
        assert ("r1", "enter") in transitions
        assert t.replicas["r1"].in_probation

    def test_deployment_wide_shift_ejects_nobody(self):
        """The adversarial case: a recompile / bigger batches slow the
        WHOLE deployment together. Every EWMA rises, the median rises
        with them — no replica is an outlier, nobody is ejected."""
        t = make_tracker()
        now = 100.0
        for i in range(6):
            for rid in ("r1", "r2", "r3"):
                t.note(rid, 0.01, now=now + i * 0.01)
        # everything shifts 20x at once, and stays there well past the
        # persistence window
        for i in range(20):
            for rid in ("r1", "r2", "r3"):
                assert t.note(rid, 0.2, now=now + 1.0 + i * 0.1) == []
        assert not any(st.in_probation for st in t.replicas.values())

    def test_recovery_needs_fresh_probe_samples(self):
        """Exit requires measurements taken IN probation: the EWMA
        frozen at entry (hedging dries up the sample stream) must not
        exit the replica by itself."""
        t = make_tracker()
        now = 100.0
        for i in range(6):
            for rid in ("r1", "r2"):
                t.note(rid, 0.01, now=now + i * 0.01)
        for dt in (0.0, 0.2, 0.6):
            t.note("r1", 0.12, now=now + 1.0 + dt)
        assert t.replicas["r1"].in_probation
        # one fast probe can never exit (the fresh-evidence gate needs
        # two measurements taken IN probation) ...
        assert ("r1", "exit") not in t.note("r1", 0.01, now=now + 2.0)
        assert t.replicas["r1"].in_probation
        # ... further fast probes decay the EWMA under recovery_ratio x
        # median and the replica recovers on its own
        exited_at = None
        for i in range(8):
            if ("r1", "exit") in t.note("r1", 0.01, now=now + 2.1 + i * 0.1):
                exited_at = i
                break
        assert exited_at is not None, t.replicas["r1"]
        assert not t.replicas["r1"].in_probation

    def test_probation_is_a_minority_verdict(self):
        """With max_eject_fraction=0.5 a 2-replica deployment ejects at
        most one — the LAST healthy replica can never be soft-ejected
        even when its latency looks awful."""
        t = make_tracker()
        now = 100.0
        for i in range(6):
            for rid in ("r1", "r2"):
                t.note(rid, 0.01, now=now + i * 0.01)
        for dt in (0.0, 0.6):
            t.note("r1", 0.2, now=now + 1.0 + dt)
        assert t.replicas["r1"].in_probation
        # now r2 degrades too — the median is r1's... the verdict must
        # NOT empty the routing set
        for dt in (0.0, 0.3, 0.6, 0.9):
            t.note("r2", 0.3, now=now + 2.0 + dt)
        assert not t.replicas["r2"].in_probation

    def test_hedge_loss_streak_enters_probation(self):
        """Once hedging rescues every request off a gray replica, its
        own samples stop (losers are cancelled, never measured) — the
        consecutive hedge-loss streak is the detection path that still
        works."""
        t = make_tracker()
        now = 100.0
        for i in range(6):
            for rid in ("r1", "r2", "r3"):
                t.note(rid, 0.01, now=now + i * 0.01)
        for _ in range(4):
            assert ("r1", "enter") not in t.note_hedge_loss("r1", now=now)
        transitions = t.note_hedge_loss("r1", now=now)
        assert ("r1", "enter") in transitions
        assert t.replicas["r1"].in_probation

    def test_measured_completion_breaks_hedge_streak(self):
        t = make_tracker()
        now = 100.0
        for i in range(6):
            for rid in ("r1", "r2"):
                t.note(rid, 0.01, now=now + i * 0.01)
        for _ in range(4):
            t.note_hedge_loss("r1", now=now)
        t.note("r1", 0.01, now=now + 1.0)  # a real sample landed
        assert t.replicas["r1"].hedge_streak == 0

    def test_hedge_delay_is_p95_derived_with_override(self):
        t = make_tracker()
        for i in range(100):
            t.note("r1", 0.010 if i % 20 else 0.050, now=100.0 + i)
        delay = t.hedge_delay_s(now=300.0)
        assert 0.010 < delay <= 0.050
        fixed = DeploymentLatencyTracker(
            "app", "dep", OutlierConfig(enabled=True, hedge_delay_s=0.123)
        )
        assert fixed.hedge_delay_s() == 0.123

    def test_disabled_detector_never_transitions(self):
        t = DeploymentLatencyTracker(
            "app", "dep", OutlierConfig(enabled=False, min_samples=2)
        )
        for i in range(10):
            t.note("r1", 0.01, now=100.0 + i)
            assert t.note("r2", 1.0, now=100.0 + i) == []
        assert t.note_hedge_loss("r2") == []


# ---------------------------------------------------------------------------
# controller-level probation state machine
# ---------------------------------------------------------------------------


class PaceableApp:
    """Per-instance controllable service time — the gray knob."""

    delays: dict = {}
    cancelled: int = 0

    def __init__(self):
        self.tag = None
        self.calls = 0

    async def work(self, x=0):
        self.calls += 1
        try:
            await asyncio.sleep(PaceableApp.delays.get(self.tag, 0.005))
        except asyncio.CancelledError:
            PaceableApp.cancelled += 1
            raise
        return {"x": x, "tag": self.tag}

    async def check_health(self):
        return "ok"  # gray failure: health always passes


@pytest.fixture
async def controller():
    c = ServeController(
        ClusterState(),
        health_check_period=3600,
        outlier_config=OutlierConfig(
            enabled=True,
            ewma_alpha=0.5,
            ratio=2.5,
            recovery_ratio=1.6,
            excursion_s=0.15,
            min_samples=4,
            probe_every=4,
            hedge_streak_limit=4,
            hedge_delay_s=0.03,
        ),
    )
    PaceableApp.delays = {}
    PaceableApp.cancelled = 0
    yield c
    await c.stop()


async def _deploy(controller, n=2, name="gf-app"):
    app = await controller.deploy(
        name,
        [
            DeploymentSpec(
                name="e",
                instance_factory=PaceableApp,
                num_replicas=n,
                autoscale=False,
            )
        ],
    )
    await asyncio.sleep(0.05)
    for i, r in enumerate(app.replicas["e"]):
        r.instance.tag = f"r{i}"
    return app


async def _drive(handle, n, options=None, x=0):
    results = await asyncio.gather(
        *(handle.call("work", x + i, options=options) for i in range(n)),
        return_exceptions=True,
    )
    bad = [r for r in results if isinstance(r, BaseException)]
    assert not bad, bad
    return results


class TestProbationStateMachine:
    async def test_excursion_probation_probe_recovery(self, controller):
        """The full loop: one replica turns gray → probation (flight
        evidence, soft-ejected from the pick, trickle still probes) →
        the instance heals → probes observe it → back to HEALTHY."""
        app = await _deploy(controller, n=2)
        r0, r1 = app.replicas["e"]
        handle = controller.get_handle("gf-app")
        opts = RequestOptions(idempotent=True)
        t0 = time.time()
        await _drive(handle, 12, opts)
        assert r0.state == ReplicaState.HEALTHY

        PaceableApp.delays = {r0.instance.tag: 0.1}  # r0 goes gray
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and r0.state != ReplicaState.PROBATION:
            await _drive(handle, 4, opts)
            await asyncio.sleep(0.02)
        assert r0.state == ReplicaState.PROBATION
        enters = [
            e
            for e in flight.get_events(
                types=("replica.probation",), since=t0
            )
            if e["attrs"].get("phase") == "enter"
        ]
        assert enters and enters[0]["attrs"]["replica"] == r0.replica_id

        # soft-ejected, still probed: under traffic the probation
        # replica serves a trickle, the healthy one the bulk
        base0 = r0.instance.calls
        base1 = r1.instance.calls
        await _drive(handle, 24, opts)
        probes = r0.instance.calls - base0
        assert probes >= 1, "trickle probe never reached the gray replica"
        assert r1.instance.calls - base1 > probes

        # health checks pass throughout and must NOT clear probation
        assert await r0.check_health() == ReplicaState.PROBATION

        PaceableApp.delays = {}  # the replica heals
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and r0.state != ReplicaState.HEALTHY:
            await _drive(handle, 6, opts)
            await asyncio.sleep(0.02)
        assert r0.state == ReplicaState.HEALTHY
        exits = [
            e
            for e in flight.get_events(
                types=("replica.probation",), since=t0
            )
            if e["attrs"].get("phase") == "exit"
        ]
        assert exits and exits[-1]["attrs"]["replica"] == r0.replica_id

    async def test_deployment_wide_slowdown_no_ejection(self, controller):
        """Recompile / bigger batches: EVERY replica slows together —
        the median moves with them and nobody enters probation."""
        app = await _deploy(controller, n=2, name="gf-app2")
        handle = controller.get_handle("gf-app2")
        opts = RequestOptions(idempotent=True)
        await _drive(handle, 12, opts)
        PaceableApp.delays = {"r0": 0.08, "r1": 0.08}
        for _ in range(6):
            await _drive(handle, 6, opts)
            await asyncio.sleep(0.02)
        assert all(
            r.state == ReplicaState.HEALTHY for r in app.replicas["e"]
        )

    async def test_undeploy_sweeps_outlier_tracker(self, controller):
        await _deploy(controller, n=2, name="gf-app3")
        handle = controller.get_handle("gf-app3")
        await _drive(handle, 4, RequestOptions(idempotent=True))
        assert ("gf-app3", "e") in controller._outliers
        await controller.undeploy("gf-app3")
        assert ("gf-app3", "e") not in controller._outliers
        assert controller._queue_depth == {}

    async def test_probation_surfaces_in_app_status(self, controller):
        await _deploy(controller, n=2, name="gf-app4")
        handle = controller.get_handle("gf-app4")
        await _drive(handle, 8, RequestOptions(idempotent=True))
        status = controller.get_app_status("gf-app4")
        gray = status["deployments"]["e"]["gray_failure"]
        assert gray["enabled"] is True
        assert gray["replicas"]
        for info in gray["replicas"].values():
            assert "ewma_s" in info and "in_probation" in info


# ---------------------------------------------------------------------------
# request hedging
# ---------------------------------------------------------------------------


class TestHedging:
    async def test_hedge_requires_idempotent(self):
        with pytest.raises(ValueError, match="idempotent"):
            RequestOptions(hedge=True, idempotent=False)

    async def test_hedge_rescues_slow_primary(self, controller):
        """The tail defense: primary stuck at 0.5s, hedge fires after
        the fixed 30ms delay, a sibling answers fast, first result
        wins. The loser is cancelled and feeds NEITHER the breaker NOR
        the outlier EWMA — the satellite regression pin."""
        app = await _deploy(controller, n=2, name="hg-app")
        r0, r1 = app.replicas["e"]
        # make round-robin deterministic: force the pick to r0 first by
        # loading r1... simpler: slow BOTH directions and accept either
        # primary — the winner must be the fast sibling either way
        PaceableApp.delays = {r0.instance.tag: 0.5}
        handle = controller.get_handle("hg-app")
        opts = RequestOptions(idempotent=True, hedge=True)
        t0 = time.time()
        tracker = controller._outlier_tracker("hg-app", "e")
        samples_before = {
            rid: tracker.sample_count(rid)
            for rid in (r0.replica_id, r1.replica_id)
        }
        cancelled_before = PaceableApp.cancelled
        # several calls: whichever replica the router picks first, any
        # call landing on r0 is rescued by its hedge within ~50ms
        t_start = time.monotonic()
        results = await _drive(handle, 6, opts)
        wall = time.monotonic() - t_start
        assert wall < 0.4, f"hedges did not rescue the tail ({wall:.3f}s)"
        assert all(r["tag"] == r1.instance.tag for r in results)

        hedge_events = flight.get_events(types=("request.hedge",), since=t0)
        wins = [e for e in hedge_events if e["attrs"]["winner"] == "hedge"]
        assert wins, hedge_events
        # cancelled losers: the slow instance observed cancellations...
        await asyncio.sleep(0.05)
        assert PaceableApp.cancelled > cancelled_before
        # ...which fed NEITHER the breaker NOR the outlier EWMA
        assert controller._breaker_counts.get(r0.replica_id) is None
        assert (
            tracker.sample_count(r0.replica_id)
            == samples_before[r0.replica_id]
        )
        # and the semaphore/ongoing accounting is exact (no leak)
        for r in (r0, r1):
            assert r._ongoing == 0
            assert r._queued == 0
            assert r._semaphore._value == r.max_ongoing_requests

    async def test_hedge_attempts_are_trace_siblings(
        self, controller, monkeypatch
    ):
        from bioengine_tpu.utils import tracing

        monkeypatch.setenv("BIOENGINE_TRACE_SAMPLE", "1.0")
        tracing.reset_env_cache()
        try:
            app = await _deploy(controller, n=2, name="hg-tr")
            r0, r1 = app.replicas["e"]
            PaceableApp.delays = {
                r0.instance.tag: 0.4,
                r1.instance.tag: 0.4,
            }
            # both slow → the hedge definitely launches; then free the
            # second replica so the hedge wins decisively
            handle = controller.get_handle("hg-tr")

            async def call():
                return await handle.call(
                    "work", 1,
                    options=RequestOptions(idempotent=True, hedge=True),
                )

            task = asyncio.create_task(call())
            await asyncio.sleep(0.06)  # hedge armed by now
            PaceableApp.delays = {}
            await asyncio.wait_for(task, 3)
            spans = tracing.get_spans(max_spans=400)
            attempts = [s for s in spans if s["name"] == "attempt"]
            hedged = [
                s for s in attempts if s["attrs"].get("hedge") is not None
            ]
            assert len(hedged) >= 2, attempts
            trace_ids = {s["trace_id"] for s in hedged[-2:]}
            assert len(trace_ids) == 1  # siblings under ONE trace
            labels = {s["attrs"]["hedge"] for s in hedged[-2:]}
            assert labels == {"primary", "hedge"}
        finally:
            monkeypatch.delenv("BIOENGINE_TRACE_SAMPLE", raising=False)
            tracing.reset_env_cache()

    async def test_single_replica_hedge_degrades_gracefully(
        self, controller
    ):
        await _deploy(controller, n=1, name="hg-one")
        handle = controller.get_handle("hg-one")
        t0 = time.time()
        result = await handle.call(
            "work", 5, options=RequestOptions(idempotent=True, hedge=True)
        )
        assert result["x"] == 5
        # nobody to hedge on → no hedge event, no error
        assert flight.get_events(types=("request.hedge",), since=t0) == []

    async def test_hedged_app_error_never_feeds_breaker(self, controller):
        """Same breaker contract as every other dispatch path: a
        deterministic APPLICATION error riding a hedged attempt (bad
        client input) must never strike a healthy replica."""

        class BuggyApp:
            async def work(self, x=0):
                raise ValueError("bad input")

        await controller.deploy(
            "hg-buggy",
            [
                DeploymentSpec(
                    name="e",
                    instance_factory=BuggyApp,
                    num_replicas=2,
                    autoscale=False,
                )
            ],
        )
        await asyncio.sleep(0.05)
        handle = controller.get_handle("hg-buggy")
        for _ in range(controller.breaker_threshold + 1):
            with pytest.raises(ValueError, match="bad input"):
                await handle.call(
                    "work",
                    options=RequestOptions(idempotent=True, hedge=True),
                )
        assert controller._breaker_counts == {}
        app = controller.apps["hg-buggy"]
        assert all(
            r.state == ReplicaState.HEALTHY for r in app.replicas["e"]
        )

    async def test_hedged_failure_still_fails_over(self, controller):
        """When the primary genuinely dies (transport), the hedged
        attempt path surfaces the same typed behavior the plain path
        would — and the outer retry loop still fails over."""

        class FlakyApp:
            failures = 0

            async def work(self, x=0):
                if FlakyApp.failures < 1:
                    FlakyApp.failures += 1
                    raise ConnectionError("synthetic transport failure")
                return {"x": x}

        FlakyApp.failures = 0
        await controller.deploy(
            "hg-flaky",
            [
                DeploymentSpec(
                    name="e",
                    instance_factory=FlakyApp,
                    num_replicas=2,
                    autoscale=False,
                )
            ],
        )
        await asyncio.sleep(0.05)
        handle = controller.get_handle("hg-flaky")
        result = await handle.call(
            "work", 3, options=RequestOptions(idempotent=True, hedge=True)
        )
        assert result["x"] == 3
