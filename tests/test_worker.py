"""End-to-end worker lifecycle + code executor tests.

Mirrors the reference's e2e tier (ref tests/end_to_end/test_worker.py,
test_code_executor.py) but hermetic: in-process control plane, local
artifact paths, no external servers.
"""

import asyncio
import base64

import cloudpickle
import pytest

from bioengine_tpu.utils.permissions import create_context
from bioengine_tpu.worker.code_executor import CodeExecutor
from bioengine_tpu.worker.worker import BioEngineWorker

pytestmark = [pytest.mark.end_to_end, pytest.mark.anyio]

ADMIN_CTX = create_context("admin", workspace="bioengine")
ANON_CTX = create_context("anonymous")

REPO_APPS = __import__("pathlib").Path(__file__).resolve().parent.parent / "apps"


# ---- code executor ----------------------------------------------------------


@pytest.fixture()
def executor():
    return CodeExecutor(admin_users=["admin"], default_timeout=60.0)


async def test_run_code_source_mode(executor):
    result = await executor.run_code(
        code="def main(x, y):\n    print('working')\n    return x + y\n",
        args=[2, 3],
        context=ADMIN_CTX,
    )
    assert result["status"] == "ok"
    assert result["result"] == 5
    assert "working" in result["stdout"]


async def test_run_code_named_function_and_async(executor):
    code = (
        "import asyncio\n"
        "async def compute(n):\n"
        "    await asyncio.sleep(0)\n"
        "    return n * 2\n"
        "def other():\n    return 'no'\n"
    )
    result = await executor.run_code(
        code=code, function_name="compute", args=[21], context=ADMIN_CTX
    )
    assert result["result"] == 42


async def test_run_code_pickle_mode(executor):
    def work(a, b=1):
        return {"sum": a + b}

    payload = base64.b64encode(cloudpickle.dumps(work)).decode()
    result = await executor.run_code(
        function=payload, mode="pickle", args=[4], kwargs={"b": 6},
        context=ADMIN_CTX,
    )
    assert result["result"] == {"sum": 10}


async def test_run_code_error_propagation(executor):
    result = await executor.run_code(
        code="def main():\n    raise ValueError('boom')\n", context=ADMIN_CTX
    )
    assert result["status"] == "error"
    assert "ValueError: boom" in result["error"]
    assert result["result"] is None


async def test_run_code_timeout(executor):
    result = await executor.run_code(
        code="import time\ndef main():\n    time.sleep(30)\n",
        timeout=1.0,
        context=ADMIN_CTX,
    )
    assert result["status"] == "timeout"


async def test_run_code_stream_callbacks(executor):
    lines: list[str] = []
    result = await executor.run_code(
        code=(
            "import sys\n"
            "def main():\n"
            "    print('out1')\n"
            "    print('err1', file=sys.stderr)\n"
            "    print('out2')\n"
        ),
        write_stdout=lines.append,
        write_stderr=lines.append,
        context=ADMIN_CTX,
    )
    assert result["status"] == "ok"
    joined = "".join(lines)
    assert "out1" in joined and "err1" in joined and "out2" in joined


async def test_run_code_env_vars(executor):
    result = await executor.run_code(
        code="import os\ndef main():\n    return os.environ['MY_FLAG']\n",
        remote_options={"env_vars": {"MY_FLAG": "on"}},
        context=ADMIN_CTX,
    )
    assert result["result"] == "on"


async def test_run_code_requires_admin(executor):
    with pytest.raises(PermissionError):
        await executor.run_code(code="def main():\n    return 1\n", context=ANON_CTX)


# ---- worker __main__ arg parsing --------------------------------------------


def test_worker_arg_parsing():
    from bioengine_tpu.worker.__main__ import (
        create_parser,
        worker_kwargs_from_args,
    )

    args = create_parser().parse_args(
        [
            "--mode", "single-machine",
            "--admin-users", "alice", "bob",
            "--startup-applications", '[{"local_path": "apps/demo-app"}]',
            "--port", "1234",
        ]
    )
    kwargs = worker_kwargs_from_args(args)
    assert kwargs["admin_users"] == ["alice", "bob"]
    assert kwargs["startup_applications"] == [{"local_path": "apps/demo-app"}]
    assert kwargs["port"] == 1234


def test_worker_startup_app_json_validation():
    from bioengine_tpu.worker.__main__ import parse_startup_applications

    assert parse_startup_applications(None) == []
    assert parse_startup_applications('{"a": 1}') == [{"a": 1}]
    with pytest.raises(ValueError):
        parse_startup_applications('["not-a-dict"]')


# ---- full worker lifecycle --------------------------------------------------


@pytest.fixture()
async def worker(tmp_path):
    w = BioEngineWorker(
        mode="single-machine",
        workspace_dir=tmp_path / "ws",
        admin_users=["admin"],
        startup_applications=[{"local_path": str(REPO_APPS / "demo-app")}],
        monitoring_interval_seconds=0.2,
        log_file="off",
    )
    await w.start()
    try:
        yield w
    finally:
        if w.is_ready:
            await w.stop()


async def test_worker_status_shape(worker):
    status = worker.get_status(context=ADMIN_CTX)
    assert status["worker"]["ready"] is True
    assert status["worker"]["uptime_seconds"] >= 0
    assert status["cluster"]["ready"] is True
    assert status["cluster"]["topology"]["n_chips"] == 8
    assert len(status["applications"]) == 1
    (app_status,) = status["applications"].values()
    assert app_status["status"] == "RUNNING"
    assert app_status["name"] == "Demo App"
    assert "ping" in app_status["available_methods"]


async def test_worker_service_call_through_rpc(worker):
    """Call the startup app through the registered RPC service surface."""
    (app_id,) = worker.apps_manager.records
    result = await worker.server.call_service_method(
        f"bioengine/{app_id}", "echo", kwargs={"message": "hi"}
    )
    assert result["echo"] == "hi"


async def test_worker_run_code_service(worker):
    result = await worker.server.call_service_method(
        "bioengine/bioengine-worker",
        "run_code",
        kwargs={"code": "def main():\n    return 7\n"},
        caller=worker.server._tokens[worker.server.issue_token("admin")],
    )
    assert result["result"] == 7


async def test_worker_monitoring_recovers_and_counts_errors(worker):
    await asyncio.sleep(0.5)  # a few monitor ticks
    assert worker._monitor_errors == 0
    assert worker.is_ready


async def test_worker_deploy_and_stop_app(worker, tmp_path):
    result = await worker.apps_manager.deploy_app(
        local_path=str(REPO_APPS / "demo-app"),
        deployment_kwargs={"demo_deployment": {"greeting": "Yo"}},
        context=ADMIN_CTX,
    )
    app_id = result["app_id"]
    echo = await worker.server.call_service_method(
        f"bioengine/{app_id}", "echo", kwargs={"message": "x"}
    )
    assert echo["greeting"] == "Yo"
    await worker.apps_manager.stop_app(app_id, context=ADMIN_CTX)
    assert app_id not in worker.apps_manager.records


async def test_worker_get_logs_requires_admin(worker):
    with pytest.raises(PermissionError):
        worker.get_logs(context=ANON_CTX)
    logs = worker.get_logs(context=ADMIN_CTX)
    assert isinstance(logs, dict)


async def test_run_code_huge_output_line(executor):
    result = await executor.run_code(
        code="def main():\n    print('x' * 200000)\n    return 1\n",
        context=ADMIN_CTX,
    )
    assert result["status"] == "ok"
    assert result["result"] == 1
    assert len(result["stdout"]) >= 200000


async def test_run_code_toplevel_exit_is_contained(executor):
    """Top-level code (incl. sys.exit) runs in the subprocess, never in
    the worker process."""
    result = await executor.run_code(
        code="import sys\nsys.exit(3)\ndef main():\n    return 1\n",
        context=ADMIN_CTX,
    )
    assert result["status"] == "error"
    assert "SystemExit" in result["error"]


async def test_stop_worker_over_websocket(tmp_path):
    """A remote stop_worker call must get its response before teardown."""
    from bioengine_tpu.rpc.client import connect_to_server

    w = BioEngineWorker(
        mode="single-machine",
        workspace_dir=tmp_path / "ws3",
        admin_users=["admin"],
        monitoring_interval_seconds=5.0,
        log_file="off",
    )
    await w.start()
    token = w.server.issue_token("admin")
    conn = await connect_to_server({"server_url": w.server.url, "token": token})
    svc = await conn.get_service("bioengine-worker")
    result = await asyncio.wait_for(svc.stop_worker(), timeout=10.0)
    assert result["status"] == "stopping"
    await conn.disconnect()
    await asyncio.wait_for(w._stop_event.wait(), timeout=10.0)
    assert not w.is_ready


async def test_worker_graceful_stop(tmp_path):
    w = BioEngineWorker(
        mode="single-machine",
        workspace_dir=tmp_path / "ws2",
        admin_users=["admin"],
        monitoring_interval_seconds=5.0,
        log_file="off",
    )
    await w.start()
    assert w.is_ready
    await w.stop()
    assert not w.is_ready
    # lock released: a second worker can start in the same workspace
    w2 = BioEngineWorker(
        mode="single-machine",
        workspace_dir=tmp_path / "ws2",
        admin_users=["admin"],
        log_file="off",
    )
    await w2.start()
    assert w2.is_ready
    await w2.stop()


async def test_worker_restart_recovers_apps(tmp_path):
    """App records persist in the workspace and a new worker on the same
    workspace re-adopts them — ref bioengine/apps/manager.py:841-935
    (VERDICT r3 missing #3)."""
    ws = tmp_path / "ws-recover"
    w = BioEngineWorker(
        mode="single-machine",
        workspace_dir=ws,
        admin_users=["admin"],
        monitoring_interval_seconds=5.0,
        log_file="off",
    )
    await w.start()
    result = await w.apps_manager.deploy_app(
        local_path=str(REPO_APPS / "demo-app"),
        app_id="persist-me",
        deployment_kwargs={"demo_deployment": {"greeting": "Back"}},
        context=ADMIN_CTX,
    )
    assert result["app_id"] == "persist-me"
    await w.stop()  # graceful stop keeps the persisted records

    w2 = BioEngineWorker(
        mode="single-machine",
        workspace_dir=ws,
        admin_users=["admin"],
        monitoring_interval_seconds=5.0,
        log_file="off",
    )
    await w2.start()
    try:
        assert "persist-me" in w2.apps_manager.records
        echo = await w2.server.call_service_method(
            "bioengine/persist-me", "echo", kwargs={"message": "again"}
        )
        assert echo["echo"] == "again"
        assert echo["greeting"] == "Back"
    finally:
        await w2.stop()


async def test_worker_restart_after_explicit_stop_forgets_apps(tmp_path):
    """An admin's explicit stop_app erases the record — only worker
    shutdown preserves deployment intent."""
    ws = tmp_path / "ws-forget"
    w = BioEngineWorker(
        mode="single-machine",
        workspace_dir=ws,
        admin_users=["admin"],
        monitoring_interval_seconds=5.0,
        log_file="off",
    )
    await w.start()
    await w.apps_manager.deploy_app(
        local_path=str(REPO_APPS / "demo-app"),
        app_id="forget-me",
        context=ADMIN_CTX,
    )
    await w.apps_manager.stop_app("forget-me", context=ADMIN_CTX)
    await w.stop()

    w2 = BioEngineWorker(
        mode="single-machine",
        workspace_dir=ws,
        admin_users=["admin"],
        monitoring_interval_seconds=5.0,
        log_file="off",
    )
    await w2.start()
    try:
        assert "forget-me" not in w2.apps_manager.records
    finally:
        await w2.stop()


async def test_worker_profiling_service(worker, tmp_path):
    """jax.profiler surface (SURVEY §5.1): trace start/stop writes
    artifacts; memory_profile returns pprof bytes + device stats."""
    trace_dir = tmp_path / "trace"
    with pytest.raises(PermissionError):
        worker.start_profiling(context=ANON_CTX)
    started = worker.start_profiling(
        trace_dir=str(trace_dir), context=ADMIN_CTX
    )
    assert started["profiling"] is True
    with pytest.raises(RuntimeError, match="already active"):
        worker.start_profiling(context=ADMIN_CTX)
    # do some device work so the trace has content
    import jax.numpy as jnp

    _ = float(jnp.ones((64, 64)).sum())
    stopped = worker.stop_profiling(context=ADMIN_CTX)
    assert stopped["trace_dir"] == str(trace_dir)
    assert any(trace_dir.rglob("*")), "trace dir is empty"
    with pytest.raises(RuntimeError, match="not active"):
        worker.stop_profiling(context=ADMIN_CTX)

    mem = worker.memory_profile(context=ADMIN_CTX)
    import base64

    assert len(base64.b64decode(mem["pprof_b64"])) > 0
    assert mem["devices"]


async def test_worker_profile_replica_routes_to_local(worker, tmp_path):
    """PR 7: profile ONE replica of a live deployment — local placement
    routes to this process's jax.profiler; the response names the
    replica that was profiled."""
    (app_id,) = worker.apps_manager.records
    with pytest.raises(PermissionError):
        await worker.profile_replica(app_id, context=ANON_CTX)
    with pytest.raises(ValueError, match="start|stop|memory"):
        await worker.profile_replica(
            app_id, action="bogus", context=ADMIN_CTX
        )
    trace_dir = tmp_path / "replica-trace"
    started = await worker.profile_replica(
        app_id, trace_dir=str(trace_dir), context=ADMIN_CTX
    )
    assert started["profiling"] is True
    assert started["host_id"] == "local"
    assert started["app_id"] == app_id
    assert started["replica_id"]
    stopped = await worker.profile_replica(
        app_id, action="stop", context=ADMIN_CTX
    )
    assert stopped["profiling"] is False
    assert any(trace_dir.rglob("*")), "trace dir is empty"
    mem = await worker.profile_replica(
        app_id, action="memory", context=ADMIN_CTX
    )
    assert mem["devices"]
    with pytest.raises(KeyError):
        await worker.profile_replica(
            app_id, replica_id="nope", context=ADMIN_CTX
        )


async def test_worker_flight_and_bundle_verbs(worker):
    """PR 7: get_flight_record (paginated) + debug_bundle return the
    incident surfaces over the worker service, admin-gated."""
    from bioengine_tpu.utils import flight

    with pytest.raises(PermissionError):
        worker.get_flight_record(context=ANON_CTX)
    flight.record("test.worker_verb", marker=1)
    record = worker.get_flight_record(limit=500, context=ADMIN_CTX)
    assert record["recorder"] == flight.recorder_id()
    assert any(
        e["type"] == "test.worker_verb" for e in record["events"]
    )
    # the startup sequence itself left evidence (replica placement)
    assert any(
        e["type"] == "replica.place" for e in record["events"]
    )
    # since-cursor pagination: nothing is older than now
    import time as _time

    assert (
        worker.get_flight_record(since=_time.time() + 60, context=ADMIN_CTX)[
            "events"
        ]
        == []
    )

    with pytest.raises(PermissionError):
        await worker.debug_bundle(context=ANON_CTX)
    bundle = await worker.debug_bundle(context=ADMIN_CTX)
    for key in (
        "events", "traces", "metrics", "cluster", "apps", "hosts", "worker",
    ):
        assert key in bundle, key
    assert bundle["worker"]["ready"] is True
    assert bundle["apps"], "deployed app missing from bundle"
    (app_status,) = bundle["apps"].values()
    assert "cost" in app_status


async def test_worker_get_traces_pagination(worker):
    """PR 7 satellite: get_traces limit/since — repeated pulls never
    re-ship the whole buffer."""
    from bioengine_tpu.utils import tracing

    tracing.clear_spans()
    for i in range(8):
        with tracing.span("verb.span", i=i):
            __import__("time").sleep(0.002)
    spans = worker.get_traces(
        name="verb.span", limit=3, context=ADMIN_CTX
    )
    assert [s["attrs"]["i"] for s in spans] == [5, 6, 7]
    cursor = worker.get_traces(name="verb.span", max_spans=100, context=ADMIN_CTX)[
        4
    ]["started_at"]
    newer = worker.get_traces(
        name="verb.span", max_spans=100, since=cursor, context=ADMIN_CTX
    )
    assert [s["attrs"]["i"] for s in newer] == [4, 5, 6, 7]


async def test_worker_dashboard_served(worker):
    """The built-in dashboard is served at /apps/_dashboard/ and its
    data endpoints (get_status via the bridge, /services) respond."""
    import aiohttp

    base = f"http://{worker.server.host}:{worker.server.port}"
    async with aiohttp.ClientSession() as http:
        async with http.get(f"{base}/apps/_dashboard/") as r:
            assert r.status == 200
            page = await r.text()
        assert "Worker Dashboard" in page
        async with http.post(
            f"{base}/call/bioengine-worker/get_status", json={}
        ) as r:
            status = (await r.json())["result"]
            assert status["worker"]["ready"] is True
            assert status["applications"]
        async with http.get(f"{base}/services") as r:
            services = await r.json()
            assert any(s["type"] == "bioengine-worker" for s in services)
