"""Mesh-aware sharded serving (the multi-chip inference engine).

Hermetic on the forced 8-virtual-host-device CPU mesh (tests/conftest.py
sets ``--xla_force_host_platform_device_count=8`` — the same trick as
the MULTICHIP dryruns), exercising the guarantees the engine makes:

- a 1-chip engine is BIT-IDENTICAL to the legacy single-device path;
- a dp=4 engine matches the single-device result within float tolerance
  on both the planar direct path and the overlap-tiled path;
- uneven batches pad to a dp multiple (equal shards) and crop back;
- compiled-program cache keys separate per mesh shape, so engines with
  different chip groups sharing one cache never mix executables;
- the replica lifecycle hands the leased chip group to the instance,
  and killing a sharded replica returns every leased chip (no leak).
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.models.unet import UNet2D
from bioengine_tpu.runtime.buckets import bucket_batch
from bioengine_tpu.runtime.engine import (
    EngineConfig,
    InferenceEngine,
    resolve_devices,
)
from bioengine_tpu.runtime.program_cache import CompiledProgramCache
from bioengine_tpu.serving import DeploymentSpec, ReplicaState, ServeController

pytestmark = pytest.mark.unit


@pytest.fixture(scope="module")
def unet():
    model = UNet2D(features=(4, 8), out_channels=1)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 64, 64, 1), jnp.float32)
    )["params"]
    return model, params


def _make_engine(unet, devices, config=None, cache=None, **kw):
    model, params = unet
    return InferenceEngine(
        "sharded-test",
        lambda p, x: model.apply({"params": p}, x),
        params,
        divisor=model.divisor,
        config=config,
        # `cache or ...` would discard an EMPTY cache (len 0 is falsy)
        cache=cache if cache is not None else CompiledProgramCache(),
        devices=devices,
        **kw,
    )


@pytest.fixture(scope="module")
def images():
    rng = np.random.default_rng(7)
    return rng.standard_normal((3, 70, 70, 1)).astype(np.float32)


class TestBucketBatchDp:
    def test_multiple_of_rounds_up_within_ladder(self):
        assert bucket_batch(3, multiple_of=4) == 4
        assert bucket_batch(5, multiple_of=4) == 8
        assert bucket_batch(2, multiple_of=4) == 4
        assert bucket_batch(4, multiple_of=4) == 4

    def test_multiple_of_one_is_legacy(self):
        for n in (1, 2, 3, 5, 17, 65, 200):
            assert bucket_batch(n) == bucket_batch(n, multiple_of=1)

    def test_off_ladder_fallback_stays_divisible(self):
        got = bucket_batch(130, multiple_of=3)
        assert got >= 130 and got % 3 == 0

    def test_non_power_of_two_dp_small_batches_stay_small(self):
        # dp=3 divides no default ladder entry; the fallback must NOT
        # balloon a 1-image request to a 64-ceil batch (observed 66)
        assert bucket_batch(1, multiple_of=3) == 3
        assert bucket_batch(5, multiple_of=3) == 6
        assert bucket_batch(7, multiple_of=3) == 12


class TestMeshParity:
    def test_one_chip_bit_identical_to_legacy(self, unet, images):
        legacy = _make_engine(unet, None)  # today's device path
        one = _make_engine(unet, jax.devices()[:1])
        try:
            assert one.mesh is None  # degenerate mesh IS the legacy path
            a = legacy.predict(images)
            b = one.predict(images)
            np.testing.assert_array_equal(a, b)
        finally:
            legacy.close()
            one.close()

    def test_dp4_planar_matches_single(self, unet, images):
        e1 = _make_engine(unet, jax.devices()[:1])
        e4 = _make_engine(unet, jax.devices()[:4])
        try:
            assert e4.mesh_shape == {"dp": 4}
            y1 = e1.predict(images)
            y4 = e4.predict(images)
            assert y4.shape == y1.shape
            np.testing.assert_allclose(y4, y1, rtol=1e-5, atol=1e-6)
        finally:
            e1.close()
            e4.close()

    def test_dp4_tiled_matches_single(self, unet, images):
        cfg = EngineConfig(
            max_tile=64, tile=48, tile_overlap=8, tile_batch=4
        )
        e1 = _make_engine(unet, jax.devices()[:1], config=cfg)
        e4 = _make_engine(unet, jax.devices()[:4], config=cfg)
        try:
            y1 = e1.predict(images)  # 70 > max_tile: overlap-tiled
            y4 = e4.predict(images)
            np.testing.assert_allclose(y4, y1, rtol=1e-5, atol=1e-6)
        finally:
            e1.close()
            e4.close()

    def test_dp4_serial_tiled_matches_too(self, unet, images):
        cfg = EngineConfig(
            max_tile=64, tile=48, tile_overlap=8, pipeline_depth=0
        )
        e4 = _make_engine(unet, jax.devices()[:4], config=cfg)
        e1 = _make_engine(unet, jax.devices()[:1], config=cfg)
        try:
            np.testing.assert_allclose(
                e4.predict_serial(images),
                e1.predict_serial(images),
                rtol=1e-5,
                atol=1e-6,
            )
        finally:
            e4.close()
            e1.close()

    def test_uneven_batch_pads_to_dp_multiple_and_crops(self, unet):
        e4 = _make_engine(unet, jax.devices()[:4])
        try:
            rng = np.random.default_rng(0)
            for b in (1, 3, 5):
                x = rng.standard_normal((b, 64, 64, 1)).astype(np.float32)
                y = e4.predict(x)
                assert y.shape[0] == b  # cropped back to the request
            # the compiled batch dims are the padded dp multiples
            batch_dims = {
                key[1]
                for key in e4.cache._programs
                if key[-1].split("@")[0] == "dp4"
            }
            assert batch_dims == {4, 8}  # 1,3 -> 4; 5 -> 8
        finally:
            e4.close()

    def test_dp_padding_rows_do_not_contaminate(self, unet):
        """Padded batch rows are zeros on the last shard; real rows must
        come back identical to a full-batch run (per-sample model)."""
        e4 = _make_engine(unet, jax.devices()[:4])
        try:
            rng = np.random.default_rng(1)
            x4 = rng.standard_normal((4, 64, 64, 1)).astype(np.float32)
            full = e4.predict(x4)
            part = e4.predict(x4[:3])
            np.testing.assert_allclose(
                part, full[:3], rtol=1e-6, atol=1e-7
            )
        finally:
            e4.close()


class TestTensorParallel:
    def test_tp_vit_embedder_matches_single(self):
        from bioengine_tpu.models.vit import ViT
        from bioengine_tpu.parallel.tensor_parallel import (
            VIT_TP_RULES,
            shard_fraction,
        )

        vit = ViT(patch_size=8, dim=64, depth=2, num_heads=4,
                  dtype=jnp.float32)
        x0 = jnp.zeros((1, 64, 64, 3), jnp.float32)
        params = vit.init(jax.random.key(0), x0)["params"]

        def apply_fn(p, x):
            return vit.apply({"params": p}, x)

        rng = np.random.default_rng(2)
        x = rng.standard_normal((4, 64, 64, 3)).astype(np.float32)
        e1 = InferenceEngine(
            "vit-tp", apply_fn, params, cache=CompiledProgramCache(),
            devices=jax.devices()[:1],
        )
        etp = InferenceEngine(
            "vit-tp", apply_fn, params, cache=CompiledProgramCache(),
            devices=jax.devices()[:4], tp=2, tp_rules=VIT_TP_RULES,
        )
        try:
            assert etp.mesh_shape == {"dp": 2, "tp": 2}
            # weights genuinely distributed, not replicated
            assert shard_fraction(etp.params) < 0.9
            np.testing.assert_allclose(
                etp.predict(x), e1.predict(x), rtol=1e-4, atol=1e-5
            )
        finally:
            e1.close()
            etp.close()

    def test_tp_must_divide_group(self, unet):
        with pytest.raises(ValueError, match="tp=3"):
            _make_engine(unet, jax.devices()[:4], tp=3)


class TestProgramCacheMeshKeys:
    def test_keys_separate_per_mesh_shape(self, unet, images):
        cache = CompiledProgramCache()
        e1 = _make_engine(unet, jax.devices()[:1], cache=cache)
        e4 = _make_engine(unet, jax.devices()[:4], cache=cache)
        try:
            e1.predict(images)
            e4.predict(images)
            tags = sorted(key[-1].split("@")[0] for key in cache._programs)
            assert tags == ["1dev", "dp4"]
            # same bucket shape in both keys — only the placement differs
            shapes = {key[1:-2] for key in cache._programs}
            assert len(shapes) == 1
            assert cache.stats.misses == 2
        finally:
            e1.close()
            e4.close()

    def test_same_shape_different_chip_groups_do_not_collide(
        self, unet, images
    ):
        # Two dp=2 engines over DISJOINT device pairs sharing one cache:
        # a shape-only key would hand engine B engine A's warmed
        # executable, and B's first hot request would silently retrace
        # and recompile on its own mesh. Placement-qualified keys give
        # each group its own entry (and its own warmup).
        cache = CompiledProgramCache()
        a = _make_engine(unet, jax.devices()[:2], cache=cache)
        b = _make_engine(unet, jax.devices()[2:4], cache=cache)
        try:
            out_a = a.predict(images)
            out_b = b.predict(images)
            assert cache.stats.misses == 2
            np.testing.assert_allclose(out_a, out_b, rtol=1e-5, atol=1e-5)
        finally:
            a.close()
            b.close()

    def test_same_mesh_shape_reuses_program(self, unet, images):
        cache = CompiledProgramCache()
        a = _make_engine(unet, jax.devices()[:4], cache=cache)
        b = _make_engine(unet, jax.devices()[:4], cache=cache)
        try:
            a.predict(images)
            b.predict(images)
            assert cache.stats.misses == 1
            assert cache.stats.hits >= 1
        finally:
            a.close()
            b.close()


class TestResolveDevices:
    def test_matches_by_id(self):
        devs = jax.local_devices()
        got = resolve_devices([devs[2].id, devs[0].id])
        assert got == [devs[2], devs[0]]

    def test_unknown_ids_preserve_width(self):
        # TpuTopology-numbered lease exercised on the CPU mesh: ids
        # don't exist here, but the mesh width must survive
        got = resolve_devices([1001, 1002, 1003, 1004])
        assert got == jax.local_devices()[:4]

    def test_oversized_lease_raises(self):
        with pytest.raises(ValueError, match="local devices"):
            resolve_devices(list(range(1000, 1099)))

    def test_partial_id_match_is_a_loud_conflict(self):
        # ids 0..98: 0-7 exist here, the rest don't — remapping would
        # stack disjoint leases onto the same chips, so it must raise
        with pytest.raises(ValueError, match="numbering conflict"):
            resolve_devices(list(range(99)))

    def test_empty_lease_is_single_device(self):
        assert resolve_devices(None) == jax.local_devices()[:1]


class MeshAwareApp:
    """Deployment that records the injected chip group (the contract
    model-runner's RuntimeDeployment consumes in async_init)."""

    def __init__(self):
        self.seen_lease = None

    async def async_init(self):
        self.seen_lease = list(getattr(self, "bioengine_device_ids", []))

    def mesh_info(self):
        return {
            "lease": self.seen_lease,
            "mesh_shape": {"dp": len(self.seen_lease or [1])},
        }

    async def echo(self, value):
        return {"echo": value}


@pytest.fixture
async def controller():
    # explicit 8-chip topology: chip ACCOUNTING must not depend on how
    # many virtual devices the current process happens to expose
    from bioengine_tpu.cluster.topology import ChipInfo, TpuTopology

    topo = TpuTopology(
        chips=tuple(
            ChipInfo(device_id=i, platform="cpu", kind="virtual",
                     process_index=0)
            for i in range(8)
        ),
        n_hosts=1,
        platform="cpu",
    )
    c = ServeController(ClusterState(topo), health_check_period=3600)
    yield c
    await c.stop()


@pytest.mark.integration
@pytest.mark.anyio
class TestShardedReplicaLifecycle:
    async def test_lease_injected_into_instance(self, controller):
        app = await controller.deploy(
            "mesh-app",
            [
                DeploymentSpec(
                    name="rt",
                    instance_factory=MeshAwareApp,
                    chips_per_replica=4,
                    autoscale=False,
                )
            ],
        )
        replica = app.replicas["rt"][0]
        assert len(replica.device_ids) == 4
        assert replica.instance.seen_lease == list(replica.device_ids)
        # describe surfaces the mesh + queue fields for the controller
        d = replica.describe()
        assert d["mesh"]["mesh_shape"] == {"dp": 4}
        assert d["queued_requests"] == 0

    async def test_status_surfaces_load_and_mesh(self, controller):
        await controller.deploy(
            "mesh-app2",
            [
                DeploymentSpec(
                    name="rt",
                    instance_factory=MeshAwareApp,
                    chips_per_replica=2,
                    autoscale=False,
                )
            ],
        )
        status = controller.get_app_status("mesh-app2")
        dep = status["deployments"]["rt"]
        for key in (
            "outstanding_calls",
            "queued_calls",
            "avg_load",
            "mesh_shapes",
            "queue_depth",
        ):
            assert key in dep, key
        assert dep["outstanding_calls"] == 0
        [shape] = dep["mesh_shapes"].values()
        assert shape == {"dp": 2}

    async def test_killed_sharded_replica_returns_all_chips(self, controller):
        """Kill a K-chip replica -> all K chips come back; the restarted
        replica leases K again; undeploy leaks nothing."""
        state = controller.cluster_state
        app = await controller.deploy(
            "mesh-app3",
            [
                DeploymentSpec(
                    name="rt",
                    instance_factory=MeshAwareApp,
                    chips_per_replica=4,
                    autoscale=False,
                )
            ],
        )
        assert state.free_chips() == 4
        old = app.replicas["rt"][0]
        old_lease = list(old.device_ids)
        assert len(old_lease) == 4
        # kill: the health loop notices and restarts on fresh chips
        old.state = ReplicaState.UNHEALTHY
        await controller.health_tick()
        await asyncio.sleep(0.05)
        new = app.replicas["rt"][0]
        assert new.replica_id != old.replica_id
        assert len(new.device_ids) == 4
        # exactly one 4-chip lease outstanding — no double-lease, no leak
        assert state.free_chips() == 4
        await controller.undeploy("mesh-app3")
        assert state.free_chips() == 8
