"""Static analyzer: rule firing (positive + negative), suppressions,
baseline behavior, CLI exit codes, and the diff-aware --changed mode.

Fixture files under tests/analysis_fixtures/ seed one violation per
rule on lines marked ``# <- RULE-ID``; each fixture also carries
negative cases (idiomatic code the rule must NOT flag).  The harness
asserts the finding set equals the marker set *exactly*, so a false
positive on any negative case fails the same assertion as a missed
detection."""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from bioengine_tpu.analysis import (
    Baseline,
    all_rules,
    analyze_file,
    analyze_source,
)
from bioengine_tpu.analysis.__main__ import main as analysis_main
from bioengine_tpu.analysis.baseline import TODO_JUSTIFICATION

pytestmark = pytest.mark.unit

FIXTURES = Path(__file__).parent / "analysis_fixtures"
_MARKER = re.compile(r"#\s*<-\s*(BE-[A-Z]+-\d+)")


def expected_markers(path: Path) -> set[tuple[str, int]]:
    out = set()
    for lineno, line in enumerate(path.read_text().splitlines(), start=1):
        for m in _MARKER.finditer(line):
            out.add((m.group(1), lineno))
    return out


FIXTURE_FILES = sorted(FIXTURES.glob("fx_*.py"))
assert FIXTURE_FILES, "fixture directory is empty"


@pytest.mark.parametrize(
    "fixture", FIXTURE_FILES, ids=lambda p: p.stem
)
def test_fixture_findings_match_markers_exactly(fixture):
    """Every marked line fires its rule; nothing else fires (the
    unmarked negative cases in the same file double as the per-rule
    negative tests)."""
    found = {(f.rule, f.line) for f in analyze_file(fixture)}
    assert found == expected_markers(fixture)


def test_every_rule_has_a_seeded_fixture_violation():
    """Every rule has at least one positive marker: module rules in
    the flat fx_* fixtures, project rules in the proj_demo fixture
    tree (tests/test_analysis_project.py asserts those exactly)."""
    seeded = set()
    for f in FIXTURE_FILES:
        seeded |= {rule for rule, _ in expected_markers(f)}
    proj_seeded = set()
    for f in sorted((FIXTURES / "proj_demo").rglob("*")):
        if f.suffix in {".py", ".md"}:
            proj_seeded |= {rule for rule, _ in expected_markers(f)}
    by_pass: dict[str, set] = {}
    for r in all_rules():
        if r.project:
            assert r.id in proj_seeded, (
                f"no proj_demo fixture seeds a violation for {r.id}"
            )
        else:
            assert r.id in seeded, (
                f"no fixture seeds a violation for {r.id}"
            )
        by_pass.setdefault(r.pass_name, set()).add(r.id)
    assert len(by_pass["async"]) >= 8  # 5 module + 3 interprocedural
    assert len(by_pass["jax"]) >= 4
    assert len(by_pass["obs"]) >= 1
    assert len(by_pass["dist"]) >= 5


def test_clean_fixture_is_clean():
    assert analyze_file(FIXTURES / "fx_clean.py") == []


def test_suppression_fixture_is_clean():
    """Same-line, line-above, and ignore-file forms all suppress."""
    assert analyze_file(FIXTURES / "fx_suppressed.py") == []


def test_suppression_is_rule_specific():
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # bioengine: ignore[BE-ASYNC-999]\n"
    )
    # wrong rule id in the ignore -> the finding still fires
    assert [f.rule for f in analyze_source(src)] == ["BE-ASYNC-001"]


def test_blanket_ignore_suppresses_everything():
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # bioengine: ignore\n"
    )
    assert analyze_source(src) == []


def test_syntax_error_reported_as_finding():
    findings = analyze_source("def broken(:\n", path="x.py")
    assert [f.rule for f in findings] == ["BE-PARSE-000"]


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def test_baseline_roundtrip_suppresses_then_goes_stale(tmp_path):
    fixture = FIXTURES / "fx_async_blocking.py"
    findings = analyze_file(fixture)
    assert findings

    bl = Baseline()
    bl.update_from(findings)
    assert all(
        e["justification"] == TODO_JUSTIFICATION for e in bl.entries.values()
    )
    new, stale = bl.apply(findings)
    assert new == [] and stale == []

    # one finding fixed -> its entry is stale, none are blocking
    new, stale = bl.apply(findings[1:])
    assert new == [] and len(stale) == 1

    # persisted form survives a round-trip
    p = tmp_path / "bl.json"
    bl.save(p)
    new, stale = Baseline.load(p).apply(findings)
    assert new == [] and stale == []


def test_baseline_fingerprint_tracks_line_content_not_number():
    src = "import time\nasync def f():\n    time.sleep(1)\n"
    moved = "import time\n# a new comment shifts lines\nasync def f():\n    time.sleep(1)\n"
    bl = Baseline()
    bl.update_from(analyze_source(src, path="m.py"))
    new, stale = bl.apply(analyze_source(moved, path="m.py"))
    assert new == [] and stale == []


# ---------------------------------------------------------------------------
# CLI (__main__.main) — exit-code contract
# ---------------------------------------------------------------------------


def test_cli_exits_nonzero_on_seeded_fixtures_without_baseline(capsys):
    rc = analysis_main([str(FIXTURES), "--no-baseline"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "BE-ASYNC-001" in out and "BE-JAX-101" in out


def test_cli_exits_zero_on_clean_file(capsys):
    rc = analysis_main([str(FIXTURES / "fx_clean.py"), "--no-baseline"])
    assert rc == 0
    assert "clean" in capsys.readouterr().out


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    bl = tmp_path / "bl.json"
    rc = analysis_main(
        [str(FIXTURES), "--baseline", str(bl), "--write-baseline"]
    )
    assert rc == 0 and bl.exists()
    rc = analysis_main([str(FIXTURES), "--baseline", str(bl)])
    assert rc == 0
    assert "baselined" in capsys.readouterr().out


def test_cli_json_format(capsys):
    rc = analysis_main(
        [
            str(FIXTURES / "fx_async_blocking.py"),
            "--no-baseline",
            "--format",
            "json",
        ]
    )
    assert rc == 1
    findings = json.loads(capsys.readouterr().out)
    assert {f["rule"] for f in findings} == {"BE-ASYNC-001"}


def test_cli_rule_filter(capsys):
    rc = analysis_main(
        [str(FIXTURES), "--no-baseline", "--rule", "BE-JAX-105"]
    )
    assert rc == 1
    out = capsys.readouterr().out
    assert "BE-JAX-105" in out and "BE-ASYNC" not in out


def test_cli_bad_path_is_usage_error():
    assert analysis_main(["definitely/not/a/path"]) == 2


def test_repo_gate_is_clean():
    """The merged tree passes its own gate: the checked-in baseline
    covers every pre-existing finding (acceptance criterion)."""
    repo = Path(__file__).parent.parent
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "bioengine_tpu.analysis",
            "bioengine_tpu/",
            "apps/",
        ],
        capture_output=True,
        text=True,
        cwd=repo,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_baseline_entries_all_justified():
    repo = Path(__file__).parent.parent
    data = json.loads((repo / ".analyze-baseline.json").read_text())
    for fp, entry in data["findings"].items():
        assert entry["justification"] != TODO_JUSTIFICATION, (
            f"baseline entry {fp} ({entry['path']}:{entry['line']}) "
            f"has no justification"
        )


# ---------------------------------------------------------------------------
# --changed (diff-aware gate)
# ---------------------------------------------------------------------------


def _git(tmp, *args):
    subprocess.run(
        ["git", *args],
        cwd=tmp,
        check=True,
        capture_output=True,
        env={
            "GIT_AUTHOR_NAME": "t",
            "GIT_AUTHOR_EMAIL": "t@t",
            "GIT_COMMITTER_NAME": "t",
            "GIT_COMMITTER_EMAIL": "t@t",
            "PATH": "/usr/bin:/bin:/usr/local/bin",
            "HOME": str(tmp),
        },
    )


def test_changed_mode_scans_only_touched_files(tmp_path, monkeypatch):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    dirty = pkg / "dirty.py"
    clean = pkg / "clean.py"
    dirty.write_text("import time\nasync def f():\n    time.sleep(1)\n")
    clean.write_text("import time\nasync def g():\n    time.sleep(1)\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "seed")

    monkeypatch.chdir(tmp_path)

    # nothing changed since HEAD -> gate passes without scanning pkg/
    assert analysis_main(["pkg", "--changed", "--no-baseline"]) == 0

    # touch only dirty.py -> its finding fires; clean.py stays unscanned
    dirty.write_text(
        "import time\nasync def f():\n    time.sleep(2)\n"
    )
    assert analysis_main(["pkg", "--changed", "--no-baseline"]) == 1

    # out-of-scope changes don't trip the gate
    assert (
        analysis_main(
            [str(pkg / "nonexistent_scope"), "--changed", "--no-baseline"]
        )
        == 2
    )

    # from a subdirectory, git's repo-root-relative names must still
    # resolve (regression: a cwd-relative resolve dropped every file
    # and reported a false clean)
    sub = tmp_path / "sub"
    sub.mkdir()
    monkeypatch.chdir(sub)
    assert (
        analysis_main([str(pkg), "--changed", "--no-baseline"]) == 1
    )
