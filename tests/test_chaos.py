"""Fault-tolerant request path: failover retries, drain, breaker,
reconnect/rejoin — proven by deterministic chaos.

The chaos harness runs REAL multi-host topologies in-process: an
RpcServer, a ServeController, and WorkerHost instances all share one
event loop but speak over real websockets, so killing a host is
severing its websocket — exactly what a node death looks like to the
controller — without subprocess spawn costs or SIGKILL timing races.
Fault points (bioengine_tpu/testing/faults.py) make every failure land
on a chosen request, every run.
"""

import asyncio
import time
from pathlib import Path

import pytest

from bioengine_tpu.apps.builder import AppBuilder
from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology
from bioengine_tpu.rpc.protocol import RemoteError
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.serving import (
    DeploymentSpec,
    ReplicaState,
    RequestOptions,
    ServeController,
)
from bioengine_tpu.serving.errors import (
    DeadlineExceeded,
    FailureKind,
    NoHealthyReplicasError,
    ReplicaUnavailableError,
    RetryableTransportError,
    classify_exception,
)
from bioengine_tpu.serving.remote import RemoteReplica
from bioengine_tpu.testing import faults
from bioengine_tpu.worker_host import WorkerHost

pytestmark = [pytest.mark.integration, pytest.mark.anyio]


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# fault injection layer
# ---------------------------------------------------------------------------


class TestFaults:
    async def test_deterministic_window(self):
        faults.configure("p", "raise", nth=3, count=2)
        for expected_ok in [True, True, False, False, True]:
            if expected_ok:
                await faults.hit("p")
            else:
                with pytest.raises(faults.FaultInjected):
                    await faults.hit("p")
        assert faults.hits("p") == 5

    async def test_drop_invokes_callback_then_raises(self):
        dropped = []

        async def drop():
            dropped.append(1)

        faults.configure("p", "drop")
        with pytest.raises(faults.FaultInjected):
            await faults.hit("p", drop=drop)
        assert dropped == [1]

    async def test_delay_action(self):
        faults.configure("p", "delay", delay_s=0.01)
        t0 = time.monotonic()
        await faults.hit("p")
        assert time.monotonic() - t0 >= 0.01

    async def test_env_parsing(self):
        faults.load_env("a.b=drop:3;c.d=raise:1:2;e.f=delay:1:5:0.5")
        assert faults._specs["a.b"].action == "drop"
        assert faults._specs["a.b"].nth == 3
        assert faults._specs["c.d"].count == 2
        assert faults._specs["e.f"].delay_s == 0.5
        assert faults.ACTIVE

    async def test_inactive_is_free(self):
        faults.clear()
        assert not faults.ACTIVE
        await faults.hit("anything")  # no spec, no counter, no error
        assert faults.hits("anything") == 0

    async def test_fault_injected_is_transport(self):
        assert classify_exception(
            faults.FaultInjected("x")
        ) is FailureKind.TRANSPORT


# ---------------------------------------------------------------------------
# error taxonomy
# ---------------------------------------------------------------------------


class TestClassification:
    def test_transport_family(self):
        for exc in (
            ConnectionError("x"),
            ConnectionResetError("x"),
            RetryableTransportError("x"),
            ReplicaUnavailableError("x"),
            NoHealthyReplicasError("x"),
            asyncio.TimeoutError(),
            OSError("x"),
            RemoteError("ConnectionError", "provider gone"),
            RemoteError("ConnectionLost", "ws dropped mid-call"),
            RemoteError("FaultInjected", "chaos"),
            RemoteError("ReplicaUnavailableError", "draining"),
            RemoteError("TimeoutError", "host-side budget"),
            RemoteError("KeyError", "\"no replica 'x' on host h\""),
        ):
            assert classify_exception(exc) is FailureKind.TRANSPORT, exc

    def test_application_family(self):
        for exc in (
            ValueError("bad arg"),
            RemoteError("ValueError", "bad arg"),
            RemoteError("KeyError", "'missing-key'"),
            KeyError("app 'x' not deployed"),
        ):
            assert classify_exception(exc) is FailureKind.APPLICATION, exc

    def test_deadline(self):
        assert classify_exception(DeadlineExceeded()) is FailureKind.DEADLINE
        # DeadlineExceeded must still satisfy asyncio.TimeoutError waiters
        assert isinstance(DeadlineExceeded(), asyncio.TimeoutError)

    def test_replica_unavailable_keeps_legacy_message_contract(self):
        # existing callers match "not healthy" on a RuntimeError
        assert issubclass(ReplicaUnavailableError, RuntimeError)


# ---------------------------------------------------------------------------
# local retry / drain / breaker / routing (no RPC, fast)
# ---------------------------------------------------------------------------


class FlakyTransportApp:
    """Raises ConnectionError (transport class) for the first
    ``fail_first`` calls ACROSS all instances (class-level counter, so
    a failover lands on a healthy sibling deterministically)."""

    fail_first = 1
    failures = 0

    def __init__(self):
        self.calls = 0

    @classmethod
    def reset(cls, fail_first: int):
        cls.fail_first = fail_first
        cls.failures = 0

    async def ping(self, value=0):
        self.calls += 1
        if FlakyTransportApp.failures < FlakyTransportApp.fail_first:
            FlakyTransportApp.failures += 1
            raise ConnectionError("synthetic transport failure")
        return {"value": value, "calls": self.calls}


@pytest.fixture
async def controller():
    c = ServeController(ClusterState(), health_check_period=3600)
    yield c
    await c.stop()


class TestRetryPolicy:
    async def test_idempotent_call_fails_over(self, controller):
        FlakyTransportApp.reset(1)
        app = await controller.deploy(
            "rt-app",
            [
                DeploymentSpec(
                    name="e",
                    instance_factory=FlakyTransportApp,
                    num_replicas=2,
                    autoscale=False,
                )
            ],
        )
        await asyncio.sleep(0.05)
        handle = controller.get_handle("rt-app")
        result = await handle.call(
            "ping", value=7, options=RequestOptions(idempotent=True)
        )
        assert result["value"] == 7
        # exactly one failover: the two replicas saw one call each
        instances = [r.instance for r in app.replicas["e"]]
        assert sorted(i.calls for i in instances) == [1, 1]

    async def test_non_idempotent_fails_fast_exactly_once(self, controller):
        FlakyTransportApp.reset(10)
        app = await controller.deploy(
            "rt-app2",
            [
                DeploymentSpec(
                    name="e",
                    instance_factory=FlakyTransportApp,
                    num_replicas=2,
                    autoscale=False,
                )
            ],
        )
        await asyncio.sleep(0.05)
        handle = controller.get_handle("rt-app2")
        with pytest.raises(RetryableTransportError, match="not retried"):
            await handle.call("ping", options=RequestOptions(idempotent=False))
        # never silently retried: exactly ONE instance saw ONE call
        assert sorted(
            r.instance.calls for r in app.replicas["e"]
        ) == [0, 1]

    async def test_application_error_never_retried(self, controller):
        class BuggyApp:
            calls = 0

            async def boom(self):
                BuggyApp.calls += 1
                raise ValueError("app bug")

        BuggyApp.calls = 0
        await controller.deploy(
            "rt-app3",
            [
                DeploymentSpec(
                    name="e",
                    instance_factory=BuggyApp,
                    num_replicas=2,
                    autoscale=False,
                )
            ],
        )
        await asyncio.sleep(0.05)
        handle = controller.get_handle("rt-app3")
        with pytest.raises(ValueError, match="app bug"):
            await handle.call("boom", options=RequestOptions(idempotent=True))
        assert BuggyApp.calls == 1

    async def test_deadline_bounds_retries(self, controller):
        FlakyTransportApp.reset(10_000)
        await controller.deploy(
            "rt-app4",
            [
                DeploymentSpec(
                    name="e",
                    instance_factory=FlakyTransportApp,
                    autoscale=False,
                )
            ],
        )
        await asyncio.sleep(0.05)
        handle = controller.get_handle("rt-app4")
        t0 = time.monotonic()
        with pytest.raises((DeadlineExceeded, RetryableTransportError)):
            await handle.call(
                "ping",
                options=RequestOptions(
                    idempotent=True,
                    deadline_s=0.5,
                    max_attempts=1000,
                    backoff_base_s=0.01,
                ),
            )
        assert time.monotonic() - t0 < 2.0

    async def test_per_attempt_timeout_propagates(self, controller):
        class SlowApp:
            async def slow(self):
                await asyncio.sleep(5)
                return "late"

        await controller.deploy(
            "rt-app5",
            [DeploymentSpec(name="e", instance_factory=SlowApp, autoscale=False)],
        )
        await asyncio.sleep(0.05)
        handle = controller.get_handle("rt-app5")
        t0 = time.monotonic()
        with pytest.raises(RetryableTransportError):
            await handle.call(
                "slow",
                options=RequestOptions(timeout_s=0.1, max_attempts=2),
            )
        assert time.monotonic() - t0 < 2.0
        # an impatient CALLER's timeout says nothing about replica
        # health: the circuit breaker must not have counted it
        assert controller._breaker_counts == {}

    async def test_non_idempotent_fails_over_when_nothing_was_sent(
        self, controller
    ):
        """A LOCAL ReplicaUnavailableError (routability check, e.g. a
        replica caught DRAINING between pick and call) means the request
        provably never left the process — even non-idempotent calls may
        safely try another replica."""

        class Ok:
            async def ping(self):
                return "ok"

        app = await controller.deploy(
            "rt-app-ne",
            [
                DeploymentSpec(
                    name="e", instance_factory=Ok,
                    num_replicas=2, autoscale=False,
                )
            ],
        )
        await asyncio.sleep(0.05)
        draining = app.replicas["e"][0]
        draining.state = ReplicaState.DRAINING
        handle = controller.get_handle("rt-app-ne")
        # several non-idempotent calls: round-robin would land half on
        # the draining replica; every one must fail over, none may error
        for _ in range(4):
            assert await handle.call(
                "ping", options=RequestOptions(idempotent=False)
            ) == "ok"

    async def test_non_idempotent_deadline_cut_raises_deadline(
        self, controller
    ):
        """When the overall deadline is what cut the attempt short, the
        caller gets DeadlineExceeded even on the non-idempotent path —
        not a transport error."""

        class SlowApp:
            async def slow(self):
                await asyncio.sleep(5)

        await controller.deploy(
            "rt-app-dl",
            [DeploymentSpec(name="e", instance_factory=SlowApp, autoscale=False)],
        )
        await asyncio.sleep(0.05)
        handle = controller.get_handle("rt-app-dl")
        with pytest.raises(DeadlineExceeded):
            await handle.call(
                "slow",
                options=RequestOptions(deadline_s=0.2, idempotent=False),
            )

    async def test_app_method_options_kwarg_passes_through(self, controller):
        class OptionsApp:
            async def configure(self, options=None):
                return {"got": options}

        await controller.deploy(
            "rt-app6",
            [DeploymentSpec(name="e", instance_factory=OptionsApp, autoscale=False)],
        )
        await asyncio.sleep(0.05)
        handle = controller.get_handle("rt-app6")
        # a plain dict is NOT a RequestOptions envelope — it reaches the app
        assert await handle.call("configure", options={"a": 1}) == {
            "got": {"a": 1}
        }

    async def test_pick_replica_waits_through_restart_window(self, controller):
        class Ok:
            async def ping(self):
                return "ok"

        app = await controller.deploy(
            "rt-app7",
            [DeploymentSpec(name="e", instance_factory=Ok, autoscale=False)],
        )
        await asyncio.sleep(0.05)
        replica = app.replicas["e"][0]
        replica.state = ReplicaState.UNHEALTHY  # restart window opens
        handle = controller.get_handle("rt-app7")
        task = asyncio.create_task(
            handle.call(
                "ping", options=RequestOptions(idempotent=True, deadline_s=5)
            )
        )
        await asyncio.sleep(0.2)
        assert not task.done()  # parked, not failed
        replica.state = ReplicaState.HEALTHY
        controller._replicas_changed.set()
        assert await asyncio.wait_for(task, 3) == "ok"

    async def test_deadline_covers_replica_wait_park(self, controller):
        """Time spent parked in _pick_replica_wait counts against the
        deadline: a replica appearing at the last moment must not grant
        the attempt a fresh full budget (deadline bounds the WHOLE
        request, wait included)."""

        class SlowApp:
            async def slow(self):
                await asyncio.sleep(10)

        app = await controller.deploy(
            "rt-app9",
            [DeploymentSpec(name="e", instance_factory=SlowApp, autoscale=False)],
        )
        await asyncio.sleep(0.05)
        replica = app.replicas["e"][0]
        replica.state = ReplicaState.UNHEALTHY  # park incoming requests
        handle = controller.get_handle("rt-app9")
        task = asyncio.create_task(
            handle.call(
                "slow",
                options=RequestOptions(idempotent=True, deadline_s=0.8),
            )
        )
        await asyncio.sleep(0.5)          # most of the budget spent parked
        replica.state = ReplicaState.HEALTHY
        controller._replicas_changed.set()
        t0 = time.monotonic()
        with pytest.raises((DeadlineExceeded, RetryableTransportError)):
            await task
        # ended ~when the deadline did, NOT after a fresh 10s attempt
        assert time.monotonic() - t0 < 2.0

    async def test_pick_replica_wait_gives_up_at_deadline(self, controller):
        class Ok:
            async def ping(self):
                return "ok"

        app = await controller.deploy(
            "rt-app8",
            [DeploymentSpec(name="e", instance_factory=Ok, autoscale=False)],
        )
        await asyncio.sleep(0.05)
        app.replicas["e"][0].state = ReplicaState.UNHEALTHY
        handle = controller.get_handle("rt-app8")
        t0 = time.monotonic()
        with pytest.raises((NoHealthyReplicasError, DeadlineExceeded)):
            await handle.call(
                "ping",
                options=RequestOptions(idempotent=True, deadline_s=0.3),
            )
        assert time.monotonic() - t0 < 1.5


class TestCircuitBreaker:
    async def test_k_failures_eject_without_health_tick(self, controller):
        FlakyTransportApp.reset(10_000)
        app = await controller.deploy(
            "cb-app",
            [
                DeploymentSpec(
                    name="e",
                    instance_factory=FlakyTransportApp,
                    num_replicas=1,
                    autoscale=False,
                )
            ],
        )
        await asyncio.sleep(0.05)
        replica = app.replicas["e"][0]
        handle = controller.get_handle("cb-app")
        for _ in range(controller.breaker_threshold):
            with pytest.raises(Exception):
                await handle.call(
                    "ping", options=RequestOptions(idempotent=False)
                )
        # ejected NOW — no health tick ran
        assert replica.state == ReplicaState.UNHEALTHY
        assert "circuit breaker" in replica.last_error
        assert controller._wake_health.is_set()

    async def test_success_resets_breaker(self, controller):
        FlakyTransportApp.reset(1)
        app = await controller.deploy(
            "cb-app2",
            [
                DeploymentSpec(
                    name="e",
                    instance_factory=FlakyTransportApp,
                    num_replicas=1,
                    autoscale=False,
                )
            ],
        )
        await asyncio.sleep(0.05)
        replica = app.replicas["e"][0]
        handle = controller.get_handle("cb-app2")
        with pytest.raises(RetryableTransportError):
            await handle.call("ping")
        assert controller._breaker_counts[replica.replica_id] == 1
        await handle.call("ping")  # instance healed after first failure
        assert replica.replica_id not in controller._breaker_counts
        assert replica.state == ReplicaState.HEALTHY


class TestDrain:
    async def test_stop_drains_in_flight_and_rejects_new(self, controller):
        release = asyncio.Event()
        entered = asyncio.Event()

        class SlowApp:
            async def slow(self):
                entered.set()
                await release.wait()
                return "finished"

        app = await controller.deploy(
            "dr-app",
            [DeploymentSpec(name="e", instance_factory=SlowApp, autoscale=False)],
        )
        await asyncio.sleep(0.05)
        replica = app.replicas["e"][0]
        handle = controller.get_handle("dr-app")
        in_flight = asyncio.create_task(handle.call("slow"))
        await asyncio.wait_for(entered.wait(), 2)

        stop_task = asyncio.create_task(replica.stop())
        await asyncio.sleep(0.05)
        assert replica.state == ReplicaState.DRAINING
        # new calls rejected while draining, typed as placement error
        with pytest.raises(ReplicaUnavailableError, match="not healthy"):
            await replica.call("slow")
        assert not stop_task.done()  # still waiting for the in-flight call
        release.set()
        assert await asyncio.wait_for(in_flight, 2) == "finished"
        await asyncio.wait_for(stop_task, 2)
        assert replica.state == ReplicaState.STOPPED

    async def test_drain_rejects_semaphore_parked_calls(self, controller):
        """A call that passed the routability check but is PARKED on the
        request semaphore when drain begins must be rejected (typed, so
        the router fails it over) — not executed against the instance
        after stop() tore it down."""
        release = asyncio.Event()
        entered = []

        class SlowApp:
            async def slow(self):
                entered.append(1)
                await release.wait()
                return "done"

        app = await controller.deploy(
            "dr-app4",
            [
                DeploymentSpec(
                    name="e", instance_factory=SlowApp,
                    max_ongoing_requests=1, autoscale=False,
                )
            ],
        )
        await asyncio.sleep(0.05)
        replica = app.replicas["e"][0]
        first = asyncio.create_task(replica.call("slow"))
        await asyncio.sleep(0.05)          # first holds the semaphore
        parked = asyncio.create_task(replica.call("slow"))
        await asyncio.sleep(0.05)          # parked passed the state check
        stop_task = asyncio.create_task(replica.stop())
        await asyncio.sleep(0.05)
        release.set()
        assert await asyncio.wait_for(first, 2) == "done"
        with pytest.raises(ReplicaUnavailableError):
            await asyncio.wait_for(parked, 2)
        await asyncio.wait_for(stop_task, 2)
        assert entered == [1]              # the parked call never ran

    async def test_drain_timeout_bounds_stop(self, controller):
        class StuckApp:
            async def stuck(self):
                await asyncio.sleep(60)

        app = await controller.deploy(
            "dr-app2",
            [DeploymentSpec(name="e", instance_factory=StuckApp, autoscale=False)],
        )
        await asyncio.sleep(0.05)
        replica = app.replicas["e"][0]
        handle = controller.get_handle("dr-app2")
        stuck = asyncio.create_task(handle.call("stuck"))
        await asyncio.sleep(0.05)
        t0 = time.monotonic()
        await replica.stop(drain_timeout_s=0.2)
        assert 0.15 < time.monotonic() - t0 < 2.0
        assert replica.state == ReplicaState.STOPPED
        stuck.cancel()
        with pytest.raises(asyncio.CancelledError):
            await stuck

    async def test_undeploy_lets_in_flight_finish(self, controller):
        release = asyncio.Event()
        entered = asyncio.Event()

        class SlowApp:
            async def slow(self):
                entered.set()
                await release.wait()
                return "done"

        await controller.deploy(
            "dr-app3",
            [DeploymentSpec(name="e", instance_factory=SlowApp, autoscale=False)],
        )
        await asyncio.sleep(0.05)
        handle = controller.get_handle("dr-app3")
        in_flight = asyncio.create_task(handle.call("slow"))
        await asyncio.wait_for(entered.wait(), 2)
        undeploy = asyncio.create_task(controller.undeploy("dr-app3"))
        await asyncio.sleep(0.05)
        release.set()
        assert await asyncio.wait_for(in_flight, 2) == "done"
        await asyncio.wait_for(undeploy, 2)


class TestConcurrentHealthTick:
    async def test_one_slow_replica_does_not_stall_others(self, controller):
        order = []

        class SlowHealth:
            async def check_health(self):
                order.append("slow-start")
                await asyncio.sleep(0.3)
                order.append("slow-end")

            async def ping(self):
                return "ok"

        class FastHealth:
            async def check_health(self):
                order.append("fast")

            async def ping(self):
                return "ok"

        await controller.deploy(
            "h-slow",
            [DeploymentSpec(name="e", instance_factory=SlowHealth, autoscale=False)],
        )
        await controller.deploy(
            "h-fast",
            [DeploymentSpec(name="e", instance_factory=FastHealth, autoscale=False)],
        )
        await asyncio.sleep(0.05)
        t0 = time.monotonic()
        await controller.health_tick()
        elapsed = time.monotonic() - t0
        # serial would be >= 0.3 with "fast" gated behind "slow-end";
        # concurrent runs "fast" while "slow" sleeps
        assert order.index("fast") < order.index("slow-end")
        assert elapsed < 1.0


# ---------------------------------------------------------------------------
# in-process multi-host chaos (real websockets, deterministic kills)
# ---------------------------------------------------------------------------

CHAOS_MANIFEST = """\
name: Chaos App
id: chaos-app
id_emoji: "\U0001F9EA"
description: idempotent arithmetic for chaos traffic
type: tpu-serve
version: 1.0.0
deployments:
  - chaos_dep:ChaosDep
authorized_users: ["*"]
deployment_config:
  chaos_dep:
    num_replicas: 2
    min_replicas: 2
    max_replicas: 2
    chips: 3
    autoscale: false
"""

CHAOS_SOURCE = '''\
import os

from bioengine_tpu.rpc import schema_method


class ChaosDep:
    def __init__(self):
        self.calls = 0

    @schema_method
    async def add(self, a: int, b: int, context=None):
        """Idempotent arithmetic."""
        self.calls += 1
        return {"sum": a + b}
'''


def _write_chaos_app(tmp_path: Path) -> Path:
    app_dir = tmp_path / "chaos-src"
    app_dir.mkdir(exist_ok=True)
    (app_dir / "manifest.yaml").write_text(CHAOS_MANIFEST)
    (app_dir / "chaos_dep.py").write_text(CHAOS_SOURCE)
    return app_dir


def _no_local_chips() -> ClusterState:
    return ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu"))


@pytest.fixture()
async def chaos_plane(tmp_path):
    server = RpcServer(host="127.0.0.1", admin_users=["admin"])
    await server.start()
    token = server.issue_token("admin", is_admin=True)
    controller = ServeController(_no_local_chips(), health_check_period=3600)
    controller.attach_rpc(server, admin_users=["admin"])
    hosts = []

    async def spawn_host(host_id: str, rejoin: bool = True) -> WorkerHost:
        host = WorkerHost(
            server_url=server.url,
            token=token,
            host_id=host_id,
            workspace_dir=tmp_path / f"ws-{host_id}",
            rejoin=rejoin,
        )
        await host.start()
        hosts.append(host)
        return host

    try:
        yield server, controller, spawn_host, tmp_path
    finally:
        for host in hosts:
            try:
                await host.stop()
            except Exception:
                pass
        await controller.stop()
        await server.stop()


async def _kill_host(host: WorkerHost) -> None:
    """Simulate host death: sever the websocket with rejoin suppressed
    (the in-process analog of SIGKILL — the server sees the socket
    close, in-flight provider calls fail, the service vanishes)."""
    host.rejoin = False
    host.connection.auto_reconnect = False
    host.connection._closing = True
    await host.connection._abort_connection()


async def _deploy_chaos_app(controller, tmp_path):
    builder = AppBuilder(workdir_root=tmp_path / "apps")
    built = builder.build(
        app_id="chaos-app", local_path=_write_chaos_app(tmp_path)
    )
    await controller.deploy("chaos-app", built.specs)
    return controller.apps["chaos-app"].replicas["chaos_dep"]


class TestChaosMultiHost:
    async def test_host_death_zero_failed_idempotent_requests(
        self, chaos_plane
    ):
        """Acceptance: 2 replicas across 2 hosts under continuous
        idempotent traffic; killing one host produces ZERO failed
        requests and the replica is re-placed within one health
        period. Non-idempotent calls fail fast exactly once."""
        server, controller, spawn_host, tmp_path = chaos_plane
        h1 = await spawn_host("h1")
        h2 = await spawn_host("h2")
        replicas = await _deploy_chaos_app(controller, tmp_path)
        assert sorted(r.host_id for r in replicas) == ["h1", "h2"]
        handle = controller.get_handle("chaos-app")
        opts = RequestOptions(
            idempotent=True, deadline_s=20, max_attempts=8
        )

        failures: list[Exception] = []
        successes = [0]
        kill_at = asyncio.Event()

        async def traffic(worker_id: int):
            for i in range(30):
                try:
                    r = await handle.call("add", worker_id, i, options=opts)
                    assert r["sum"] == worker_id + i
                    successes[0] += 1
                except Exception as e:  # noqa: BLE001 — counted, not raised
                    failures.append(e)
                if i == 8 and worker_id == 0:
                    kill_at.set()
                await asyncio.sleep(0.005)

        tasks = [asyncio.create_task(traffic(w)) for w in range(4)]
        await asyncio.wait_for(kill_at.wait(), 10)
        victim = next(h for h in (h1, h2) if h.host_id == "h1")
        await _kill_host(victim)

        # recovery loop: prune + breaker + restart, all inside what one
        # health period covers in production
        recovered = False
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            await controller.health_tick()
            reps = controller.apps["chaos-app"].replicas["chaos_dep"]
            routable = [
                r
                for r in reps
                if r.state in (ReplicaState.HEALTHY, ReplicaState.TESTING)
            ]
            if len(routable) == 2 and all(
                r.host_id == "h2" for r in routable
            ):
                recovered = True
                break
            await asyncio.sleep(0.1)
        await asyncio.gather(*tasks)

        assert failures == []          # ZERO failed idempotent requests
        assert successes[0] == 120
        assert recovered, "replica was not re-placed on the survivor"
        # chip accounting: released exactly once — the dead host holds
        # nothing, the survivor holds both replicas' leases
        assert controller.cluster_state.hosts["h1"].chips_in_use == {}
        assert not controller.cluster_state.hosts["h1"].alive
        h2_leases = controller.cluster_state.hosts["h2"].chips_in_use
        assert len(h2_leases) == 6  # 2 replicas x 3 chips, no double lease
        assert len(set(h2_leases.values())) == 2

    async def test_non_idempotent_fails_fast_exactly_once_remote(
        self, chaos_plane
    ):
        server, controller, spawn_host, tmp_path = chaos_plane
        await spawn_host("h1")
        await spawn_host("h2")
        await _deploy_chaos_app(controller, tmp_path)
        handle = controller.get_handle("chaos-app")
        # first routed replica call dies in transport on the host
        faults.configure("host.replica_call", "raise", nth=1, count=1)
        with pytest.raises(RetryableTransportError, match="not retried"):
            await handle.call(
                "add", 1, 1, options=RequestOptions(idempotent=False)
            )
        assert faults.hits("host.replica_call") == 1  # no silent retry
        # the same failure under an idempotent envelope fails over
        faults.configure("host.replica_call", "raise", nth=1, count=1)
        result = await handle.call(
            "add", 20, 22, options=RequestOptions(idempotent=True)
        )
        assert result["sum"] == 42
        assert faults.hits("host.replica_call") == 2

    async def test_restart_path_with_fault_point_kill(self, chaos_plane):
        """Satellite: kill a host via the fault layer mid-call; the
        replica is re-placed on the surviving host and chip accounting
        is released exactly once (no leak, no double release)."""
        server, controller, spawn_host, tmp_path = chaos_plane
        h1 = await spawn_host("h1", rejoin=False)
        await spawn_host("h2")
        replicas = await _deploy_chaos_app(controller, tmp_path)
        state = controller.cluster_state
        victim = next(r for r in replicas if r.host_id == "h1")
        dead_id = victim.replica_id
        handle = controller.get_handle("chaos-app")

        # round-robin alternates h1, h2, h1, ... — the 3rd hit is the
        # 2nd call served by h1, and it severs h1's websocket mid-call
        h1.connection.auto_reconnect = False
        faults.configure("host.replica_call", "drop", nth=3, count=1)
        opts = RequestOptions(idempotent=True, deadline_s=20, max_attempts=8)
        for i in range(8):
            r = await handle.call("add", i, 1, options=opts)
            assert r["sum"] == i + 1
        # every request succeeded across the kill; now heal placement
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            await controller.health_tick()
            reps = controller.apps["chaos-app"].replicas["chaos_dep"]
            if (
                len(reps) == 2
                and all(r.host_id == "h2" for r in reps)
                and all(
                    r.state
                    in (ReplicaState.HEALTHY, ReplicaState.TESTING)
                    for r in reps
                )
            ):
                break
            await asyncio.sleep(0.1)

        assert state.hosts["h1"].chips_in_use == {}
        assert len(state.hosts["h2"].chips_in_use) == 6
        # the dead replica's record is dead exactly once, successor alive
        dead_recs = [r for r in state.replicas("chaos-app") if not r.alive]
        assert dead_id in {r.replica_id for r in dead_recs}
        live = [r for r in state.replicas("chaos-app") if r.alive]
        assert len(live) == 2

    async def test_host_rejoin_keeps_warm_replicas(self, chaos_plane):
        """A connection BLIP (not a death): the host auto-reconnects,
        re-registers, and the controller re-adopts the still-warm
        replica — same instance object, no rebuild, chips re-leased."""
        server, controller, spawn_host, tmp_path = chaos_plane
        h1 = await spawn_host("h1", rejoin=True)
        builder = AppBuilder(workdir_root=tmp_path / "apps")
        built = builder.build(
            app_id="chaos-app", local_path=_write_chaos_app(tmp_path)
        )
        # single replica fits this single-host variant
        built.specs[0].num_replicas = 1
        built.specs[0].min_replicas = 1
        await controller.deploy("chaos-app", built.specs)
        replica = controller.apps["chaos-app"].replicas["chaos_dep"][0]
        assert isinstance(replica, RemoteReplica)
        warm_instance = h1.replicas[replica.replica_id].instance
        handle = controller.get_handle("chaos-app")
        assert (await handle.call("add", 1, 1))["sum"] == 2

        await h1.connection._abort_connection()  # network blip
        # wait for the client to heal + host to rejoin
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if (
                h1.connection.connected
                and controller.cluster_state.hosts["h1"].alive
                and controller.cluster_state.hosts["h1"].chips_in_use
            ):
                break
            await asyncio.sleep(0.05)
        assert h1.connection.connected
        # the warm replica was re-adopted, not rebuilt
        assert h1.replicas[replica.replica_id].instance is warm_instance
        assert replica.state in (ReplicaState.HEALTHY, ReplicaState.TESTING)
        assert (
            controller.cluster_state.hosts["h1"].chips_in_use
            == {d: replica.replica_id for d in replica.device_ids}
        )
        # and it serves traffic again (calls before the tick succeed)
        result = await handle.call(
            "add", 2, 3, options=RequestOptions(idempotent=True)
        )
        assert result["sum"] == 5
        # a later health tick keeps exactly one replica (no duplicate)
        await controller.health_tick()
        assert len(controller.apps["chaos-app"].replicas["chaos_dep"]) == 1

    async def test_rejoin_after_replacement_drops_stale_replica(
        self, chaos_plane
    ):
        """If the controller already re-placed the replica before the
        host rejoined, the rejoin answer tells the host to discard its
        stale copy (and the deployment does not end up over-replicated)."""
        server, controller, spawn_host, tmp_path = chaos_plane
        h1 = await spawn_host("h1", rejoin=True)
        h2 = await spawn_host("h2")
        builder = AppBuilder(workdir_root=tmp_path / "apps")
        built = builder.build(
            app_id="chaos-app", local_path=_write_chaos_app(tmp_path)
        )
        built.specs[0].num_replicas = 1
        built.specs[0].min_replicas = 1
        await controller.deploy("chaos-app", built.specs)
        replica = controller.apps["chaos-app"].replicas["chaos_dep"][0]
        first_host = replica.host_id
        other = "h2" if first_host == "h1" else "h1"
        victim = h1 if first_host == "h1" else h2

        # gate the victim's reconnect behind an event so the controller
        # DETERMINISTICALLY re-places the replica before the rejoin
        gate = asyncio.Event()
        orig_establish = victim.connection._establish

        async def gated_establish():
            await gate.wait()
            await orig_establish()

        victim.connection._establish = gated_establish
        await victim.connection._abort_connection()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            await controller.health_tick()
            reps = controller.apps["chaos-app"].replicas["chaos_dep"]
            if reps and reps[0].host_id == other and reps[0].state in (
                ReplicaState.HEALTHY,
                ReplicaState.TESTING,
            ):
                break
            await asyncio.sleep(0.05)
        reps = controller.apps["chaos-app"].replicas["chaos_dep"]
        assert reps[0].host_id == other
        gate.set()  # now let the victim rejoin

        # when the victim rejoins it must drop its stale warm copy
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if victim.connection.connected and not victim.replicas:
                break
            await asyncio.sleep(0.05)
        assert victim.replicas == {}
        await controller.health_tick()
        assert len(controller.apps["chaos-app"].replicas["chaos_dep"]) == 1


# ---------------------------------------------------------------------------
# RPC client reconnect (transport layer on its own)
# ---------------------------------------------------------------------------


class TestClientReconnect:
    async def test_inflight_fails_fast_and_services_reregister(self):
        from bioengine_tpu.rpc.client import ConnectionLost, connect_to_server

        server = RpcServer(host="127.0.0.1", admin_users=["admin"])
        await server.start()
        token = server.issue_token("admin", is_admin=True)
        conn = await connect_to_server(
            {"server_url": server.url, "token": token, "reconnect": True}
        )
        try:
            release = asyncio.Event()

            async def slow_echo(x):
                await release.wait()
                return x

            svc = await conn.register_service(
                {"id": "reconnect-svc", "echo": slow_echo,
                 "fast": lambda x: x * 2}
            )
            full_id = svc["id"]
            # a call in flight THROUGH the server to our own service
            in_flight = asyncio.create_task(
                server.call_service_method(full_id, "echo", ("v",))
            )
            await asyncio.sleep(0.1)
            t0 = time.monotonic()
            await conn._abort_connection()
            # the provider-side drop fails the routed call fast (server
            # classifies provider loss as ConnectionError)
            with pytest.raises(ConnectionError):
                await asyncio.wait_for(in_flight, 5)
            assert time.monotonic() - t0 < 5
            release.set()

            # the client heals itself and re-registers its services
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if conn.connected and any(
                    s["id"].endswith("/reconnect-svc")
                    for s in server.list_services()
                ):
                    break
                await asyncio.sleep(0.05)
            assert conn.connected
            result = await server.call_service_method(
                full_id, "fast", (21,)
            )
            assert result == 42
        finally:
            await conn.disconnect()
            await server.stop()

    async def test_disconnect_suppresses_reconnect(self):
        from bioengine_tpu.rpc.client import connect_to_server

        server = RpcServer(host="127.0.0.1")
        await server.start()
        conn = await connect_to_server(
            {"server_url": server.url, "reconnect": True}
        )
        await conn.disconnect()
        await asyncio.sleep(0.3)
        assert not conn.connected  # no zombie reconnect
        assert conn._reconnect_task is None
        await server.stop()

    async def test_client_send_fault_point(self):
        from bioengine_tpu.rpc.client import connect_to_server

        server = RpcServer(host="127.0.0.1")
        await server.start()
        conn = await connect_to_server(
            {"server_url": server.url, "reconnect": True}
        )
        try:
            faults.configure("rpc.client.send", "drop", nth=1, count=1)
            with pytest.raises(ConnectionError):
                await conn.list_services()
            # reconnect heals; the next call goes through
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not conn.connected:
                await asyncio.sleep(0.05)
            assert isinstance(await conn.list_services(), list)
        finally:
            await conn.disconnect()
            await server.stop()


# ---------------------------------------------------------------------------
# slow soak: repeated kill/rejoin cycles (scripts/workflows/chaos.sh)
# ---------------------------------------------------------------------------


@pytest.mark.slow
async def test_chaos_soak_no_leaks(chaos_plane):
    """Repeated blip/heal cycles under traffic: every request succeeds,
    no background tasks or pending futures leak, transport stats stay
    sane, chip accounting stays exact."""
    import os

    from bioengine_tpu.utils import tasks as task_registry

    server, controller, spawn_host, tmp_path = chaos_plane
    h1 = await spawn_host("h1", rejoin=True)
    builder = AppBuilder(workdir_root=tmp_path / "apps")
    built = builder.build(
        app_id="chaos-app", local_path=_write_chaos_app(tmp_path)
    )
    built.specs[0].num_replicas = 1
    built.specs[0].min_replicas = 1
    await controller.deploy("chaos-app", built.specs)
    replica = controller.apps["chaos-app"].replicas["chaos_dep"][0]
    handle = controller.get_handle("chaos-app")
    opts = RequestOptions(idempotent=True, deadline_s=30, max_attempts=10)

    cycles = int(os.environ.get("BIOENGINE_CHAOS_CYCLES", "5"))
    h1.connection.reconnect_max_backoff_s = 0.5
    for cycle in range(cycles):
        results = await asyncio.gather(
            *(handle.call("add", cycle, i, options=opts) for i in range(10))
        )
        assert [r["sum"] for r in results] == [cycle + i for i in range(10)]
        await h1.connection._abort_connection()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if (
                h1.connection.connected
                and controller.cluster_state.hosts["h1"].alive
                and controller.cluster_state.hosts["h1"].chips_in_use
            ):
                break
            await asyncio.sleep(0.05)
        assert h1.connection.connected, f"cycle {cycle}: never rejoined"

    # final traffic burst must be fully healthy
    results = await asyncio.gather(
        *(handle.call("add", 0, i, options=opts) for i in range(20))
    )
    assert [r["sum"] for r in results] == list(range(20))

    # leak checks: pending futures drained, supervised task registry
    # settles, replica inventory exact, chip accounting exact
    await asyncio.sleep(0.5)
    assert controller.cluster_state.hosts["h1"].chips_in_use == {
        d: replica.replica_id for d in replica.device_ids
    }
    assert list(h1.replicas) == [replica.replica_id]
    assert h1.connection._pending == {}
    assert server._pending == {}
    lingering = [
        t for t in task_registry._BACKGROUND_TASKS if not t.done()
    ]
    assert len(lingering) < 10, lingering
    stats = server.describe()["transport"]
    assert stats["msgs_out"] > 0  # stats surface stays wired
