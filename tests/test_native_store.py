"""Native shared-memory object store: correctness, eviction, pinning,
cross-process visibility, allocator stress, and the pure-Python
fallback's API parity."""

import multiprocessing as mp
import os
import secrets
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from bioengine_tpu.native import (
    LocalObjectStore,
    SharedObjectStore,
    StoreError,
    native_available,
    open_store,
)

pytestmark = pytest.mark.unit

needs_native = pytest.mark.skipif(
    not native_available(), reason="native lib unavailable"
)


def _xproc_child(store_name, q):
    from bioengine_tpu.native import SharedObjectStore

    cs = SharedObjectStore(store_name, create=False)
    q.put(cs.get_bytes("from-parent"))
    cs.put("from-child", b"child-data")
    cs.close()


@pytest.fixture
def store():
    name = f"bes-test-{secrets.token_hex(4)}"
    s = SharedObjectStore(name, capacity=1024 * 1024, n_slots=256)
    yield s
    s.destroy()


@needs_native
class TestSharedObjectStore:
    def test_put_get_roundtrip(self, store):
        store.put("a", b"hello world")
        with store.pinned("a") as view:
            assert bytes(view) == b"hello world"
        assert store.get_bytes("missing") is None

    def test_zero_copy_view(self, store):
        data = os.urandom(4096)
        store.put("blob", data)
        view = store.get("blob")
        assert view is not None and len(view) == 4096
        arr = np.frombuffer(view, np.uint8)  # no copy
        assert bytes(arr.tobytes()) == data
        del arr
        view.release()
        store.release("blob")

    def test_duplicate_put_rejected(self, store):
        store.put("k", b"1")
        with pytest.raises(FileExistsError):
            store.put("k", b"2")

    def test_delete(self, store):
        store.put("k", b"x")
        assert store.delete("k") is True
        assert store.delete("k") is False
        assert store.get_bytes("k") is None
        store.put("k", b"y")  # slot reusable after delete
        assert store.get_bytes("k") == b"y"

    def test_contains(self, store):
        assert not store.contains("k")
        store.put("k", b"x")
        assert store.contains("k")

    def test_lru_eviction(self):
        name = f"bes-evict-{secrets.token_hex(4)}"
        s = SharedObjectStore(name, capacity=64 * 1024, n_slots=64)
        try:
            for i in range(8):
                s.put(f"k{i}", bytes(16 * 1024))  # 8x16K > 64K
            stats = s.stats()
            assert stats["evictions"] >= 4
            # newest survives, oldest evicted
            assert s.get_bytes("k7") is not None
            assert s.get_bytes("k0") is None
        finally:
            s.destroy()

    def test_pin_blocks_eviction(self):
        name = f"bes-pin-{secrets.token_hex(4)}"
        s = SharedObjectStore(name, capacity=64 * 1024, n_slots=64)
        try:
            s.put("keep", bytes(30 * 1024))
            view = s.get("keep")  # pin it
            s.put("a", bytes(20 * 1024))
            s.put("b", bytes(20 * 1024))  # must evict 'a', not 'keep'
            assert s.get_bytes("keep") is not None
            view.release()
            s.release("keep")
        finally:
            s.destroy()

    def test_too_large_rejected(self, store):
        with pytest.raises(StoreError):
            store.put("huge", bytes(2 * 1024 * 1024))

    def test_everything_pinned_enospc(self):
        name = f"bes-full-{secrets.token_hex(4)}"
        s = SharedObjectStore(name, capacity=64 * 1024, n_slots=64)
        try:
            s.put("a", bytes(50 * 1024))
            v = s.get("a")
            with pytest.raises(StoreError):
                s.put("b", bytes(50 * 1024))
            v.release()
            s.release("a")
            s.put("b", bytes(50 * 1024))  # now evictable
        finally:
            s.destroy()

    def test_allocator_stress_fragmentation(self):
        """Random put/delete churn with verification — exercises split
        + coalesce + eviction paths."""
        name = f"bes-stress-{secrets.token_hex(4)}"
        s = SharedObjectStore(name, capacity=256 * 1024, n_slots=512)
        rng = np.random.default_rng(0)
        shadow = {}
        try:
            for i in range(400):
                op = rng.random()
                if op < 0.6 or not shadow:
                    key = f"obj-{i}"
                    size = int(rng.integers(1, 12000))
                    payload = bytes([i % 256]) * size
                    s.put(key, payload)
                    shadow[key] = payload
                else:
                    key = rng.choice(list(shadow))
                    s.delete(key)
                    del shadow[key]
                # spot check a few live keys (evictions allowed)
                for k in list(shadow)[:3]:
                    got = s.get_bytes(k)
                    if got is not None:
                        assert got == shadow[k], f"corruption at {k}"
            stats = s.stats()
            assert stats["put_count"] >= 200
        finally:
            s.destroy()

    def test_cross_process_visibility(self):
        name = f"bes-xproc-{secrets.token_hex(4)}"
        s = SharedObjectStore(name, capacity=1024 * 1024, n_slots=128)
        try:
            s.put("from-parent", b"parent-data")

            ctx = mp.get_context("spawn")
            q = ctx.Queue()
            p = ctx.Process(target=_xproc_child, args=(name, q))
            p.start()
            got = q.get(timeout=60)
            p.join(timeout=60)
            assert got == b"parent-data"
            assert s.get_bytes("from-child") == b"child-data"
        finally:
            s.destroy()


class TestLocalFallback:
    def test_api_parity(self):
        s = LocalObjectStore(capacity=1024)
        s.put("a", b"x" * 100)
        assert s.get_bytes("a") == b"x" * 100
        with pytest.raises(FileExistsError):
            s.put("a", b"y")
        with s.pinned("a") as view:
            assert bytes(view) == b"x" * 100
        assert s.contains("a")
        # eviction
        for i in range(20):
            s.put(f"k{i}", b"z" * 100)
        assert s.stats()["evictions"] > 0
        assert s.delete("k19") is True
        s.destroy()
        assert s.stats()["n_objects"] == 0

    def test_open_store_returns_something(self):
        name = f"bes-open-{secrets.token_hex(4)}"
        s = open_store(name, capacity=64 * 1024, n_slots=32)
        try:
            s.put("k", b"v")
            assert s.get_bytes("k") == b"v"
        finally:
            s.destroy()


class TestSharedChunkCache:
    @pytest.mark.anyio
    async def test_chunk_cache_api(self):
        from bioengine_tpu.datasets.chunk_cache import SharedChunkCache

        name = f"bes-chunks-{secrets.token_hex(4)}"
        cache = SharedChunkCache(max_bytes=1024 * 1024, name=name)
        try:
            assert await cache.get("c0") is None
            await cache.put("c0", b"chunk-bytes")
            assert await cache.get("c0") == b"chunk-bytes"
            await cache.put("c0", b"chunk-bytes")  # idempotent
            assert cache.misses >= 1 and cache.hits >= 1
            assert len(cache) == 1
            await cache.clear()
            assert await cache.get("c0") is None
        finally:
            cache._store.destroy()

    @pytest.mark.anyio
    async def test_zarr_store_through_shared_cache(self, tmp_path):
        """HttpZarrStore served chunks land in (and come back from) the
        shared cache."""
        from bioengine_tpu.datasets.chunk_cache import SharedChunkCache

        name = f"bes-zc-{secrets.token_hex(4)}"
        cache = SharedChunkCache(max_bytes=4 * 1024 * 1024, name=name)
        try:
            await cache.put("ds/x.zarr/c/0/0", b"\x01\x02\x03")
            assert await cache.get("ds/x.zarr/c/0/0") == b"\x01\x02\x03"
        finally:
            cache._store.destroy()


@needs_native
class TestAttachSemantics:
    def test_late_attach_does_not_wipe(self):
        """A second process/handle opening the same name must join the
        segment, not re-create it (the late-replica case)."""
        name = f"bes-attach-{secrets.token_hex(4)}"
        a = SharedObjectStore(name, capacity=256 * 1024)
        try:
            a.put("shared", b"cached-by-a")
            b = SharedObjectStore(name, capacity=256 * 1024)  # attach
            assert b.get_bytes("shared") == b"cached-by-a"
            b.close()
        finally:
            a.destroy()

    def test_in_place_clear_visible_to_all_handles(self):
        name = f"bes-clear-{secrets.token_hex(4)}"
        a = SharedObjectStore(name, capacity=256 * 1024)
        b = SharedObjectStore(name, capacity=256 * 1024)
        try:
            a.put("k", b"v")
            assert b.get_bytes("k") == b"v"
            removed = b.clear()
            assert removed == 1
            assert a.get_bytes("k") is None
            a.put("k2", b"v2")  # space fully reusable after clear
            assert b.get_bytes("k2") == b"v2"
        finally:
            b.close()
            a.destroy()


# ---------------------------------------------------------------------------
# Sanitized builds (ASan / TSan) — CI job scripts/workflows/native_sanitizers.sh
# ---------------------------------------------------------------------------

_REPO_ROOT = Path(__file__).resolve().parent.parent
_SAN_BUILD = _REPO_ROOT / "native" / "build"

_SAN_DRIVER = """
import secrets, threading
from bioengine_tpu.native import SharedObjectStore, native_available

assert native_available(), "sanitized library failed to load"
name = f"bes-san-{secrets.token_hex(4)}"
store = SharedObjectStore(name, capacity=1 << 20, n_slots=512)
errors = []

def hammer(i):
    try:
        for j in range(300):
            key = f"k{i}-{j}"  # put is put-once: keys must be unique
            store.put(key, bytes([i + 1]) * (64 + j % 512))
            val = store.get_bytes(key)  # may be None if LRU-evicted
            if val is not None and (not val or val[0] != i + 1):
                errors.append(f"torn read on {key}")
            if j % 40:  # keep a bounded live set; churn the allocator
                store.delete(key)
    except Exception as e:  # noqa: BLE001 - report into the parent assert
        errors.append(repr(e))

threads = [threading.Thread(target=hammer, args=(i,)) for i in range(8)]
for t in threads:
    t.start()
for t in threads:
    t.join()
stats = store.stats()
store.destroy()
assert not errors, errors[:5]
print("SAN-DRIVER-OK", stats["put_count"])
"""


def _sanitizer_runtime(san: str) -> str | None:
    try:
        out = subprocess.run(
            ["gcc", f"-print-file-name=lib{san}.so"],
            capture_output=True, text=True, timeout=30, check=True,
        ).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        return None
    return out if out and os.path.isabs(out) and os.path.exists(out) else None


@pytest.mark.slow
@pytest.mark.parametrize("san", ["asan", "tsan"])
def test_sanitized_store_concurrent_put_get(san):
    """Concurrent put/get/delete against the ASan/TSan-instrumented
    store (built by ``make -C native sanitizers``) in a subprocess with
    the sanitizer runtime preloaded.  Skips when the sanitized .so or
    the runtime is absent, so plain dev runs stay green; the CI
    native-sanitizers job builds both and runs this for real."""
    lib = _SAN_BUILD / f"libbioengine_store_{san}.so"
    if not lib.exists():
        pytest.skip(f"{lib.name} not built (make -C native sanitizers)")
    runtime = _sanitizer_runtime(san)
    if runtime is None:
        pytest.skip(f"lib{san}.so runtime not found via gcc")

    env = dict(os.environ)
    env.update(
        LD_PRELOAD=runtime,
        BIOENGINE_STORE_LIB=str(lib),
        # CPython intentionally leaks at shutdown; we sanitize the
        # store, not the interpreter
        ASAN_OPTIONS="detect_leaks=0",
        TSAN_OPTIONS="exitcode=66",
        PYTHONPATH=str(_REPO_ROOT),
    )
    proc = subprocess.run(
        [sys.executable, "-c", _SAN_DRIVER],
        capture_output=True, text=True, timeout=300,
        cwd=_REPO_ROOT, env=env,
    )
    report = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"driver failed ({proc.returncode}):\n{report}"
    assert "SAN-DRIVER-OK" in proc.stdout, report
    assert "AddressSanitizer" not in report, report
    assert "ThreadSanitizer" not in report, report
