"""Multi-host worker runtime integration tests (VERDICT round-1 gap #1).

A REAL second process (``python -m bioengine_tpu.worker_host``) joins
the controller's RPC plane, registers its topology, gets a replica
placed on it from a shipped artifact payload, serves calls routed
through the controller, and — when killed — triggers a restart of its
replica on another host. Mirrors the reference semantics of SLURM
workers joining the Ray cluster (ref bioengine/cluster/
slurm_workers.py:153-296) and Serve scheduling pending replicas onto
them (ref bioengine/apps/manager.py:355-455).
"""

import asyncio
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

from bioengine_tpu.apps.builder import AppBuilder
from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.serving.controller import DeploymentHandle, ServeController
from bioengine_tpu.serving.remote import RemoteReplica
from bioengine_tpu.serving.replica import ReplicaState

pytestmark = [pytest.mark.integration, pytest.mark.anyio]

REPO_ROOT = Path(__file__).resolve().parent.parent

CHIP_APP_MANIFEST = """\
name: Chip App
id: chip-app
id_emoji: "\U0001F9EA"
description: needs chips, so it must be placed on a worker host
type: tpu-serve
version: 1.0.0
deployments:
  - chip_deployment:ChipDeployment
authorized_users: ["*"]
deployment_config:
  chip_deployment:
    num_replicas: 1
    max_replicas: 2
    chips: 2
    autoscale: false
"""

CHIP_APP_SOURCE = '''\
import os
import socket

from bioengine_tpu.rpc import schema_method


class ChipDeployment:
    def __init__(self, tag: str = "none"):
        self.tag = tag

    async def async_init(self):
        self.pid = os.getpid()

    @schema_method
    async def where(self, context=None):
        """Report which process/host this replica runs in."""
        return {"pid": self.pid, "hostname": socket.gethostname(),
                "tag": self.tag}

    @schema_method
    async def add(self, a: int, b: int, context=None):
        """Add two ints (routing smoke check)."""
        return {"sum": a + b}
'''

COMPO_MANIFEST = """\
name: Compo App
id: compo-app
id_emoji: "\U0001F517"
description: remote entry composing a local sibling through the router
type: tpu-serve
version: 1.0.0
deployments:
  - entry_dep:EntryDep
  - backend_dep:BackendDep
authorized_users: ["*"]
deployment_config:
  entry_dep:
    chips: 2
    autoscale: false
  backend_dep:
    chips: 0
    autoscale: false
"""

COMPO_ENTRY = '''\
from bioengine_tpu.rpc import schema_method


class EntryDep:
    def __init__(self, backend_dep):
        self.backend = backend_dep

    @schema_method
    async def compute(self, x: int, context=None):
        """Delegate to the backend deployment via its handle."""
        doubled = await self.backend.call("double", x)
        return {"result": doubled["value"] + 1}
'''

COMPO_BACKEND = '''\
import os

from bioengine_tpu.rpc import schema_method


class BackendDep:
    def __init__(self):
        self.pid = os.getpid()

    @schema_method
    async def double(self, x: int, context=None):
        """Double a number; reports its pid for placement assertions."""
        return {"value": 2 * x, "pid": self.pid}
'''


def _no_local_chips() -> ClusterState:
    """A controller host with ZERO local chips — every chip-requiring
    replica must go to a joined worker host."""
    return ClusterState(
        TpuTopology(chips=(), n_hosts=1, platform="cpu")
    )


@pytest.fixture()
async def control_plane(tmp_path):
    server = RpcServer(host="127.0.0.1", admin_users=["admin"])
    await server.start()
    token = server.issue_token("admin", is_admin=True)
    controller = ServeController(_no_local_chips(), health_check_period=3600)
    controller.attach_rpc(server, admin_users=["admin"])
    await controller.start()
    try:
        yield server, controller, token
    finally:
        await controller.stop()
        await server.stop()


def _spawn_host(server_url: str, token: str, host_id: str, tmp_path: Path):
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "PYTHONPATH": str(REPO_ROOT),
        }
    )
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "bioengine_tpu.worker_host",
            "--server-url", server_url,
            # = form: a token_urlsafe value starting with '-' would be
            # rejected as an option by argparse (latent flake)
            f"--token={token}",
            "--host-id", host_id,
            "--platform", "cpu",
            "--workspace-dir", str(tmp_path / f"ws-{host_id}"),
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


async def _wait_for_host(controller: ServeController, host_id: str, timeout=40):
    deadline = time.time() + timeout
    while time.time() < deadline:
        host = controller.cluster_state.hosts.get(host_id)
        if host is not None and host.alive:
            return host
        await asyncio.sleep(0.2)
    raise TimeoutError(f"host {host_id} never joined")


def _write_app(tmp_path: Path, manifest: str, files: dict) -> Path:
    app_dir = tmp_path / "app-src"
    app_dir.mkdir(exist_ok=True)
    (app_dir / "manifest.yaml").write_text(manifest)
    for name, text in files.items():
        (app_dir / name).write_text(text)
    return app_dir


async def test_host_join_place_route_and_failover(control_plane, tmp_path):
    server, controller, token = control_plane
    app_dir = _write_app(
        tmp_path, CHIP_APP_MANIFEST, {"chip_deployment.py": CHIP_APP_SOURCE}
    )
    builder = AppBuilder(workdir_root=tmp_path / "apps")
    built = builder.build(
        app_id="chip-app",
        local_path=app_dir,
        deployment_kwargs={"chip_deployment": {"tag": "multihost"}},
    )

    host1 = _spawn_host(server.url, token, "h1", tmp_path)
    try:
        rec1 = await _wait_for_host(controller, "h1")
        assert rec1.n_chips == 4

        # ---- placement: zero local chips, so the replica MUST be remote
        await controller.deploy("chip-app", built.specs)
        replicas = controller.apps["chip-app"].replicas["chip_deployment"]
        assert len(replicas) == 1
        replica = replicas[0]
        assert isinstance(replica, RemoteReplica)
        assert replica.host_id == "h1"
        assert len(replica.device_ids) == 2
        # per-host chip accounting
        assert len(rec1.chips_in_use) == 2
        assert controller.cluster_state.cluster_free_chips() == 2

        # ---- a call routes through the controller to the host process
        handle = controller.get_handle("chip-app", "chip_deployment")
        where = await handle.call("where")
        assert where["pid"] == host1.pid  # actually ran over there
        assert where["tag"] == "multihost"  # kwargs shipped with payload
        add = await handle.call("add", 20, 22)
        assert add["sum"] == 42

        # ---- failover: kill h1, health tick re-places on h2
        host2 = _spawn_host(server.url, token, "h2", tmp_path)
        try:
            await _wait_for_host(controller, "h2")
            host1.send_signal(signal.SIGKILL)
            host1.wait(timeout=10)
            # let the RPC server notice the closed websocket
            deadline = time.time() + 15
            while time.time() < deadline:
                await controller.health_tick()
                reps = controller.apps["chip-app"].replicas["chip_deployment"]
                healthy = [
                    r for r in reps
                    if r.state in (ReplicaState.HEALTHY, ReplicaState.TESTING)
                ]
                if healthy and getattr(healthy[0], "host_id", None) == "h2":
                    break
                await asyncio.sleep(0.3)
            assert not controller.cluster_state.hosts["h1"].alive
            reps = controller.apps["chip-app"].replicas["chip_deployment"]
            healthy = [
                r for r in reps
                if r.state in (ReplicaState.HEALTHY, ReplicaState.TESTING)
            ]
            assert len(healthy) == 1
            assert healthy[0].host_id == "h2"
            where2 = await handle.call("where")
            assert where2["pid"] == host2.pid
        finally:
            host2.terminate()
            host2.wait(timeout=10)
    finally:
        if host1.poll() is None:
            host1.kill()
            host1.wait(timeout=10)


async def test_remote_entry_composes_local_backend_via_router(
    control_plane, tmp_path
):
    """A chip-requiring ENTRY lands on the worker host; its handle to the
    chip-free backend (placed locally on the controller) routes back
    through serve-router.route_call."""
    server, controller, token = control_plane
    app_dir = _write_app(
        tmp_path,
        COMPO_MANIFEST,
        {"entry_dep.py": COMPO_ENTRY, "backend_dep.py": COMPO_BACKEND},
    )
    builder = AppBuilder(workdir_root=tmp_path / "apps")
    built = builder.build(
        app_id="compo-app",
        local_path=app_dir,
        make_handle=lambda name: DeploymentHandle(
            controller, "compo-app", name
        ),
    )

    host = _spawn_host(server.url, token, "hx", tmp_path)
    try:
        await _wait_for_host(controller, "hx")
        await controller.deploy("compo-app", built.specs)
        entry = controller.apps["compo-app"].replicas["entry_dep"][0]
        backend = controller.apps["compo-app"].replicas["backend_dep"][0]
        assert isinstance(entry, RemoteReplica) and entry.host_id == "hx"
        assert not isinstance(backend, RemoteReplica)

        handle = controller.get_handle("compo-app", "entry_dep")
        result = await handle.call("compute", 10)
        assert result["result"] == 21  # 2*10 computed locally, +1 remotely
    finally:
        host.terminate()
        host.wait(timeout=10)


async def test_no_host_no_chips_raises_and_enqueues_pending(control_plane, tmp_path):
    server, controller, token = control_plane
    app_dir = _write_app(
        tmp_path, CHIP_APP_MANIFEST, {"chip_deployment.py": CHIP_APP_SOURCE}
    )
    built = AppBuilder(workdir_root=tmp_path / "apps").build(
        app_id="chip-app2", local_path=app_dir
    )
    with pytest.raises(RuntimeError, match="none free"):
        await controller.deploy("chip-app2", built.specs)
    pending = controller.cluster_state.pending()
    assert any(p.workload_id == "chip-app2/chip_deployment" for p in pending)


async def test_run_code_dispatches_to_host_with_chips(control_plane, tmp_path):
    """Chip-requesting run_code lands on the joined worker host with a
    leased chip set visible to the child process (ref
    bioengine/worker/code_executor.py:469-487); chip-free run_code stays
    local; unsatisfiable requests fail loudly (VERDICT r3 missing #8)."""
    from bioengine_tpu.utils.permissions import create_context
    from bioengine_tpu.worker.code_executor import CodeExecutor

    server, controller, token = control_plane
    executor = CodeExecutor(
        admin_users=["admin"],
        cluster_state=controller.cluster_state,
        call_host=controller._call_host,
    )
    admin = create_context("admin")
    code = (
        "import os\n"
        "def main():\n"
        "    return {'host': os.environ.get('BIOENGINE_HOST_ID'),\n"
        "            'chips': os.environ.get('BIOENGINE_LEASED_CHIPS')}\n"
    )

    # no chips requested: local subprocess, no host involved
    local = await executor.run_code(code=code, context=admin)
    assert local["status"] == "ok"
    assert local["result"]["host"] is None

    # chips requested but nothing anywhere: loud error, not silence
    with pytest.raises(RuntimeError, match="no joined host"):
        await executor.run_code(
            code=code, remote_options={"num_chips": 2}, context=admin
        )

    host = _spawn_host(server.url, token, "hcode", tmp_path)
    try:
        await _wait_for_host(controller, "hcode")
        result = await executor.run_code(
            code=code, remote_options={"num_chips": 2}, context=admin
        )
        assert result["status"] == "ok", result
        assert result["host_id"] == "hcode"
        assert result["result"]["host"] == "hcode"
        assert result["result"]["chips"] == "0,1"
        assert result["device_ids"] == [0, 1]
        # lease released after the run
        hrec = controller.cluster_state.hosts["hcode"]
        assert hrec.chips_in_use == {}

        # more chips than the host has: loud error
        with pytest.raises(RuntimeError, match="no joined host"):
            await executor.run_code(
                code=code, remote_options={"num_chips": 64}, context=admin
            )
    finally:
        host.terminate()
        host.wait(timeout=10)

    # unknown remote_options are rejected, not dropped
    with pytest.raises(ValueError, match="unsupported remote_options"):
        await executor.run_code(
            code=code, remote_options={"num_gpus": 1}, context=admin
        )


async def test_protected_host_service_rejects_non_admin(control_plane, tmp_path):
    """Anonymous/non-admin clients must not reach worker-host verbs
    (start_replica executes arbitrary payloads — admin only)."""
    from bioengine_tpu.rpc.client import connect_to_server

    server, controller, token = control_plane
    host = _spawn_host(server.url, token, "hsec", tmp_path)
    try:
        await _wait_for_host(controller, "hsec")
        svc_id = controller.cluster_state.hosts["hsec"].service_id
        conn = await connect_to_server({"server_url": server.url})
        try:
            with pytest.raises(Exception, match="protected"):
                await conn.call(svc_id, "describe")
        finally:
            await conn.disconnect()
        # admin still passes
        conn = await connect_to_server(
            {"server_url": server.url, "token": token}
        )
        try:
            desc = await conn.call(svc_id, "describe")
            assert desc["host_id"] == "hsec"
        finally:
            await conn.disconnect()
    finally:
        host.terminate()
        host.wait(timeout=10)


async def test_run_code_host_death_fails_fast_and_releases_lease(
    control_plane, tmp_path
):
    """SIGKILL the worker host while run_code executes there: the
    in-flight RPC fails immediately (provider-disconnect fail-fast in
    rpc/server.py _drop_client) and the chip lease is released."""
    from bioengine_tpu.utils.permissions import create_context
    from bioengine_tpu.worker.code_executor import CodeExecutor

    server, controller, token = control_plane
    executor = CodeExecutor(
        admin_users=["admin"],
        cluster_state=controller.cluster_state,
        call_host=controller._call_host,
    )
    host = _spawn_host(server.url, token, "hkill", tmp_path)
    try:
        await _wait_for_host(controller, "hkill")
        slow_code = (
            "import time\n"
            "def main():\n"
            "    time.sleep(60)\n"
            "    return 'never'\n"
        )
        task = asyncio.create_task(
            executor.run_code(
                code=slow_code,
                remote_options={"num_chips": 1},
                timeout=90.0,
                context=create_context("admin"),
            )
        )
        # wait until the lease lands on the host, then kill it
        deadline = time.time() + 20
        hrec = controller.cluster_state.hosts["hkill"]
        while not hrec.chips_in_use and time.time() < deadline:
            await asyncio.sleep(0.1)
        assert hrec.chips_in_use, "run_code never leased chips"
        host.kill()
        t0 = time.time()
        with pytest.raises(ConnectionError):
            await task
        # fail-fast: well under the 90s call timeout
        assert time.time() - t0 < 20
        # the finally-block released the lease despite the error
        assert hrec.chips_in_use == {}
    finally:
        if host.poll() is None:
            host.kill()
        host.wait(timeout=10)
