"""Regenerate the published-checkpoint key→shape manifest fixtures.

VERDICT r5 item 3: the weight converters were validated only against
``synthetic_cpsam_state_dict`` — a layout the same repo also wrote.
These manifests pin the converters to the *published* checkpoint
layouts instead, so drift in either direction (a cellpose/DINOv2
release moving a key, or a local name-map edit) fails the suite
without any download.

The TPU images have no egress, so the manifests are derived from the
upstream model definitions rather than dumped from the files:

- **DINOv2 ViT-B/14** (``dinov2_vitb14_pretrain.pth``):
  facebookresearch/dinov2 ``vision_transformer.DinoVisionTransformer``
  at embed_dim 768 / depth 12 / patch 14, pretrained at 518×518
  (pos_embed = (518/14)² + 1 cls = 1370 tokens) with ``mask_token``
  (1, 768) and per-block LayerScale ``ls1/ls2.gamma``.
- **cpsam** (Cellpose-SAM, the reference finetuning app's default
  ``pretrained_model``): ``cellpose.vit_sam.Transformer`` =
  segment-anything ``ImageEncoderViT`` ViT-L under an ``encoder.``
  prefix (patch 8, dim 1024, depth 24, heads 16, window 14, global
  attention at blocks 5/11/17/23, pretrain grid 32, neck 256) plus a
  ``ConvTranspose2d(256, 3, 8, 8)`` readout ``out``.

If a future release changes a layout, re-derive here, update the
name map, and the manifest test enforces the new contract.

Run from the repo root: ``python tests/generate_checkpoint_manifests.py``
"""

from __future__ import annotations

import json
from pathlib import Path

OUT_DIR = Path(__file__).resolve().parent


def dinov2_vitb14_manifest() -> dict[str, list[int]]:
    dim, depth, mlp = 768, 12, 3072
    m = {
        "cls_token": [1, 1, dim],
        "mask_token": [1, dim],
        "pos_embed": [1, 1370, dim],   # 518/14 = 37; 37*37 + 1
        "patch_embed.proj.weight": [dim, 3, 14, 14],
        "patch_embed.proj.bias": [dim],
        "norm.weight": [dim],
        "norm.bias": [dim],
    }
    for i in range(depth):
        b = f"blocks.{i}"
        m.update(
            {
                f"{b}.norm1.weight": [dim],
                f"{b}.norm1.bias": [dim],
                f"{b}.attn.qkv.weight": [3 * dim, dim],
                f"{b}.attn.qkv.bias": [3 * dim],
                f"{b}.attn.proj.weight": [dim, dim],
                f"{b}.attn.proj.bias": [dim],
                f"{b}.ls1.gamma": [dim],
                f"{b}.ls2.gamma": [dim],
                f"{b}.norm2.weight": [dim],
                f"{b}.norm2.bias": [dim],
                f"{b}.mlp.fc1.weight": [mlp, dim],
                f"{b}.mlp.fc1.bias": [mlp],
                f"{b}.mlp.fc2.weight": [dim, mlp],
                f"{b}.mlp.fc2.bias": [dim],
            }
        )
    return m


def cpsam_vitl_manifest() -> dict[str, list[int]]:
    dim, depth, heads, mlp, neck = 1024, 24, 16, 4096, 256
    patch, grid, window = 8, 32, 14
    global_attn = (5, 11, 17, 23)
    head_dim = dim // heads
    m = {
        "encoder.patch_embed.proj.weight": [dim, 3, patch, patch],
        "encoder.patch_embed.proj.bias": [dim],
        # SAM stores pos_embed pre-shaped (1, gh, gw, dim) — NHWC
        "encoder.pos_embed": [1, grid, grid, dim],
        "encoder.neck.0.weight": [neck, dim, 1, 1],
        "encoder.neck.1.weight": [neck],
        "encoder.neck.1.bias": [neck],
        "encoder.neck.2.weight": [neck, neck, 3, 3],
        "encoder.neck.3.weight": [neck],
        "encoder.neck.3.bias": [neck],
        # torch ConvTranspose2d(256, 3, 8, 8): (in, out, kH, kW)
        "out.weight": [neck, 3, patch, patch],
        "out.bias": [3],
    }
    for i in range(depth):
        b = f"encoder.blocks.{i}"
        s = grid if i in global_attn else window
        m.update(
            {
                f"{b}.norm1.weight": [dim],
                f"{b}.norm1.bias": [dim],
                f"{b}.attn.qkv.weight": [3 * dim, dim],
                f"{b}.attn.qkv.bias": [3 * dim],
                f"{b}.attn.proj.weight": [dim, dim],
                f"{b}.attn.proj.bias": [dim],
                f"{b}.attn.rel_pos_h": [2 * s - 1, head_dim],
                f"{b}.attn.rel_pos_w": [2 * s - 1, head_dim],
                f"{b}.norm2.weight": [dim],
                f"{b}.norm2.bias": [dim],
                f"{b}.mlp.lin1.weight": [mlp, dim],
                f"{b}.mlp.lin1.bias": [mlp],
                f"{b}.mlp.lin2.weight": [dim, mlp],
                f"{b}.mlp.lin2.bias": [dim],
            }
        )
    return m


def main() -> None:
    for name, manifest in (
        ("fixtures_manifest_dinov2_vitb14.json", dinov2_vitb14_manifest()),
        ("fixtures_manifest_cpsam_vitl.json", cpsam_vitl_manifest()),
    ):
        path = OUT_DIR / name
        path.write_text(json.dumps(manifest, indent=1, sort_keys=True) + "\n")
        print(f"wrote {path} ({len(manifest)} keys)")


if __name__ == "__main__":
    main()
