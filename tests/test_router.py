"""Scale-out router tier: table publication, epoch fencing, the
standalone router's request path, and the shared-contract pins.

What the suite proves, layer by layer:

- **Publication** (``RoutingTablePublisher``): versions advance only on
  content changes, diffs carry only what changed, advisory hints (load,
  breaker counts) ride along without churning versions.
- **Fencing** (``StandaloneRouter.apply_table``): a stale controller's
  push — lower epoch, or lower version under the same epoch — is
  rejected TYPED (``StaleTableError``) and never regresses the router's
  newer view; a diff cannot cross controller generations; epochs come
  from the real PR 15 journal (two controllers minting against one
  ``control_dir``), not hand-rolled counters.
- **Serving** (``shared_object_resolver`` / ``remote_replica_resolver``):
  a synced router routes the identical ``RouterCore`` path the
  controller runs, keeps serving its last-good table when pushes go
  stale, sheds typed at its inflight cap, and fails new requests over
  typed when killed.
- **Contract** (the bugfix-sweep pin): exactly ONE copy of the
  breaker/caller-timeout exemption and of the ``_best_replica`` scorer
  argmin exists in the tree, and the router half of the old controller
  lives ONLY in ``RouterCore`` — no drift between the in-process and
  standalone paths is possible because there is nothing to drift.
"""

from __future__ import annotations

import asyncio
import re
from pathlib import Path

import pytest

from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.serving import (
    DeploymentSpec,
    ReplicaState,
    RequestOptions,
    RouterCore,
    SchedulingConfig,
    ServeController,
    StandaloneRouter,
    remote_replica_resolver,
    shared_object_resolver,
)
from bioengine_tpu.serving.errors import (
    AdmissionRejectedError,
    RetryableTransportError,
    RouterClosedError,
    RouterSaturatedError,
    StaleEpochError,
    StaleTableError,
)
from bioengine_tpu.serving.router import TABLE_SCHEMA, DeploymentHandle
from bioengine_tpu.utils import metrics

pytestmark = [pytest.mark.integration, pytest.mark.anyio]

SRC_ROOT = Path(__file__).resolve().parent.parent / "bioengine_tpu"


class _Echo:
    async def work(self, a: int = 0, b: int = 0):
        return {"sum": a + b}


class _Slow:
    async def work(self, a: int = 0, b: int = 0):
        await asyncio.sleep(0.2)
        return {"sum": a + b}


async def _deploy(controller, factory=_Echo, n=2, scheduling=None,
                  app_id="app", dep="dep"):
    await controller.deploy(
        app_id,
        [
            DeploymentSpec(
                name=dep,
                instance_factory=factory,
                num_replicas=n,
                min_replicas=n,
                max_replicas=n,
                autoscale=False,
                scheduling=scheduling,
            )
        ],
    )
    return controller


@pytest.fixture
async def controller():
    c = ServeController(ClusterState(), health_check_period=3600)
    await _deploy(c)
    yield c
    await c.stop()


# ---------------------------------------------------------------------------
# publication
# ---------------------------------------------------------------------------


class TestTablePublication:
    async def test_full_table_schema(self, controller):
        t = controller.router_publisher.table()
        assert t["schema"] == TABLE_SCHEMA
        assert t["full"] is True
        assert t["epoch"] == controller.epoch
        assert t["version"] >= 1
        entries = t["deployments"]["app"]["dep"]["entries"]
        assert len(entries) == 2
        for e in entries:
            assert e["state"] == "HEALTHY"
            assert "replica_id" in e

    async def test_version_stable_without_changes(self, controller):
        pub = controller.router_publisher
        v1 = pub.table()["version"]
        v2 = pub.table()["version"]
        assert v1 == v2, "refresh without content change must not churn"

    async def test_diff_carries_only_changes(self, controller):
        pub = controller.router_publisher
        v1 = pub.table()["version"]
        await _deploy(controller, app_id="app2", dep="dep2")
        diff = pub.table(since_version=v1)
        assert diff["full"] is False
        assert "app2" in diff["deployments"]
        assert "app" not in diff["deployments"], (
            "unchanged deployment must not ride the diff"
        )

    async def test_undeploy_rides_diff_as_removal(self, controller):
        pub = controller.router_publisher
        await _deploy(controller, app_id="app2", dep="dep2")
        v = pub.table()["version"]
        await controller.undeploy("app2")
        diff = pub.table(since_version=v)
        assert ["app2", "dep2"] in diff["removed"]

    async def test_sync_report_lands_in_app_status(self, controller):
        router = StandaloneRouter(
            "r-status", shared_object_resolver(controller)
        )
        router.sync_from(controller)
        tier = controller.get_app_status("app")["router_tier"]
        assert tier["table_epoch"] == controller.epoch
        reported = {r["router_id"] for r in tier["routers"]}
        assert "r-status" in reported
        row = next(
            r for r in tier["routers"] if r["router_id"] == "r-status"
        )
        assert row["acked_version"] == tier["table_version"]
        assert row["staleness_s"] is not None


# ---------------------------------------------------------------------------
# epoch fencing (real journal epochs — the PR 15 fixture idiom)
# ---------------------------------------------------------------------------


class TestEpochFencing:
    async def test_stale_epoch_push_rejected_typed(self, tmp_path):
        """Two controller generations against ONE journal directory:
        the router adopts gen-2's table, then gen-1 (the wedged-then-
        revived old controller) pushes — rejected typed, view kept."""
        control = str(tmp_path / "control")
        old = ServeController(
            ClusterState(), health_check_period=3600, control_dir=control
        )
        await _deploy(old)
        assert old.epoch == 1
        new = ServeController(
            ClusterState(), health_check_period=3600, control_dir=control
        )
        await _deploy(new)
        assert new.epoch == 2

        router = StandaloneRouter("r-fence", shared_object_resolver(new))
        router.sync_from(new)
        held = (router.table_epoch, router.table_version)
        assert held[0] == 2

        with pytest.raises(StaleTableError) as exc:
            router.apply_table(old.router_publisher.table())
        assert exc.value.seen_epoch == 2
        assert exc.value.got_epoch == 1
        # typed as the NON-retryable epoch-fencing class: re-pushing a
        # stale table can never succeed
        assert isinstance(exc.value, StaleEpochError)
        assert not isinstance(exc.value, RetryableTransportError)
        # the newer view is untouched, and the router still routes
        assert (router.table_epoch, router.table_version) == held
        r = await router.get_handle("app", "dep").call("work", 2, 3)
        assert r == {"sum": 5}
        await old.stop()
        await new.stop()

    async def test_stale_version_same_epoch_rejected(self, controller):
        router = StandaloneRouter(
            "r-ver", shared_object_resolver(controller)
        )
        stale = controller.router_publisher.table()
        await _deploy(controller, app_id="app2", dep="dep2")
        router.sync_from(controller)
        held_version = router.table_version
        assert held_version > stale["version"]
        with pytest.raises(StaleTableError):
            router.apply_table(stale)
        assert router.table_version == held_version

    async def test_duplicate_push_is_noop_but_confirms_freshness(
        self, controller
    ):
        router = StandaloneRouter(
            "r-dup", shared_object_resolver(controller)
        )
        router.sync_from(controller)
        await asyncio.sleep(0.05)
        aged = router.table_staleness_s
        assert aged >= 0.05
        out = router.sync_from(controller)
        assert out["applied"] is False
        assert out["reason"] == "duplicate"
        # a live publisher confirming "nothing changed" RESETS the
        # staleness clock — a quiet fleet is fresh, not stale
        assert router.table_staleness_s < aged

    async def test_diff_cannot_cross_epochs(self, tmp_path):
        control = str(tmp_path / "control")
        old = ServeController(
            ClusterState(), health_check_period=3600, control_dir=control
        )
        await _deploy(old)
        router = StandaloneRouter("r-gen", shared_object_resolver(old))
        router.sync_from(old)

        new = ServeController(
            ClusterState(), health_check_period=3600, control_dir=control
        )
        await _deploy(new)
        diff = new.router_publisher.table(since_version=1)
        assert diff["full"] is False
        with pytest.raises(ValueError, match="cannot cross"):
            router.apply_table(diff)
        # a FULL table from the new generation applies cleanly
        router.apply_table(new.router_publisher.table())
        assert router.table_epoch == 2
        await old.stop()
        await new.stop()

    async def test_last_good_serving_through_controller_restart(
        self, tmp_path
    ):
        """The availability contract: the controller dies, sync fails,
        the router keeps routing its last-good table (staleness grows);
        the restarted generation's full table is adopted on first
        sync."""
        control = str(tmp_path / "control")
        old = ServeController(
            ClusterState(), health_check_period=3600, control_dir=control
        )
        await _deploy(old)
        router = StandaloneRouter(
            "r-crash", shared_object_resolver(lambda: old)
        )
        router.sync_from(old)

        # "crash": the publisher is unreachable — sync raises, the
        # router's view (and the live replica objects) survive
        class _Dead:
            def __getattr__(self, name):
                raise ConnectionError("controller down")

        with pytest.raises(Exception):
            router.sync_from(_Dead())
        r = await router.get_handle("app", "dep").call("work", 20, 22)
        assert r == {"sum": 42}

        new = ServeController(
            ClusterState(), health_check_period=3600, control_dir=control
        )
        await _deploy(new)
        assert new.epoch == old.epoch + 1
        router._resolver = shared_object_resolver(new)
        router.sync_from(new)
        assert router.table_epoch == new.epoch
        r = await router.get_handle("app", "dep").call("work", 1, 1)
        assert r == {"sum": 2}
        await old.stop()
        await new.stop()


# ---------------------------------------------------------------------------
# the standalone request path
# ---------------------------------------------------------------------------


class TestStandaloneRouting:
    async def test_routes_after_sync(self, controller):
        router = StandaloneRouter(
            "r-route", shared_object_resolver(controller)
        )
        router.sync_from(controller)
        r = await router.get_handle("app", "dep").call("work", 3, 4)
        assert r == {"sum": 7}

    async def test_unsynced_router_has_no_apps(self, controller):
        router = StandaloneRouter(
            "r-empty", shared_object_resolver(controller)
        )
        with pytest.raises(KeyError):
            router.get_handle("app", "dep")

    async def test_kill_rejects_new_requests_retryable(self, controller):
        router = StandaloneRouter(
            "r-kill", shared_object_resolver(controller)
        )
        router.sync_from(controller)
        router.kill()
        with pytest.raises(RouterClosedError) as exc:
            await router.get_handle("app", "dep").call("work", 1, 2)
        # retryable BY DESIGN: the client's typed-retry machinery fails
        # the request over to a sibling router
        assert isinstance(exc.value, RetryableTransportError)

    async def test_inflight_cap_sheds_typed(self):
        c = ServeController(ClusterState(), health_check_period=3600)
        await _deploy(c, factory=_Slow, n=1)
        router = StandaloneRouter(
            "r-cap", shared_object_resolver(c), max_inflight=1
        )
        router.sync_from(c)
        handle = router.get_handle("app", "dep")
        first = asyncio.ensure_future(handle.call("work", 1, 2))
        await asyncio.sleep(0.05)
        with pytest.raises(RouterSaturatedError) as exc:
            await handle.call("work", 3, 4)
        # saturated is ADMISSION backpressure, not a transport fault —
        # never failed over (every sibling shares the replica pool)
        assert isinstance(exc.value, AdmissionRejectedError)
        assert exc.value.reason == "router_saturated"
        assert await first == {"sum": 3}
        # the gate drained: the next request admits normally
        assert await handle.call("work", 5, 6) == {"sum": 11}
        await c.stop()

    async def test_scheduler_attaches_from_table(self):
        c = ServeController(ClusterState(), health_check_period=3600)
        await _deploy(
            c, n=2,
            scheduling=SchedulingConfig(max_batch=4, max_wait_ms=1.0),
        )
        router = StandaloneRouter("r-sched", shared_object_resolver(c))
        router.sync_from(c)
        assert ("app", "dep") in router._schedulers
        r = await router.get_handle("app", "dep").call(
            "work", 1, 2,
            options=RequestOptions(priority="interactive"),
        )
        assert r == {"sum": 3}
        router.kill()
        assert not router._schedulers, "kill() detaches schedulers"
        await c.stop()

    async def test_metrics_surface_epoch_and_staleness(self, controller):
        router = StandaloneRouter(
            "r-metrics", shared_object_resolver(controller)
        )
        router.sync_from(controller)
        text = metrics.render_prometheus()
        assert (
            f'router_table_epoch{{router="r-metrics"}} '
            f"{controller.epoch}" in text
        )
        assert 'router_table_staleness_seconds{router="r-metrics"}' in text
        assert 'router_inflight_requests{router="r-metrics"} 0' in text


# ---------------------------------------------------------------------------
# the remote resolver (a router in its own process)
# ---------------------------------------------------------------------------


class TestRemoteResolver:
    def _table(self, controller):
        return controller.router_publisher.table()

    async def test_routes_over_fake_transport(self, controller):
        calls = []

        async def call_host(service_id, verb, *args, **kwargs):
            calls.append((service_id, verb, args))
            rid, method, call_args, _kw = args[0], args[1], args[2], args[3]
            assert method == "work"
            return {"sum": call_args[0] + call_args[1]}

        # dress the published entries as host-bound (the publisher
        # passes through host_service_id=None for local replicas)
        table = self._table(controller)
        for e in table["deployments"]["app"]["dep"]["entries"]:
            e["host_id"] = "h1"
            e["host_service_id"] = "svc-h1"
        router = StandaloneRouter(
            "r-remote", remote_replica_resolver(call_host)
        )
        router.apply_table(table)
        r = await router.get_handle("app", "dep").call("work", 5, 6)
        assert r == {"sum": 11}
        assert calls[0][0] == "svc-h1"
        assert calls[0][1] == "replica_call"

    async def test_states_follow_table_and_pool_prunes(self, controller):
        async def call_host(*a, **k):
            return {}

        table = self._table(controller)
        entries = table["deployments"]["app"]["dep"]["entries"]
        for e in entries:
            e["host_id"] = "h1"
            e["host_service_id"] = "svc-h1"
        router = StandaloneRouter(
            "r-own", remote_replica_resolver(call_host)
        )
        router.apply_table(table)
        pool = router.apps["app"].replicas["dep"]
        assert [r.state for r in pool] == [ReplicaState.HEALTHY] * 2
        assert {r.replica_id for r in pool} == {
            e["replica_id"] for e in entries
        }

        # next generation of the table drops one replica and marks the
        # other DRAINING — the router's owned pool follows
        survivor = dict(entries[0], state="DRAINING")
        table2 = dict(table, version=table["version"] + 1)
        table2["deployments"] = {"app": {"dep": {
            **table["deployments"]["app"]["dep"], "entries": [survivor],
        }}}
        router.apply_table(table2)
        pool = router.apps["app"].replicas["dep"]
        assert len(pool) == 1
        assert pool[0].state is ReplicaState.DRAINING

    async def test_local_breaker_verdict_vetoes_table_health(
        self, controller
    ):
        """The router saw the transport failures FIRST-HAND; a table
        still claiming HEALTHY (the controller's view lags a health
        tick) must not reopen the breaker for breaker_hold_s."""
        async def call_host(*a, **k):
            return {}

        table = self._table(controller)
        entries = table["deployments"]["app"]["dep"]["entries"]
        for e in entries:
            e["host_id"] = "h1"
            e["host_service_id"] = "svc-h1"
        router = StandaloneRouter(
            "r-veto", remote_replica_resolver(call_host),
            breaker_threshold=3,
        )
        router.apply_table(table)
        victim = router.apps["app"].replicas["dep"][0]
        for _ in range(3):
            router._breaker_failure(victim, ConnectionError("boom"))
        assert victim.state is ReplicaState.UNHEALTHY

        repush = dict(table, version=table["version"] + 1)
        router.apply_table(repush)
        assert victim.state is ReplicaState.UNHEALTHY, (
            "table health must not outrank a fresh local breaker verdict"
        )
        # once the hold expires the table's view wins again
        router.breaker_hold_s = 0.0
        router.apply_table(dict(table, version=table["version"] + 2))
        assert victim.state is ReplicaState.HEALTHY


# ---------------------------------------------------------------------------
# shared-contract pins (the bugfix sweep)
# ---------------------------------------------------------------------------


class TestSharedContract:
    ROUTER_METHODS = (
        "get_handle",
        "_pick_replica",
        "_pick_replica_wait",
        "_breaker_failure",
        "_breaker_success",
        "_note_attempt_latency",
        "_apply_probation_transitions",
        "hedge_delay_s",
    )

    def test_router_half_lives_only_in_routercore(self):
        """The seam: ServeController and StandaloneRouter both ROUTE
        through the single RouterCore implementation — neither may
        shadow it (a shadow is exactly the drift the sweep forbids)."""
        for name in self.ROUTER_METHODS:
            assert name in RouterCore.__dict__, name
            assert name not in ServeController.__dict__, (
                f"ServeController shadows RouterCore.{name}"
            )
            assert name not in StandaloneRouter.__dict__, (
                f"StandaloneRouter shadows RouterCore.{name}"
            )
        assert issubclass(ServeController, RouterCore)
        assert issubclass(StandaloneRouter, RouterCore)

    def test_exactly_one_breaker_exemption_and_scorer_argmin(self):
        """Source-level pin: ONE definition of the caller-timeout
        breaker exemption (errors.is_caller_timeout) and ONE
        _best_replica scorer argmin in the whole tree."""
        defs = {"def is_caller_timeout": [], "def _best_replica": []}
        for path in SRC_ROOT.rglob("*.py"):
            text = path.read_text()
            for needle, hits in defs.items():
                hits.extend(
                    (path, m.start())
                    for m in re.finditer(re.escape(needle), text)
                )
        for needle, hits in defs.items():
            assert len(hits) == 1, (
                f"{needle!r} defined {len(hits)}x: "
                f"{[str(p) for p, _ in hits]}"
            )

    def test_handle_is_the_router_module_class(self):
        """controller.get_handle returns the ONE DeploymentHandle —
        the class that moved to router.py; controller.py re-imports it
        (bit-compatible path, single implementation)."""
        from bioengine_tpu.serving import controller as controller_mod

        assert controller_mod.DeploymentHandle is DeploymentHandle
        assert DeploymentHandle.__module__ == "bioengine_tpu.serving.router"


# ---------------------------------------------------------------------------
# pick-miss health wake
# ---------------------------------------------------------------------------


class TestPickMissHealthWake:
    """A request waiting in ``_pick_replica_wait`` with nothing routable
    rings ``_wake_health`` — the same signal a breaker trip sends — so
    the health loop runs its restart/top-up pass NOW instead of up to
    ``health_check_period`` later. Found by the chaos fuzzer: a host
    rejoining after a blip sat unplaced for a request's whole deadline
    because nothing woke placement."""

    async def test_pick_miss_sets_wake_health(self, controller):
        import time

        from bioengine_tpu.serving.errors import NoHealthyReplicasError

        for r in controller.apps["app"].replicas["dep"]:
            r.state = ReplicaState.UNHEALTHY
        controller._wake_health.clear()
        with pytest.raises(NoHealthyReplicasError):
            await controller._pick_replica_wait(
                "app", "dep", deadline=time.monotonic() + 0.3
            )
        assert controller._wake_health.is_set()

    async def test_waiting_request_recovers_via_woken_health_loop(self):
        """End to end: every replica is unroutable, the health loop is
        idle on a 3600 s period — only the pick-miss wake can save the
        request before its deadline. It must."""
        c = ServeController(ClusterState(), health_check_period=3600)
        await _deploy(c, n=1)
        await c.start()
        try:
            for r in c.apps["app"].replicas["dep"]:
                r.state = ReplicaState.UNHEALTHY
            handle = c.get_handle("app", "dep")
            result = await handle.call(
                "work", 2, 3, options=RequestOptions(deadline_s=5.0)
            )
            assert result["sum"] == 5
        finally:
            await c.stop()
